"""adversarial_spec_tpu — a TPU-native adversarial spec-debate framework.

A ground-up rebuild of the capabilities of ``zscole/adversarial-spec``
(multi-model adversarial critique of PRDs / tech specs, looping until all
models agree) with the remote-API inference substrate replaced by an in-tree
JAX/XLA engine: a ``tpu://`` provider loads HF checkpoints into pjit-sharded
JAX models over an ICI mesh, per-opponent fan-out becomes one batched decode,
and the decode hot loop uses Pallas TPU kernels.

Layer map (mirrors reference SURVEY §1, substrate swapped):

- ``adversarial_spec_tpu.cli``      — CLI front-end (reference: scripts/debate.py)
- ``adversarial_spec_tpu.debate``   — round orchestration, parsing, convergence,
  usage/cost, sessions, profiles, prompts (reference: models.py/session.py/
  providers.py/prompts.py)
- ``adversarial_spec_tpu.engine``   — inference engines: mock + TPU
  (reference L1: litellm HTTP / CLI subprocess transport)
- ``adversarial_spec_tpu.models``   — JAX transformer model families
- ``adversarial_spec_tpu.ops``      — Pallas TPU kernels + attention ops
- ``adversarial_spec_tpu.parallel`` — mesh, sharding rules, collectives,
  ring attention
"""

__version__ = "0.1.0"
