"""CLI front-end — the L4 layer.

Behavioral parity with reference scripts/debate.py: same action set
(``critique, providers, send-final, diff, export-tasks, focus-areas,
personas, profiles, save-profile, sessions`` — reference :397-413), with the
reference's ``bedrock`` gateway action replaced by the TPU-native analog
``registry`` (local model registry management, SURVEY §2.3). Same exit-code
contract (0 ok / 1 runtime error / 2 validation failure, reference :39-43),
same stderr-human/stdout-JSON split, and the same JSON output schema
(reference :909-941) so the L5 agent protocol can drive either
implementation unchanged.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from adversarial_spec_tpu.debate import journal as journal_mod
from adversarial_spec_tpu.debate import prompts
from adversarial_spec_tpu.debate.core import RoundConfig, run_round
from adversarial_spec_tpu.debate.parsing import extract_tasks, generate_diff
from adversarial_spec_tpu.debate.profiles import (
    apply_profile,
    list_profiles,
    load_profile,
    save_profile,
)
from adversarial_spec_tpu.debate.session import (
    CorruptSessionState,
    InvalidSessionId,
    SessionState,
    save_checkpoint,
)
from adversarial_spec_tpu.debate.usage import CostTracker
from adversarial_spec_tpu.engine import registry as model_registry
from adversarial_spec_tpu.engine.dispatch import get_engine
from adversarial_spec_tpu.engine.types import ChatRequest, SamplingParams

EXIT_OK = 0
EXIT_ERROR = 1
EXIT_VALIDATION = 2

ACTIONS = [
    "critique",
    "providers",
    "send-final",
    "diff",
    "export-tasks",
    "focus-areas",
    "personas",
    "profiles",
    "save-profile",
    "sessions",
    "registry",
    "serve",
]

DEFAULT_MODELS = ["mock://critic?agree_after=3"]

# Bigger models make better critics; used to rank registry entries when
# auto-picking a default opponent (reference analog: priority-ordered
# default-model detection, providers.py:394-415).
_SIZE_RANK = {"70b": 6, "9b": 5, "8b": 4, "7b": 3, "3b": 2, "1b": 1, "tiny": 0}


def get_default_models() -> list[str]:
    """Best servable opponent: a registry alias with a real, resolvable
    checkpoint (largest first); else the mock critic so the loop always
    runs."""
    reg = model_registry.load_registry()
    real = [
        (spec, alias)
        for alias, spec in reg.items()
        if spec.checkpoint != "random"
        and model_registry.validate_tpu_model(f"tpu://{alias}", registry=reg)
        is None
    ]
    if real:
        real.sort(key=lambda e: _SIZE_RANK.get(e[0].size, -1), reverse=True)
        return [f"tpu://{real[0][1]}"]
    return list(DEFAULT_MODELS)


def _err(msg: str) -> None:
    print(msg, file=sys.stderr)


def create_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="debate",
        description="TPU-native adversarial spec debate engine",
    )
    parser.add_argument("action", choices=ACTIONS, help="Command to run")

    g = parser.add_argument_group("debate")
    g.add_argument(
        "--models",
        "-m",
        help="Comma-separated model ids (mock://... or tpu://alias)",
    )
    g.add_argument(
        "--doc-type",
        choices=["prd", "tech", "generic"],
        default=None,
        help="Document type (default: generic)",
    )
    g.add_argument("--round", type=int, default=1, help="Debate round number")
    g.add_argument("--focus", help="Focus area (see focus-areas action)")
    g.add_argument("--persona", help="Persona key or freeform persona text")
    g.add_argument(
        "--preserve-intent",
        action="store_true",
        help="Constrain critique to preserve the author's intent",
    )
    g.add_argument(
        "--press",
        action="store_true",
        help="Press round: force models to re-justify quick agreement",
    )
    g.add_argument(
        "--context",
        action="append",
        default=None,
        help="Context file injected into prompts (repeatable)",
    )

    s = parser.add_argument_group("session")
    s.add_argument("--session", help="Session id to create/update")
    s.add_argument("--resume", help="Resume a previous session by id")
    s.add_argument("--profile", help="Load settings from a saved profile")
    s.add_argument("--name", help="Profile name (for save-profile)")
    s.add_argument(
        "--journal",
        action=argparse.BooleanOptionalAction,
        default=None,  # None = inherit ADVSPEC_JOURNAL (default on)
        help="Crash-safe round journal for sessions: every opponent "
        "completion is fsync'd to <session>.journal.jsonl the moment "
        "it resolves, and --resume after a crash serves completed "
        "opponents from the journal byte-identically instead of "
        "re-decoding them (--no-journal disables; ADVSPEC_JOURNAL=0 "
        "sets the process default)",
    )

    o = parser.add_argument_group("output")
    o.add_argument("--json", "-j", action="store_true", help="JSON output")
    o.add_argument(
        "--show-cost", action="store_true", help="Print cost/usage summary"
    )
    o.add_argument("--previous", help="Previous spec file (diff action)")
    o.add_argument("--current", help="Current spec file (diff action)")
    o.add_argument(
        "--notify",
        action="store_true",
        help="Send round summary to Telegram and poll for feedback",
    )
    o.add_argument(
        "--feedback-timeout",
        type=int,
        default=0,
        help="Seconds to wait for Telegram feedback (0 = don't poll)",
    )
    o.add_argument(
        "--profile-dir",
        help="Write a jax.profiler trace for the round to this directory",
    )

    b = parser.add_argument_group("observability")
    b.add_argument(
        "--metrics-out",
        help="Write the round's metrics registry to this file in "
        "Prometheus text exposition format",
    )
    b.add_argument(
        "--events-out",
        help="Write the flight recorder's event ring to this file as "
        "JSONL at end of round; fault/timeout evictions auto-dump the "
        "ring to a sibling <stem>.<trigger>.jsonl the moment they "
        "happen",
    )
    b.add_argument(
        "--flight-recorder-size",
        type=int,
        default=None,
        help="Events the flight recorder ring retains (default 512; "
        "ADVSPEC_FLIGHT_RECORDER_SIZE sets the process default)",
    )
    b.add_argument(
        "--obs",
        action=argparse.BooleanOptionalAction,
        default=None,  # None = inherit ADVSPEC_OBS (default on)
        help="Observability subsystem: metrics registry + flight "
        "recorder + retrace watch (--no-obs disables every emit; "
        "ADVSPEC_OBS=0 sets the process default)",
    )
    b.add_argument(
        "--slo-ttft-ms",
        type=float,
        default=None,  # None = inherit ADVSPEC_SLO_TTFT_MS (default off)
        help="Per-request TTFT SLO budget in milliseconds: a request "
        "whose own prefill wall breaches it arms ONE flight-recorder "
        "dump scoped to its trace (sibling <stem>.slo_ttft.jsonl of "
        "--events-out, the fault-dump discipline). 0 disables; "
        "ADVSPEC_SLO_TTFT_MS sets the process default",
    )
    b.add_argument(
        "--slo-round-s",
        type=float,
        default=None,  # None = inherit ADVSPEC_SLO_ROUND_S (default off)
        help="Per-request service SLO budget in seconds (prefill + "
        "decode, the per-opponent round latency): a breaching request "
        "self-captures once to <stem>.slo_round.jsonl. 0 disables; "
        "ADVSPEC_SLO_ROUND_S sets the process default",
    )

    d = parser.add_argument_group("decode")
    d.add_argument(
        "--max-new-tokens",
        type=int,
        default=None,
        help="Response token cap (default 1024)",
    )
    d.add_argument(
        "--temperature", type=float, default=None, help="Sampling temperature"
    )
    d.add_argument(
        "--greedy", action="store_true", help="Greedy (argmax) decoding"
    )
    d.add_argument("--seed", type=int, default=None, help="Sampling PRNG seed")
    d.add_argument(
        "--timeout",
        type=float,
        default=None,
        help="Per-round wall-clock budget in seconds (default 600)",
    )
    d.add_argument(
        "--request-deadline-s",
        type=float,
        default=None,  # None = inherit ADVSPEC_REQUEST_DEADLINE_S (off)
        help="Per-REQUEST watchdog deadline in seconds: a single "
        "hung/slow opponent request is evicted as a TIMEOUT fault at "
        "this deadline (partial text kept, co-residents unaffected) "
        "and re-admitted ONCE on a tightened budget, where --timeout "
        "would have expired the whole round at once. 0 disables; "
        "ADVSPEC_REQUEST_DEADLINE_S sets the process default",
    )
    d.add_argument(
        "--prefix-cache",
        action=argparse.BooleanOptionalAction,
        default=True,
        help="Cross-round prefix KV cache: shared spec/transcript "
        "prefixes prefill once and are reused via ref-counted page "
        "sharing (--no-prefix-cache disables)",
    )
    d.add_argument(
        "--prefix-cache-pages",
        type=int,
        default=0,
        help="Cap on KV pages the prefix cache may retain "
        "(0 = bounded only by the pool, evicting LRU under pressure)",
    )
    d.add_argument(
        "--kv-tier",
        action=argparse.BooleanOptionalAction,
        default=None,  # None = inherit ADVSPEC_KV_TIER (default on)
        help="Tiered KV cache: LRU-evicted prefix blocks demote to "
        "host RAM and promote back instead of re-prefilling; with "
        "--kv-store-dir they also persist to a content-addressed disk "
        "store a restarted server rehydrates from (--no-kv-tier "
        "disables; ADVSPEC_KV_TIER=0 sets the process default)",
    )
    d.add_argument(
        "--kv-host-mb",
        type=int,
        default=None,  # None = inherit ADVSPEC_KV_HOST_MB (default 256)
        help="Host-RAM KV tier budget in MiB (0 disables tier 1; "
        "default 256, ADVSPEC_KV_HOST_MB sets the process default)",
    )
    d.add_argument(
        "--kv-store-dir",
        default=None,  # None = inherit ADVSPEC_KV_STORE_DIR (default off)
        help="Root directory of the persistent content-addressed KV "
        "block store (tier 2); entries are namespaced by a "
        "model/config fingerprint, written atomically, and corrupt "
        "entries quarantine instead of serving (unset disables; "
        "ADVSPEC_KV_STORE_DIR sets the process default)",
    )
    d.add_argument(
        "--kv-flush-blocks",
        type=int,
        default=None,  # None = inherit ADVSPEC_KV_FLUSH_BLOCKS (default 0)
        help="Write-through flush threshold for the disk KV store: "
        "flush pending demoted blocks every N enqueued blocks instead "
        "of only at settle, bounding the publish window a crash can "
        "lose (0 = settle-only, the default; "
        "ADVSPEC_KV_FLUSH_BLOCKS sets the process default)",
    )
    d.add_argument(
        "--weight-res",
        action=argparse.BooleanOptionalAction,
        default=None,  # None = inherit ADVSPEC_WEIGHT_RES (default on)
        help="Weight residency paging: an opponent model evicted from "
        "HBM demotes its (quantized) shards to host RAM and promotes "
        "back with one committed device_put on its next turn, instead "
        "of paying a full checkpoint re-materialization per swap "
        "(--no-weight-res restores naive evict-reload; "
        "ADVSPEC_WEIGHT_RES=0 sets the process default)",
    )
    d.add_argument(
        "--weight-host-mb",
        type=int,
        default=None,  # None = inherit ADVSPEC_WEIGHT_HOST_MB
        help="Host-RAM budget in MiB for demoted model weights "
        "(LRU overflow frees; 0 disables paging; default 2048, "
        "ADVSPEC_WEIGHT_HOST_MB sets the process default)",
    )
    d.add_argument(
        "--interleave",
        action=argparse.BooleanOptionalAction,
        default=None,  # None = inherit ADVSPEC_INTERLEAVE (default on)
        help="Fused prefill+decode steps and the two-deep pipelined "
        "scheduler drive loop (default on; --no-interleave restores "
        "the legacy serialized loop, ADVSPEC_INTERLEAVE=0 sets the "
        "process default)",
    )
    d.add_argument(
        "--pipeline-depth",
        type=int,
        default=None,
        help="Scheduler steps kept in flight (1-2; default 2; 1 = fused "
        "but synchronous)",
    )
    d.add_argument(
        "--speculative",
        action=argparse.BooleanOptionalAction,
        default=None,  # None = inherit ADVSPEC_SPECULATIVE (default on)
        help="Per-slot prompt-lookup speculative decoding in the "
        "continuous batcher: draft up to γ tokens per row from its own "
        "context, verify in one multi-position forward (default on; "
        "greedy output is byte-identical either way; "
        "ADVSPEC_SPECULATIVE=0 sets the process default)",
    )
    d.add_argument(
        "--gamma",
        type=int,
        default=None,  # None = inherit ADVSPEC_GAMMA (default 8)
        help="Draft length per speculative step (>= 1; default 8, "
        "ADVSPEC_GAMMA sets the process default; the tpu_ladder gamma "
        "sweep measures the on-chip crossover)",
    )

    d.add_argument(
        "--stream",
        action=argparse.BooleanOptionalAction,
        default=None,  # None = inherit ADVSPEC_STREAM (default on)
        help="Stream tokens per request from the serving path to a "
        "host-side consumer at the drive loop's existing fetch points "
        "(default on; --no-stream restores the blocking path, "
        "byte-identical end to end; ADVSPEC_STREAM=0 sets the process "
        "default)",
    )
    d.add_argument(
        "--early-cancel",
        action=argparse.BooleanOptionalAction,
        default=None,  # None = inherit ADVSPEC_EARLY_CANCEL (default on)
        help="Cancel an opponent's request mid-decode the moment its "
        "verdict marker ([AGREE]) appears in the stream: the slot and "
        "pages free immediately and queued requests admit into them "
        "(default on; needs --stream; transcripts stay byte-identical "
        "up to each cancellation point; ADVSPEC_EARLY_CANCEL=0 sets "
        "the process default)",
    )

    z = parser.add_argument_group("resilience")
    z.add_argument(
        "--chaos",
        help=(
            "Arm fault injection: kind@seam[:p=F][:after=N][:times=N]"
            "[:slot=K], comma-separated (kinds: oom, device_lost, "
            "preempted, timeout, shed, bug; seams: generate, scheduler_chunk, "
            "kv_alloc, kv_swap, checkpoint_load, crash, replica). Also "
            "via ADVSPEC_CHAOS"
        ),
    )
    z.add_argument(
        "--chaos-seed",
        type=int,
        default=None,
        help="Seed for probabilistic chaos rules (reproducible runs)",
    )
    z.add_argument(
        "--breaker-threshold",
        type=int,
        default=None,
        help="Consecutive failures before a model's circuit opens (default 3)",
    )
    z.add_argument(
        "--breaker-cooldown",
        type=float,
        default=None,
        help="Seconds an open circuit waits before a half-open probe "
        "(default 30)",
    )
    z.add_argument(
        "--no-breaker",
        action="store_true",
        help="Disable circuit breakers (always query every model)",
    )
    z.add_argument(
        "--fleet",
        action=argparse.BooleanOptionalAction,
        default=None,  # None = inherit ADVSPEC_FLEET (default off)
        help="Route requests across N replicated engines with "
        "prefix-affinity placement (one replica per debate via "
        "consistent hashing over --session), per-(replica, model) "
        "breaker-aware failover, and shared-store KV recovery "
        "(docs/fleet.md; ADVSPEC_FLEET=1 sets the process default)",
    )
    z.add_argument(
        "--fleet-replicas",
        type=int,
        default=None,  # None = inherit ADVSPEC_FLEET_REPLICAS (default 2)
        help="Engine replicas behind the fleet router (>= 2 to route; "
        "ADVSPEC_FLEET_REPLICAS sets the process default)",
    )
    z.add_argument(
        "--fleet-transport",
        choices=["inproc", "worker"],
        default=None,  # None = inherit ADVSPEC_FLEET_TRANSPORT (inproc)
        help="Replica transport: fresh in-process engines (inproc) or "
        "one subprocess per replica (worker — the SIGKILL-able "
        "topology tools/chaos_run.py --replica-kill drills)",
    )
    z.add_argument(
        "--fleet-autoscale",
        action=argparse.BooleanOptionalAction,
        default=None,  # None = inherit ADVSPEC_FLEET_AUTOSCALE (off)
        help="Elastic fleet: a backlog-driven control loop grows and "
        "shrinks membership between --fleet-min and --fleet-max — "
        "warm-before-ring scale-out, lose-nothing drain on scale-in "
        "(docs/fleet.md; ADVSPEC_FLEET_AUTOSCALE=1 sets the default)",
    )
    z.add_argument(
        "--fleet-min",
        type=int,
        default=None,  # None = inherit ADVSPEC_FLEET_MIN (default 1)
        help="Autoscaler replica floor (ADVSPEC_FLEET_MIN)",
    )
    z.add_argument(
        "--fleet-max",
        type=int,
        default=None,  # None = inherit ADVSPEC_FLEET_MAX (default 4)
        help="Autoscaler replica ceiling (ADVSPEC_FLEET_MAX)",
    )
    z.add_argument(
        "--fleet-prefill-replicas",
        type=int,
        default=None,  # None = inherit ADVSPEC_FLEET_PREFILL_REPLICAS
        help="Disaggregated serving: founders carrying the prefill "
        "role — large admissions prefill there and ship their KV "
        "blocks to a decode replica through the shared store "
        "(docs/fleet.md; 0 = symmetric fleet, the default; "
        "ADVSPEC_FLEET_PREFILL_REPLICAS sets the process default)",
    )
    z.add_argument(
        "--scale-cooldown-s",
        type=float,
        default=None,  # None = inherit ADVSPEC_FLEET_SCALE_COOLDOWN_S
        help="Minimum seconds between membership changes — the flap "
        "damper, and the scale-in drain budget "
        "(ADVSPEC_FLEET_SCALE_COOLDOWN_S, default 5.0)",
    )
    z.add_argument(
        "--scale-interval-s",
        type=float,
        default=None,  # None = inherit ADVSPEC_FLEET_SCALE_INTERVAL_S
        help="Autoscaler decision-tick period "
        "(ADVSPEC_FLEET_SCALE_INTERVAL_S, default 0.25)",
    )

    v = parser.add_argument_group("serve")
    v.add_argument(
        "--socket",
        default=None,  # None = inherit ADVSPEC_SERVE_SOCKET
        help="Unix socket path the serve daemon listens on (default "
        "./advspec-serve.sock; ADVSPEC_SERVE_SOCKET sets the process "
        "default). Transport: line-delimited JSON request/stream "
        "(docs/serving.md)",
    )
    v.add_argument(
        "--serve-queue-depth",
        type=int,
        default=None,  # None = inherit ADVSPEC_SERVE_QUEUE_DEPTH
        help="Per-tenant outstanding-debate cap: admissions past it "
        "shed with a typed queue_full refusal (default 8; "
        "ADVSPEC_SERVE_QUEUE_DEPTH sets the process default)",
    )
    v.add_argument(
        "--serve-backlog-tokens",
        type=int,
        default=None,  # None = inherit ADVSPEC_SERVE_BACKLOG_TOKENS
        help="Estimated-token-backlog cap: admissions that would cross "
        "it shed with a typed backlog refusal carrying retry_after_s "
        "(default 65536; ADVSPEC_SERVE_BACKLOG_TOKENS sets the process "
        "default). Brownout enters at 75%% of this cap",
    )
    v.add_argument(
        "--serve-quota-tokens",
        type=int,
        default=None,  # None = inherit ADVSPEC_SERVE_QUOTA_TOKENS
        help="Per-tenant token quota, debited with actual Usage tokens "
        "on completion and refillable via the refill op (0 = unlimited, "
        "the default; ADVSPEC_SERVE_QUOTA_TOKENS sets the process "
        "default)",
    )
    v.add_argument(
        "--serve-drain-deadline-s",
        type=float,
        default=None,  # None = inherit ADVSPEC_SERVE_DRAIN_DEADLINE_S
        help="Seconds SIGTERM waits for in-flight debates before "
        "shedding the queue (typed, journal-resumable) and cancelling "
        "running units (default 5; ADVSPEC_SERVE_DRAIN_DEADLINE_S sets "
        "the process default)",
    )
    v.add_argument(
        "--serve-ttft-slo-ms",
        type=float,
        default=None,  # None = inherit ADVSPEC_SERVE_TTFT_SLO_MS
        help="Interactive-tier TTFT SLO budget in milliseconds — the "
        "batch-preemption policy's trigger (preempt at half the "
        "budget; 0 = preempt the moment interactive work waits; "
        "ADVSPEC_SERVE_TTFT_SLO_MS sets the process default)",
    )
    v.add_argument(
        "--drain-report",
        default=None,
        help="Also write the SIGTERM drain report to this file "
        "(atomic tmp+rename; the report always prints to stdout)",
    )

    r = parser.add_argument_group("registry")
    r.add_argument("--checkpoint", help="HF checkpoint dir (registry add-model)")
    r.add_argument(
        "--family",
        choices=["llama", "mistral", "gemma2", "qwen2"],
        default="llama",
    )
    r.add_argument("--size", default="tiny", help="Named size config")
    r.add_argument("--tokenizer", default="", help="Tokenizer path")
    r.add_argument("--dtype", default=None, help="Param dtype (bfloat16)")
    r.add_argument("--tp", type=int, default=0, help="Tensor-parallel degree")
    r.add_argument(
        "--quant",
        choices=list(model_registry.QUANT_FORMATS),
        default="",
        help="Weight-only quantization for this model (int4 packs two "
        "weights per byte — docs/weight_residency.md)",
    )
    r.add_argument(
        "--kv",
        choices=["dense", "paged"],
        default="dense",
        help="KV-cache layout for decode",
    )
    r.add_argument(
        "--kv-dtype",
        choices=["", "int8"],
        default="",
        help="KV-cache storage dtype (int8 halves cache HBM)",
    )
    return parser


def parse_models(args: argparse.Namespace) -> list[str]:
    """Comma-separated ids, or the default opponent when unset.

    Parity: reference parse_models + default-model auto-detection
    (debate.py:553-611, providers.py:394-415) — here "available" means mock
    (always) plus any registry alias whose checkpoint resolves.
    """
    if args.models:
        return [m.strip() for m in args.models.split(",") if m.strip()]
    models = get_default_models()
    _err(f"no --models given; defaulting to {','.join(models)}")
    return models


def validate_models_before_run(models: list[str]) -> list[str]:
    """Collect actionable validation errors (exit code 2 when non-empty).

    Parity: reference validate_models_before_run (debate.py:976-1022) →
    credential preflight; here it is provider-prefix + registry/checkpoint
    validation via each engine's ``validate``.
    """
    errors = []
    reg = None
    for m in models:
        if m.startswith("tpu://"):
            if reg is None:
                reg = model_registry.load_registry()
            err = model_registry.validate_tpu_model(m, registry=reg)
            if err is None:
                try:
                    get_engine(m)
                except ValueError as e:
                    err = str(e)
        else:
            try:
                err = get_engine(m).validate(m)
            except ValueError as e:
                err = str(e)
        if err:
            errors.append(f"{m}: {err}")
    return errors


def _read_spec_stdin() -> str:
    spec = sys.stdin.read().strip()
    if not spec:
        _err("error: no spec provided on stdin")
        raise SystemExit(EXIT_VALIDATION)
    return spec


def _env_request_deadline() -> float:
    try:
        return max(
            0.0, float(os.environ.get("ADVSPEC_REQUEST_DEADLINE_S", "0") or "0")
        )
    except ValueError:
        return 0.0


def _sampling_from_args(args: argparse.Namespace) -> SamplingParams:
    return SamplingParams(
        max_new_tokens=args.max_new_tokens or 1024,
        temperature=0.7 if args.temperature is None else args.temperature,
        greedy=bool(args.greedy),
        seed=args.seed,
        timeout_s=max(0.0, float(600.0 if args.timeout is None else args.timeout)),
        # Flag-else-env-default each invocation, like the obs knobs.
        request_deadline_s=max(
            0.0,
            float(
                _env_request_deadline()
                if getattr(args, "request_deadline_s", None) is None
                else args.request_deadline_s
            ),
        ),
    )


def load_or_resume_session(
    args: argparse.Namespace,
) -> tuple[str, SessionState | None]:
    """Returns (spec, session_state). Resume restores args wholesale
    (parity: reference debate.py:739-795)."""
    if args.resume:
        state = SessionState.load(args.resume)
        args.round = state.round
        args.doc_type = state.doc_type
        if state.models:
            args.models = ",".join(state.models)
        args.focus = state.focus
        args.persona = state.persona
        args.preserve_intent = state.preserve_intent
        args.session = state.session_id
        return state.spec, state
    spec = _read_spec_stdin()
    if args.session:
        state = SessionState(
            session_id=args.session,
            spec=spec,
            round=args.round,
            doc_type=args.doc_type or "generic",
        )
        return spec, state
    return spec, None


def _configure_resilience(args: argparse.Namespace):
    """Arm chaos injection and tune the breaker registry from flags.

    Returns the breaker registry so the report can snapshot its states.
    """
    from adversarial_spec_tpu.resilience import breaker, faults, injector

    if args.chaos:
        injector.install(
            injector.FaultInjector(
                injector.parse_chaos_spec(args.chaos), seed=args.chaos_seed
            )
        )
        _err(f"chaos armed: {args.chaos}")
    else:
        # Materialize (and thereby validate) any ADVSPEC_CHAOS env spec
        # NOW: a typo'd spec must fail loudly at startup, not surface as
        # a swallowed per-model BUG when the first seam hook fires.
        injector.active()
    breakers = breaker.default_registry()
    breakers.configure(
        threshold=args.breaker_threshold,
        cooldown_s=args.breaker_cooldown,
        enabled=not args.no_breaker,
    )
    faults.reset()  # per-round counts in the report
    return breakers


def _configure_prefix_cache(args: argparse.Namespace):
    """Arm the prefix cache from flags; returns the module for reporting.

    One CLI invocation is one round: stats reset here so the JSON
    ``perf.prefix_cache`` block accounts exactly this round's prefills,
    while the cache CONTENT itself persists wherever the engine lives.
    """
    from adversarial_spec_tpu.engine import prefix_cache

    prefix_cache.configure(
        enabled=args.prefix_cache, max_pages=args.prefix_cache_pages
    )
    prefix_cache.reset_stats()
    return prefix_cache


def _configure_interleave(args: argparse.Namespace):
    """Arm the fused/pipelined drive loop from flags; returns the module
    for reporting. Stats reset per invocation (one invocation = one
    round) so ``perf.interleave`` accounts exactly this round's steps;
    the batcher itself persists on the engine across rounds."""
    from adversarial_spec_tpu.engine import interleave

    interleave.configure(
        enabled=args.interleave, pipeline_depth=args.pipeline_depth
    )
    interleave.reset_stats()
    return interleave


def _configure_kv_tier(args: argparse.Namespace):
    """Arm the tiered KV cache from flags; returns the module for
    reporting. Flag-else-env-default each invocation (one invocation =
    one round), like obs/spec: one round's --no-kv-tier or store dir
    must not leak into the next. Stats reset per invocation so
    ``perf.kv_tier`` accounts exactly this round's swaps; the tiers
    themselves live on the engine's persistent batcher (rebuilt when
    these knobs change — the batcher key covers them)."""
    from adversarial_spec_tpu.engine import kvtier

    kvtier.configure(
        enabled=(
            args.kv_tier if args.kv_tier is not None else kvtier.env_enabled()
        ),
        host_mb=(
            args.kv_host_mb
            if args.kv_host_mb is not None
            else kvtier.env_host_mb()
        ),
        store_dir=(
            args.kv_store_dir
            if args.kv_store_dir is not None
            else kvtier.env_store_dir()
        ),
        flush_blocks=(
            args.kv_flush_blocks
            if args.kv_flush_blocks is not None
            else kvtier.env_flush_blocks()
        ),
    )
    kvtier.reset_stats()
    return kvtier


def _configure_weightres(args: argparse.Namespace):
    """Arm weight-residency paging from flags; returns the module for
    reporting. Flag-else-env-default each invocation (one invocation =
    one round), like obs/kvtier: one round's --no-weight-res or host
    budget must not leak into the next. Stats reset per invocation so
    ``perf.weights`` accounts exactly this round's loads/swaps; the
    ledger itself lives on the engine and persists round to round."""
    from adversarial_spec_tpu.engine import weightres

    weightres.configure(
        enabled=(
            args.weight_res
            if getattr(args, "weight_res", None) is not None
            else weightres.env_enabled()
        ),
        host_mb=(
            args.weight_host_mb
            if getattr(args, "weight_host_mb", None) is not None
            else weightres.env_host_mb()
        ),
    )
    weightres.reset_stats()
    return weightres


def _configure_fleet(args: argparse.Namespace):
    """Arm the fleet layer from flags; returns the module for
    reporting. Flag-else-env-default each invocation (one invocation =
    one round), like obs/kvtier: one round's --fleet must not leak
    into the next. Stats reset per invocation so ``perf.fleet``
    accounts exactly this round's routing; the replicas themselves
    persist on the process fleet engine (rebuilt when the topology
    knobs change — fleet.fleet_engine keys on them)."""
    from adversarial_spec_tpu import fleet

    fleet.configure(
        enabled=(
            args.fleet if args.fleet is not None else fleet.env_enabled()
        ),
        replicas=(
            args.fleet_replicas
            if args.fleet_replicas is not None
            else fleet.env_replicas()
        ),
        transport=(
            args.fleet_transport
            if args.fleet_transport is not None
            else fleet.env_transport()
        ),
        autoscale=(
            args.fleet_autoscale
            if getattr(args, "fleet_autoscale", None) is not None
            else fleet.env_autoscale()
        ),
        min_replicas=(
            args.fleet_min
            if getattr(args, "fleet_min", None) is not None
            else fleet.env_min_replicas()
        ),
        max_replicas=(
            args.fleet_max
            if getattr(args, "fleet_max", None) is not None
            else fleet.env_max_replicas()
        ),
        scale_cooldown_s=(
            args.scale_cooldown_s
            if getattr(args, "scale_cooldown_s", None) is not None
            else fleet.env_scale_cooldown_s()
        ),
        scale_interval_s=(
            args.scale_interval_s
            if getattr(args, "scale_interval_s", None) is not None
            else fleet.env_scale_interval_s()
        ),
        prefill_replicas=(
            args.fleet_prefill_replicas
            if getattr(args, "fleet_prefill_replicas", None) is not None
            else fleet.env_prefill_replicas()
        ),
        handoff_threshold_tokens=fleet.env_handoff_threshold_tokens(),
    )
    fleet.reset_stats()
    return fleet


def _configure_speculative(args: argparse.Namespace):
    """Apply speculation flags to the process config (one CLI invocation
    is one round) so ``perf.spec`` accounts exactly this round's verify
    steps; the engine's persistent batcher re-resolves the config at the
    next drain. Flag-else-env-default each invocation, like obs: one
    round's --no-speculative/--gamma must not leak into the next."""
    from adversarial_spec_tpu.engine import spec

    spec.configure(
        enabled=(
            args.speculative
            if args.speculative is not None
            else spec.env_enabled()
        ),
        gamma=args.gamma if args.gamma is not None else spec.env_gamma(),
    )
    spec.reset_stats()
    return spec


def _configure_streaming(args: argparse.Namespace):
    """Arm token streaming + early cancellation from flags; returns the
    module for reporting. Flag-else-env-default each invocation (one
    invocation = one round), like obs/spec: one round's --no-stream or
    --no-early-cancel must not leak into the next. Stats reset per
    invocation so ``perf.stream`` accounts exactly this round's
    deliveries and cancels."""
    from adversarial_spec_tpu.engine import streaming

    streaming.configure(
        enabled=(
            args.stream if args.stream is not None else streaming.env_enabled()
        ),
        early_cancel=(
            args.early_cancel
            if args.early_cancel is not None
            else streaming.env_early_cancel()
        ),
    )
    streaming.reset_stats()
    return streaming


def _configure_obs(args: argparse.Namespace):
    """Arm the observability subsystem from flags; returns the module
    for reporting. One CLI invocation is one round: metrics zero, the
    flight-recorder ring clears, and the retrace watch starts fresh, so
    ``perf.obs`` / ``--metrics-out`` / ``--events-out`` account exactly
    this round."""
    from adversarial_spec_tpu import obs

    # Every knob re-resolves to flag-else-env-default each invocation:
    # one invocation's --no-obs / --flight-recorder-size / --events-out
    # must not leak into the next round's (one process can run several
    # invocations — tests, library callers).
    obs.configure(
        enabled=args.obs if args.obs is not None else obs.env_enabled(),
        recorder_size=(
            args.flight_recorder_size
            if args.flight_recorder_size is not None
            else obs.env_recorder_size()
        ),
        events_out=args.events_out or "",
        slo_ttft_ms=(
            args.slo_ttft_ms
            if getattr(args, "slo_ttft_ms", None) is not None
            else obs.env_slo_ttft_ms()
        ),
        slo_round_s=(
            args.slo_round_s
            if getattr(args, "slo_round_s", None) is not None
            else obs.env_slo_round_s()
        ),
    )
    obs.reset_stats()
    return obs


def handle_serve(args: argparse.Namespace) -> int:
    """``debate serve`` — the persistent multi-debate daemon
    (adversarial_spec_tpu/serve). Unlike every other action, this one
    configures the process-wide subsystems ONCE and then serves until
    drained: the per-invocation reset cascade must never run mid-serve
    (concurrent debates would lose their counters and trace scopes —
    the collision docs/serving.md explains)."""
    import os as _os

    from adversarial_spec_tpu import serve as serve_mod
    from adversarial_spec_tpu.serve.daemon import run_daemon

    # One-time arming of the same knobs a critique round would arm.
    _configure_resilience(args)
    _configure_prefix_cache(args)
    _configure_interleave(args)
    _configure_speculative(args)
    _configure_kv_tier(args)
    _configure_weightres(args)
    _configure_streaming(args)
    _configure_fleet(args)
    _configure_obs(args)
    serve_mod.configure(
        max_queue_depth=(
            args.serve_queue_depth
            if args.serve_queue_depth is not None
            else serve_mod.env_queue_depth()
        ),
        max_backlog_tokens=(
            args.serve_backlog_tokens
            if args.serve_backlog_tokens is not None
            else serve_mod.env_backlog_tokens()
        ),
        tenant_quota_tokens=(
            args.serve_quota_tokens
            if args.serve_quota_tokens is not None
            else serve_mod.env_quota_tokens()
        ),
        drain_deadline_s=(
            args.serve_drain_deadline_s
            if args.serve_drain_deadline_s is not None
            else serve_mod.env_drain_deadline_s()
        ),
        interactive_ttft_slo_ms=(
            args.serve_ttft_slo_ms
            if args.serve_ttft_slo_ms is not None
            else serve_mod.env_ttft_slo_ms()
        ),
    )
    serve_mod.reset_stats()
    socket_path = (
        args.socket
        or _os.environ.get("ADVSPEC_SERVE_SOCKET")
        or "./advspec-serve.sock"
    )
    cfg = serve_mod.config()
    _err(
        f"advspec serve: listening on {socket_path} "
        f"(queue depth {cfg.max_queue_depth}/tenant, backlog cap "
        f"{cfg.max_backlog_tokens} tokens, drain deadline "
        f"{cfg.drain_deadline_s}s); SIGTERM drains gracefully"
    )
    return run_daemon(
        socket_path,
        drain_report_path=args.drain_report,
    )


def run_critique(args: argparse.Namespace) -> int:
    from adversarial_spec_tpu.utils.tracing import Tracer, maybe_profile

    tracer = Tracer()
    breakers = _configure_resilience(args)
    prefix_cache = _configure_prefix_cache(args)
    interleave = _configure_interleave(args)
    spec_cfg = _configure_speculative(args)
    kv_tier = _configure_kv_tier(args)
    weightres = _configure_weightres(args)
    streaming = _configure_streaming(args)
    fleet = _configure_fleet(args)
    obs = _configure_obs(args)
    spec, session_state = load_or_resume_session(args)
    if session_state is not None and session_state.breakers:
        # One CLI invocation = one round: open circuits from earlier
        # rounds of this session must survive the process boundary.
        breakers.restore(session_state.breakers)
    models = parse_models(args)
    with tracer.span("validate"):
        errors = validate_models_before_run(models)
    if errors:
        for e in errors:
            _err(f"validation error: {e}")
        return EXIT_VALIDATION

    cfg = RoundConfig(
        doc_type=args.doc_type or "generic",
        focus=args.focus,
        persona=args.persona,
        preserve_intent=args.preserve_intent,
        press=args.press,
        context_files=args.context or [],
        sampling=_sampling_from_args(args),
        # Fleet placement identity: one key per SESSION, so every
        # round of a session's debate lands on the replica holding its
        # prefix KV (sessionless rounds fall back to the spec hash in
        # run_round).
        debate_id=(
            session_state.session_id if session_state is not None else ""
        ),
    )
    journal = None
    if session_state is not None:
        # Durability first (docs/resilience.md "Durability and
        # recovery"): persist the session BEFORE the round runs — a
        # crash mid-round must leave a resumable session file carrying
        # the spec and round the crashed process was serving (the
        # post-round save below then advances it). The journal rides
        # the same sessions dir; flag-else-env-default per invocation.
        use_journal = (
            args.journal
            if getattr(args, "journal", None) is not None
            else journal_mod.env_enabled()
        )
        session_state.models = models
        session_state.save()
        if use_journal:
            journal = journal_mod.RoundJournal(session_state.session_id)
            cfg.journal = journal
    _err(
        f"Round {args.round}: querying {len(models)} model(s): "
        + ", ".join(models)
    )
    with tracer.span("round"), maybe_profile(args.profile_dir):
        result = run_round(spec, models, round_num=args.round, cfg=cfg)

    for r in result.failed:
        _err(f"warning: {r.model} failed: {r.error}")

    tracker = CostTracker()
    for r in result.responses:
        tracker.add(r.model, r.usage)
    tracer.count("decode_tokens", result.total_usage.decode_tokens)
    tracer.spans["decode"] = result.total_usage.decode_time_s
    # Resilience telemetry: classified fault counts + breaker transitions
    # become tracer counters; the full snapshot rides on the JSON report.
    from adversarial_spec_tpu.resilience import faults as faults_mod

    fault_counts = faults_mod.snapshot()
    tracer.count_many({f"fault.{k}": v for k, v in fault_counts.items()})
    tracer.count_many(breakers.counters())
    # Prefix-cache telemetry: hit/miss/evict/tokens-saved counters ride
    # the tracer (and the full snapshot lands on perf.prefix_cache).
    prefix_snap = prefix_cache.snapshot()
    tracer.count_many(
        {
            f"prefix_cache.{k}": float(v)
            for k, v in prefix_snap.items()
            if isinstance(v, (int, float)) and not isinstance(v, bool)
        }
    )
    # Per-opponent spans from the debate layer graft under "debate/" —
    # one report carries both layers' phase breakdowns (span_tree).
    tracer.merge(result.tracer, prefix="debate")
    perf = tracer.report()
    perf["decode_tokens_per_sec"] = round(tracer.rate("decode_tokens", "decode"), 1)
    perf["resilience"] = {
        "faults": fault_counts,
        "breakers": breakers.states(),
    }
    perf["prefix_cache"] = prefix_snap
    # Fused-step / pipeline telemetry: how much admission prefill hid
    # under resident decode vs genuinely stalled the batch (their sum IS
    # the round's prefill_time_s), plus step/sync counts.
    perf["interleave"] = interleave.snapshot()
    # Speculation telemetry: verify steps, acceptance rate, tokens/step,
    # rollback pages, draft/verify wall split (engine/spec.py).
    perf["spec"] = spec_cfg.snapshot()
    # Tiered-KV telemetry: per-tier hit rates, demotions/promotions/
    # rehydrations, store writes + quarantines, swap walls
    # (engine/kvtier.py).
    perf["kv_tier"] = kv_tier.snapshot()
    # Weight-residency telemetry: loads vs promotions (the reload the
    # host tier avoided), demote/promote walls, swap-overlap fraction,
    # coalesced groups/units (engine/weightres.py).
    perf["weights"] = weightres.snapshot()
    # Streaming telemetry: requests streamed, deliveries, cancels, and
    # the decode tokens early cancellation saved (engine/streaming.py).
    perf["stream"] = streaming.snapshot()
    # Fleet telemetry: routed/affinity-hit/failover counts, replica
    # lifecycle, reissued work across replica deaths (fleet/router.py).
    perf["fleet"] = fleet.snapshot()
    # Observability report: flight-recorder occupancy, event mix, host
    # syncs by reason, retrace watch (unexpected recompiles flagged).
    perf["obs"] = obs.snapshot()
    if args.metrics_out:
        obs.write_metrics(args.metrics_out)
        _err(f"metrics written to {args.metrics_out}")
    if args.events_out:
        n = obs.dump_events(args.events_out)
        _err(f"{n} flight-recorder event(s) written to {args.events_out}")
    if perf["obs"]["retrace"]["unexpected_recompiles"]:
        _err(
            "warning: "
            f"{perf['obs']['retrace']['unexpected_recompiles']} unexpected "
            "jit recompile(s) detected — see perf.obs.retrace in --json"
        )
    if perf["obs"]["slo"]["breaches"]:
        breaches = perf["obs"]["slo"]["breaches"]
        where = (
            "trace-scoped flight-recorder capture(s) written next to "
            "--events-out (see tools/trace_view.py)"
            if args.events_out
            # No armed destination = counted but not captured; don't
            # send the operator hunting for files that don't exist.
            else "pass --events-out to capture trace-scoped dumps"
        )
        _err(
            "warning: SLO breach(es) "
            + ", ".join(f"{k}={v}" for k, v in breaches.items())
            + " — "
            + where
        )
    _err(
        f"perf: round {perf['spans'].get('round', 0):.2f}s, "
        f"decode {perf['decode_tokens_per_sec']} tok/s"
    )
    if prefix_snap["enabled"] and prefix_snap["lookups"]:
        _err(
            f"prefix cache: {prefix_snap['hits']}/{prefix_snap['lookups']} "
            f"hits, {prefix_snap['saved_tokens']} prefill tokens saved"
        )
    stream_snap = perf["stream"]
    if stream_snap["cancels"]:
        _err(
            f"early cancel: {stream_snap['cancels']} request(s) stopped "
            f"at their verdict marker, {stream_snap['tokens_saved']} "
            "decode token(s) saved"
        )
    fleet_snap = perf["fleet"]
    if fleet_snap["enabled"] and fleet_snap["routed_requests"]:
        _err(
            f"fleet: {fleet_snap['routed_requests']} request(s) routed "
            f"across {fleet_snap['replicas']} replica(s), affinity hit "
            f"rate {fleet_snap['affinity_hit_rate']:.0%}"
            + (
                f", {fleet_snap['reissued_requests']} reissued after "
                "replica loss"
                if fleet_snap["reissued_requests"]
                else ""
            )
        )
    tier_snap = perf["kv_tier"]
    if tier_snap["enabled"] and (
        tier_snap["promoted_tokens"] or tier_snap["rehydrated_tokens"]
    ):
        _err(
            f"kv tier: {tier_snap['promoted_tokens']} tokens promoted "
            f"from host RAM, {tier_snap['rehydrated_tokens']} rehydrated "
            "from the disk store"
        )
    if fault_counts:
        total_faults = sum(fault_counts.values())
        _err(
            f"resilience: {total_faults} fault(s) classified and "
            "contained; see the --json resilience section"
        )

    # The revised spec for the next round: last successful revision wins
    # (the L5 agent synthesizes across critiques; this is the raw material).
    revised = next(
        (r.revised_spec for r in reversed(result.successful) if r.revised_spec),
        None,
    )

    if session_state is not None:
        save_checkpoint(spec, args.round, session_state.session_id)
        session_state.spec = revised or spec
        session_state.round = args.round + 1
        session_state.models = models
        session_state.focus = args.focus
        session_state.persona = args.persona
        session_state.preserve_intent = args.preserve_intent
        session_state.history.append(
            {
                "round": args.round,
                "all_agreed": result.all_agreed,
                "models": {r.model: r.agreed for r in result.successful},
            }
        )
        session_state.breakers = breakers.snapshot_for_resume()
        session_state.save()
        if journal is not None:
            # Round-commit AFTER the advanced session state is durable:
            # a crash in the gap replays a committed round, which is
            # deterministic and therefore harmless; the reverse order
            # could lose the round.
            try:
                journal.log_round_commit(args.round, result.all_agreed)
            except Exception as e:
                _err(f"warning: round-journal commit failed: {e}")

    served = int(result.tracer.counters.get("journal.served", 0))
    if served:
        _err(
            f"recovery: {served} opponent(s) served from the round "
            "journal (no engine work re-paid)"
        )

    user_feedback = None
    if args.notify:
        user_feedback = _telegram_notify(args, result, tracker)

    output_results(
        args, result, models, tracker, session_state, user_feedback, perf
    )
    return EXIT_OK


def _telegram_notify(args, result, tracker) -> str | None:
    from adversarial_spec_tpu.debate import telegram

    config = telegram.get_config()
    if config is None:
        _err(
            "warning: Telegram not configured "
            "(set TELEGRAM_BOT_TOKEN and TELEGRAM_CHAT_ID); skipping notify"
        )
        return None
    try:
        return telegram.notify_round(
            config,
            result,
            total_cost=tracker.total_cost,
            feedback_timeout=args.feedback_timeout,
        )
    except Exception as e:  # notify must never kill the round
        _err(f"warning: Telegram notify failed: {e}")
        return None


def output_results(
    args: argparse.Namespace,
    result,
    models: list[str],
    tracker: CostTracker,
    session_state: SessionState | None,
    user_feedback: str | None = None,
    perf: dict | None = None,
) -> None:
    """Emit round results. JSON schema parity: reference debate.py:909-941."""
    if args.json:
        out = {
            "all_agreed": result.all_agreed,
            "round": args.round,
            "doc_type": args.doc_type or "generic",
            # The round's causal trace id: every flight-recorder event
            # this round caused carries it (tools/trace_view.py joins
            # the events JSONL back to this report on it).
            "trace_id": getattr(result, "trace_id", ""),
            "models": models,
            "focus": args.focus,
            "persona": args.persona,
            "preserve_intent": bool(args.preserve_intent),
            "session": session_state.session_id if session_state else args.session,
            "results": [
                {
                    "model": r.model,
                    "agreed": r.agreed,
                    "response": r.critique,
                    "spec": r.revised_spec,
                    "error": r.error,
                    "span_id": r.span_id,
                    "input_tokens": r.usage.input_tokens,
                    "output_tokens": r.usage.output_tokens,
                    "cached_tokens": r.usage.cached_tokens,
                    "prefill_time_s": round(r.usage.prefill_time_s, 4),
                    "decode_time_s": round(r.usage.decode_time_s, 4),
                    "cost": round(r.usage.cost_for(r.model), 6),
                }
                for r in result.responses
            ],
            "cost": tracker.report(),
        }
        if perf is not None:
            out["perf"] = perf
        if user_feedback:
            out["user_feedback"] = user_feedback
        print(json.dumps(out, indent=2))
        return

    doc_name = prompts.get_doc_type_name(args.doc_type or "generic")
    print(f"\n=== Round {args.round} Results ({doc_name}) ===\n")
    for r in result.responses:
        print(f"--- {r.model} ---")
        if r.error:
            print(f"ERROR: {r.error}")
        elif r.agreed:
            print("[AGREE]")
        else:
            print(r.critique)
        print()
    if result.all_agreed:
        print("=== ALL MODELS AGREE ===")
    else:
        agreed = [r.model for r in result.successful if r.agreed]
        disagreed = [r.model for r in result.successful if not r.agreed]
        if agreed:
            print(f"Agreed: {', '.join(agreed)}")
        if disagreed:
            print(f"Critiqued: {', '.join(disagreed)}")
    if user_feedback:
        print("\n=== User Feedback ===")
        print(user_feedback)
    if args.show_cost:
        print()
        print(tracker.format_text())


def handle_export_tasks(args: argparse.Namespace) -> int:
    """Spec → structured task list via the first model.

    Parity: reference handle_export_tasks (debate.py:688-736) — stdin spec,
    EXPORT_TASKS_PROMPT, low temperature, ``extract_tasks``, ``--json``.
    """
    _configure_prefix_cache(args)
    _configure_interleave(args)
    _configure_speculative(args)
    _configure_kv_tier(args)
    _configure_weightres(args)
    _configure_streaming(args)
    obs = _configure_obs(args)
    spec = _read_spec_stdin()
    models = parse_models(args)
    errors = validate_models_before_run(models[:1])
    if errors:
        for e in errors:
            _err(f"validation error: {e}")
        return EXIT_VALIDATION
    model = models[0]
    req = ChatRequest(
        model=model, system="", user=prompts.EXPORT_TASKS_PROMPT.format(spec=spec)
    )
    params = SamplingParams(
        max_new_tokens=args.max_new_tokens or 2048,
        temperature=0.3 if args.temperature is None else args.temperature,
        seed=args.seed,
    )
    comp = get_engine(model).chat([req], params)[0]
    if args.metrics_out:
        obs.write_metrics(args.metrics_out)
    if args.events_out:
        obs.dump_events(args.events_out)
    if not comp.ok:
        _err(f"error: {model} failed: {comp.error}")
        return EXIT_ERROR
    tasks = extract_tasks(comp.text)
    if args.json:
        print(json.dumps([t.to_dict() for t in tasks], indent=2))
    else:
        if not tasks:
            print("No [TASK] blocks found in model response.")
        for i, t in enumerate(tasks, 1):
            print(f"{i}. [{t.priority}] {t.title}")
            if t.description:
                print(f"   {t.description}")
            if t.dependencies:
                print(f"   depends on: {', '.join(t.dependencies)}")
            if t.estimate:
                print(f"   estimate: {t.estimate}")
    return EXIT_OK


def handle_diff(args: argparse.Namespace) -> int:
    if not args.previous or not args.current:
        _err("error: diff requires --previous and --current spec files")
        return EXIT_VALIDATION
    try:
        old = open(args.previous).read()
        new = open(args.current).read()
    except OSError as e:
        _err(f"error: {e}")
        return EXIT_VALIDATION
    diff = generate_diff(old, new)
    print(diff if diff else "No differences.")
    return EXIT_OK


def handle_providers(args: argparse.Namespace) -> int:
    """List servable models: mock behaviors + registry entries + devices.

    Parity: reference ``providers`` action (providers.py:247-333) listing
    providers with availability; here availability = checkpoint resolves.
    """
    reg = model_registry.load_registry()
    entries = []
    for alias, spec in sorted(reg.items()):
        err = model_registry.validate_tpu_model(f"tpu://{alias}", registry=reg)
        entries.append(
            {
                "model": f"tpu://{alias}",
                "family": spec.family,
                "size": spec.size,
                "checkpoint": spec.checkpoint,
                "available": err is None,
                "error": err,
            }
        )
    mock_models = [
        {"model": "mock://agree", "available": True},
        {"model": "mock://critic", "available": True},
        {"model": "mock://critic?agree_after=N", "available": True},
    ]
    if args.json:
        print(
            json.dumps(
                {"tpu": entries, "mock": mock_models, "devices": _device_info()},
                indent=2,
            )
        )
        return EXIT_OK
    print("TPU models (local registry):")
    for e in entries:
        status = "ok" if e["available"] else f"UNAVAILABLE: {e['error']}"
        print(f"  {e['model']:28s} {e['family']:8s} {e['size']:5s} [{status}]")
    print("Mock models (always available):")
    for e in mock_models:
        print(f"  {e['model']}")
    return EXIT_OK


def _device_info() -> dict:
    try:
        from adversarial_spec_tpu.utils.jaxenv import configure_jax

        configure_jax()
        import jax

        devs = jax.devices()
        return {
            "platform": devs[0].platform if devs else "none",
            "device_count": len(devs),
        }
    except Exception as e:
        return {"platform": "unavailable", "error": str(e)}


def handle_registry(args: argparse.Namespace, rest: list[str]) -> int:
    """Local model registry management — the Bedrock-mode analog.

    Subcommands mirror reference handle_bedrock_command
    (providers.py:489-656): status / list-models / add-model / remove-model.
    """
    sub = rest[0] if rest else "status"
    if sub in ("status", "list-models"):
        reg = model_registry.load_registry()
        if args.json:
            print(json.dumps({a: s.to_dict() for a, s in sorted(reg.items())}, indent=2))
        else:
            print(f"Registry: {model_registry.REGISTRY_PATH}")
            for alias, spec in sorted(reg.items()):
                print(
                    f"  {alias:24s} family={spec.family:8s} size={spec.size:5s} "
                    f"checkpoint={spec.checkpoint}"
                )
        return EXIT_OK
    if sub == "add-model":
        if len(rest) < 2:
            _err("usage: debate registry add-model <alias> --checkpoint DIR")
            return EXIT_VALIDATION
        alias = rest[1]
        spec = model_registry.ModelSpec(
            alias=alias,
            family=args.family,
            checkpoint=args.checkpoint or "random",
            tokenizer=args.tokenizer,
            size=args.size,
            dtype=args.dtype or "bfloat16",
            mesh={"tp": args.tp} if args.tp else {},
            quant=args.quant,
            kv=args.kv,
            kv_dtype=args.kv_dtype,
        )
        model_registry.save_registry_entry(spec)
        print(f"registered tpu://{alias}")
        return EXIT_OK
    if sub == "remove-model":
        if len(rest) < 2:
            _err("usage: debate registry remove-model <alias>")
            return EXIT_VALIDATION
        if model_registry.remove_registry_entry(rest[1]):
            print(f"removed {rest[1]}")
            return EXIT_OK
        _err(f"error: no registry entry named {rest[1]}")
        return EXIT_VALIDATION
    if sub == "alias":
        # Friendly-name aliasing (parity: reference bedrock `alias`
        # subcommand, providers.py:489-656). Snapshot semantics: the new
        # alias is an independent COPY of the existing entry's
        # configuration at this moment — later edits to the source do not
        # follow.
        if len(rest) < 3:
            _err("usage: debate registry alias <new-alias> <existing-alias>")
            return EXIT_VALIDATION
        new_alias, existing = rest[1], rest[2]
        reg = model_registry.load_registry()
        if existing not in reg:
            _err(f"error: no registry entry named {existing}")
            return EXIT_VALIDATION
        if new_alias in reg:
            # Guard against swapped arguments silently destroying an
            # existing model's configuration.
            _err(
                f"error: {new_alias} already exists; remove it first with "
                f"'registry remove-model {new_alias}'"
            )
            return EXIT_VALIDATION
        import dataclasses

        model_registry.save_registry_entry(
            dataclasses.replace(reg[existing], alias=new_alias)
        )
        print(
            f"registered tpu://{new_alias} as a copy of {existing}'s "
            "current configuration"
        )
        return EXIT_OK
    _err(f"error: unknown registry subcommand {sub!r}")
    return EXIT_VALIDATION


def handle_send_final(args: argparse.Namespace) -> int:
    """Send the final document to the configured Telegram chat.

    Parity: reference handle_send_final (debate.py:670-685).
    """
    from adversarial_spec_tpu.debate import telegram

    doc = _read_spec_stdin()
    config = telegram.get_config()
    if config is None:
        _err("error: Telegram not configured (TELEGRAM_BOT_TOKEN/CHAT_ID)")
        return EXIT_VALIDATION
    telegram.send_long_message(config, "FINAL DOCUMENT\n\n" + doc)
    print("Final document sent.")
    return EXIT_OK


def handle_info_command(args: argparse.Namespace) -> int | None:
    if args.action == "focus-areas":
        payload = {
            k: v.strip().splitlines()[0] for k, v in prompts.FOCUS_AREAS.items()
        }
        if args.json:
            print(json.dumps(payload, indent=2))
        else:
            for k, first_line in payload.items():
                print(f"{k}: {first_line}")
        return EXIT_OK
    if args.action == "personas":
        if args.json:
            print(json.dumps(prompts.PERSONAS, indent=2))
        else:
            for k, v in prompts.PERSONAS.items():
                print(f"{k}: {v[:88]}...")
        return EXIT_OK
    if args.action == "profiles":
        profs = list_profiles()
        if args.json:
            print(json.dumps(profs, indent=2))
        elif not profs:
            print("No saved profiles.")
        else:
            for name, settings in profs.items():
                print(f"{name}: {json.dumps(settings)}")
        return EXIT_OK
    if args.action == "sessions":
        sessions = SessionState.list_sessions()
        if args.json:
            print(json.dumps(sessions, indent=2))
        elif not sessions:
            print("No saved sessions.")
        else:
            for s in sessions:
                print(
                    f"{s['session_id']}: round {s['round']}, "
                    f"{s['doc_type']}, models={','.join(s['models'])}"
                )
        return EXIT_OK
    if args.action == "providers":
        return handle_providers(args)
    return None


def handle_save_profile(args: argparse.Namespace) -> int:
    if not args.name:
        _err("error: save-profile requires --name")
        return EXIT_VALIDATION
    settings = {}
    if args.models:
        settings["models"] = [m.strip() for m in args.models.split(",")]
    if args.doc_type:
        settings["doc_type"] = args.doc_type
    if args.focus:
        settings["focus"] = args.focus
    if args.persona:
        settings["persona"] = args.persona
    if args.preserve_intent:
        settings["preserve_intent"] = True
    if args.max_new_tokens:
        settings["max_new_tokens"] = args.max_new_tokens
    if args.temperature is not None:
        settings["temperature"] = args.temperature
    save_profile(args.name, settings)
    print(f"Profile '{args.name}' saved.")
    return EXIT_OK


def main(argv: list[str] | None = None) -> int:
    parser = create_parser()
    args, rest = parser.parse_known_args(argv)

    try:
        if args.profile and args.action in ("critique", "export-tasks"):
            profile = load_profile(args.profile)
            # Profile "models" come back as a list; args wants a CSV string.
            if "models" in profile and not args.models:
                args.models = ",".join(profile.pop("models"))
            applied = apply_profile(args, profile)
            if applied:
                _err(f"profile '{args.profile}' applied: {', '.join(applied)}")

        info = handle_info_command(args)
        if info is not None:
            return info
        if args.action == "critique":
            return run_critique(args)
        if args.action == "serve":
            return handle_serve(args)
        if args.action == "export-tasks":
            return handle_export_tasks(args)
        if args.action == "diff":
            return handle_diff(args)
        if args.action == "registry":
            return handle_registry(args, rest)
        if args.action == "send-final":
            return handle_send_final(args)
        if args.action == "save-profile":
            return handle_save_profile(args)
        _err(f"error: unhandled action {args.action}")
        return EXIT_ERROR
    except SystemExit as e:
        return int(e.code or 0)
    except (FileNotFoundError, InvalidSessionId, CorruptSessionState) as e:
        _err(f"error: {e}")
        return EXIT_VALIDATION
    except Exception as e:
        _err(f"error: {type(e).__name__}: {e}")
        return EXIT_ERROR


if __name__ == "__main__":
    sys.exit(main())
