"""Debate orchestration: rounds, parsing, convergence, usage, sessions."""

from adversarial_spec_tpu.debate.types import ModelResponse, RoundResult
from adversarial_spec_tpu.debate.parsing import (
    detect_agreement,
    extract_spec,
    extract_tasks,
    get_critique_summary,
    generate_diff,
)
from adversarial_spec_tpu.debate.usage import Usage, CostTracker

__all__ = [
    "ModelResponse",
    "RoundResult",
    "detect_agreement",
    "extract_spec",
    "extract_tasks",
    "get_critique_summary",
    "generate_diff",
    "Usage",
    "CostTracker",
]
