"""Round orchestration: fan a spec out to N opponents, collect responses.

Reference hot path: ``run_critique`` → ``call_models_parallel`` →
ThreadPoolExecutor(thread per model) → per-model HTTP/subprocess call
(scripts/debate.py:798-888, models.py:681-722). TPU-native restructure
(SURVEY §1 "TPU mapping"): opponents are *grouped by engine* and each group is
executed as ONE batched ``chat`` call — on the TPU engine that is N rows of a
single sharded decode over the mesh, not N threads. The retry loop survives
(it now covers recompile/OOM/transient device errors instead of HTTP 429s)
with the reference's exact policy: 3 attempts, exponential backoff 1s/2s/4s
(models.py:46-47), errors captured rather than raised, and rounds degrading
gracefully when some opponents fail (debate.py:845-853).

On top of that policy sits the per-model circuit breaker
(resilience/breaker.py): every completion outcome feeds the model's
breaker, and a model whose breaker is OPEN is degraded up front — zero
engine calls, zero retry budget — until its cooldown elapses and a
half-open probe re-admits it. Persistent failure costs one errored
response per round instead of 3 retries x backoff.
"""

from __future__ import annotations

import dataclasses
import time
from dataclasses import dataclass, field
from pathlib import Path

from adversarial_spec_tpu import obs as obs_mod
from adversarial_spec_tpu.debate import journal as journal_mod
from adversarial_spec_tpu.debate import prompts
from adversarial_spec_tpu.debate.parsing import (
    StreamScanner,
    detect_agreement,
    extract_spec,
    has_malformed_spec,
)
from adversarial_spec_tpu.debate.types import ModelResponse, RoundResult
from adversarial_spec_tpu.engine import streaming as stream_mod
from adversarial_spec_tpu.engine.dispatch import get_engine
from adversarial_spec_tpu.engine.types import ChatRequest, Completion, SamplingParams
from adversarial_spec_tpu.resilience import breaker as breaker_mod
from adversarial_spec_tpu.resilience import faults as faults_mod
from adversarial_spec_tpu.resilience.faults import FaultKind, classify_message
from adversarial_spec_tpu.utils.tracing import Tracer

MAX_RETRIES = 3
RETRY_BASE_DELAY = 1.0
# A watchdog-expired opponent gets ONE hedged re-admission on this
# fraction of its original per-request deadline — the slot already
# burnt a full deadline's worth of decode, so the second chance must
# not double the round's worst-case wall.
HEDGE_BUDGET_FACTOR = 0.5


@dataclass
class RoundConfig:
    """Everything that shapes one critique round's prompts and decode."""

    doc_type: str = "generic"
    focus: str | None = None
    persona: str | None = None
    preserve_intent: bool = False
    press: bool = False
    context_files: list[str] = field(default_factory=list)
    sampling: SamplingParams = field(default_factory=SamplingParams)
    # Per-model circuit breakers; None = the process default registry.
    # Tests pass their own (fake clock, tight thresholds).
    breakers: breaker_mod.BreakerRegistry | None = None
    # Crash-safe round journal (debate/journal.py RoundJournal, armed by
    # the CLI when a session is active and --journal is on; None = no
    # durability). run_round logs round-start + per-opponent completion
    # records through it and serves already-completed opponents from a
    # replay on resume.
    journal: object | None = None
    # Fleet placement identity (fleet/hashring.py): ONE stable id per
    # debate, so every round of this debate consistent-hashes onto the
    # replica already holding its prefix KV. The CLI sets it to the
    # session id; "" falls back to hashing the round's spec — rounds
    # of an unnamed one-shot debate still co-locate with each other
    # only while the spec's hash is stable.
    debate_id: str = ""
    # Trace-minting scope (obs/trace.py daemon scopes): "" keeps the
    # CLI's process-wide counter (tier-1 pins exact ids on it); the
    # serve daemon sets its per-debate id so concurrent rounds mint
    # from their OWN counters — deterministic per debate,
    # collision-free across the debates of one long-lived process.
    trace_scope: str = ""
    # Injected for tests; defaults to real sleep for backoff.
    sleep = staticmethod(time.sleep)


def load_context_files(paths: list[str]) -> str:
    """Concatenate supporting context files into a prompt block.

    Parity: reference scripts/models.py:130-146 — repeatable ``--context``
    flag, each file labeled, missing files raise with a clear message.
    """
    if not paths:
        return ""
    blocks = []
    for p in paths:
        path = Path(p)
        if not path.is_file():
            raise FileNotFoundError(f"context file not found: {p}")
        blocks.append(f"--- CONTEXT FILE: {path.name} ---\n{path.read_text()}")
    return "\n\n".join(blocks) + "\n\n"


def build_request(
    model: str, spec: str, round_num: int, cfg: RoundConfig
) -> ChatRequest:
    """Assemble one opponent's system+user messages."""
    system = prompts.get_system_prompt(
        doc_type=cfg.doc_type,
        focus=cfg.focus,
        persona=cfg.persona,
        preserve_intent=cfg.preserve_intent,
    )
    template = (
        prompts.PRESS_PROMPT_TEMPLATE if cfg.press else prompts.REVIEW_PROMPT_TEMPLATE
    )
    user = load_context_files(cfg.context_files) + template.format(
        round=round_num, spec=spec
    )
    return ChatRequest(model=model, system=system, user=user)


def _early_cancel_consumer():
    """One chat call's early-convergence stream consumer: an
    incremental marker scanner per batch row (parsing.StreamScanner
    over EARLY_CANCEL_MARKERS). The moment a row's verdict is
    decidable — its marker's last character arrives, however the
    stream was chunked — it returns False and the engine cancels that
    request mid-decode. The truncated transcript contains the full
    marker, so ``detect_agreement`` on it gives exactly the verdict
    the full text would; everything past the marker is decode the
    debate never reads (the matched-ceiling study's point: round
    COUNT, not round length, drives quality). Built fresh per attempt:
    a retried request streams from scratch."""
    scanners: dict[int, StreamScanner] = {}

    def consume(row: int, text: str) -> bool:
        sc = scanners.get(row)
        if sc is None:
            sc = scanners[row] = StreamScanner()
        return sc.feed(text) is None

    return consume


def _journal_fault(e: BaseException) -> None:
    """A journal failure must never kill the round: classify and count
    it (the injector's ``crash`` seam keeps its name; real I/O errors
    land at seam ``journal``), then move on — the round merely loses
    durability for that one record."""
    faults_mod.record(
        faults_mod.classify(e), getattr(e, "seam", None) or "journal"
    )


def _journal_safe(fn, *args, **kwargs) -> None:
    try:
        fn(*args, **kwargs)
    except Exception as e:
        _journal_fault(e)


def _to_response(
    model: str, comp: Completion, latency_s: float, span_id: str = ""
) -> ModelResponse:
    if not comp.ok:
        return ModelResponse(
            model=model,
            error=comp.error,
            usage=comp.usage,
            latency_s=latency_s,
            span_id=span_id,
        )
    resp = ModelResponse(
        model=model,
        critique=comp.text,
        agreed=detect_agreement(comp.text),
        revised_spec=extract_spec(comp.text),
        usage=comp.usage,
        latency_s=latency_s,
        span_id=span_id,
    )
    if has_malformed_spec(comp.text):
        # Parity: warn-not-crash on malformed [SPEC] (models.py:633-637);
        # surfaced via the response so the CLI can print the warning.
        resp.critique += "\n\n[warning: unterminated [SPEC] tag in response]"
    return resp


def run_round(
    spec: str,
    models: list[str],
    round_num: int = 1,
    cfg: RoundConfig | None = None,
) -> RoundResult:
    """Execute one critique round across all opponents.

    Opponents are grouped by serving engine; each group is one batched chat
    call. Transient per-request failures are retried with exponential
    backoff (3 attempts total, sleeping 1 s then 2 s between them — the
    reference's policy); retries re-batch only the failed requests, and a
    nonzero ``sampling.timeout_s`` bounds the whole round (no retry starts
    past the deadline).

    REENTRANT: the serve daemon runs many of these concurrently, one
    per debate thread. Everything mutable here is either local, lock-
    protected (breakers), per-session (journal), or thread-local (the
    ambient trace scope) — and ``cfg.trace_scope`` gives each debate
    its own id counter so concurrent rounds never collide.
    """
    cfg = cfg or RoundConfig()
    # The debate layer's own tracer: per-opponent chat walls + attempt
    # counters, merged into the CLI's round tracer (Tracer.merge) so the
    # engine-level and debate-level spans compose into one report.
    tracer = Tracer()
    breakers = (
        cfg.breakers
        if cfg.breakers is not None
        else breaker_mod.default_registry()
    )
    deadline = (
        time.monotonic() + cfg.sampling.timeout_s
        if cfg.sampling.timeout_s > 0
        else None
    )
    # Causal tracing (obs/trace.py): ONE trace per round, ONE span per
    # opponent request, minted HERE — above any engine choice — so the
    # mock and real serving paths carry byte-identical ids for the same
    # invocation sequence. The ids ride the requests by value; the
    # ambient scope below covers emitters that don't know their request.
    trace_id = obs_mod.trace.mint_trace(
        round_num, scope=cfg.trace_scope or None
    )
    # Fleet routing key (fleet/router.py): the whole debate shares one
    # affinity key, so a fleet places all its rounds on one replica —
    # where the document prefix's KV already lives.
    affinity = cfg.debate_id or journal_mod.spec_sha(spec)[:16]
    requests = [
        dataclasses.replace(
            build_request(m, spec, round_num, cfg),
            trace_id=trace_id,
            span_id=obs_mod.trace.mint_span(trace_id, i),
            affinity_key=affinity,
        )
        for i, m in enumerate(models)
    ]

    results: list[ModelResponse | None] = [None] * len(requests)

    # Crash recovery (debate/journal.py): replay the session's
    # write-ahead journal and serve opponents whose completion records
    # are already durable — zero engine work, byte-identical
    # transcripts (the record feeds the same ``_to_response`` the live
    # path uses). Everything else — errored, partial, never started —
    # re-issues below; the breaker snapshot restored onto the registry
    # still vetoes models whose circuit was open when the process died.
    # Journaling is best-effort by contract: any journal failure is
    # contained (``_journal_safe``) and the round proceeds unjournaled.
    journal = cfg.journal
    replayed: dict[int, dict] = {}
    if journal is not None:
        try:
            journal.ensure_round_start(
                round_num,
                spec,
                models,
                {
                    "doc_type": cfg.doc_type,
                    "focus": cfg.focus,
                    "persona": cfg.persona,
                    "preserve_intent": cfg.preserve_intent,
                    "press": cfg.press,
                },
                trace_id=trace_id,
            )
            replayed = journal.replay(round_num, spec, models)
        except Exception as e:
            _journal_fault(e)
            replayed = {}
    for i, rec in sorted(replayed.items()):
        comp, rec_latency = journal_mod.completion_from_record(rec)
        results[i] = _to_response(
            models[i], comp, rec_latency, requests[i].span_id
        )
        tracer.count("journal.served", 1)
        tracer.count(
            "journal.salvaged_decode_tokens",
            float(results[i].usage.output_tokens),
        )
        if obs_mod.config().enabled:
            obs_mod.emit(
                obs_mod.JournalEvent(
                    op="serve",
                    rtype="completion",
                    round_num=round_num,
                    index=i,
                    trace_id=trace_id,
                    span_id=requests[i].span_id,
                )
            )

    # Group indices by engine so co-resident models batch together. A
    # model whose circuit breaker is open degrades HERE — no engine call,
    # no retry budget — and rejoins after its cooldown's half-open probe.
    groups: dict[int, tuple[object, list[int]]] = {}
    for i, req in enumerate(requests):
        if results[i] is not None:
            continue  # served from the journal above
        if not breakers.allow(req.model):
            remaining = breakers.cooldown_remaining(req.model)
            results[i] = ModelResponse(
                model=req.model,
                error=(
                    "circuit open: skipped after repeated faults "
                    f"(probe in {remaining:.0f}s)"
                ),
                span_id=req.span_id,
            )
            continue
        engine = get_engine(req.model)
        groups.setdefault(id(engine), (engine, []))[1].append(i)

    if journal is not None and replayed:
        n_reissued = sum(len(ix) for _, ix in groups.values())
        obs_mod.emit(
            obs_mod.RecoveryEvent(
                round_num=round_num,
                served=len(replayed),
                reissued=n_reissued,
                records=getattr(journal, "replay_records", len(replayed)),
                skipped=getattr(journal, "replay_skipped", 0),
                trace_id=trace_id,
            )
        )
        if obs_mod.config().enabled:
            obs_mod.metrics.counter(
                "advspec_recovery_requests_total",
                help="opponents resolved on a journal replay, by source",
                source="journal",
            ).inc(len(replayed))
            obs_mod.metrics.counter(
                "advspec_recovery_requests_total", source="reissued"
            ).inc(n_reissued)

    # The round's ambient trace scope: every event emitted below this
    # frame — engine fan-in counters, scheduler steps, prefix-cache and
    # tier ops, retrace compiles — inherits the round's trace_id unless
    # its emitter stamped a more specific span. Round/opponent SpanEvents
    # are ORDERING markers (wall_s 0): the debate layer's walls are real
    # host time, which would break the mock round's byte-deterministic
    # JSONL pin — the measured per-request decomposition lives in the
    # engine-emitted request spans (and, for humans, in the report's
    # latency_s), not here.
    obs_mod.trace.set_ambient(trace_id, "")
    obs_mod.emit(
        obs_mod.SpanEvent(name="round", phase="begin", trace_id=trace_id)
    )
    for i, req in enumerate(requests):
        obs_mod.emit(
            obs_mod.SpanEvent(
                name="opponent",
                phase="begin",
                req_id=i,
                trace_id=trace_id,
                span_id=req.span_id,
            )
        )
        if results[i] is not None:
            # Breaker-open degrade resolved this opponent above with
            # zero engine calls — close its span immediately so the
            # stream never carries a begun-but-never-ended opponent
            # for a request that already has its response.
            obs_mod.emit(
                obs_mod.SpanEvent(
                    name="opponent",
                    phase="end",
                    req_id=i,
                    trace_id=trace_id,
                    span_id=req.span_id,
                )
            )
    try:
        for engine, indices in groups.values():
            # Streaming early cancellation (docs/streaming.md): when
            # armed AND the engine's chat exposes the consumer seam,
            # each request streams through a marker scanner and stops
            # the moment its verdict is decidable. Engines without the
            # seam (test fakes, the dense fallback) serve the blocking
            # path unchanged.
            stream_ok = stream_mod.armed() and stream_mod.consumer_supported(
                engine
            )

            def _chat(batch, sampling, engine=engine, stream_ok=stream_ok):
                return (
                    engine.chat(
                        batch, sampling, consumer=_early_cancel_consumer()
                    )
                    if stream_ok
                    else engine.chat(batch, sampling)
                )

            def _resolve(i: int, comp: Completion, latency: float) -> None:
                """Final resolution of one opponent: build the response,
                make the outcome durable (a clean completion becomes a
                replayable journal record THE MOMENT it resolves; an
                evicted request's salvaged partial text is journaled
                for diagnosis, never replayed), close its span."""
                results[i] = _to_response(
                    requests[i].model, comp, latency, requests[i].span_id
                )
                if journal is not None:
                    if comp.ok:
                        _journal_safe(
                            journal.log_completion,
                            round_num,
                            i,
                            requests[i].model,
                            comp,
                            latency,
                            trace_id=trace_id,
                            span_id=requests[i].span_id,
                        )
                    elif comp.text:
                        _journal_safe(
                            journal.log_partial,
                            round_num,
                            i,
                            requests[i].model,
                            comp,
                            trace_id=trace_id,
                            span_id=requests[i].span_id,
                        )
                obs_mod.emit(
                    obs_mod.SpanEvent(
                        name="opponent",
                        phase="end",
                        req_id=i,
                        trace_id=trace_id,
                        span_id=requests[i].span_id,
                    )
                )

            hedge_armed = cfg.sampling.request_deadline_s > 0
            # (index, original completion, its latency): watchdog-
            # expired opponents awaiting their one hedged re-admission.
            hedge_pending: list[tuple[int, Completion, float]] = []
            pending = list(indices)
            for attempt in range(MAX_RETRIES):
                batch = [requests[i] for i in pending]
                t0 = time.monotonic()
                completions = _chat(batch, cfg.sampling)
                latency = time.monotonic() - t0
                tracer.add_span("engine_chat", latency)
                still_pending = []
                for i, comp in zip(pending, completions):
                    # The group's wall IS each rider's wall: rows of one
                    # batched decode finish together from the caller's
                    # view.
                    tracer.add_span(f"opponent/{requests[i].model}", latency)
                    tracer.count(f"attempts.{requests[i].model}", 1)
                    # Every attempt's outcome feeds the model's breaker:
                    # threshold consecutive failures open it. EXCEPT a
                    # serving-layer SHED (daemon quota/drain policy) —
                    # the model did nothing wrong, and a drain storm
                    # counting as N failures per opponent would open
                    # every circuit in the pool (found by the SIGTERM
                    # drain drill).
                    fail_kind = (
                        None
                        if comp.ok
                        else classify_message(comp.error or "")
                    )
                    if comp.ok:
                        breakers.record(requests[i].model, ok=True)
                    elif fail_kind is not FaultKind.SHED:
                        breakers.record(
                            requests[i].model, ok=False, kind=fail_kind
                        )
                    # A watchdog-expired request does NOT re-enter the
                    # 3-attempt backoff ladder (its per-request deadline
                    # already bounded it once; full retries would pay up
                    # to 3 more deadlines plus backoff): it gets exactly
                    # ONE hedged re-admission on a tightened budget
                    # after this group resolves — and only while its
                    # breaker still allows the model.
                    if (
                        hedge_armed
                        and not comp.ok
                        and classify_message(comp.error or "")
                        is FaultKind.TIMEOUT
                    ):
                        if breakers.allow(requests[i].model):
                            hedge_pending.append((i, comp, latency))
                        else:
                            _resolve(i, comp, latency)
                    # Retry only while the breaker still allows the
                    # model: a failed half-open probe reopens the circuit
                    # and must cost ONE attempt, not the full 3x backoff
                    # budget it exists to avoid.
                    elif (
                        not comp.ok
                        and comp.transient
                        and attempt < MAX_RETRIES - 1
                        and breakers.allow(requests[i].model)
                    ):
                        still_pending.append(i)
                    else:
                        _resolve(i, comp, latency)
                pending = still_pending
                if not pending:
                    break
                if deadline is not None and time.monotonic() >= deadline:
                    break  # round budget exhausted: no further retries
                cfg.sleep(RETRY_BASE_DELAY * (2**attempt))
            for i in pending:  # exhausted retries
                _resolve(i, Completion(error="retries exhausted"), 0.0)
            if hedge_pending and (
                deadline is not None and time.monotonic() >= deadline
            ):
                # Round budget exhausted: keep the watchdog partials.
                for i, orig, orig_lat in hedge_pending:
                    _resolve(i, orig, orig_lat)
            elif hedge_pending:
                # The single hedged re-admission: one more batched chat
                # for every deadline-evicted opponent, under a deadline
                # tightened to HEDGE_BUDGET_FACTOR of the original —
                # the freed slots re-admit immediately, and a model
                # that is genuinely hung (not merely slow) costs one
                # tightened deadline more, never another full ladder.
                tightened = dataclasses.replace(
                    cfg.sampling,
                    request_deadline_s=(
                        cfg.sampling.request_deadline_s
                        * HEDGE_BUDGET_FACTOR
                    ),
                )
                batch = [requests[i] for i, _, _ in hedge_pending]
                t0 = time.monotonic()
                completions = _chat(batch, tightened)
                latency = time.monotonic() - t0
                tracer.add_span("engine_chat", latency)
                for (i, orig, orig_lat), comp in zip(
                    hedge_pending, completions
                ):
                    tracer.add_span(f"opponent/{requests[i].model}", latency)
                    tracer.count(f"attempts.{requests[i].model}", 1)
                    tracer.count(f"hedge.{requests[i].model}", 1)
                    if comp.ok:
                        breakers.record(requests[i].model, ok=True)
                        _resolve(i, comp, latency)
                    else:
                        hedge_kind = classify_message(comp.error or "")
                        if hedge_kind is not FaultKind.SHED:
                            breakers.record(
                                requests[i].model, ok=False, kind=hedge_kind
                            )
                        # The hedge lost too: keep the ORIGINAL partial
                        # (more salvaged text, the first failure's true
                        # latency). No third attempt.
                        _resolve(i, orig, orig_lat)
    finally:
        obs_mod.emit(
            obs_mod.SpanEvent(name="round", phase="end", trace_id=trace_id)
        )
        obs_mod.trace.set_ambient("", "")

    return RoundResult(
        responses=[r for r in results if r is not None],
        round_num=round_num,
        tracer=tracer,
        trace_id=trace_id,
    )
