"""Round orchestration: fan a spec out to N opponents, collect responses.

Reference hot path: ``run_critique`` → ``call_models_parallel`` →
ThreadPoolExecutor(thread per model) → per-model HTTP/subprocess call
(scripts/debate.py:798-888, models.py:681-722). TPU-native restructure
(SURVEY §1 "TPU mapping"): opponents are *grouped by engine* and each group is
executed as ONE batched ``chat`` call — on the TPU engine that is N rows of a
single sharded decode over the mesh, not N threads. The retry loop survives
(it now covers recompile/OOM/transient device errors instead of HTTP 429s)
with the reference's exact policy: 3 attempts, exponential backoff 1s/2s/4s
(models.py:46-47), errors captured rather than raised, and rounds degrading
gracefully when some opponents fail (debate.py:845-853).

On top of that policy sits the per-model circuit breaker
(resilience/breaker.py): every completion outcome feeds the model's
breaker, and a model whose breaker is OPEN is degraded up front — zero
engine calls, zero retry budget — until its cooldown elapses and a
half-open probe re-admits it. Persistent failure costs one errored
response per round instead of 3 retries x backoff.
"""

from __future__ import annotations

import dataclasses
import time
from dataclasses import dataclass, field
from pathlib import Path

from adversarial_spec_tpu import obs as obs_mod
from adversarial_spec_tpu.debate import prompts
from adversarial_spec_tpu.debate.parsing import (
    StreamScanner,
    detect_agreement,
    extract_spec,
    has_malformed_spec,
)
from adversarial_spec_tpu.debate.types import ModelResponse, RoundResult
from adversarial_spec_tpu.engine import streaming as stream_mod
from adversarial_spec_tpu.engine.dispatch import get_engine
from adversarial_spec_tpu.engine.types import ChatRequest, Completion, SamplingParams
from adversarial_spec_tpu.resilience import breaker as breaker_mod
from adversarial_spec_tpu.resilience.faults import classify_message
from adversarial_spec_tpu.utils.tracing import Tracer

MAX_RETRIES = 3
RETRY_BASE_DELAY = 1.0


@dataclass
class RoundConfig:
    """Everything that shapes one critique round's prompts and decode."""

    doc_type: str = "generic"
    focus: str | None = None
    persona: str | None = None
    preserve_intent: bool = False
    press: bool = False
    context_files: list[str] = field(default_factory=list)
    sampling: SamplingParams = field(default_factory=SamplingParams)
    # Per-model circuit breakers; None = the process default registry.
    # Tests pass their own (fake clock, tight thresholds).
    breakers: breaker_mod.BreakerRegistry | None = None
    # Injected for tests; defaults to real sleep for backoff.
    sleep = staticmethod(time.sleep)


def load_context_files(paths: list[str]) -> str:
    """Concatenate supporting context files into a prompt block.

    Parity: reference scripts/models.py:130-146 — repeatable ``--context``
    flag, each file labeled, missing files raise with a clear message.
    """
    if not paths:
        return ""
    blocks = []
    for p in paths:
        path = Path(p)
        if not path.is_file():
            raise FileNotFoundError(f"context file not found: {p}")
        blocks.append(f"--- CONTEXT FILE: {path.name} ---\n{path.read_text()}")
    return "\n\n".join(blocks) + "\n\n"


def build_request(
    model: str, spec: str, round_num: int, cfg: RoundConfig
) -> ChatRequest:
    """Assemble one opponent's system+user messages."""
    system = prompts.get_system_prompt(
        doc_type=cfg.doc_type,
        focus=cfg.focus,
        persona=cfg.persona,
        preserve_intent=cfg.preserve_intent,
    )
    template = (
        prompts.PRESS_PROMPT_TEMPLATE if cfg.press else prompts.REVIEW_PROMPT_TEMPLATE
    )
    user = load_context_files(cfg.context_files) + template.format(
        round=round_num, spec=spec
    )
    return ChatRequest(model=model, system=system, user=user)


def _early_cancel_consumer():
    """One chat call's early-convergence stream consumer: an
    incremental marker scanner per batch row (parsing.StreamScanner
    over EARLY_CANCEL_MARKERS). The moment a row's verdict is
    decidable — its marker's last character arrives, however the
    stream was chunked — it returns False and the engine cancels that
    request mid-decode. The truncated transcript contains the full
    marker, so ``detect_agreement`` on it gives exactly the verdict
    the full text would; everything past the marker is decode the
    debate never reads (the matched-ceiling study's point: round
    COUNT, not round length, drives quality). Built fresh per attempt:
    a retried request streams from scratch."""
    scanners: dict[int, StreamScanner] = {}

    def consume(row: int, text: str) -> bool:
        sc = scanners.get(row)
        if sc is None:
            sc = scanners[row] = StreamScanner()
        return sc.feed(text) is None

    return consume


def _to_response(
    model: str, comp: Completion, latency_s: float, span_id: str = ""
) -> ModelResponse:
    if not comp.ok:
        return ModelResponse(
            model=model,
            error=comp.error,
            usage=comp.usage,
            latency_s=latency_s,
            span_id=span_id,
        )
    resp = ModelResponse(
        model=model,
        critique=comp.text,
        agreed=detect_agreement(comp.text),
        revised_spec=extract_spec(comp.text),
        usage=comp.usage,
        latency_s=latency_s,
        span_id=span_id,
    )
    if has_malformed_spec(comp.text):
        # Parity: warn-not-crash on malformed [SPEC] (models.py:633-637);
        # surfaced via the response so the CLI can print the warning.
        resp.critique += "\n\n[warning: unterminated [SPEC] tag in response]"
    return resp


def run_round(
    spec: str,
    models: list[str],
    round_num: int = 1,
    cfg: RoundConfig | None = None,
) -> RoundResult:
    """Execute one critique round across all opponents.

    Opponents are grouped by serving engine; each group is one batched chat
    call. Transient per-request failures are retried with exponential
    backoff (3 attempts total, sleeping 1 s then 2 s between them — the
    reference's policy); retries re-batch only the failed requests, and a
    nonzero ``sampling.timeout_s`` bounds the whole round (no retry starts
    past the deadline).
    """
    cfg = cfg or RoundConfig()
    # The debate layer's own tracer: per-opponent chat walls + attempt
    # counters, merged into the CLI's round tracer (Tracer.merge) so the
    # engine-level and debate-level spans compose into one report.
    tracer = Tracer()
    breakers = (
        cfg.breakers
        if cfg.breakers is not None
        else breaker_mod.default_registry()
    )
    deadline = (
        time.monotonic() + cfg.sampling.timeout_s
        if cfg.sampling.timeout_s > 0
        else None
    )
    # Causal tracing (obs/trace.py): ONE trace per round, ONE span per
    # opponent request, minted HERE — above any engine choice — so the
    # mock and real serving paths carry byte-identical ids for the same
    # invocation sequence. The ids ride the requests by value; the
    # ambient scope below covers emitters that don't know their request.
    trace_id = obs_mod.trace.mint_trace(round_num)
    requests = [
        dataclasses.replace(
            build_request(m, spec, round_num, cfg),
            trace_id=trace_id,
            span_id=obs_mod.trace.mint_span(trace_id, i),
        )
        for i, m in enumerate(models)
    ]

    # Group indices by engine so co-resident models batch together. A
    # model whose circuit breaker is open degrades HERE — no engine call,
    # no retry budget — and rejoins after its cooldown's half-open probe.
    groups: dict[int, tuple[object, list[int]]] = {}
    results: list[ModelResponse | None] = [None] * len(requests)
    for i, req in enumerate(requests):
        if not breakers.allow(req.model):
            remaining = breakers.cooldown_remaining(req.model)
            results[i] = ModelResponse(
                model=req.model,
                error=(
                    "circuit open: skipped after repeated faults "
                    f"(probe in {remaining:.0f}s)"
                ),
                span_id=req.span_id,
            )
            continue
        engine = get_engine(req.model)
        groups.setdefault(id(engine), (engine, []))[1].append(i)

    # The round's ambient trace scope: every event emitted below this
    # frame — engine fan-in counters, scheduler steps, prefix-cache and
    # tier ops, retrace compiles — inherits the round's trace_id unless
    # its emitter stamped a more specific span. Round/opponent SpanEvents
    # are ORDERING markers (wall_s 0): the debate layer's walls are real
    # host time, which would break the mock round's byte-deterministic
    # JSONL pin — the measured per-request decomposition lives in the
    # engine-emitted request spans (and, for humans, in the report's
    # latency_s), not here.
    obs_mod.trace.set_ambient(trace_id, "")
    obs_mod.emit(
        obs_mod.SpanEvent(name="round", phase="begin", trace_id=trace_id)
    )
    for i, req in enumerate(requests):
        obs_mod.emit(
            obs_mod.SpanEvent(
                name="opponent",
                phase="begin",
                req_id=i,
                trace_id=trace_id,
                span_id=req.span_id,
            )
        )
        if results[i] is not None:
            # Breaker-open degrade resolved this opponent above with
            # zero engine calls — close its span immediately so the
            # stream never carries a begun-but-never-ended opponent
            # for a request that already has its response.
            obs_mod.emit(
                obs_mod.SpanEvent(
                    name="opponent",
                    phase="end",
                    req_id=i,
                    trace_id=trace_id,
                    span_id=req.span_id,
                )
            )
    try:
        for engine, indices in groups.values():
            # Streaming early cancellation (docs/streaming.md): when
            # armed AND the engine's chat exposes the consumer seam,
            # each request streams through a marker scanner and stops
            # the moment its verdict is decidable. Engines without the
            # seam (test fakes, the dense fallback) serve the blocking
            # path unchanged.
            stream_ok = stream_mod.armed() and stream_mod.consumer_supported(
                engine
            )
            pending = list(indices)
            for attempt in range(MAX_RETRIES):
                batch = [requests[i] for i in pending]
                t0 = time.monotonic()
                completions = (
                    engine.chat(
                        batch,
                        cfg.sampling,
                        consumer=_early_cancel_consumer(),
                    )
                    if stream_ok
                    else engine.chat(batch, cfg.sampling)
                )
                latency = time.monotonic() - t0
                tracer.add_span("engine_chat", latency)
                still_pending = []
                for i, comp in zip(pending, completions):
                    # The group's wall IS each rider's wall: rows of one
                    # batched decode finish together from the caller's
                    # view.
                    tracer.add_span(f"opponent/{requests[i].model}", latency)
                    tracer.count(f"attempts.{requests[i].model}", 1)
                    # Every attempt's outcome feeds the model's breaker:
                    # threshold consecutive failures open it.
                    if comp.ok:
                        breakers.record(requests[i].model, ok=True)
                    else:
                        breakers.record(
                            requests[i].model,
                            ok=False,
                            kind=classify_message(comp.error or ""),
                        )
                    # Retry only while the breaker still allows the
                    # model: a failed half-open probe reopens the circuit
                    # and must cost ONE attempt, not the full 3x backoff
                    # budget it exists to avoid.
                    if (
                        not comp.ok
                        and comp.transient
                        and attempt < MAX_RETRIES - 1
                        and breakers.allow(requests[i].model)
                    ):
                        still_pending.append(i)
                    else:
                        results[i] = _to_response(
                            requests[i].model,
                            comp,
                            latency,
                            requests[i].span_id,
                        )
                        obs_mod.emit(
                            obs_mod.SpanEvent(
                                name="opponent",
                                phase="end",
                                req_id=i,
                                trace_id=trace_id,
                                span_id=requests[i].span_id,
                            )
                        )
                pending = still_pending
                if not pending:
                    break
                if deadline is not None and time.monotonic() >= deadline:
                    break  # round budget exhausted: no further retries
                cfg.sleep(RETRY_BASE_DELAY * (2**attempt))
            for i in pending:  # exhausted retries
                results[i] = ModelResponse(
                    model=requests[i].model,
                    error="retries exhausted",
                    span_id=requests[i].span_id,
                )
                obs_mod.emit(
                    obs_mod.SpanEvent(
                        name="opponent",
                        phase="end",
                        req_id=i,
                        trace_id=trace_id,
                        span_id=requests[i].span_id,
                    )
                )
    finally:
        obs_mod.emit(
            obs_mod.SpanEvent(name="round", phase="end", trace_id=trace_id)
        )
        obs_mod.trace.set_ambient("", "")

    return RoundResult(
        responses=[r for r in results if r is not None],
        round_num=round_num,
        tracer=tracer,
        trace_id=trace_id,
    )
