"""Crash-safe write-ahead journal for debate rounds.

``SessionState`` is saved only AFTER a round completes, so before this
module a crash mid-round lost the entire round: every opponent's decode
was re-paid on ``--resume`` even when the process died one opponent
short of synthesis. The journal closes that window with an append-only
per-session record stream (``<sessions_dir>/<session_id>.journal.jsonl``)
written at the three durability points of a round:

- ``round_start`` — the round number, a sha-256 of the spec, the model
  list and the round config, logged before the first engine call. The
  spec hash is the replay guard: records are only served back to a
  resume that is re-running the SAME round of the SAME spec.
- ``completion`` — one record per opponent, written (fsync'd) the
  moment its streamed request finishes or cancels: model, full text,
  cancelled flag, usage, latency, trace/span ids. Errored opponents
  get no completion record (a resume re-issues them — with the breaker
  snapshot on ``SessionState`` still skipping models whose circuit is
  open); a deadline/fault-evicted opponent's partial text is journaled
  as a ``partial`` record for diagnosis but never replayed.
- ``round_commit`` — the round synthesized and the session file
  advanced; the journal's job for this round is done.

``--resume`` replays the journal (``replay``): opponents with a durable
completion record are served from it byte-identically with ZERO engine
work — and with PR 7's content-addressed disk store rehydrating the
shared prefix KV, the re-issued remainder's prefill is mostly free too.
Only unfinished opponents re-enter the engine. ``tools/chaos_run.py
--crash`` and ``bench.py --mode recover`` drive the full
SIGKILL-mid-round → resume loop.

Durability mechanics: every append is a single JSON line written,
flushed and ``os.fsync``'d before the caller proceeds (the fsync wall
is the ``advspec_journal_fsync_seconds`` histogram). A crash mid-append
leaves at most one half-written line with no trailing newline; the
NEXT append heals it with a leading newline so the torn garbage is
confined to its own line, and the reader skips undecodable lines
ALONE — records appended after a crash stay replayable through a
second crash. Records with a foreign ``v`` (version) or failing the
field schema are likewise skipped and counted, never fatal. Journal
failures are contained
by the caller (debate/core.py): a round must survive its journal — the
chaos injector's ``crash`` seam fires before every append to prove it.

``ADVSPEC_JOURNAL_KILL_AFTER=N`` (the kill-chaos harness's
deterministic trigger) SIGKILLs the process the moment the N-th
completion record becomes durable — a REAL kill, after a REAL fsync,
at a reproducible point mid-round.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import signal
import time
from pathlib import Path

from adversarial_spec_tpu import obs as obs_mod
from adversarial_spec_tpu.debate import session as session_mod
from adversarial_spec_tpu.debate.usage import Usage
from adversarial_spec_tpu.engine.types import Completion
from adversarial_spec_tpu.resilience import injector

JOURNAL_VERSION = 1

RECORD_TYPES = ("round_start", "completion", "partial", "round_commit")

# Record schema (the journal's analog of obs EVENT_FIELDS): type ->
# {field: python type}. ``v``/``type`` are common to every record.
# tools/lint_all.py runs ``self_check()`` against this table so the
# writer, the validator and the examples can never drift apart.
RECORD_FIELDS: dict[str, dict[str, type]] = {
    "round_start": {
        "round": int,
        "spec_sha": str,
        "models": list,
        "config": dict,
        "trace_id": str,
    },
    "completion": {
        "round": int,
        "index": int,
        "model": str,
        "text": str,
        "cancelled": bool,
        "latency_s": float,
        "usage": dict,
        "trace_id": str,
        "span_id": str,
    },
    "partial": {
        "round": int,
        "index": int,
        "model": str,
        "text": str,
        "error": str,
        "usage": dict,
        "trace_id": str,
        "span_id": str,
    },
    "round_commit": {
        "round": int,
        "all_agreed": bool,
    },
}

# Examples of every record type, used by ``self_check`` (each must pass
# ``validate_record`` after a JSON round-trip) and as documentation of
# the on-disk shape.
_EXAMPLES: dict[str, dict] = {
    "round_start": {
        "round": 1,
        "spec_sha": "0" * 64,
        "models": ["mock://critic"],
        "config": {"doc_type": "generic"},
        "trace_id": "tr-001-01",
    },
    "completion": {
        "round": 1,
        "index": 0,
        "model": "mock://critic",
        "text": "1. Critique...\n[SPEC]...[/SPEC]",
        "cancelled": False,
        "latency_s": 0.25,
        "usage": {"input_tokens": 10, "output_tokens": 20},
        "trace_id": "tr-001-01",
        "span_id": "tr-001-01/s00",
    },
    "partial": {
        "round": 1,
        "index": 1,
        "model": "mock://critic",
        "text": "1. Cri",
        "error": "DEADLINE_EXCEEDED: per-request watchdog deadline",
        "usage": {},
        "trace_id": "tr-001-01",
        "span_id": "tr-001-01/s01",
    },
    "round_commit": {"round": 1, "all_agreed": False},
}


def spec_sha(spec: str) -> str:
    """The replay guard: journal records bind to this exact spec."""
    return hashlib.sha256(spec.encode("utf-8")).hexdigest()


def env_enabled() -> bool:
    """The process default for ``--journal`` (``ADVSPEC_JOURNAL``)."""
    return os.environ.get("ADVSPEC_JOURNAL", "1") != "0"


def validate_record(obj) -> list[str]:
    """Schema-check one decoded journal line; returns human-readable
    problems (empty = valid). Unknown versions are a VALIDATION error
    here — the tolerant reader skips them before validation."""
    if not isinstance(obj, dict):
        return [f"not an object: {obj!r}"]
    errors: list[str] = []
    if obj.get("v") != JOURNAL_VERSION:
        errors.append(f"unknown journal version {obj.get('v')!r}")
    rtype = obj.get("type")
    if rtype not in RECORD_FIELDS:
        return errors + [f"unknown record type {rtype!r}"]
    fields = RECORD_FIELDS[rtype]
    for name, py in fields.items():
        if name not in obj:
            errors.append(f"{rtype}: missing field {name!r}")
            continue
        v = obj[name]
        if py is bool:
            ok = isinstance(v, bool)
        elif py is int:
            ok = isinstance(v, int) and not isinstance(v, bool)
        elif py is float:
            ok = isinstance(v, (int, float)) and not isinstance(v, bool)
        elif py is list:
            ok = isinstance(v, list)
        elif py is dict:
            ok = isinstance(v, dict)
        else:
            ok = isinstance(v, str)
        if not ok:
            errors.append(
                f"{rtype}: field {name!r} expected {py.__name__}, "
                f"got {type(v).__name__}"
            )
    for name in obj:
        if name not in fields and name not in ("v", "type"):
            errors.append(f"{rtype}: unknown field {name!r}")
    return errors


def self_check() -> list[str]:
    """Journal schema self-check (a tools/lint_all.py stage): every
    record type has a schema and an example, every example round-trips
    JSON and validates clean, and the validator actually FIRES on a
    broken record (a silently dead validator is worse than none)."""
    problems: list[str] = []
    if set(RECORD_FIELDS) != set(RECORD_TYPES):
        problems.append(
            f"RECORD_FIELDS types {sorted(RECORD_FIELDS)} != "
            f"RECORD_TYPES {sorted(RECORD_TYPES)}"
        )
    if set(_EXAMPLES) != set(RECORD_TYPES):
        problems.append("every record type needs an example")
    for rtype, example in _EXAMPLES.items():
        rec = {"v": JOURNAL_VERSION, "type": rtype, **example}
        rec = json.loads(json.dumps(rec))
        errs = validate_record(rec)
        if errs:
            problems.append(f"example {rtype!r} invalid: {errs}")
    # Must-fail fixtures: wrong version, unknown type, missing field,
    # wrong field type, unknown field.
    good = {"v": JOURNAL_VERSION, "type": "round_commit", "round": 1,
            "all_agreed": True}
    for bad, why in (
        ({**good, "v": JOURNAL_VERSION + 1}, "foreign version"),
        ({**good, "type": "nope"}, "unknown type"),
        ({"v": JOURNAL_VERSION, "type": "round_commit", "round": 1},
         "missing field"),
        ({**good, "round": "one"}, "wrong field type"),
        ({**good, "extra": 1}, "unknown field"),
    ):
        if not validate_record(bad):
            problems.append(f"validator failed to fire on {why}")
    return problems


def completion_from_record(rec: dict) -> tuple[Completion, float]:
    """Rebuild the engine-seam ``Completion`` a journal record captured
    — the replay path feeds it through the SAME ``_to_response`` the
    live path uses, so agreement/spec extraction on a byte-identical
    transcript is byte-identical too. Returns (completion, latency_s)."""
    u = rec.get("usage") or {}
    known = {f.name for f in dataclasses.fields(Usage)}
    usage = Usage(**{k: v for k, v in u.items() if k in known})
    return (
        Completion(
            text=rec.get("text", ""),
            cancelled=bool(rec.get("cancelled", False)),
            usage=usage,
        ),
        float(rec.get("latency_s", 0.0)),
    )


class RoundJournal:
    """Append-only per-session round journal (one file per session)."""

    def __init__(self, session_id: str, journal_dir: Path | None = None):
        session_mod._validate_session_id(session_id)
        self.session_id = session_id
        self._dir = journal_dir
        self._n_completions = 0
        # Stats of the most recent replay() read, for the caller's
        # RecoveryEvent: total readable records and lines discarded
        # (torn tail / foreign version / schema mismatch).
        self.replay_records = 0
        self.replay_skipped = 0
        kill = os.environ.get("ADVSPEC_JOURNAL_KILL_AFTER", "")
        try:
            self._kill_after = max(0, int(kill)) if kill else 0
        except ValueError:
            self._kill_after = 0

    @property
    def path(self) -> Path:
        # Resolved per access, not cached: tests patch
        # session.SESSIONS_DIR per-case (the module-constant fixture
        # pattern) and the journal must follow.
        directory = Path(self._dir or session_mod.SESSIONS_DIR)
        return directory / f"{self.session_id}.journal.jsonl"

    # -- durable writes ----------------------------------------------------

    def _write(self, rtype: str, payload: dict, *, fresh: bool = False) -> None:
        """Append one record durably (write + flush + fsync). ``fresh``
        rewrites the file to just this record (atomic tmp + replace —
        the round-boundary truncation that keeps the journal one round
        long; the committed previous round lives on in SessionState's
        history, not here)."""
        # The chaos seam: a fault here is a record that never became
        # durable. Callers contain it — the round must outlive its
        # journal (debate/core.py's _journal_safe).
        injector.fire("crash")
        record = {"v": JOURNAL_VERSION, "type": rtype, **payload}
        line = json.dumps(record, separators=(",", ":")) + "\n"
        path = self.path
        path.parent.mkdir(parents=True, exist_ok=True)
        t0 = time.monotonic()
        if fresh:
            tmp = path.with_name(f"{path.name}.{os.getpid()}.tmp")
            try:
                with open(tmp, "w", encoding="utf-8") as f:
                    f.write(line)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, path)
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
        else:
            # Heal a torn tail before appending: a crash mid-append
            # leaves a half-written line with NO trailing newline, and
            # appending straight onto it would fuse this record into
            # the garbage — unreadable, and before the reader learned
            # to skip mid-stream garbage it cost every later record in
            # the round too. A leading newline confines the torn line
            # to itself; the reader skips it alone.
            heal = False
            try:
                with open(path, "rb") as rf:
                    rf.seek(-1, os.SEEK_END)
                    heal = rf.read(1) != b"\n"
            except (OSError, ValueError):
                heal = False  # missing or empty file: nothing to heal
            with open(path, "a", encoding="utf-8") as f:
                if heal:
                    f.write("\n")
                f.write(line)
                f.flush()
                os.fsync(f.fileno())
        dt = time.monotonic() - t0
        if obs_mod.config().enabled:
            obs_mod.hot.journal_fsync.observe(dt)
            obs_mod.metrics.counter(
                "advspec_journal_records_total",
                help="durable round-journal appends by record type",
                type=rtype,
            ).inc()
            obs_mod.emit(
                obs_mod.JournalEvent(
                    op="append",
                    rtype=rtype,
                    round_num=int(payload.get("round", 0)),
                    index=int(payload.get("index", -1)),
                    fsync_s=dt,
                    trace_id=payload.get("trace_id", ""),
                    span_id=payload.get("span_id", ""),
                )
            )
        if rtype == "completion" and self._kill_after:
            # Kill-chaos trigger: die HARD right after this record
            # became durable — the harness's deterministic mid-round
            # SIGKILL (tools/chaos_run.py --crash).
            self._n_completions += 1
            if self._n_completions >= self._kill_after:
                os.kill(os.getpid(), signal.SIGKILL)

    def ensure_round_start(
        self,
        round_num: int,
        spec: str,
        models: list[str],
        config: dict,
        trace_id: str = "",
    ) -> bool:
        """Log the round-start marker once per (round, spec). A resume
        of an already-started round appends nothing (its completions
        must stay replayable); a NEW round truncates the journal to the
        fresh marker — the previous round committed into SessionState
        and its records are dead weight. Returns True when a marker was
        written."""
        records, _ = self.read()
        for rec in records:
            if (
                rec["type"] == "round_start"
                and rec["round"] == round_num
                and rec["spec_sha"] == spec_sha(spec)
            ):
                return False
        self._write(
            "round_start",
            {
                "round": round_num,
                "spec_sha": spec_sha(spec),
                "models": list(models),
                "config": dict(config),
                "trace_id": trace_id,
            },
            fresh=True,
        )
        return True

    def log_completion(
        self,
        round_num: int,
        index: int,
        model: str,
        comp: Completion,
        latency_s: float,
        trace_id: str = "",
        span_id: str = "",
    ) -> None:
        self._write(
            "completion",
            {
                "round": round_num,
                "index": index,
                "model": model,
                "text": comp.text,
                "cancelled": bool(comp.cancelled),
                "latency_s": round(float(latency_s), 6),
                "usage": dataclasses.asdict(comp.usage),
                "trace_id": trace_id,
                "span_id": span_id,
            },
        )

    def log_partial(
        self,
        round_num: int,
        index: int,
        model: str,
        comp: Completion,
        trace_id: str = "",
        span_id: str = "",
    ) -> None:
        """A deadline/fault-evicted opponent's salvaged partial text:
        journaled for diagnosis (what did the budget buy before the
        watchdog fired?), never replayed — a resume re-issues it."""
        self._write(
            "partial",
            {
                "round": round_num,
                "index": index,
                "model": model,
                "text": comp.text,
                "error": comp.error or "",
                "usage": dataclasses.asdict(comp.usage),
                "trace_id": trace_id,
                "span_id": span_id,
            },
        )

    def log_round_commit(self, round_num: int, all_agreed: bool) -> None:
        self._write(
            "round_commit",
            {"round": round_num, "all_agreed": bool(all_agreed)},
        )

    # -- tolerant reads + replay -------------------------------------------

    def read(self) -> tuple[list[dict], int]:
        """Every valid record, in order, plus the count of lines that
        were skipped. An UNDECODABLE line is a tear artifact — a crash
        mid-append (the one crash shape an fsync'd append-only file
        has) — and is skipped ALONE: the appender heals a newline-less
        torn tail before its next write, so every record after a tear
        sits on its own durably-appended line and stays replayable (a
        reader that discarded everything past the tear re-paid every
        post-crash opponent on the NEXT crash). Records are
        independently keyed (replay re-checks round/spec/model per
        record), so skipping garbage alone never resurrects unordered
        state. A decodable record that fails validation or carries a
        foreign version likewise skips alone — the append completed;
        the record just isn't ours to act on."""
        path = self.path
        if not path.is_file():
            return [], 0
        records: list[dict] = []
        skipped = 0
        lines = path.read_text(encoding="utf-8", errors="replace").splitlines()
        for line in lines:
            if not line.strip():
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                skipped += 1
                continue
            if validate_record(obj):
                skipped += 1
                continue
            records.append(obj)
        return records, skipped

    def replay(
        self, round_num: int, spec: str, models: list[str]
    ) -> dict[int, dict]:
        """The resume path: completion records for THIS round of THIS
        spec, keyed by opponent index — the opponents a restarted
        process serves from the journal instead of the engine. Guards:
        the last round_start for the round must hash-match the resumed
        spec (a revised spec invalidates every record); the resumed
        opponent POOL must be the journaled pool as a multiset (a
        changed model set refuses replay cleanly — every opponent
        re-issues); and each record serves THE MODEL IT NAMES — at its
        recorded index when the pool order held, re-homed to the
        model's new index when the pool was merely permuted (an
        unambiguous, single-occurrence model only; duplicate ids keep
        the strict per-index match)."""
        records, skipped = self.read()
        self.replay_records = len(records)
        self.replay_skipped = skipped
        start = None
        for rec in records:
            if rec["type"] == "round_start" and rec["round"] == round_num:
                start = rec
        if start is None or start["spec_sha"] != spec_sha(spec):
            return {}
        if sorted(start.get("models", [])) != sorted(models):
            # A changed model SET invalidates the round's records: a
            # completion for a model no longer (or newly) in the pool
            # must not be half-served. Clean refusal — re-issue all.
            return {}
        out: dict[int, dict] = {}
        rehome: list[dict] = []
        for rec in records:
            if rec["type"] != "completion" or rec["round"] != round_num:
                continue
            i = rec["index"]
            if 0 <= i < len(models) and rec["model"] == models[i]:
                out[i] = rec
            else:
                rehome.append(rec)
        # Permuted pool (same multiset, different order): serve each
        # leftover record at ITS model's new index — the per-index
        # model match still decides, just at the re-homed position.
        for rec in rehome:
            model = rec.get("model")
            if models.count(model) != 1:
                continue  # ambiguous under duplicates: re-issue
            j = models.index(model)
            if j not in out:
                out[j] = rec
        if skipped and obs_mod.config().enabled:
            obs_mod.metrics.counter(
                "advspec_journal_records_skipped_total",
                help="journal lines discarded on read (torn tail, "
                "foreign version, schema mismatch)",
            ).inc(skipped)
        return out
