"""Parsers for the debate tag protocol.

The wire protocol between the orchestrator and opponent models is plain text
with three markers (behavioral parity with reference scripts/models.py:149-247):

- ``[AGREE]`` anywhere in a response means the model approves the spec as-is.
- ``[SPEC] ... [/SPEC]`` brackets a full revised spec.
- ``[TASK] ... [/TASK]`` blocks carry structured implementation tasks, with
  ``field: value`` lines (title / description / priority / dependencies /
  estimate) used by export-tasks.
"""

from __future__ import annotations

import difflib
import re
from dataclasses import dataclass, field

AGREE_MARKER = "[AGREE]"
SPEC_OPEN, SPEC_CLOSE = "[SPEC]", "[/SPEC]"
TASK_RE = re.compile(r"\[TASK\](.*?)\[/TASK\]", re.DOTALL)

# Markers that make a STREAMED response's verdict decidable the moment
# they appear (the debate core's early-cancel consumer, docs/
# streaming.md): everything decoded past one of these is never read by
# the debate loop, so the request cancels mid-decode and the freed
# capacity serves queued work. Substring semantics deliberately mirror
# ``detect_agreement`` — a marker inside a code fence still counts —
# so the incremental verdict can NEVER diverge from the whole-text
# parse of the same prefix. This tuple also drives the summary cleanup
# below: a section marker added here is stripped from critique
# summaries by the same path, with no second list to forget.
EARLY_CANCEL_MARKERS: tuple[str, ...] = (AGREE_MARKER,)


class StreamScanner:
    """Incremental marker scanner over a growing text stream.

    ``feed`` receives the text decoded SO FAR (each call a superset of
    the last) and returns the earliest marker whose full text has
    appeared, or None while the verdict is undecidable. Only the
    unseen tail plus a ``max(len(marker)) - 1`` lookback window is
    rescanned, so a marker split across any chunking of the stream —
    token boundaries never align with marker boundaries — is caught
    exactly when its last character arrives, and feeding the whole
    text again stays O(stream length) overall. The verdict is sticky:
    once found, later feeds return it without rescanning (the consumer
    has already asked for cancellation; extra chunks may still arrive
    from steps in flight)."""

    def __init__(self, markers: tuple[str, ...] = EARLY_CANCEL_MARKERS):
        self.markers = tuple(markers)
        self._lookback = max(
            (len(m) for m in self.markers), default=1
        ) - 1
        self._pos = 0  # stream offset scanned so far
        self.found: str | None = None
        self.found_at: int = -1  # stream offset of the found marker

    def feed(self, text_so_far: str) -> str | None:
        if self.found is not None or not self.markers:
            return self.found
        start = max(self._pos - self._lookback, 0)
        window = text_so_far[start:]
        best: str | None = None
        best_at = -1
        for marker in self.markers:
            i = window.find(marker)
            if i != -1 and (best_at == -1 or i < best_at):
                best, best_at = marker, i
        self._pos = len(text_so_far)
        if best is not None:
            self.found = best
            self.found_at = start + best_at
        return self.found

_TASK_FIELDS = ("title", "description", "priority", "dependencies", "estimate")
_PRIORITIES = {"critical", "high", "medium", "low"}


def detect_agreement(response: str) -> bool:
    """True iff the response contains the [AGREE] marker.

    Parity: reference scripts/models.py:149-151 — a bare substring check, so
    agreement plus commentary still counts as agreement.
    """
    return AGREE_MARKER in response


def extract_spec(response: str) -> str | None:
    """Pull the revised spec out of [SPEC]...[/SPEC], or None.

    Deliberate departure from the reference (scripts/models.py:154-160,
    which takes the FIRST close tag): we take first open tag → LAST close
    tag. Models sometimes nest examples containing literal [/SPEC] tags;
    the widest span preserves them, where the reference would truncate the
    spec at the embedded tag. Outputs diverge only on multi-close-tag
    responses (pinned in tests/test_parsing.py).
    """
    start = response.find(SPEC_OPEN)
    if start == -1:
        return None
    end = response.rfind(SPEC_CLOSE)
    # start >= 0 here, so end < start also covers the not-found end == -1.
    if end < start:
        return None
    return response[start + len(SPEC_OPEN) : end].strip()


def has_malformed_spec(response: str) -> bool:
    """An open [SPEC] without a matching close — warn, don't crash.

    Parity: reference warns on malformed responses (scripts/models.py:633-637).
    """
    return SPEC_OPEN in response and extract_spec(response) is None


@dataclass
class Task:
    """One implementation task parsed from a [TASK] block."""

    title: str = ""
    description: str = ""
    priority: str = "medium"
    dependencies: list[str] = field(default_factory=list)
    estimate: str = ""

    def to_dict(self) -> dict:
        return {
            "title": self.title,
            "description": self.description,
            "priority": self.priority,
            "dependencies": self.dependencies,
            "estimate": self.estimate,
        }


def extract_tasks(response: str) -> list[Task]:
    """Parse every [TASK]...[/TASK] block into a structured Task.

    Parity: reference scripts/models.py:163-247. Lines are ``field: value``;
    unknown fields are ignored; a block with no recognized fields but
    non-empty text becomes a task whose title is the first line. Priority is
    normalized to one of critical/high/medium/low (default medium).
    Dependencies split on commas.
    """
    tasks: list[Task] = []
    for block in TASK_RE.findall(response):
        task = Task()
        saw_field = False
        for raw_line in block.strip().splitlines():
            line = raw_line.strip()
            if not line or ":" not in line:
                continue
            key, _, value = line.partition(":")
            key = key.strip().lower().lstrip("-* ").strip()
            value = value.strip()
            if key not in _TASK_FIELDS or not value:
                continue
            saw_field = True
            if key == "priority":
                norm = value.lower().strip()
                task.priority = norm if norm in _PRIORITIES else "medium"
            elif key == "dependencies":
                task.dependencies = [
                    d.strip() for d in value.split(",") if d.strip()
                ]
            else:
                setattr(task, key, value)
        if not saw_field:
            text = block.strip()
            if not text:
                continue
            first, _, rest = text.partition("\n")
            task.title = first.strip()
            task.description = rest.strip()
        tasks.append(task)
    return tasks


def get_critique_summary(critique: str, max_chars: int = 200) -> str:
    """First-line-ish summary of a critique for progress display.

    Parity: reference scripts/models.py:250-260 — strip tags, take the first
    non-empty line, truncate with an ellipsis.
    """
    # Marker-list-driven cleanup: every verdict marker the streaming
    # path can cancel on (EARLY_CANCEL_MARKERS) is stripped here too —
    # one list, so a section marker added for early cancel can never
    # leak into summaries.
    cleaned = critique
    for marker in EARLY_CANCEL_MARKERS:
        cleaned = cleaned.replace(marker, "")
    cleaned = cleaned.strip()
    cleaned = re.sub(
        re.escape(SPEC_OPEN) + ".*?" + re.escape(SPEC_CLOSE),
        "",
        cleaned,
        flags=re.DOTALL,
    ).strip()
    for line in cleaned.splitlines():
        line = line.strip()
        if line:
            if len(line) > max_chars:
                return line[: max_chars - 3] + "..."
            return line
    return ""


def generate_diff(old_spec: str, new_spec: str, n_context: int = 3) -> str:
    """Unified diff between two spec versions.

    Parity: reference scripts/models.py:263-271 (difflib unified_diff with
    previous/revised labels).
    """
    diff = difflib.unified_diff(
        old_spec.splitlines(keepends=True),
        new_spec.splitlines(keepends=True),
        fromfile="previous_spec",
        tofile="revised_spec",
        n=n_context,
    )
    return "".join(diff)
