"""Profiles and global config.

Behavioral parity with reference scripts/providers.py:88-244:
- Named profiles persist a bundle of debate settings; loading a profile only
  fills arguments the user did not set explicitly on the command line
  (flag > profile precedence, reference debate.py:529-550).
- A global config JSON holds cross-run settings; in the reference this is
  the Bedrock gateway section, here it is the default mesh/dtype and the
  model-registry location for the ``tpu://`` provider.

Module-level path constants for test patchability (SURVEY §4).
"""

from __future__ import annotations

import json
from pathlib import Path

from adversarial_spec_tpu.obs.events import atomic_write_text

PROFILES_DIR = Path.home() / ".config" / "adversarial-spec-tpu" / "profiles"
GLOBAL_CONFIG_PATH = (
    Path.home() / ".config" / "adversarial-spec-tpu" / "config.json"
)

# Settings a profile may carry. Mirrors the reference's profile surface
# (models/doc-type/focus/persona/preserve-intent/timeout) plus TPU-native
# decode fields. Mesh/dtype live in the model registry, not profiles.
PROFILE_FIELDS = (
    "models",
    "doc_type",
    "focus",
    "persona",
    "preserve_intent",
    "timeout",
    "max_new_tokens",
    "temperature",
)


def save_profile(
    name: str, settings: dict, profiles_dir: Path | None = None
) -> Path:
    directory = Path(profiles_dir or PROFILES_DIR)
    directory.mkdir(parents=True, exist_ok=True)
    unknown = set(settings) - set(PROFILE_FIELDS)
    if unknown:
        raise ValueError(f"unknown profile fields: {sorted(unknown)}")
    path = directory / f"{name}.json"
    # tmp+replace (GL-ATOMIC): a crash mid-save must not tear a profile
    # a later run then half-loads.
    atomic_write_text(str(path), json.dumps(settings, indent=2))
    return path


def load_profile(name: str, profiles_dir: Path | None = None) -> dict:
    directory = Path(profiles_dir or PROFILES_DIR)
    path = directory / f"{name}.json"
    if not path.is_file():
        raise FileNotFoundError(f"profile {name!r} not found at {path}")
    data = json.loads(path.read_text())
    return {k: v for k, v in data.items() if k in PROFILE_FIELDS}


def list_profiles(profiles_dir: Path | None = None) -> dict[str, dict]:
    directory = Path(profiles_dir or PROFILES_DIR)
    if not directory.is_dir():
        return {}
    out: dict[str, dict] = {}
    for path in sorted(directory.glob("*.json")):
        try:
            out[path.stem] = json.loads(path.read_text())
        except (json.JSONDecodeError, OSError):
            continue
    return out


def apply_profile(args, profile: dict) -> list[str]:
    """Fill unset argparse fields from a profile; explicit flags win.

    Returns the list of field names the profile actually supplied, for
    user-facing reporting. Parity: reference debate.py:538-550 — only
    ``None``/falsy (never-set) argument slots are filled.
    """
    applied = []
    for key, value in profile.items():
        if key not in PROFILE_FIELDS:
            continue
        current = getattr(args, key, None)
        # Identity checks: 0 / 0.0 are real user choices (0 == False would
        # make `--temperature 0` profile-overridable).
        unset = (
            current is None
            or current is False
            or (isinstance(current, list) and not current)
        )
        if unset:
            setattr(args, key, value)
            applied.append(key)
    return applied


def load_global_config(config_path: Path | None = None) -> dict:
    path = Path(config_path or GLOBAL_CONFIG_PATH)
    if not path.is_file():
        return {}
    try:
        return json.loads(path.read_text())
    except (json.JSONDecodeError, OSError):
        return {}


def save_global_config(config: dict, config_path: Path | None = None) -> Path:
    path = Path(config_path or GLOBAL_CONFIG_PATH)
    path.parent.mkdir(parents=True, exist_ok=True)
    # tmp+replace (GL-ATOMIC): same torn-state discipline as profiles.
    atomic_write_text(str(path), json.dumps(config, indent=2))
    return path
