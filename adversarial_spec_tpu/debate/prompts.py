"""Prompt library: system prompts, round templates, focus areas, personas.

Behavioral parity with reference scripts/prompts.py (same public surface —
PRESERVE_INTENT_PROMPT, FOCUS_AREAS with 6 keys, PERSONAS with 10 keys plus
freeform custom, SYSTEM_PROMPT_{PRD,TECH,GENERIC}, REVIEW_PROMPT_TEMPLATE,
PRESS_PROMPT_TEMPLATE, EXPORT_TASKS_PROMPT, get_system_prompt,
get_doc_type_name); all text written fresh for this framework.

This module is a leaf: pure data plus two lookup helpers, consumed by the
debate core when assembling each opponent's chat messages.
"""

from __future__ import annotations

PRESERVE_INTENT_PROMPT = """
IMPORTANT CONSTRAINT — preserve the author's intent. The goal of this review
is to strengthen the document the author set out to write, not to redesign
the product. Do not propose changes to the core concept, target users, or
declared scope. Confine your critique to correctness, completeness, clarity,
feasibility, and internal consistency of what is already proposed. If you
believe the fundamental direction is wrong, note it in at most one sentence
and move on.
"""

FOCUS_AREAS: dict[str, str] = {
    "security": """
PRIORITY FOCUS: security. Scrutinize authentication and authorization flows,
trust boundaries, input validation, secret handling, injection and SSRF
surfaces, data-at-rest and in-transit protection, tenant isolation, and abuse
or fraud vectors. Call out any place where the spec is silent on threat
model, key rotation, or least-privilege access.
""",
    "scalability": """
PRIORITY FOCUS: scalability. Examine how every component behaves at 10x and
100x the stated load: hot partitions, unbounded fan-out, N+1 access patterns,
single writers, coordination bottlenecks, queue growth, and state that cannot
be sharded. Demand explicit capacity assumptions and a story for horizontal
scaling of each stateful part.
""",
    "performance": """
PRIORITY FOCUS: performance. Look for missing latency budgets, chatty
interfaces, synchronous paths that should be async, cache strategy and
invalidation, payload bloat, and algorithmic complexity hiding in innocuous
requirements. Every user-facing operation should have a target latency and a
plan for measuring it.
""",
    "ux": """
PRIORITY FOCUS: user experience. Evaluate the flows from the user's seat:
first-run experience, error and empty states, loading and offline behavior,
discoverability, consistency of terminology, and accessibility. Flag any
interaction the spec describes from the system's point of view without
saying what the user actually sees and does.
""",
    "reliability": """
PRIORITY FOCUS: reliability. Probe failure modes: partial failures,
timeouts, retries and idempotency, data loss windows, backup and restore,
degraded modes, rollout and rollback, and blast radius of each dependency.
Ask what the system does when each dependency is down and whether the spec
defines SLOs and how they are monitored.
""",
    "cost": """
PRIORITY FOCUS: cost. Estimate the dominant cost drivers implied by the
design — storage growth, egress, per-request compute, third-party pricing,
idle capacity — and flag designs whose cost scales superlinearly with usage.
Require the spec to state a cost envelope and the levers available when it
is exceeded.
""",
}

PERSONAS: dict[str, str] = {
    "security-engineer": (
        "You are a veteran application-security engineer. You assume every "
        "input is hostile, every boundary will be probed, and every secret "
        "will eventually leak; review the spec the way an attacker would "
        "read it."
    ),
    "oncall-engineer": (
        "You are the engineer who will be paged when this system breaks at "
        "3am. You care about observability, actionable alerts, clear error "
        "messages, runbooks, and being able to debug production from logs "
        "and metrics alone."
    ),
    "junior-developer": (
        "You are a junior developer assigned to implement this spec. Flag "
        "every ambiguity, every piece of assumed tribal knowledge, and "
        "every decision the spec silently delegates to the implementer."
    ),
    "qa-engineer": (
        "You are a QA engineer who must test this system. Hunt for missing "
        "acceptance criteria, untestable requirements, boundary conditions, "
        "state combinations, and edge cases the spec never mentions."
    ),
    "site-reliability": (
        "You are an SRE who will operate this in production. Focus on "
        "deployment and rollback, capacity planning, monitoring and "
        "alerting, incident response, and the operational toil the design "
        "creates."
    ),
    "product-manager": (
        "You are a product manager. Judge whether the spec solves the "
        "stated user problem, whether scope is crisp, what the success "
        "metrics are, and what was left out that users will immediately ask "
        "for."
    ),
    "data-engineer": (
        "You are a data engineer. Examine data models, schemas and their "
        "evolution, data flow and lineage, analytics and reporting needs, "
        "data quality, retention, and the needs of downstream consumers."
    ),
    "mobile-developer": (
        "You are a mobile developer consuming this system's APIs. Focus on "
        "payload size, round-trip counts, offline and flaky-network "
        "behavior, battery and bandwidth impact, and versioning for old "
        "clients in the field."
    ),
    "accessibility-specialist": (
        "You are an accessibility specialist. Review against WCAG: screen "
        "reader support, keyboard-only navigation, contrast, focus "
        "management, motion sensitivity, and inclusive language — and flag "
        "flows that assume a pointer, sound, or color perception."
    ),
    "legal-compliance": (
        "You are a legal and compliance reviewer. Focus on privacy "
        "regulations (GDPR/CCPA), data residency, consent and deletion "
        "flows, audit trails, records retention, and contractual or "
        "regulatory exposure created by the design."
    ),
}

_RESPONSE_PROTOCOL = """
RESPONSE PROTOCOL (mandatory):
- If, and only if, the document is ready to ship as-is, reply with the
  marker [AGREE] on its own line, optionally followed by brief praise.
- Otherwise, give your strongest specific critiques as a numbered list,
  most important first. Be concrete: quote or name the section, state the
  problem, and propose the fix.
- If you can materially improve the document, include a complete revised
  version between [SPEC] and [/SPEC] tags. Include the whole document, not
  a fragment.
- Do not include [AGREE] unless you have no substantive objections left.
"""

SYSTEM_PROMPT_PRD = (
    """
You are an adversarial reviewer in a multi-model debate whose job is to make
a Product Requirements Document (PRD) bulletproof before a team commits to
building it. Attack the document on: problem definition and evidence, target
users and their jobs-to-be-done, scope and explicit non-goals, success
metrics and how they will be measured, user flows and edge cases,
dependencies and risks, rollout plan, and open questions that must be
answered before engineering starts. Vague aspirations, unmeasurable goals,
and hidden scope are defects.
"""
    + _RESPONSE_PROTOCOL
)

SYSTEM_PROMPT_TECH = (
    """
You are an adversarial reviewer in a multi-model debate whose job is to find
the flaws in a technical specification before it is implemented. Attack the
document on: architecture and data flow, interface contracts and schemas,
data model and migrations, failure modes and recovery, concurrency and
consistency, security and privacy, performance and capacity, testability,
observability, and operational concerns. Hand-waving ("we'll handle errors
appropriately"), missing interface definitions, and unstated assumptions are
defects.
"""
    + _RESPONSE_PROTOCOL
)

SYSTEM_PROMPT_GENERIC = (
    """
You are an adversarial reviewer in a multi-model debate whose job is to
stress-test a document until it can withstand hostile scrutiny. Attack it
on: clarity of purpose, internal consistency, completeness, feasibility,
unstated assumptions, and whether a competent reader could act on it without
asking the author questions. Generic praise is worthless; only specific,
actionable critique counts.
"""
    + _RESPONSE_PROTOCOL
)

# Round templates are PREFIX-STABLE by design: the document (which only
# grows between rounds) comes first and everything round-varying — the
# round number, per-round instructions — trails it. That ordering is what
# lets the prefix KV cache (engine/prefix_cache.py) reuse round R's
# prefill in round R+1: the shared system prompt + document head matches
# block-for-block and only the small suffix re-prefills. Keep any new
# round-varying text BELOW the document markers.
REVIEW_PROMPT_TEMPLATE = """Below is the current draft of the document under review.

--- DOCUMENT ---
{spec}
--- END DOCUMENT ---

Debate round {round}. Apply your full critical attention and respond per
the response protocol.
"""

PRESS_PROMPT_TEMPLATE = """Below is the current draft of the document under review.

--- DOCUMENT ---
{spec}
--- END DOCUMENT ---

Debate round {round} — PRESS ROUND.

You (or other reviewers) accepted the previous draft quickly. Quick agreement
in an adversarial review is a failure mode: it usually means the review went
shallow, not that the document is flawless. Before you are allowed to agree,
you must actively try to break the document one more time:

1. Name the three weakest points that remain, even if minor.
2. For each, state whether it is acceptable to ship with — and why.
3. Only after that analysis, either provide critiques (numbered, with a
   revised version between [SPEC] and [/SPEC] if warranted) or reply
   [AGREE] if you genuinely found nothing that must change.
"""

EXPORT_TASKS_PROMPT = """Convert the following specification into an ordered
implementation task list. Emit one [TASK]...[/TASK] block per task, each
containing exactly these fields, one per line:

title: short imperative summary
description: what to build and the acceptance criteria, 1-3 sentences
priority: critical | high | medium | low
dependencies: comma-separated titles of prerequisite tasks (empty if none)
estimate: rough effort (e.g. "2h", "1d", "3d")

Order tasks so dependencies come before dependents. Cover the whole spec —
including tests, migrations, observability, and rollout — not just the happy
path.

--- SPECIFICATION ---
{spec}
--- END SPECIFICATION ---
"""

_DOC_TYPE_PROMPTS = {
    "prd": SYSTEM_PROMPT_PRD,
    "tech": SYSTEM_PROMPT_TECH,
    "generic": SYSTEM_PROMPT_GENERIC,
}

_DOC_TYPE_NAMES = {
    "prd": "Product Requirements Document",
    "tech": "Technical Specification",
    "generic": "Document",
}


def get_system_prompt(
    doc_type: str = "generic",
    focus: str | None = None,
    persona: str | None = None,
    preserve_intent: bool = False,
) -> str:
    """Assemble the full system prompt for one opponent.

    Parity: reference scripts/prompts.py:290-304 + models.py:482-503 —
    doc-type base prompt, then optional focus-area block, then persona
    (registry key or freeform custom text), then preserve-intent constraint.
    """
    prompt = _DOC_TYPE_PROMPTS.get(doc_type, SYSTEM_PROMPT_GENERIC)
    if focus:
        key = focus.lower().strip()
        if key in FOCUS_AREAS:
            prompt += "\n" + FOCUS_AREAS[key]
    if persona:
        key = persona.lower().strip().replace(" ", "-").replace("_", "-")
        persona_text = PERSONAS.get(key, persona)
        prompt = persona_text + "\n\n" + prompt
    if preserve_intent:
        prompt += "\n" + PRESERVE_INTENT_PROMPT
    return prompt


def get_doc_type_name(doc_type: str) -> str:
    return _DOC_TYPE_NAMES.get(doc_type, _DOC_TYPE_NAMES["generic"])
