"""Session persistence and per-round spec checkpoints.

Behavioral parity with reference scripts/session.py:
- ``SessionState`` serialized as JSON under a sessions dir, written after
  every round with ``round`` advanced and history appended
  (session.py:16-39, debate.py:865-878).
- ``--resume`` restores all debate arguments and the current spec
  (session.py:41-50, debate.py:753-773).
- Per-round spec snapshots under ``./.adversarial-spec-checkpoints/`` for
  manual rollback (session.py:74-82).
- Path-traversal guard on session ids (session.py:37-38, 45-46).

Durability (docs/resilience.md "Durability and recovery"): every write
here goes through ``obs.atomic_write_text`` (pid-suffixed tmp +
``os.replace``) — a crash mid-write leaves the previous complete file
intact, never a torn one, because ``--resume`` depends on this file. A
session file that is nonetheless corrupt on disk (torn by an older
writer, bad storage) is QUARANTINED to ``<name>.corrupt`` on load
(DiskStore's discipline, engine/kvtier.py) and surfaced as a clear
``CorruptSessionState`` naming the path and the recovery options,
instead of a raw ``JSONDecodeError`` with no context.

All directories are module-level constants precisely so tests can patch them
(the reference's patch-the-module-constant fixture pattern, SURVEY §4).
``ADVSPEC_SESSIONS_DIR`` overrides the sessions dir for subprocess
harnesses (tools/chaos_run.py --crash, bench.py --mode recover) that
must not touch the operator's real ``~/.config`` state.
"""

from __future__ import annotations

import json
import os
import re
import time
from dataclasses import dataclass, field, asdict
from pathlib import Path

from adversarial_spec_tpu.obs.events import atomic_write_text

SESSIONS_DIR = Path(
    os.environ.get("ADVSPEC_SESSIONS_DIR")
    or Path.home() / ".config" / "adversarial-spec-tpu" / "sessions"
)
CHECKPOINTS_DIR = Path(".adversarial-spec-checkpoints")

_SESSION_ID_RE = re.compile(r"^[A-Za-z0-9._-]+$")


class InvalidSessionId(ValueError):
    pass


class CorruptSessionState(ValueError):
    """A session file failed to parse; it has been quarantined aside."""


def _validate_session_id(session_id: str) -> str:
    if not session_id or not _SESSION_ID_RE.match(session_id):
        raise InvalidSessionId(
            f"invalid session id {session_id!r}: only letters, digits, "
            "dot, underscore and dash are allowed"
        )
    return session_id


@dataclass
class SessionState:
    """Resumable debate state: spec + round + all debate arguments."""

    session_id: str
    spec: str = ""
    round: int = 1
    doc_type: str = "generic"
    models: list[str] = field(default_factory=list)
    focus: str | None = None
    persona: str | None = None
    preserve_intent: bool = False
    created_at: float = 0.0
    updated_at: float = 0.0
    # Per-round history: [{"round", "all_agreed", "models": {name: agreed}}].
    history: list[dict] = field(default_factory=list)
    # Circuit-breaker snapshot (resilience/breaker.py:snapshot_for_resume):
    # one CLI invocation is one round, so open circuits must ride the
    # session to skip persistently failing models on the NEXT round.
    breakers: dict = field(default_factory=dict)

    def save(self, sessions_dir: Path | None = None) -> Path:
        directory = Path(sessions_dir or SESSIONS_DIR)
        _validate_session_id(self.session_id)
        directory.mkdir(parents=True, exist_ok=True)
        now = time.time()
        if not self.created_at:
            self.created_at = now
        self.updated_at = now
        path = directory / f"{self.session_id}.json"
        # Atomic: a crash anywhere in this write leaves the previous
        # complete session file (the thing --resume replays) intact and
        # no orphan tmp behind — the same crash-window contract
        # --metrics-out and the events JSONL already honor.
        atomic_write_text(str(path), json.dumps(asdict(self), indent=2))
        return path

    @classmethod
    def load(
        cls, session_id: str, sessions_dir: Path | None = None
    ) -> "SessionState":
        directory = Path(sessions_dir or SESSIONS_DIR)
        _validate_session_id(session_id)
        path = directory / f"{session_id}.json"
        try:
            data = json.loads(path.read_text())
            if not isinstance(data, dict):
                raise ValueError(
                    f"top-level JSON is {type(data).__name__}, not an object"
                )
        except (json.JSONDecodeError, UnicodeDecodeError, ValueError) as e:
            # Quarantine, then fail with the path and a way forward —
            # corruption in ANY shape (truncated JSON, non-UTF-8 bytes
            # from bad storage, a rewritten non-object) must not
            # present as a stack trace, and leaving the file in place
            # would make every retry hit the same wall (DiskStore's
            # corrupt-entry discipline).
            quarantine = path.with_name(path.name + ".corrupt")
            try:
                os.replace(path, quarantine)
                where = f"quarantined to {quarantine}"
            except OSError:
                where = "quarantine failed; file left in place"
            raise CorruptSessionState(
                f"session file {path} is corrupt ({e}); {where}. "
                f"Start over with --session {session_id}, or restore a "
                f"spec snapshot from {CHECKPOINTS_DIR}/"
            ) from e
        known = {f for f in cls.__dataclass_fields__}
        return cls(**{k: v for k, v in data.items() if k in known})

    @classmethod
    def list_sessions(cls, sessions_dir: Path | None = None) -> list[dict]:
        """Summaries of saved sessions, most recently updated first."""
        directory = Path(sessions_dir or SESSIONS_DIR)
        if not directory.is_dir():
            return []
        sessions = []
        for path in directory.glob("*.json"):
            try:
                data = json.loads(path.read_text())
            except (json.JSONDecodeError, OSError):
                continue
            sessions.append(
                {
                    "session_id": data.get("session_id", path.stem),
                    "round": data.get("round", 1),
                    "doc_type": data.get("doc_type", "generic"),
                    "models": data.get("models", []),
                    "updated_at": data.get("updated_at", 0.0),
                }
            )
        sessions.sort(key=lambda s: s["updated_at"], reverse=True)
        return sessions


def save_checkpoint(
    spec: str,
    round_num: int,
    session_id: str | None = None,
    checkpoints_dir: Path | None = None,
) -> Path:
    """Snapshot the spec for this round to a rollback file."""
    directory = Path(checkpoints_dir or CHECKPOINTS_DIR)
    directory.mkdir(parents=True, exist_ok=True)
    prefix = f"{_validate_session_id(session_id)}-" if session_id else ""
    path = directory / f"{prefix}round-{round_num}.md"
    # Atomic like the session file: the checkpoint is the manual
    # rollback of last resort — a crash mid-write must not destroy it.
    atomic_write_text(str(path), spec)
    return path
