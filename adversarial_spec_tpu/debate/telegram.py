"""Telegram human-in-the-loop channel.

Behavioral parity with reference scripts/telegram_bot.py: raw-urllib Bot API
client (api_call :47-75, 30 s timeout), message splitting at the 4096-char
Telegram limit preferring paragraph/line/space boundaries (:97-133),
send_long_message with inter-chunk pacing (:136-156), long-poll feedback
window sliced into ≤30 s getUpdates calls (:175-220), chat-id discovery
(:223-263), and a standalone CLI (setup/send/poll/notify :266-439).

Config comes from TELEGRAM_BOT_TOKEN / TELEGRAM_CHAT_ID env vars (:42-44).
Network errors never propagate into the debate round — callers treat this
channel as best-effort.
"""

from __future__ import annotations

import json
import os
import sys
import time
import urllib.parse
import urllib.request
from dataclasses import dataclass

API_BASE = "https://api.telegram.org"
MAX_MESSAGE_LEN = 4096
API_TIMEOUT_S = 30
CHUNK_PACING_S = 0.5
POLL_SLICE_S = 25


@dataclass(frozen=True)
class TelegramConfig:
    token: str
    chat_id: str


def get_config() -> TelegramConfig | None:
    token = os.environ.get("TELEGRAM_BOT_TOKEN", "").strip()
    chat_id = os.environ.get("TELEGRAM_CHAT_ID", "").strip()
    if not token or not chat_id:
        return None
    return TelegramConfig(token=token, chat_id=chat_id)


def api_call(token: str, method: str, params: dict | None = None) -> dict:
    """POST one Bot API method; returns the decoded ``result`` payload."""
    url = f"{API_BASE}/bot{token}/{method}"
    data = urllib.parse.urlencode(params or {}).encode()
    req = urllib.request.Request(url, data=data)
    with urllib.request.urlopen(req, timeout=API_TIMEOUT_S) as resp:
        payload = json.loads(resp.read().decode())
    if not payload.get("ok"):
        raise RuntimeError(f"Telegram API {method} failed: {payload}")
    return payload.get("result", {})


def split_message(text: str, limit: int = MAX_MESSAGE_LEN) -> list[str]:
    """Split into ≤limit chunks, preferring paragraph > line > space breaks.

    Parity: reference telegram_bot.py:97-133 — a break point is only taken
    if it lands in the second half of the window so pathological inputs
    cannot degrade into tiny chunks.
    """
    if len(text) <= limit:
        return [text] if text else []
    chunks = []
    rest = text
    while len(rest) > limit:
        window = rest[:limit]
        cut = -1
        for sep in ("\n\n", "\n", " "):
            idx = window.rfind(sep)
            if idx > limit // 2:
                cut = idx + len(sep)
                break
        if cut == -1:
            cut = limit
        chunks.append(rest[:cut].rstrip("\n"))
        rest = rest[cut:]
    if rest:
        chunks.append(rest)
    return chunks


def send_message(config: TelegramConfig, text: str) -> None:
    api_call(
        config.token,
        "sendMessage",
        {"chat_id": config.chat_id, "text": text},
    )


def send_long_message(
    config: TelegramConfig, text: str, sleep=time.sleep
) -> int:
    """Send text in order as ≤4096-char chunks with pacing; returns count."""
    chunks = split_message(text)
    for i, chunk in enumerate(chunks):
        send_message(config, chunk)
        if i < len(chunks) - 1:
            sleep(CHUNK_PACING_S)
    return len(chunks)


def get_last_update_id(config: TelegramConfig) -> int:
    """Highest update id seen so far (so polling only sees new replies)."""
    updates = api_call(config.token, "getUpdates", {"timeout": 0})
    if not updates:
        return 0
    return max(u.get("update_id", 0) for u in updates)


def poll_for_reply(
    config: TelegramConfig,
    after_update_id: int,
    timeout_s: int,
    clock=time.monotonic,
) -> str | None:
    """Wait up to timeout_s for a text reply in the configured chat.

    Long-polls getUpdates in ≤POLL_SLICE_S slices (parity: reference
    :175-220); returns the first matching message text, or None on timeout.
    """
    deadline = clock() + timeout_s
    offset = after_update_id + 1
    while clock() < deadline:
        slice_s = min(POLL_SLICE_S, max(1, int(deadline - clock())))
        updates = api_call(
            config.token,
            "getUpdates",
            {"timeout": slice_s, "offset": offset},
        )
        for u in updates:
            offset = max(offset, u.get("update_id", 0) + 1)
            msg = u.get("message") or {}
            chat = str((msg.get("chat") or {}).get("id", ""))
            text = msg.get("text", "")
            if chat == str(config.chat_id) and text:
                return text
    return None


def discover_chat_id(token: str) -> str | None:
    """Find the chat id of the most recent message sent to the bot."""
    updates = api_call(token, "getUpdates", {"timeout": 0})
    for u in reversed(updates):
        msg = u.get("message") or {}
        chat = msg.get("chat") or {}
        if "id" in chat:
            return str(chat["id"])
    return None


def format_round_summary(result, total_cost: float = 0.0) -> str:
    """Human-readable per-round summary for the notification message."""
    from adversarial_spec_tpu.debate.parsing import get_critique_summary

    lines = [f"Debate round {result.round_num}:"]
    for r in result.responses:
        if r.error:
            lines.append(f"  ✗ {r.model}: ERROR {r.error}")
        elif r.agreed:
            lines.append(f"  ✓ {r.model}: AGREE")
        else:
            lines.append(
                f"  … {r.model}: {get_critique_summary(r.critique, 120)}"
            )
    lines.append(
        "All models agree!" if result.all_agreed else "Debate continues."
    )
    if total_cost:
        lines.append(f"Cost so far: ${total_cost:.4f}")
    return "\n".join(lines)


def notify_round(
    config: TelegramConfig,
    result,
    total_cost: float = 0.0,
    feedback_timeout: int = 0,
) -> str | None:
    """Send the round summary; optionally poll for human feedback."""
    last_id = get_last_update_id(config) if feedback_timeout > 0 else 0
    send_long_message(config, format_round_summary(result, total_cost))
    if feedback_timeout > 0:
        send_message(
            config,
            f"Reply within {feedback_timeout}s to inject feedback into the "
            "next round.",
        )
        return poll_for_reply(config, last_id, feedback_timeout)
    return None


def _cli(argv: list[str]) -> int:
    """Standalone utility: setup | send | poll | notify (reference :266-439)."""
    if not argv:
        print("usage: telegram {setup|send|poll} ...", file=sys.stderr)
        return 2
    cmd = argv[0]
    if cmd == "setup":
        token = os.environ.get("TELEGRAM_BOT_TOKEN", "").strip()
        if not token:
            print("set TELEGRAM_BOT_TOKEN first", file=sys.stderr)
            return 2
        chat_id = discover_chat_id(token)
        if chat_id is None:
            print(
                "no messages found — send your bot a message, then rerun",
                file=sys.stderr,
            )
            return 1
        print(f"export TELEGRAM_CHAT_ID={chat_id}")
        return 0
    config = get_config()
    if config is None:
        print(
            "error: set TELEGRAM_BOT_TOKEN and TELEGRAM_CHAT_ID",
            file=sys.stderr,
        )
        return 2
    if cmd == "send":
        text = " ".join(argv[1:]) or sys.stdin.read()
        send_long_message(config, text)
        return 0
    if cmd == "poll":
        timeout_s = int(argv[1]) if len(argv) > 1 else 60
        reply = poll_for_reply(config, get_last_update_id(config), timeout_s)
        if reply is None:
            print("(no reply)", file=sys.stderr)
            return 1
        print(reply)
        return 0
    if cmd == "notify":
        # Send a message and (optionally) wait for a reply in one step —
        # the round-notification primitive as a standalone command.
        # First arg is the timeout if numeric; otherwise it's message text
        # and no reply is awaited (mirrors `send`'s calling convention).
        rest = argv[1:]
        timeout_s = 0
        if rest and rest[0].isdigit():
            timeout_s = int(rest[0])
            rest = rest[1:]
        text = " ".join(rest) or sys.stdin.read()
        last_id = get_last_update_id(config) if timeout_s > 0 else 0
        send_long_message(config, text)
        if timeout_s > 0:
            reply = poll_for_reply(config, last_id, timeout_s)
            if reply is None:
                print("(no reply)", file=sys.stderr)
                return 1
            print(reply)
        return 0
    print(f"unknown subcommand {cmd!r}", file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(_cli(sys.argv[1:]))
