"""Core result types for a debate round.

Behavioral parity: the reference models a per-opponent result as a
``ModelResponse`` dataclass (reference scripts/models.py:67-78) carrying the
model id, raw critique text, the agreement bit, an optional revised spec, an
optional error string, and token usage. We keep that surface but make usage a
first-class value (``Usage``) returned from pure calls and reduced at the
caller, instead of the reference's mutable module-global cost tracker
(scripts/models.py:127) which is racily updated from worker threads.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from adversarial_spec_tpu.debate.usage import Usage
from adversarial_spec_tpu.utils.tracing import Tracer


@dataclass
class ModelResponse:
    """Result of one opponent model's critique of the spec."""

    model: str
    critique: str = ""
    agreed: bool = False
    revised_spec: str | None = None
    error: str | None = None
    usage: Usage = field(default_factory=Usage)
    latency_s: float = 0.0
    # This opponent request's causal-trace span (obs/trace.py): joins
    # the CLI report row to the flight-recorder events and the
    # tools/trace_view.py waterfall for this exact request.
    span_id: str = ""

    @property
    def ok(self) -> bool:
        return self.error is None

    def to_dict(self) -> dict:
        return {
            "model": self.model,
            "agreed": self.agreed,
            "critique": self.critique,
            "revised_spec": self.revised_spec,
            "error": self.error,
            "usage": self.usage.to_dict(),
            "latency_s": round(self.latency_s, 3),
            "span_id": self.span_id,
        }


@dataclass
class RoundResult:
    """Aggregate of one critique round across all opponents.

    ``all_agreed`` counts only successful responses, matching the reference's
    convergence rule (scripts/debate.py:852-853): failed models degrade the
    round gracefully rather than blocking agreement.
    """

    responses: list[ModelResponse]
    round_num: int = 1
    # The debate layer's own span tracer (per-opponent chat walls,
    # retry/backoff accounting); the CLI merges it into the round-level
    # tracer via ``Tracer.merge`` so one report nests both layers.
    tracer: Tracer = field(default_factory=Tracer)
    # The round's causal trace id (obs/trace.py): every flight-recorder
    # event this round caused carries it.
    trace_id: str = ""

    @property
    def successful(self) -> list[ModelResponse]:
        return [r for r in self.responses if r.ok]

    @property
    def failed(self) -> list[ModelResponse]:
        return [r for r in self.responses if not r.ok]

    @property
    def all_agreed(self) -> bool:
        ok = self.successful
        return bool(ok) and all(r.agreed for r in ok)

    @property
    def total_usage(self) -> Usage:
        total = Usage()
        for r in self.responses:
            total = total + r.usage
        return total
