"""Token-usage and cost accounting.

Behavioral parity: the reference tracks per-model dollar cost in a
``CostTracker`` keyed by a static price table (scripts/models.py:81-127,
scripts/providers.py:18-45), surfaced via ``--show-cost`` and the ``--json``
output object. Local TPU models have no per-token dollar price, so the primary
currency here is tokens and device-seconds; a price table is still supported so
that mock/remote-style models report dollars and the JSON schema keeps the
reference's cost block shape.

Design departure (deliberate): the reference mutates one module-global tracker
from ThreadPoolExecutor worker threads with unsynchronized ``+=`` (a latent
lost-update race, scripts/models.py:90-107 under :699). Here ``Usage`` is an
immutable-ish value returned by each engine call; the caller folds them into a
``CostTracker`` single-threaded. This is also the JAX-idiomatic shape: pure
functions returning values, reduction at the caller.
"""

from __future__ import annotations

from dataclasses import dataclass, field

# Per-1M-token (input, output) dollar prices. TPU-local models cost $0 —
# their "cost" is device time, reported separately. The mock provider uses a
# nonzero price so cost-path logic stays exercised in CPU-only CI.
MODEL_COSTS: dict[str, tuple[float, float]] = {
    "mock://": (1.0, 2.0),
    "tpu://": (0.0, 0.0),
}
DEFAULT_COST: tuple[float, float] = (0.0, 0.0)


def model_cost_rates(model: str) -> tuple[float, float]:
    """Longest-prefix lookup so families share a price entry."""
    best = DEFAULT_COST
    best_len = -1
    for prefix, rates in MODEL_COSTS.items():
        if model.startswith(prefix) and len(prefix) > best_len:
            best, best_len = rates, len(prefix)
    return best


@dataclass
class Usage:
    """Token and time accounting for one model call (or a sum of calls)."""

    input_tokens: int = 0
    output_tokens: int = 0
    # Wall-clock seconds spent inside the engine (prefill + decode).
    device_time_s: float = 0.0
    # Decode-only throughput bookkeeping for the north-star metric.
    decode_tokens: int = 0
    decode_time_s: float = 0.0
    # Prompt tokens served from the prefix KV cache (subset of
    # input_tokens) and this request's own prefill wall-clock — the
    # per-request view of the cache's effect (engine/prefix_cache.py).
    cached_tokens: int = 0
    prefill_time_s: float = 0.0

    @property
    def total_tokens(self) -> int:
        return self.input_tokens + self.output_tokens

    def cost_for(self, model: str) -> float:
        in_rate, out_rate = model_cost_rates(model)
        return (self.input_tokens * in_rate + self.output_tokens * out_rate) / 1e6

    def __add__(self, other: "Usage") -> "Usage":
        return Usage(
            input_tokens=self.input_tokens + other.input_tokens,
            output_tokens=self.output_tokens + other.output_tokens,
            device_time_s=self.device_time_s + other.device_time_s,
            decode_tokens=self.decode_tokens + other.decode_tokens,
            decode_time_s=self.decode_time_s + other.decode_time_s,
            cached_tokens=self.cached_tokens + other.cached_tokens,
            prefill_time_s=self.prefill_time_s + other.prefill_time_s,
        )

    def to_dict(self) -> dict:
        return {
            "input_tokens": self.input_tokens,
            "output_tokens": self.output_tokens,
            "total_tokens": self.total_tokens,
            "cached_tokens": self.cached_tokens,
            "device_time_s": round(self.device_time_s, 4),
            "prefill_time_s": round(self.prefill_time_s, 4),
            "decode_time_s": round(self.decode_time_s, 4),
        }


@dataclass
class CostTracker:
    """Caller-side reduction of per-model usage into a cost report.

    Output shape mirrors the reference's JSON cost block
    (scripts/debate.py:930-937): per-model input/output tokens and dollars,
    plus totals.
    """

    by_model: dict[str, Usage] = field(default_factory=dict)

    def add(self, model: str, usage: Usage) -> None:
        prev = self.by_model.get(model, Usage())
        self.by_model[model] = prev + usage

    @property
    def total_usage(self) -> Usage:
        total = Usage()
        for u in self.by_model.values():
            total = total + u
        return total

    @property
    def total_cost(self) -> float:
        return sum(u.cost_for(m) for m, u in self.by_model.items())

    def tokens_per_sec(self, model: str | None = None) -> float:
        """Decode throughput (the north-star metric's numerator)."""
        u = self.by_model.get(model, Usage()) if model else self.total_usage
        return u.decode_tokens / u.decode_time_s if u.decode_time_s > 0 else 0.0

    def report(self) -> dict:
        return {
            "models": {
                m: {**u.to_dict(), "cost_usd": round(u.cost_for(m), 6)}
                for m, u in sorted(self.by_model.items())
            },
            "total_tokens": self.total_usage.total_tokens,
            "total_cost_usd": round(self.total_cost, 6),
            "total_device_time_s": round(self.total_usage.device_time_s, 4),
        }

    def format_text(self) -> str:
        lines = ["Cost summary:"]
        for m, u in sorted(self.by_model.items()):
            lines.append(
                f"  {m}: {u.input_tokens} in / {u.output_tokens} out"
                f" tokens, ${u.cost_for(m):.4f}"
            )
        lines.append(
            f"  TOTAL: {self.total_usage.total_tokens} tokens,"
            f" ${self.total_cost:.4f}"
        )
        return "\n".join(lines)
