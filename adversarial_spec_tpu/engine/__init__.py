"""Inference engines.

This package replaces the reference's L1 transport layer (litellm HTTP to
remote APIs, scripts/models.py:607-678; CLI subprocesses, :274-454) with
in-process engines behind one interface:

- ``mock://``  — scripted engine for tests/CI and BASELINE config 1.
- ``tpu://``   — JAX/XLA engine: HF checkpoints → pjit-sharded params →
  batched autoregressive decode on the TPU mesh.

The prefix-dispatch seam mirrors the reference's ``model.startswith(prefix)``
provider routing (scripts/models.py:506-558) — identified in SURVEY §5 as the
cleanest extension point in the reference design.
"""

from adversarial_spec_tpu.engine.types import (
    ChatRequest,
    Completion,
    SamplingParams,
    Engine,
)
from adversarial_spec_tpu.engine.dispatch import get_engine, clear_engine_cache

__all__ = [
    "ChatRequest",
    "Completion",
    "SamplingParams",
    "Engine",
    "get_engine",
    "clear_engine_cache",
]
