"""Native checkpoint cache: Orbax save/load of converted param pytrees.

SURVEY §5 (checkpoint/resume): the reference's model-side "checkpointing"
obligation is checkpoint *loading* — here HF safetensors convert once into
the layer-stacked native layout and are cached via Orbax, so subsequent
engine starts restore directly into the target shardings (no per-layer
stacking, no transposes, no torch-layout work). The debate-state tier
(sessions/round snapshots, debate/session.py) is unchanged and independent.

Cache location: ``<checkpoint_dir>/.native-cache/<fingerprint>`` beside the
HF checkpoint, fingerprinted by family/size/dtype/quant — plus the
transposed-head flag when the config ties embeddings (the flag adds an
``lm_head_t`` leaf, i.e. changes the pytree layout) — so neither a config
change nor an env toggle ever reads a stale layout.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import uuid
from pathlib import Path

import jax


def _source_stat(checkpoint: str) -> list:
    """Cheap identity of the source weights: (name, size, mtime_ns) of
    every safetensors/index file — no content read. Replacing the weights
    in place (fine-tune update) therefore changes the fingerprint."""
    ckpt = Path(checkpoint)
    entries = []
    for pattern in ("*.safetensors", "*.safetensors.index.json"):
        for f in sorted(ckpt.glob(pattern)):
            st = f.stat()
            entries.append([f.name, st.st_size, st.st_mtime_ns])
    return entries


def transposed_head_flag() -> bool:
    """ONE reading of ADVSPEC_TRANSPOSED_HEAD (default on) — the cache
    fingerprint, the restore template, and the HF loader must all parse
    it identically or caches thrash (save one layout, template another)."""
    return os.environ.get("ADVSPEC_TRANSPOSED_HEAD", "1") != "0"


def cache_dir_for(
    checkpoint: str,
    family: str,
    size: str,
    dtype: str,
    quant: str = "",
    tied_embeddings: bool = False,
) -> Path:
    # For tied-embedding configs the transposed-head flag changes the
    # pytree LAYOUT (extra lm_head_t leaf), so it must be part of the
    # fingerprint: toggling ADVSPEC_TRANSPOSED_HEAD must select a
    # different cache dir, not thrash or silently serve the old layout.
    # Untied configs have identical layout under both flag values — keep
    # their fingerprint flag-independent (no spurious reconversion).
    t_head = tied_embeddings and transposed_head_flag()
    fingerprint = hashlib.sha1(
        json.dumps(
            [family, size, dtype, quant, int(t_head), _source_stat(checkpoint)]
        ).encode()
    ).hexdigest()[:12]
    return Path(checkpoint) / ".native-cache" / fingerprint


def _sweep_stale_tmp(cache_parent: Path, max_age_s: float = 86400.0) -> None:
    """Remove abandoned writer tmp dirs (``*.tmp-<pid>-<hex>``).

    A process killed mid-save (daemon prefetch thread at interpreter
    exit, OOM-kill, tunnel wedge) leaves its multi-GB tmp dir behind —
    its finally never runs. Each new writer sweeps siblings older than
    a day: old enough that no live writer (saves take minutes, not
    days) can be holding them. Best-effort; errors never block a save.
    """
    import time as _time

    try:
        now = _time.time()
        for entry in cache_parent.iterdir():
            if ".tmp-" in entry.name and entry.is_dir():
                try:
                    if now - entry.stat().st_mtime > max_age_s:
                        shutil.rmtree(entry, ignore_errors=True)
                except OSError:
                    pass
    except OSError:
        pass


def save_native(params, cache_dir: Path) -> None:
    """Write the converted pytree atomically.

    Per-writer unique tmp dir + rename: concurrent cold-cache processes
    (multi-opponent CLIs, one process per host on a pod) never see each
    other's partial writes, and whichever rename lands first wins.
    """
    import orbax.checkpoint as ocp

    cache_dir = Path(cache_dir)
    cache_dir.parent.mkdir(parents=True, exist_ok=True)
    _sweep_stale_tmp(cache_dir.parent)
    tmp = cache_dir.with_name(
        f"{cache_dir.name}.tmp-{os.getpid()}-{uuid.uuid4().hex[:6]}"
    )
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(tmp.resolve(), params)
    try:
        tmp.rename(cache_dir)
    except OSError:
        if cache_dir.exists():  # another writer won the race — fine
            shutil.rmtree(tmp, ignore_errors=True)
        else:
            raise


def load_native(cache_dir: Path, like_params):
    """Restore into the shardings/dtypes of ``like_params`` (an abstract
    pytree of jax.ShapeDtypeStruct with shardings is enough)."""
    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckptr:
        return ckptr.restore(Path(cache_dir).resolve(), like_params)


def has_native(cache_dir: Path) -> bool:
    return Path(cache_dir).is_dir()


def abstract_like(params):
    """ShapeDtypeStruct pytree (with shardings) describing ``params``."""
    return jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(
            x.shape, x.dtype, sharding=getattr(x, "sharding", None)
        ),
        params,
    )
