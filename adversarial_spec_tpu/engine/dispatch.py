"""Provider dispatch: model-id prefix → engine instance.

Mirrors the reference's prefix routing (``codex/``, ``gemini-cli/``, else
litellm — scripts/models.py:506-558), which SURVEY §5 calls out as the seam
where ``tpu://`` slots in. Engines are cached: all ``tpu://`` models share one
``TpuEngine`` so co-resident opponents can batch onto one mesh.
"""

from __future__ import annotations

from adversarial_spec_tpu.engine.types import Engine

_ENGINE_CACHE: dict[str, Engine] = {}


def get_engine(model: str) -> Engine:
    """Return the (cached) engine that serves this model id."""
    if model.startswith("mock://"):
        key = "mock"
    elif model.startswith("tpu://"):
        key = "tpu"
    else:
        raise ValueError(
            f"unknown provider for model {model!r}: expected a 'mock://' or "
            "'tpu://' id (remote HTTP providers are intentionally not part "
            "of this framework — register a local checkpoint instead)"
        )
    if key not in _ENGINE_CACHE:
        if key == "mock":
            from adversarial_spec_tpu.engine.mock import MockEngine

            _ENGINE_CACHE[key] = MockEngine()
        else:
            # Deferred import: pulls in jax; mock-only flows never pay it.
            from adversarial_spec_tpu.utils.jaxenv import configure_jax

            configure_jax()
            try:
                from adversarial_spec_tpu.engine.tpu import TpuEngine
            except ImportError as e:
                raise ValueError(
                    f"tpu:// engine unavailable in this installation: {e}"
                ) from e
            _ENGINE_CACHE[key] = TpuEngine()
    return _ENGINE_CACHE[key]


def clear_engine_cache() -> None:
    """Test hook: drop cached engines (and their loaded weights)."""
    _ENGINE_CACHE.clear()
