"""Provider dispatch: model-id prefix → engine instance.

Mirrors the reference's prefix routing (``codex/``, ``gemini-cli/``, else
litellm — scripts/models.py:506-558), which SURVEY §5 calls out as the seam
where ``tpu://`` slots in. Engines are cached: all ``tpu://`` models share one
``TpuEngine`` so co-resident opponents can batch onto one mesh.
"""

from __future__ import annotations

from adversarial_spec_tpu.engine.types import Engine
from adversarial_spec_tpu.resilience import lockdep as lockdep_mod

_ENGINE_CACHE: dict[str, Engine] = {}
# The serve daemon resolves engines from concurrent debate threads;
# double-building a provider's engine (two allocators, two weight
# sets) must not be a race outcome.
_CACHE_LOCK = lockdep_mod.make_lock("dispatch._CACHE_LOCK")


def _provider_key(model: str) -> str:
    if model.startswith("mock://"):
        return "mock"
    if model.startswith("tpu://"):
        return "tpu"
    raise ValueError(
        f"unknown provider for model {model!r}: expected a 'mock://' or "
        "'tpu://' id (remote HTTP providers are intentionally not part "
        "of this framework — register a local checkpoint instead)"
    )


def new_engine(model: str) -> Engine:
    """A FRESH engine instance for this model's provider — the replica
    lifecycle seam (fleet/replica.py): each fleet replica must own its
    engine (allocator, prefix cache, batchers), so replicas build here
    instead of sharing the process cache below."""
    key = _provider_key(model)
    if key == "mock":
        from adversarial_spec_tpu.engine.mock import MockEngine

        return MockEngine()
    # Deferred import: pulls in jax; mock-only flows never pay it.
    from adversarial_spec_tpu.utils.jaxenv import configure_jax

    configure_jax()
    try:
        from adversarial_spec_tpu.engine.tpu import TpuEngine
    except ImportError as e:
        raise ValueError(
            f"tpu:// engine unavailable in this installation: {e}"
        ) from e
    return TpuEngine()


def get_engine(model: str) -> Engine:
    """Return the engine that serves this model id: the process fleet
    (one FleetEngine over N replicas) when the fleet is armed, else
    the cached single engine per provider — all ``tpu://`` models
    share one ``TpuEngine`` so co-resident opponents can batch onto
    one mesh. While the serve daemon is up, the result is additionally
    wrapped by the scheduler gate (serve/gate.py): same Engine
    protocol, but chat calls interleave fair-share with every other
    debate's — the round driver cannot tell, which is the point."""
    from adversarial_spec_tpu import fleet as fleet_mod
    from adversarial_spec_tpu.serve import gate as serve_gate

    key = _provider_key(model)  # validate the id either way
    if fleet_mod.armed():
        return serve_gate.wrap(fleet_mod.fleet_engine())
    with _CACHE_LOCK:
        if key not in _ENGINE_CACHE:
            _ENGINE_CACHE[key] = new_engine(model)
        engine = _ENGINE_CACHE[key]
    return serve_gate.wrap(engine)


def cached_engines() -> list[Engine]:
    """The process's live inner engines (no gate wrappers) — the serve
    daemon's ``check`` op walks these for allocator/tier invariants."""
    with _CACHE_LOCK:
        return list(_ENGINE_CACHE.values())


def clear_engine_cache() -> None:
    """Test hook: drop cached engines (and their loaded weights) and
    tear down the process fleet."""
    from adversarial_spec_tpu import fleet as fleet_mod

    _ENGINE_CACHE.clear()
    fleet_mod.shutdown_fleet()
