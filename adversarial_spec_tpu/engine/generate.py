"""Batched autoregressive generation: jitted prefill + chunked decode.

Execution model (TPU-first, SURVEY §3.1 "TPU mapping" — the reference's
network boundary becomes a device-program dispatch; its per-model retry hot
loop becomes this decode loop):

- **Left-padded static batches.** N opponents' prompts are left-padded to a
  shared bucketed length, so every row's KV lands at the same slot index
  (one ``dynamic_update_slice`` per layer, no per-row scatter) and the last
  prompt logit is always at slot ``S-1``. Bucketing (powers of two) bounds
  the number of compiled prefill programs.
- **Prefill** is one jitted forward over the whole padded prompt (MXU-sized
  matmuls), returning the first sampled token.
- **Decode** runs as a ``lax.while_loop`` of single-token steps *inside*
  jit, emitted in host-level chunks of ``DECODE_CHUNK`` steps: the loop
  early-exits when every row hit EOS, and the host checks the wall-clock
  budget between chunks (the enforcement point for SamplingParams.timeout_s
  — an XLA program cannot be interrupted mid-flight).

The same code path serves 1 opponent on 1 chip and N opponents TP-sharded
over a mesh: sharding enters via the params/cache shardings baked into the
jitted functions (parallel/sharding.py), not via this file's logic.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from adversarial_spec_tpu.engine.sampling import sample_tokens
from adversarial_spec_tpu.models.config import ModelConfig
from adversarial_spec_tpu.models.transformer import (
    Cache,
    Params,
    forward,
    init_cache,
)

DECODE_CHUNK = int(os.environ.get("ADVSPEC_DECODE_CHUNK", "128"))
MIN_BUCKET = 128

# Context-length floor below which decode auto-selects XLA attention over
# the fused Pallas kernel. Round 2's (B, Hkv, T/block) grid lost to XLA at
# short T (v5e: jnp 491 vs kernel 384 tok/s at T=1280 — 160 sequential
# tiny programs), hiding behind a 4096 floor; the round-3 head-folded grid
# (ops/pallas_decode.py: (B, T/block), Hkv-fold fewer programs with
# Hkv-fold larger DMAs) targets exactly that regime, so the default floor
# is now 0 (kernel always) until an on-chip crossover measurement says
# otherwise. Explicit use_pallas_decode=True always wins over this
# heuristic; ADVSPEC_PALLAS_MIN_T restores a floor without a code change.
PALLAS_DECODE_MIN_T = int(os.environ.get("ADVSPEC_PALLAS_MIN_T", "0"))


def _host_fetch(x) -> np.ndarray:
    """Fetch a possibly-sharded device array to every host.

    Single-process: plain np.asarray. Multi-host: dp-sharded arrays span
    non-addressable devices, so gather them to a replicated copy first
    (an ICI/DCN all_gather — once per generate() call, on the two small
    output arrays only, never in the decode loop)."""
    if jax.process_count() > 1 and not x.is_fully_replicated:
        from jax.experimental import multihost_utils

        x = multihost_utils.process_allgather(x, tiled=True)
    return np.asarray(x)


def bucket_length(n: int, minimum: int = MIN_BUCKET) -> int:
    """Next power-of-two bucket ≥ n (≥ minimum) — bounds recompiles."""
    b = minimum
    while b < n:
        b *= 2
    return b


def pad_batch(
    prompt_ids: list[list[int]], pad_id: int, bucket: int | None = None
) -> tuple[np.ndarray, np.ndarray]:
    """Left-pad prompts to a shared bucketed length.

    Returns (tokens [B, S] int32, pad_lens [B] int32).
    """
    max_len = max(len(p) for p in prompt_ids)
    S = bucket if bucket is not None else bucket_length(max_len)
    if S < max_len:
        raise ValueError(f"bucket {S} smaller than longest prompt {max_len}")
    B = len(prompt_ids)
    tokens = np.full((B, S), pad_id, dtype=np.int32)
    pad_lens = np.zeros((B,), dtype=np.int32)
    for i, p in enumerate(prompt_ids):
        tokens[i, S - len(p) :] = np.asarray(p, dtype=np.int32)
        pad_lens[i] = S - len(p)
    return tokens, pad_lens


PREFILL_CHUNK = 1024

# One-time flag for the speculative×paged seam warning below: paged
# generate() has no dense speculative loop (paged speculation is the
# ContinuousBatcher's per-slot draft/verify step), and the combination
# used to be silently ignored.
_PAGED_SPEC_WARNED = False


def _sample_step(
    logits, key, finished, out_buf, step, eos_ids, *, greedy, top_k,
    temperature, top_p, use_top_p=True,
):
    """Shared per-decode-step tail for BOTH cache layouts: sample, record
    EOS (the EOS token itself is kept; finished rows emit 0 thereafter),
    write the output slot. Any change here applies to dense and paged
    decode alike — and must be mirrored in the vectorized emission logic
    of engine/speculative.py (same EOS contract, γ+1 tokens at a time)."""
    key, sub = jax.random.split(key)
    nxt = sample_tokens(
        logits,
        sub,
        greedy=greedy,
        top_k=top_k,
        temperature=temperature,
        top_p=top_p,
        use_top_p=use_top_p,
    )
    is_eos = (nxt[:, None] == eos_ids[None, :]).any(axis=-1)
    nxt = jnp.where(finished, 0, nxt)
    out_buf = jax.lax.dynamic_update_slice(out_buf, nxt[:, None], (0, step))
    return key, nxt, finished | is_eos, out_buf


def _chunk_bound(start_step, chunk, stop_at, max_new):
    return jnp.minimum(jnp.minimum(start_step + chunk, stop_at), max_new)


def _prefill_chunk_impl(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,  # [B, Sc] one left-padded prompt chunk
    pad_lens: jnp.ndarray,  # [B]
    cache: Cache,
    cache_index: jnp.ndarray,  # scalar: slot of this chunk's first token
) -> tuple[Cache, jnp.ndarray]:
    """Run ONE prompt chunk through the model.

    Long prompts (16k-context PRDs, BASELINE config 5) prefill as a
    sequence of fixed-size chunks: activation memory is O(chunk·dim)
    instead of O(S·dim), and every chunk reuses one compiled program.
    Returns (cache, last-position logits [B, vocab]).

    ``prefill_chunk`` is this body jitted (with cache donation); it is
    also inlined — alongside the decode-chunk body — into the
    scheduler's fused prefill+decode program
    (engine/scheduler.py:fused_prefill_decode_chunk), so the admission
    prompt math exists exactly once whether it runs standalone or rides
    a fused step.
    """
    B, Sc = tokens.shape
    T = cache["k"].shape[3]  # [L, B, Hkv, T, D]
    positions = jnp.maximum(
        cache_index + jnp.arange(Sc, dtype=jnp.int32)[None, :]
        - pad_lens[:, None],
        0,
    )
    kv_valid = jnp.arange(T)[None, :] >= pad_lens[:, None]
    logits, cache = forward(
        params,
        cfg,
        tokens,
        positions,
        cache,
        cache_index,
        kv_valid,
        lm_head_last_only=True,
    )
    return cache, logits[:, -1]


# The public jitted entry point — the same body, not a hand-forwarded
# wrapper (see scheduler_decode_chunk for the rationale).
prefill_chunk = partial(
    jax.jit, static_argnames=("cfg",), donate_argnames=("cache",)
)(_prefill_chunk_impl)


@partial(
    jax.jit,
    static_argnames=(
        "cfg",
        "prompt_len",
        "chunk",
        "greedy",
        "top_k",
        "use_top_p",
        "use_pallas_decode",
        "use_pallas_matmul",
        "pallas_interpret",
        "mesh",
    ),
    donate_argnames=("cache", "out_buf"),
)
def decode_chunk_steps(
    params: Params,
    cfg: ModelConfig,
    cache: Cache,
    cur_tokens: jnp.ndarray,  # [B] last sampled token per row
    pad_lens: jnp.ndarray,  # [B]
    finished: jnp.ndarray,  # [B] bool
    out_buf: jnp.ndarray,  # [B, max_new]
    start_step: jnp.ndarray,  # scalar: decode step at chunk entry
    stop_at: jnp.ndarray,  # scalar: decode no further than this step
    eos_ids: jnp.ndarray,  # [E]
    key: jax.Array,
    temperature: jnp.ndarray,
    top_p: jnp.ndarray,
    *,
    prompt_len: int,
    chunk: int,
    greedy: bool,
    top_k: int,
    use_top_p: bool = True,
    use_pallas_decode: bool = False,
    use_pallas_matmul: bool = False,
    pallas_interpret: bool = False,
    mesh=None,
) -> tuple[Cache, jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Up to ``chunk`` single-token decode steps inside one XLA program.

    The while_loop early-exits once every row is finished, so converged
    batches don't burn MXU cycles padding out the chunk.
    """
    B = cur_tokens.shape[0]
    T = cache["k"].shape[3]  # [L, B, Hkv, T, D]
    max_new = out_buf.shape[1]
    kv_base = jnp.arange(T)[None, :] >= pad_lens[:, None]

    def cond(state):
        step, _, _, finished, _, _ = state
        return (
            step < _chunk_bound(start_step, chunk, stop_at, max_new)
        ) & ~finished.all()

    def body(state):
        step, cur, cache, finished, out_buf, key = state
        # ``cur`` is the token at out index step-1, i.e. sequence slot
        # prompt_len + step - 1 (slot prompt_len holds the first sampled
        # token; prompt KV occupies [0, prompt_len)).
        cache_index = prompt_len + step - 1
        positions = (cache_index - pad_lens)[:, None]
        kv_valid = kv_base & (jnp.arange(T)[None, :] <= cache_index)
        logits, cache = forward(
            params,
            cfg,
            cur[:, None],
            positions,
            cache,
            cache_index,
            kv_valid,
            use_pallas_decode=use_pallas_decode,
            use_pallas_matmul=use_pallas_matmul,
            pallas_interpret=pallas_interpret,
            mesh=mesh,
        )
        key, nxt, finished, out_buf = _sample_step(
            logits[:, 0],
            key,
            finished,
            out_buf,
            step,
            eos_ids,
            greedy=greedy,
            top_k=top_k,
            temperature=temperature,
            top_p=top_p,
            use_top_p=use_top_p,
        )
        return step + 1, nxt, cache, finished, out_buf, key

    step, cur, cache, finished, out_buf, key = jax.lax.while_loop(
        cond,
        body,
        (start_step, cur_tokens, cache, finished, out_buf, key),
    )
    return cache, cur, finished, out_buf, step


@dataclass
class GenerateResult:
    tokens: np.ndarray  # [B, <=max_new] generated ids (0 past each row's end)
    n_generated: np.ndarray  # [B] tokens produced per row (incl. EOS)
    prefill_time_s: float
    decode_time_s: float
    decode_tokens: int  # total across batch (north-star numerator)
    timed_out: bool = False


def generate(
    params: Params,
    cfg: ModelConfig,
    prompt_ids: list[list[int]],
    *,
    max_new_tokens: int,
    eos_ids: list[int],
    pad_id: int = 0,
    greedy: bool = False,
    temperature: float = 0.7,
    top_k: int = 0,
    top_p: float = 1.0,
    seed: int | None = None,
    timeout_s: float = 0.0,
    mesh=None,
    use_pallas_decode: bool | None = None,
    use_pallas_matmul: bool | None = None,
    share_prefix: bool = True,
    paged: bool = False,
    page_size: int = 128,
    speculative: bool | None = None,
    kv_dtype: str = "",
) -> GenerateResult:
    """End-to-end batched generation (host orchestration).

    With a ``mesh``, batch rows are sharded over ``dp`` (rows padded up to
    a dp multiple by replicating the last prompt; extra rows dropped from
    the result) and token inputs are placed with NamedShardings — GSPMD
    propagates dp through activations and the KV cache, while params carry
    their tp shardings from the loader (parallel/sharding.py). The fused
    decode kernel runs under shard_map on such meshes (dp over rows, tp
    over KV heads) whenever tp divides n_kv_heads.

    ``share_prefix``: a debate round sends IDENTICAL prompts to every
    opponent sharing a model (round-level focus/persona apply to all), so
    when all rows are equal the prompt prefills ONCE (B=1) and the KV
    cache is tiled to B rows before decode — prefill FLOPs drop by B×,
    SURVEY §7 hard part (e)'s prefix-caching lever. Rows then diverge via
    per-row sampling. Applies off-mesh only (dp sharding wants real rows).

    ``paged``: decode against the paged KV pool (engine/kvcache.py +
    ops/pallas_paged.py) instead of the dense per-row cache — prompt KV is
    scattered into pages after prefill and every decode step writes through
    the page table. Scales over dp-only meshes (per-device pools,
    independent per-device chunk loops), tp-only meshes (head-sharded
    global pool, kernel under shard_map), and mixed dp×tp meshes (one
    GSPMD chunk loop over a per-dp-slice pool layout, kernel under a
    dp×tp shard_map); sp meshes warn and use the dense path.

    ``speculative``: prompt-lookup speculative decoding
    (engine/speculative.py) — greedy, single-row, dense-cache runs draft
    tokens from n-gram matches in the prompt and verify several per
    forward; bit-identical outputs, multiple tokens per step on
    revision-style outputs. None = auto (on when eligible).

    ``kv_dtype="int8"``: store the KV cache int8 with per-token-head
    scales — half the cache HBM and half the bytes read per decoded
    token. Composes with the fused decode kernel (dequant inside the
    kernel tiles), with sharded meshes, with ``paged`` (int8 pages +
    scale pages, in-kernel dequant), and with sp prefill (quantized at
    the reshard-to-decode boundary).
    """
    # An explicit use_pallas_decode=True records caller intent (it
    # selects a louder fallback when the mesh can't support the kernel).
    explicit_pallas = use_pallas_decode is True
    # The PAGED kernel switch ignores the dense-path context-length
    # heuristic below: the paged alternative is the gather reference path
    # (densifies the whole pool every layer), strictly worse than the
    # kernel at any context length. Only an explicit caller False (or a
    # non-TPU backend) disables it.
    requested_pallas = use_pallas_decode

    n_real = len(prompt_ids)
    if mesh is not None:
        from adversarial_spec_tpu.parallel.mesh import DP

        dp = mesh.shape[DP]
        short = (-len(prompt_ids)) % dp
        prompt_ids = prompt_ids + [prompt_ids[-1]] * short

    tokens_np, pad_lens_np = pad_batch(prompt_ids, pad_id)
    B, S = tokens_np.shape
    max_new = bucket_length(max_new_tokens, minimum=DECODE_CHUNK)
    total_len = S + max_new

    if use_pallas_decode is None:
        # Auto: fused kernel on a real TPU, but only once the cache is
        # long enough for streaming to beat XLA's attention (see
        # PALLAS_DECODE_MIN_T). Multi-device meshes run it under
        # shard_map (batch over dp, KV heads over tp); the support gate
        # below demotes unsupported tp degrees for auto and explicit
        # callers alike.
        use_pallas_decode = (
            jax.default_backend() == "tpu"
            and total_len >= PALLAS_DECODE_MIN_T
        )
    pallas_interpret = jax.default_backend() == "cpu"
    # Fused dequant-matmul (ops/pallas_quant.py): auto = real TPU. Either
    # way it only engages when the params actually carry quantized
    # leaves, and only single-device (models/transformer.py gates on the
    # mesh) — CPU callers opt in explicitly to run the kernels under
    # interpret mode (the parity harness).
    from adversarial_spec_tpu.ops.quant import has_quantized_weights

    if use_pallas_matmul is None:
        use_pallas_matmul = jax.default_backend() == "tpu"
    use_pallas_matmul = bool(use_pallas_matmul) and has_quantized_weights(
        params
    )
    if use_pallas_decode and mesh is not None and mesh.size > 1:
        from adversarial_spec_tpu.ops.pallas_decode import (
            tp_decode_supported,
        )

        if not tp_decode_supported(cfg.n_kv_heads, mesh):
            if explicit_pallas:
                import sys as _sys

                print(
                    f"warning: fused decode needs tp | n_kv_heads "
                    f"({cfg.n_kv_heads}); using the jnp attention path",
                    file=_sys.stderr,
                )
            use_pallas_decode = False

    tokens = jnp.asarray(tokens_np)
    pad_lens = jnp.asarray(pad_lens_np)
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P
        from adversarial_spec_tpu.parallel.mesh import DP

        rows = NamedSharding(mesh, P(DP))
        tokens = jax.device_put(tokens, NamedSharding(mesh, P(DP, None)))
        pad_lens = jax.device_put(pad_lens, rows)
    if seed is None:
        # Fresh entropy per call: unseeded debate rounds must actually vary
        # (seed=0 aliasing would make every round's "samples" identical).
        seed = int.from_bytes(os.urandom(4), "little")
    # Sampling draws full-vocab uniforms every step (gumbel-max
    # categorical); threefry is pure ALU and shows up at 128k vocab. The
    # TPU's hardware RNG ("rbg") generates the same bits-shape orders of
    # magnitude cheaper. Tradeoffs, deliberate: (1) streams differ
    # between impls, so seeds are reproducible per platform, not across
    # platforms (never promised); (2) JAX only guarantees independent
    # streams after split/fold_in for threefry — this loop splits per
    # chunk and the dp wrappers fold_in per device, so rbg streams carry
    # a weaker (empirical, not proven) independence guarantee. For
    # sampling diversity in a debate round that is acceptable; callers
    # needing threefry's guarantees set ADVSPEC_PRNG=threefry (the full
    # impl string "threefry2x32" is accepted too).
    impl = (
        "rbg"
        if jax.default_backend() == "tpu"
        and not os.environ.get("ADVSPEC_PRNG", "rbg").startswith("threefry")
        else "threefry2x32"
    )
    key = jax.random.key(seed, impl=impl)
    key, prefill_key = jax.random.split(key)
    temp = jnp.float32(temperature)
    tp = jnp.float32(top_p)
    use_top_p = float(top_p) < 1.0  # static: skip the no-op vocab sort
    eos = jnp.asarray(sorted(set(eos_ids)) or [-1], dtype=jnp.int32)

    deadline = time.monotonic() + timeout_s if timeout_s > 0 else None
    # Paged decode scales over dp (per-device page pools, zero cross-
    # device page traffic — engine/scheduler.py:
    # sharded_scheduler_decode_chunk), over tp-only meshes (global
    # pool, head axis tp-sharded, kernel under shard_map —
    # ops/pallas_paged.py:paged_decode_attention_tp), over mixed
    # dp×tp meshes (per-dp-slice pool layout, GSPMD chunk loop, kernel
    # under the dp×tp wrapper), and over sp meshes (sp is a PREFILL
    # axis — during decode it idles/replicates, exactly as the dense
    # decode path behaves after reshard_cache_for_decode, so the
    # global-pool and per-dp-slice layouts carry over unchanged with
    # the sp axis simply unmentioned in the shard_map specs). Resolve
    # now so the prefill cache can be sized to the prompt only.
    paged_dp = paged_tp = 1
    paged_mixed = False
    paged_sp = False  # sp axis present: replicated during decode
    paged_gspmd = False  # multi-device paged, not dp-only: the chunk
    # loop runs under GSPMD and the kernel needs the mesh passed down
    if paged and mesh is not None and mesh.size > 1:
        from adversarial_spec_tpu.parallel.mesh import (
            DP as _DP,
            SP as _SP,
            TP as _TP,
        )

        if mesh.size == mesh.shape[_DP]:
            paged_dp = mesh.shape[_DP]
        elif cfg.n_kv_heads % mesh.shape[_TP] != 0:
            import sys

            print(
                f"warning: paged KV decode requires tp | n_kv_heads "
                f"({mesh.shape[_TP]} ∤ {cfg.n_kv_heads}); falling back "
                f"to the dense cache on this mesh ({dict(mesh.shape)})",
                file=sys.stderr,
            )
            paged = False
        elif mesh.shape[_DP] == 1:
            # tp-only, sp-only, or sp×tp: ONE global pool, heads
            # tp-sharded (trivially so when tp == 1), sp replicated.
            paged_tp = mesh.shape[_TP]
            paged_sp = mesh.shape[_SP] > 1
            paged_gspmd = True
        else:
            # Mixed dp×tp (a v5e-8 at dp=4×tp=2) — and dp×sp(×tp):
            # ONE GSPMD-partitioned chunk loop over a per-dp-slice
            # pool layout — rows + page slabs shard over dp, heads
            # over tp; the kernel runs under the dp×tp shard_map
            # wrapper with global→local id shift
            # (ops/pallas_paged.py:paged_decode_attention_dp_tp).
            paged_tp = mesh.shape[_TP]
            paged_mixed = True
            paged_sp = mesh.shape[_SP] > 1
            paged_gspmd = True

    # Shared-prefix: identical rows prefill once and tile. Qualifies off-
    # mesh and on single-device meshes (the TpuEngine always passes a
    # mesh, so the single-chip case — the common debate setup — must
    # qualify); dp>1 meshes want real rows for the sharded prefill.
    shared = (
        share_prefix
        and (mesh is None or mesh.size == 1)
        and B > 1
        and all(p == prompt_ids[0] for p in prompt_ids[1:])
    )
    # PARTIAL sharing: equal-length rows that diverge only in a suffix
    # (per-opponent personas over one spec) prefill their common prefix
    # ONCE at B=1, tile the cache, and run only the divergent tail at
    # full batch. Equal lengths ⇒ equal pads ⇒ the shared slots hold
    # identical KV for every row. Granularity is the prefill chunk.
    shared_until = 0
    if (
        share_prefix
        and not shared
        and (mesh is None or mesh.size == 1)
        and B > 1
        and all(len(p) == len(prompt_ids[0]) for p in prompt_ids[1:])
    ):
        p0 = prompt_ids[0]
        common = len(p0)
        for p in prompt_ids[1:]:
            i = 0
            while i < common and p[i] == p0[i]:
                i += 1
            common = i
        chunk0 = min(S, PREFILL_CHUNK)
        # Divergence slot in padded coordinates, floored to chunk grid.
        shared_until = ((S - len(p0) + common) // chunk0) * chunk0
    prefill_tokens = tokens[:1] if shared else tokens
    prefill_pads = pad_lens[:1] if shared else pad_lens

    t0 = time.monotonic()
    cache_device = None
    if mesh is not None and mesh.size > 1:
        from adversarial_spec_tpu.parallel.sharding import cache_sharding

        # Born sharded: batch over dp, heads over tp — never replicated
        # through one chip's HBM.
        cache_device = cache_sharding(mesh)

    sp = mesh.shape.get("sp", 1) if mesh is not None else 1
    use_sp_prefill = sp > 1 and S % sp == 0
    if use_sp_prefill:
        # Long-context path: sequence-parallel prefill (ring attention
        # over the sp axis — parallel/sp.py), then reshard the
        # sequence-sharded cache into the decode layout.
        from jax.sharding import NamedSharding, PartitionSpec as P
        from adversarial_spec_tpu.parallel.mesh import SP as SP_AXIS
        from adversarial_spec_tpu.parallel.sp import (
            reshard_cache_for_decode,
            sp_prefill,
        )

        # Tokens enter sequence-sharded so shard_map needs no reshard.
        sp_tokens = jax.device_put(
            prefill_tokens, NamedSharding(mesh, P(None, SP_AXIS))
        )
        last_logits, cache = sp_prefill(
            params, cfg, sp_tokens, prefill_pads, mesh
        )
        # int8 KV quantizes at this reshard boundary — the ring itself
        # ran on full-precision K/V. Paged runs migrate prompt KV into
        # pages right below, so their resharded dense cache only needs
        # the prompt slots, not the decode region.
        cache = reshard_cache_for_decode(
            cache, mesh, S if paged else total_len, kv_dtype=kv_dtype
        )
    else:
        # Paged runs drop the dense cache after migrating prompt KV, so
        # it only needs the prompt slots — not the decode region.
        cache = init_cache(
            cfg,
            1 if shared_until else prefill_tokens.shape[0],
            S if paged else total_len,
            dtype=params["embed"].dtype,
            device=cache_device,
            kv_dtype=kv_dtype,
        )
        chunk_len = min(S, PREFILL_CHUNK)
        last_logits = None
        for ci in range(0, S, chunk_len):
            if shared_until and ci == shared_until:
                # Common prefix done: fan the 1-row cache out to B rows
                # and finish the divergent tails at full batch.
                cache = jax.tree.map(
                    lambda x: jnp.repeat(x, B, axis=1), cache
                )
            one_row = bool(shared_until) and ci < shared_until
            cache, last_logits = prefill_chunk(
                params,
                cfg,
                (prefill_tokens[:1] if one_row else prefill_tokens)[
                    :, ci : ci + chunk_len
                ],
                prefill_pads[:1] if one_row else prefill_pads,
                cache,
                jnp.int32(ci),
            )
        if shared_until:
            from adversarial_spec_tpu.engine import prefix_cache as _pc

            _pc.stats.record_prefill(0, (B - 1) * shared_until)
    # Paged + identical prompts: rows can SHARE physical prompt pages
    # (never written after migration — decode slots start at S, which is
    # page-aligned when page_size divides the pow2 bucket), so skip the
    # B-way cache tile entirely; only logits tile.
    share_prompt_pages = shared and paged and S % page_size == 0
    if shared:
        if not share_prompt_pages:
            cache = jax.tree.map(lambda x: jnp.repeat(x, B, axis=1), cache)
        last_logits = jnp.repeat(last_logits, B, axis=0)
        from adversarial_spec_tpu.engine import prefix_cache as _pc

        _pc.stats.record_prefill(0, (B - 1) * S)
    first = sample_tokens(
        last_logits,
        prefill_key,
        greedy=greedy,
        top_k=top_k,
        temperature=temp,
        top_p=tp,
        use_top_p=use_top_p,
    )
    first.block_until_ready()
    prefill_time = time.monotonic() - t0

    out_buf = jnp.zeros((B, max_new), jnp.int32)
    is_eos_first = (first[:, None] == eos[None, :]).any(axis=-1)
    out_buf = out_buf.at[:, 0].set(first)
    finished = is_eos_first
    cur = first
    step = jnp.int32(1)
    timed_out = False

    page_table = None
    if paged:
        from adversarial_spec_tpu.engine.kvcache import (
            PageAllocator,
            PagedCacheLayout,
            init_page_pool,
            write_tokens,
        )

        # Physical page 0 is the TRASH page (scheduler_decode_chunk
        # redirects inactive rows' writes there), so allocator ids shift
        # +1 — the scheduler's convention, which this path shares. Without
        # the reservation, an early-EOS row's redirected writes would
        # corrupt whichever row's KV occupied physical page 0.
        n_pages_per_row = -(-total_len // page_size)
        if share_prompt_pages:
            # One physical copy of the prompt pages, shared by all rows;
            # only the decode region is per-row.
            prompt_pages = S // page_size
            decode_pages = n_pages_per_row - prompt_pages
            allocator = PageAllocator(
                prompt_pages + B * decode_pages, page_size
            )
            allocator.new_sequence("prompt")
            allocator.extend("prompt", S)
            shared_table = np.asarray(allocator.table("prompt"), np.int32)
            for b in range(B):
                allocator.new_sequence(b)
                allocator.extend(b, total_len - S)
            table_np = (
                np.concatenate(
                    [
                        np.broadcast_to(shared_table, (B, prompt_pages)),
                        allocator.table_array(list(range(B)), decode_pages),
                    ],
                    axis=1,
                )
                + 1
            )
            n_phys_pages = prompt_pages + B * decode_pages
        elif paged_dp > 1 or paged_mixed:
            # Per-dp-slice pool layout, shared by the dp-only and mixed
            # dp×tp modes: slice d owns local pages [0, Lp) with local
            # page 0 reserved as that slice's trash page (shard sizes
            # stay equal); global id = local + d·Lp. The dp-only chunk
            # loop is shard_mapped — each device indexes its LOCAL pool
            # slice, so its table carries local ids and only the
            # (global-pool) migration uses global ids. The mixed chunk
            # loop runs under GSPMD — global view — so its table IS the
            # global one, and the kernel wrapper shifts back to local
            # (ops/pallas_paged.py:paged_decode_attention_dp_tp). The
            # TRASH_PAGE=0 write redirect lands on slice 0's trash page,
            # which no table ever references.
            slice_dp = paged_dp if paged_dp > 1 else mesh.shape[_DP]
            local_rows = B // slice_dp
            local_pool_pages = 1 + local_rows * n_pages_per_row
            lr = np.arange(B) % local_rows
            dev = np.arange(B) // local_rows
            local_table = (
                1
                + lr[:, None] * n_pages_per_row
                + np.arange(n_pages_per_row)[None, :]
            ).astype(np.int32)
            global_table = local_table + (dev * local_pool_pages)[:, None]
            table_np = global_table if paged_mixed else local_table
            migrate_table_np = global_table
            n_pool_pages = slice_dp * local_pool_pages
        else:
            allocator = PageAllocator(B * n_pages_per_row, page_size)
            for b in range(B):
                allocator.new_sequence(b)
                allocator.extend(b, total_len)
            table_np = (
                allocator.table_array(list(range(B)), n_pages_per_row) + 1
            )
            n_phys_pages = B * n_pages_per_row
        if paged_dp == 1 and not paged_mixed:
            migrate_table_np = table_np
            n_pool_pages = n_phys_pages + 1  # +1: trash page 0
        page_table = jnp.asarray(table_np)
        layout = PagedCacheLayout(
            n_pages=n_pool_pages,
            page_size=page_size,
            n_layers=cfg.n_layers,
            n_kv_heads=cfg.n_kv_heads,
            head_dim=cfg.head_dim,
        )
        pool = init_page_pool(
            layout,
            dtype=params["embed"].dtype if kv_dtype else cache["k"].dtype,
            kv_dtype=kv_dtype,
        )
        if paged_dp > 1:
            from jax.sharding import NamedSharding, PartitionSpec as P
            from adversarial_spec_tpu.parallel.mesh import DP as _DP

            pool = jax.tree.map(
                lambda x: jax.device_put(
                    x, NamedSharding(mesh, P(None, _DP, None, None, None))
                ),
                pool,
            )
        elif paged_mixed:
            # Page slabs over dp (per-slice layout above), heads over tp.
            from jax.sharding import NamedSharding, PartitionSpec as P
            from adversarial_spec_tpu.parallel.mesh import (
                DP as _DP,
                TP as _TP,
            )

            pool = jax.tree.map(
                lambda x: jax.device_put(
                    x, NamedSharding(mesh, P(None, _DP, _TP, None, None))
                ),
                pool,
            )
        elif paged_tp > 1 or paged_sp:
            # Global pool, head axis tp-sharded — each device holds every
            # page's slice of its own KV heads (same placement the dense
            # tp cache uses). On sp(-only) meshes tp may be 1: the spec
            # then replicates the pool, matching the idle-sp decode
            # semantics of the dense path.
            from jax.sharding import NamedSharding, PartitionSpec as P
            from adversarial_spec_tpu.parallel.mesh import TP as _TP

            pool = jax.tree.map(
                lambda x: jax.device_put(
                    x, NamedSharding(mesh, P(None, None, _TP, None, None))
                ),
                pool,
            )
        # Migrate prompt KV (slots [0, S)) from the dense prefill cache
        # into pages (vectorized table lookup); pad-slot garbage lands too
        # but stays masked by the per-row bounds start. With shared prompt
        # pages the (untiled, single-row) cache scatters ONCE.
        B_mig = cache["k"].shape[1]
        slots = np.tile(np.arange(S, dtype=np.int32)[None, :], (B_mig, 1))
        page_ids = migrate_table_np[
            np.arange(B_mig)[:, None], slots // page_size
        ]
        offsets = slots % page_size
        pool = write_tokens(
            pool,
            cache["k"][..., :S, :],
            cache["v"][..., :S, :],
            page_ids,
            offsets,
            ks_new=cache["ks"][..., :S, :] if "ks" in cache else None,
            vs_new=cache["vs"][..., :S, :] if "ks" in cache else None,
        )
        cache = None  # dense cache no longer needed
        # NOT the dense-path switch: the paged fallback (gather path)
        # densifies the whole pool every layer, so the kernel wins at any
        # context length — only an explicit caller False or a non-TPU
        # backend turns it off (interpret mode keeps it testable on CPU).
        use_paged_kernel = (
            requested_pallas
            if requested_pallas is not None
            else jax.default_backend() == "tpu"
        )
        # Per-row decode state for the shared paged loop
        # (engine/scheduler.py::scheduler_decode_chunk — one loop serves
        # both this round-synchronous path and the continuous batcher).
        paged_cur_len = jnp.full((B,), S + 1, jnp.int32)
        paged_n_emitted = jnp.ones((B,), jnp.int32)
        paged_max_new = jnp.full((B,), max_new_tokens, jnp.int32)
        paged_active = ~finished

    # Speculative eligibility: dense cache and enough output budget for
    # at least one γ+1 span — every mesh shape (incl. sp and multi-host)
    # is served by one of three execution modes (any batch size, any
    # sampling mode — per-row accept lengths + rejection sampling; the
    # bench shape of 4 opponents at temperature 0.7 is the target
    # workload):
    #   - single device: plain jitted accept loop;
    #   - dp-only mesh: shard_map wrappers (rows shard over dp, each
    #     device runs its own INDEPENDENT accept loop — per-row desync
    #     never crosses devices);
    #   - any other mesh (tp, dp×tp, sp×…): one GSPMD-partitioned
    #     program — the layer matmuls shard via the params' Megatron
    #     shardings, the compiler inserts the psums, and idle axes
    #     (sp during decode) replicate (mesh=… below).
    # Composes with the fused kernels: the tail loop runs the
    # single-query kernel (under its shard_map wrapper on meshes); the
    # verification span runs the multi-query kernel single-device and
    # the jnp attention path (GSPMD head-sharded) under tp.
    from adversarial_spec_tpu.engine import spec as spec_cfg_mod

    _sp_cfg = spec_cfg_mod.config()
    gamma = _sp_cfg.gamma
    spec_explicit = speculative is not None
    if speculative is None:
        # Unspecified → the process switchboard (engine/spec.py): env
        # ADVSPEC_SPECULATIVE seeds it, CLI --no-speculative/--gamma and
        # tests retune it via configure() — the SAME knob the batcher
        # consults, so the documented escape hatch reaches the dense
        # fallback path (sharded meshes, non-paged calls) too. The
        # adaptive off-switch below still bounds the cost per call
        # either way.
        speculative = _sp_cfg.enabled
    spec_dp = 1
    spec_mesh = None
    if mesh is not None and mesh.size > 1:
        from adversarial_spec_tpu.parallel.mesh import DP as _SPEC_DP

        # Multi-host safe: speculation's host-side control flow
        # (spec_fits, _steps_exit, catch-up targets) reduces
        # steps_rows/finished to REPLICATED scalars on device before
        # fetching, so no host ever touches a non-addressable shard and
        # every host takes identical branches (BASELINE config 5's
        # v5p-16 decode lever; exercised by the two-process spec parity
        # test in tests/test_multihost.py).
        if mesh.size == mesh.shape[_SPEC_DP]:
            spec_dp = mesh.shape[_SPEC_DP]
        else:
            # tp / dp×tp / sp meshes: ONE GSPMD-partitioned program.
            # On sp meshes this runs AFTER reshard_cache_for_decode put
            # the cache in the standard decode layout (batch over dp,
            # heads over tp, sp idle/replicated — parallel/sp.py), so
            # the compiler partitions over dp×tp and replicates the sp
            # axis exactly as the plain chunked-decode path already
            # does. The 16k-context config keeps its decode lever
            # (VERDICT r3 item 9).
            spec_mesh = mesh
    use_spec = (
        speculative and not paged and max_new_tokens > gamma + 1
    )
    if spec_explicit and speculative and paged and (
        max_new_tokens > gamma + 1
    ):
        # The dense speculative loop has no paged variant here — paged
        # speculation lives in the ContinuousBatcher (engine/scheduler's
        # per-slot draft/verify step), which is where the serving path
        # already runs. Say so ONCE instead of silently decoding
        # token-at-a-time under a flag combination that reads like
        # "speculation on". Only for an EXPLICIT speculative=True: a
        # paged call that merely inherited the default-on process config
        # (the engine's dense fallback) asked for nothing and gets no
        # spurious warning.
        global _PAGED_SPEC_WARNED
        if not _PAGED_SPEC_WARNED:
            _PAGED_SPEC_WARNED = True
            import sys as _sys

            print(
                "warning: speculative=True is ignored when paged=True in "
                "generate() — dense-path speculation has no paged "
                "variant; paged speculation runs per-slot in the "
                "ContinuousBatcher (TpuEngine.chat / run_all). "
                "Pass speculative=False to silence this.",
                file=_sys.stderr,
            )
    desynced = False  # per-row steps diverge after any speculative phase
    steps_rows = None
    if use_spec:
        from adversarial_spec_tpu.engine.speculative import (
            rowwise_decode_steps,
            speculative_decode_steps,
        )

        prev_rows = tokens[:, -1]
        steps_rows = jnp.ones((B,), jnp.int32)
        # One attention implementation governs the whole speculative call
        # (verify and tail see the same near-tie argmaxes): MQ kernel for
        # spans, single-query kernel for the tail — both read int8 tiles.
        spec_pallas = use_pallas_decode

    t1 = time.monotonic()

    def _steps_exit() -> int:
        """Host-side loop scalar: min over rows of (done ? max_new :
        steps) — max_new only once every row is finished or at budget.

        The reduction runs ON DEVICE so only a replicated scalar is
        fetched: steps_rows/finished are dp-sharded, and on a multi-host
        mesh a host-side np.asarray of them would touch non-addressable
        shards and raise. Replicated scalars are identical on every
        host, so all hosts take the same branch (SPMD lockstep)."""
        if steps_rows is None:
            return int(step)
        return int(
            jnp.where(finished, jnp.int32(max_new_tokens), steps_rows).min()
        )

    while _steps_exit() < max_new_tokens and not bool(finished.all()):
        if deadline is not None and time.monotonic() >= deadline:
            timed_out = True
            break
        key, chunk_key = jax.random.split(key)
        if use_spec:
            # Device-side reduction → replicated bool (multi-host safe).
            spec_fits = bool(
                jnp.any(
                    ~finished & (steps_rows + gamma + 1 <= max_new_tokens)
                )
            )
        else:
            spec_fits = False
        if spec_fits:
            spec_static = dict(
                prompt_len=S,
                gamma=gamma,
                iters=max(1, DECODE_CHUNK // (gamma + 1)),
                greedy=greedy,
                top_k=top_k,
                use_top_p=use_top_p,
                use_pallas=spec_pallas,
                pallas_interpret=pallas_interpret,
            )
            spec_args = (
                tokens,
                prev_rows,
                cur,
                pad_lens,
                finished,
                out_buf,
                steps_rows,
                jnp.int32(max_new_tokens),
                eos,
                chunk_key,
                temp,
                tp,
            )
            if spec_dp > 1:
                from adversarial_spec_tpu.engine.speculative import (
                    speculative_decode_steps_dp,
                )

                ret = speculative_decode_steps_dp(
                    mesh, params, cfg, cache, *spec_args, **spec_static
                )
            else:
                ret = speculative_decode_steps(
                    params,
                    cfg,
                    cache,
                    *spec_args,
                    # None off-mesh; the tp/GSPMD path partitions the
                    # program over the mesh (dp wrappers take the mesh
                    # positionally instead, and their inner calls must
                    # see mesh=None — they already run under shard_map).
                    mesh=spec_mesh,
                    **spec_static,
                )
            (
                cache,
                prev_rows,
                cur,
                finished,
                out_buf,
                steps_rows,
                n_iters,
                n_emitted,
                n_row_iters,
            ) = ret
            desynced = True
            step = jnp.max(steps_rows)
            # Adaptive off-switch: each verification forward is γ+1 wide;
            # if it averages barely more than one emitted token per
            # active row-iteration (exact count from the device loop),
            # drafts aren't matching and plain decode is cheaper.
            if int(n_emitted) / max(int(n_row_iters), 1) < 1.5:
                use_spec = False
        elif desynced:
            # Rows no longer share a step count. If speculation is OFF
            # with budget left, only let the laggards CATCH UP to the
            # frontmost UNFINISHED row (rowwise slots are ~2x slower per
            # step than the shared-slot loop: per-row scattered cache
            # writes), then clear the desync so the rest of the budget
            # decodes synced. With speculation merely out of span-budget,
            # rowwise runs the whole tail.
            need_catchup = True
            if use_spec:
                target = max_new_tokens
            else:
                # Unfinished-row max as a replicated device scalar; the
                # outer loop guarantees at least one unfinished row.
                target = min(
                    int(
                        jnp.where(
                            finished, jnp.int32(-1), steps_rows
                        ).max()
                    ),
                    max_new_tokens,
                )
                if bool(jnp.all(finished | (steps_rows >= target))):
                    # Already level (e.g. B == 1, or equal accept
                    # counts): no catch-up dispatch needed.
                    desynced = False
                    step = jnp.int32(target)
                    need_catchup = False
            if need_catchup:
                rw_args = (
                    cur,
                    pad_lens,
                    finished,
                    out_buf,
                    steps_rows,
                    jnp.int32(target),
                    eos,
                    chunk_key,
                    temp,
                    tp,
                )
                rw_static = dict(
                    prompt_len=S,
                    chunk=DECODE_CHUNK,
                    greedy=greedy,
                    top_k=top_k,
                    use_top_p=use_top_p,
                    use_pallas=spec_pallas,
                    pallas_interpret=pallas_interpret,
                )
                if spec_dp > 1:
                    from adversarial_spec_tpu.engine.speculative import (
                        rowwise_decode_steps_dp,
                    )

                    cache, cur, finished, out_buf, steps_rows = (
                        rowwise_decode_steps_dp(
                            mesh, params, cfg, cache, *rw_args, **rw_static
                        )
                    )
                else:
                    cache, cur, finished, out_buf, steps_rows = (
                        rowwise_decode_steps(
                            params,
                            cfg,
                            cache,
                            *rw_args,
                            mesh=spec_mesh,
                            **rw_static,
                        )
                    )
                step = jnp.max(steps_rows)
                if not use_spec:
                    if bool(jnp.all(finished | (steps_rows >= target))):
                        # Level again: unfinished rows all sit at target.
                        desynced = False
                        step = jnp.int32(target)
        elif paged:
            from adversarial_spec_tpu.engine.scheduler import (
                scheduler_decode_chunk,
                sharded_scheduler_decode_chunk,
            )

            static_kw = dict(
                chunk=DECODE_CHUNK,
                greedy=greedy,
                top_k=top_k,
                use_top_p=use_top_p,
                use_pallas=use_paged_kernel,
                use_pallas_matmul=use_pallas_matmul,
                pallas_interpret=pallas_interpret,
            )
            chunk_args = (
                params,
                cfg,
                pool,
                page_table,
                cur,
                paged_cur_len,
                pad_lens,
                paged_n_emitted,
                paged_max_new,
                paged_active,
                out_buf,
                eos,
                chunk_key,
                temp,
                tp,
            )
            (
                pool,
                cur,
                paged_cur_len,
                paged_n_emitted,
                out_buf,
                paged_active,
            ) = (
                sharded_scheduler_decode_chunk(
                    mesh, *chunk_args, **static_kw
                )
                if paged_dp > 1
                # tp/sp/mixed meshes: the kernel runs under shard_map
                # inside the GSPMD program (head-sharded pool, sp
                # replicated); the dp path above shards whole
                # per-device pools instead.
                else scheduler_decode_chunk(
                    *chunk_args,
                    **static_kw,
                    mesh=mesh if paged_gspmd else None,
                )
            )
            step = jnp.max(paged_n_emitted)
            finished = ~paged_active
        else:
            # Plain chunked decode owns the rest of the budget (nothing
            # re-enables speculation once it is off, and paged never
            # reaches here) — run it PIPELINED: dispatch chunk N+1
            # before blocking on chunk N's exit flags, so the host's
            # per-chunk work (PRNG split, arg staging, dispatch) always
            # overlaps device compute and the device never idles on a
            # host round-trip between chunks. The exit check trails one
            # chunk behind; its cost is at most one extra dispatch whose
            # while_loop exits immediately (all rows finished or budget
            # reached) — and the FIRST trailing check is free, because
            # the outer loop condition already fetched the entry step.
            while True:
                # Deadline BEFORE dispatch (host clock only — no device
                # sync on the fast path): once the deadline passes, no
                # further chunk is dispatched, so a timeout overshoots
                # by at most the chunk already in flight. At the
                # deadline we DO sync on that in-flight chunk — if it
                # completed the generation, this is a finished result
                # that happens to end near the deadline, not a timeout.
                if deadline is not None and time.monotonic() >= deadline:
                    if not (
                        int(step) >= max_new_tokens
                        or bool(finished.all())
                    ):
                        timed_out = True
                    break
                prev_step, prev_finished = step, finished
                cache, cur, finished, out_buf, step = decode_chunk_steps(
                    params,
                    cfg,
                    cache,
                    cur,
                    pad_lens,
                    finished,
                    out_buf,
                    step,
                    jnp.int32(max_new_tokens),
                    eos,
                    chunk_key,
                    temp,
                    tp,
                    prompt_len=S,
                    chunk=DECODE_CHUNK,
                    greedy=greedy,
                    top_k=top_k,
                    use_top_p=use_top_p,
                    use_pallas_decode=use_pallas_decode,
                    use_pallas_matmul=use_pallas_matmul,
                    pallas_interpret=pallas_interpret,
                    mesh=mesh
                    if (mesh is not None and mesh.size > 1)
                    else None,
                )
                key, chunk_key = jax.random.split(key)
                if int(prev_step) >= max_new_tokens or bool(
                    prev_finished.all()
                ):
                    break
            if steps_rows is not None:
                # Synced again after a speculative phase + catch-up:
                # every unfinished row advanced to `step`. Raising a
                # finished row's count only widens its EOS-scan region —
                # the scan still stops at its first EOS (zeros follow).
                steps_rows = jnp.maximum(steps_rows, step)
    decode_time = time.monotonic() - t1

    out_np = _host_fetch(out_buf)[:n_real, :max_new_tokens]
    B = n_real  # dp-padding rows dropped
    # Per-row step counts: shared scalar on the synced paths; the
    # speculative paths desynchronize rows (a timeout can strand them at
    # different steps — a shared max would count a slower row's zero
    # slots as output).
    if steps_rows is not None:
        row_steps = np.minimum(
            _host_fetch(steps_rows)[:n_real], max_new_tokens
        )
    else:
        row_steps = np.full((B,), min(int(step), max_new_tokens))
    eos_np = np.asarray(sorted(set(eos_ids)) or [-1])
    n_generated = np.zeros((B,), np.int64)
    for b in range(B):
        row = out_np[b, : row_steps[b]]
        eos_hits = np.isin(row, eos_np)
        if eos_hits.any():
            n_generated[b] = int(np.argmax(eos_hits)) + 1
        else:
            n_generated[b] = row_steps[b]
    return GenerateResult(
        tokens=out_np,
        n_generated=n_generated,
        prefill_time_s=prefill_time,
        decode_time_s=decode_time,
        decode_tokens=int(n_generated.sum()),
        timed_out=timed_out,
    )
