"""Fused-step / pipelined-drive-loop config and telemetry (host side).

The ContinuousBatcher's drive loop (engine/scheduler.py) can run in two
modes:

- **fused + pipelined** (default): each iteration issues ONE device
  program that advances the in-flight admission's prompt chunk AND every
  resident row's decode chunk together (Sarathi-style piggybacked
  chunked prefill), and the host keeps up to two steps in flight —
  inspecting step N-1's fetched ``active`` flags while step N runs, so
  queue admission, prefix-cache lookups, page allocation, and result
  collection all overlap device compute. The host syncs only at
  admission handoff, slot completion, and fault/timeout decision points.
- **legacy** (``--no-interleave`` / ``ADVSPEC_INTERLEAVE=0``): the
  original serialized loop — prompt chunk, full host sync, decode chunk,
  full host sync — kept as the escape hatch and the bench baseline.

This module is the process-wide switchboard for that choice plus the
telemetry both engines (TPU scheduler and the mock's deterministic CPU
accounting) record into, à la ``resilience.faults`` / ``prefix_cache``:

- ``stalled_prefill_s``: admission prefill wall-clock the batch actually
  waited on (standalone chunks with nothing to overlap, and the
  admission-handoff scatter+sample).
- ``overlapped_prefill_s``: prefill wall-clock attributed to chunks that
  rode inside a fused step — decode was running anyway, so this time was
  hidden under it.

``prefill_time_s`` is BY CONSTRUCTION the sum of the two buckets (the
snapshot computes it), so ``stalled + overlapped == prefill`` holds
exactly — the invariant tier-1 pins on the mock engine's deterministic
numbers. Deliberately imports no jax: the mock engine uses it on CPU.

The config/stats mechanics live in ``engine/procconfig.py`` (shared
with ``spec``, ``prefix_cache``, ``kvtier``); this module keeps only
what is interleave-specific — the knobs, the counters, and the
depth clamp.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

from adversarial_spec_tpu.engine import procconfig

# The drive loop keeps at most this many device steps in flight. Depth 1
# degenerates to "fused but synchronous" (fetch each step right after
# dispatch); depth 2 is the double buffer — deeper would only delay
# fault/EOS detection by more chunks for no extra overlap.
MAX_PIPELINE_DEPTH = 2


@dataclass
class InterleaveConfig:
    """Process-wide knobs, set once per CLI round (or by tests)."""

    enabled: bool = True
    pipeline_depth: int = MAX_PIPELINE_DEPTH


@dataclass
class InterleaveStats(procconfig.StatsBase):
    """Process-wide counters, aggregated across every batcher (and the
    mock engine's accounting). ``reset`` zeroes in place so engines
    holding a reference keep counting into the same object."""

    fused_steps: int = 0  # dispatches carrying prefill AND decode
    decode_steps: int = 0  # decode-only dispatches
    prefill_steps: int = 0  # standalone (stalled) prefill chunks
    sync_points: int = 0  # sanctioned host syncs (handoff/fault/timeout)
    stalled_prefill_s: float = 0.0
    overlapped_prefill_s: float = 0.0

    def record_step(self, *, fused: bool, prefill_only: bool = False) -> None:
        if fused:
            self.fused_steps += 1
        elif prefill_only:
            self.prefill_steps += 1
        else:
            self.decode_steps += 1

    def record_prefill_time(self, seconds: float, *, overlapped: bool) -> None:
        if overlapped:
            self.overlapped_prefill_s += seconds
        else:
            self.stalled_prefill_s += seconds

    def record_sync(self) -> None:
        self.sync_points += 1

    def snapshot(self) -> dict:
        out = self.as_dict()
        # The invariant the telemetry promises: total prefill time IS
        # the two buckets — there is no third place prefill time can
        # hide. Computed here (NOT rounded: rounding the addends would
        # break the exact ``stalled + overlapped == prefill`` pin).
        out["prefill_time_s"] = (
            self.stalled_prefill_s + self.overlapped_prefill_s
        )
        return out


def _depth_from_env() -> int:
    try:
        d = int(os.environ.get("ADVSPEC_PIPELINE_DEPTH", MAX_PIPELINE_DEPTH))
    except ValueError:
        d = MAX_PIPELINE_DEPTH
    return max(1, min(d, MAX_PIPELINE_DEPTH))


def _clamp_depth(depth) -> int:
    return max(1, min(int(depth), MAX_PIPELINE_DEPTH))


_state = procconfig.ProcState(
    InterleaveConfig(
        enabled=os.environ.get("ADVSPEC_INTERLEAVE", "1") != "0",
        pipeline_depth=_depth_from_env(),
    ),
    InterleaveStats(),
    coerce={"pipeline_depth": _clamp_depth},
)
_config = _state.config
stats = _state.stats


def config() -> InterleaveConfig:
    return _state.config


def configure(
    enabled: bool | None = None, pipeline_depth: int | None = None
) -> InterleaveConfig:
    return _state.configure(enabled=enabled, pipeline_depth=pipeline_depth)


def reset_stats() -> None:
    _state.reset_stats()


def snapshot() -> dict:
    """Stats + config, the ``perf.interleave`` payload."""
    return _state.snapshot()
