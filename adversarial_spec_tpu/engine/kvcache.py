"""Paged KV-cache manager: page allocator + device page pool.

Host-side bookkeeping (free list, per-sequence page tables) stays in numpy
— it is O(pages) integer work with data-dependent control flow that has no
business inside an XLA program — while the page pool itself lives on
device as two dense arrays [n_pages, Hkv, page_size, D] per layer group,
written with vectorized scatters and read by the paged Pallas kernel
(ops/pallas_paged.py).

Sizing: a debate round's opponents share the pool; ``n_pages`` bounds
total resident tokens across all rows, not per-row length — the property
that lets a 16k-context judge coexist with short critics (SURVEY §5
long-context obligation).

Pages are REF-COUNTED: a page may back several sequences at once (a
cached prefix adopted by every opponent in a round — engine/
prefix_cache.py) plus one reference held by the prefix cache itself. A
page returns to the free list only when its last reference drops.
Sharing is copy-on-append rather than true copy-on-write: block content
is immutable once a page is full, and a writer's positions always lie
past its adopted prefix, so no write path ever touches a shared page.

jax is imported lazily (inside the device-pool functions only): the
host-side allocator must stay importable from jax-free flows (the mock
engine routes its prefix-cache accounting through ``PageAllocator``).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


class OutOfPages(RuntimeError):
    pass


@dataclass
class PagedCacheLayout:
    n_pages: int
    page_size: int
    n_layers: int
    n_kv_heads: int
    head_dim: int

    @property
    def tokens_capacity(self) -> int:
        return self.n_pages * self.page_size


class PageAllocator:
    """Free-list page allocator with per-sequence ordered page tables.

    Every allocated page carries a reference count: 1 per sequence whose
    table contains it plus 1 if the prefix cache holds it. ``extend``
    allocates fresh pages at refcount 1; ``adopt`` appends already-
    allocated (shared) pages to a new sequence's table, bumping their
    counts; ``free_sequence`` / ``cache_unref`` drop references and a
    page returns to the free list only at zero.
    """

    def __init__(self, n_pages: int, page_size: int):
        self.n_pages = n_pages
        self.page_size = page_size
        self._free = list(range(n_pages - 1, -1, -1))  # pop() → page 0 first
        self._tables: dict[int, list[int]] = {}
        self._lengths: dict[int, int] = {}
        self._refs: dict[int, int] = {}  # page -> reference count
        # Pages with an in-flight tier swap (a host->device promotion
        # scatter targeting them — engine/kvtier.py): they must stay
        # referenced until the swap owner unpins, and freeing one is a
        # bookkeeping corruption check_invariants / _release catch.
        self._swap_pins: dict[int, int] = {}  # page -> pin count

    @property
    def free_pages(self) -> int:
        return len(self._free)

    def refcount(self, page: int) -> int:
        return self._refs.get(page, 0)

    def new_sequence(self, seq_id: int) -> None:
        if seq_id in self._tables:
            raise ValueError(f"sequence {seq_id} already allocated")
        self._tables[seq_id] = []
        self._lengths[seq_id] = 0

    def pages_needed(self, seq_id: int, n_tokens: int) -> int:
        """Fresh pages an ``extend(seq_id, n_tokens)`` would allocate."""
        needed = -(-(self._lengths[seq_id] + n_tokens) // self.page_size)
        return max(0, needed - len(self._tables[seq_id]))

    def extend(self, seq_id: int, n_tokens: int) -> list[int]:
        """Reserve room for n_tokens more; returns newly allocated pages."""
        table = self._tables[seq_id]
        length = self._lengths[seq_id]
        needed_pages = -(-(length + n_tokens) // self.page_size)
        new_pages = []
        while len(table) < needed_pages:
            if not self._free:
                # Roll back this call's allocations before failing.
                for p in new_pages:
                    table.remove(p)
                    del self._refs[p]
                    self._free.append(p)
                raise OutOfPages(
                    f"paged KV cache exhausted: {self.n_pages} pages of "
                    f"{self.page_size} tokens all in use"
                )
            p = self._free.pop()
            table.append(p)
            self._refs[p] = 1
            new_pages.append(p)
        self._lengths[seq_id] = length + n_tokens
        return new_pages

    def adopt(self, seq_id: int, pages: list[int], n_tokens: int) -> None:
        """Share already-allocated ``pages`` (a cached prefix) into a fresh
        sequence. Must precede any ``extend`` for the sequence — adopted
        pages form its table head, exactly covering ``n_tokens``."""
        if self._tables[seq_id] or self._lengths[seq_id]:
            raise ValueError(
                f"sequence {seq_id} already has pages; adopt must come first"
            )
        if n_tokens != len(pages) * self.page_size:
            raise ValueError(
                f"adopt of {len(pages)} pages must cover exactly "
                f"{len(pages) * self.page_size} tokens, got {n_tokens}"
            )
        for p in pages:
            if p not in self._refs:
                raise ValueError(f"cannot adopt unallocated page {p}")
        for p in pages:
            self._refs[p] += 1
        self._tables[seq_id].extend(pages)
        self._lengths[seq_id] = n_tokens

    def cache_ref(self, page: int) -> None:
        """Take the prefix cache's reference on an allocated page."""
        if page not in self._refs:
            raise ValueError(f"cannot cache-ref unallocated page {page}")
        self._refs[page] += 1

    def cache_unref(self, page: int) -> None:
        """Drop the prefix cache's reference (page frees at zero)."""
        self._release(page)

    def swap_pin(self, page: int) -> None:
        """Mark ``page`` as the target of an in-flight tier swap (a
        promotion's host→device write — engine/kvtier.py). Freeing a
        pinned page is a refcount corruption: the swap would scatter
        into storage another sequence may own by then. Pins pair with
        ``swap_unpin`` in try/finally (GL-REFCOUNT enforces the
        pairing statically)."""
        if page not in self._refs:
            raise ValueError(f"cannot swap-pin unallocated page {page}")
        self._swap_pins[page] = self._swap_pins.get(page, 0) + 1

    def swap_unpin(self, page: int) -> None:
        """Drop one swap pin (the promotion write was dispatched — the
        page's owning references keep it alive from here)."""
        n = self._swap_pins.get(page, 0)
        if n <= 0:
            raise RuntimeError(f"swap-unpin without pin on page {page}")
        if n == 1:
            del self._swap_pins[page]
        else:
            self._swap_pins[page] = n - 1

    def _release(self, page: int) -> None:
        refs = self._refs.get(page, 0)
        if refs <= 0:
            raise RuntimeError(f"double free of page {page}")
        if refs == 1:
            if page in self._swap_pins:
                raise RuntimeError(
                    f"freeing page {page} with a tier swap in flight "
                    "(swap_pin held)"
                )
            del self._refs[page]
            self._free.append(page)
        else:
            self._refs[page] = refs - 1

    def truncate(self, seq_id: int, n_tokens: int) -> list[int]:
        """Shrink ``seq_id`` to ``n_tokens``, releasing tail pages that no
        longer back any of its tokens. The speculative-decode rollback
        primitive: a verify step reserves pages for the full γ-token
        draft up front, then rolls the rejected tail back here — each
        released page drops ONE reference, so a tail page shared with
        the prefix cache (or another sequence) merely loses this
        sequence's hold and stays resident for its other owners
        (callers never truncate below an adopted prefix: the accepted
        length always covers the prompt, and shared prefix pages sit at
        the table head — the copy-on-append boundary).

        Returns the pages this sequence released (refcount dropped; they
        are back on the free list only if that was the last reference).
        """
        length = self._lengths[seq_id]
        if not 0 <= n_tokens <= length:
            raise ValueError(
                f"cannot truncate sequence {seq_id} ({length} tokens) "
                f"to {n_tokens}"
            )
        table = self._tables[seq_id]
        keep = -(-n_tokens // self.page_size)
        released = table[keep:]
        del table[keep:]
        for p in released:
            self._release(p)
        self._lengths[seq_id] = n_tokens
        return released

    def length(self, seq_id: int) -> int:
        return self._lengths[seq_id]

    def covered_tokens(self, seq_id: int) -> int:
        """KV slots actually writable for this sequence — its page count
        times the page size (≥ ``length``; the page-rounded bound the
        scheduler's speculative write mask is built from)."""
        return len(self._tables[seq_id]) * self.page_size

    def table(self, seq_id: int) -> list[int]:
        return list(self._tables[seq_id])

    def free_sequence(self, seq_id: int) -> None:
        for p in self._tables.pop(seq_id):
            self._release(p)
        del self._lengths[seq_id]

    def check_invariants(self) -> None:
        """Raise RuntimeError on any bookkeeping violation: a page both
        free and referenced, a duplicate free-list entry, a table entry
        without a reference, a refcount below what the tables imply, or
        pages leaked/conjured. Cheap (O(pages)); the fuzz harness calls
        it after every operation."""
        free = self._free
        free_set = set(free)
        if len(free_set) != len(free):
            raise RuntimeError("free list contains duplicate pages")
        if free_set & self._refs.keys():
            raise RuntimeError(
                f"pages both free and referenced: "
                f"{sorted(free_set & self._refs.keys())}"
            )
        if len(free) + len(self._refs) != self.n_pages:
            raise RuntimeError(
                f"page conservation violated: {len(free)} free + "
                f"{len(self._refs)} referenced != {self.n_pages}"
            )
        table_refs: dict[int, int] = {}
        for seq_id, table in self._tables.items():
            if len(set(table)) != len(table):
                raise RuntimeError(f"sequence {seq_id} table has dup pages")
            for p in table:
                table_refs[p] = table_refs.get(p, 0) + 1
        for p, n in table_refs.items():
            if p in free_set:
                raise RuntimeError(f"free page {p} is in a live table")
            if self._refs.get(p, 0) < n:
                raise RuntimeError(
                    f"page {p}: {n} table refs exceed refcount "
                    f"{self._refs.get(p, 0)}"
                )
        for p, r in self._refs.items():
            if r < 1:
                raise RuntimeError(f"page {p} has nonpositive refcount {r}")
            # Leak check: a page's references are its table memberships
            # plus AT MOST ONE prefix-cache hold (one cache per pool;
            # PrefixCache._by_page is keyed by page, so it can never
            # double-ref). Anything beyond that is a leaked reference
            # that would keep the page out of the free list forever.
            if r > table_refs.get(p, 0) + 1:
                raise RuntimeError(
                    f"page {p}: refcount {r} exceeds "
                    f"{table_refs.get(p, 0)} table refs + 1 cache ref "
                    "(leaked reference)"
                )
        # Tier-swap pins: a pinned page must be live (referenced) — a
        # pin on a freed page means a promotion is scattering into
        # storage nobody owns — and pin counts must be positive.
        for p, n in self._swap_pins.items():
            if n < 1:
                raise RuntimeError(f"page {p} has nonpositive swap pin {n}")
            if p not in self._refs:
                raise RuntimeError(
                    f"page {p} swap-pinned but not referenced "
                    "(in-flight swap against a freed page)"
                )

    def table_array(self, seq_ids: list[int], max_pages: int) -> np.ndarray:
        """Batched page table [B, max_pages], -1-padded, for the kernel."""
        out = np.full((len(seq_ids), max_pages), -1, np.int32)
        for i, sid in enumerate(seq_ids):
            t = self._tables[sid]
            if len(t) > max_pages:
                raise ValueError(
                    f"sequence {sid} spans {len(t)} pages > {max_pages}"
                )
            out[i, : len(t)] = t
        return out


def init_page_pool(
    layout: PagedCacheLayout, dtype=None, kv_dtype: str = ""
) -> dict[str, "jnp.ndarray"]:
    """Device page pool: per-layer stacked K/V pages.

    ``kv_dtype="int8"``: pages store int8 K/V plus per-(token, head)
    f32 scale pages ("ks"/"vs", trailing dim 1) — the paged counterpart
    of the dense cache's int8 layout (models/transformer.py:init_cache).
    Presence of "ks" marks a quantized pool.
    """
    import jax.numpy as jnp

    if dtype is None:
        dtype = jnp.bfloat16
    shape = (
        layout.n_layers,
        layout.n_pages,
        layout.n_kv_heads,
        layout.page_size,
        layout.head_dim,
    )
    if kv_dtype == "int8":
        sshape = shape[:-1] + (1,)
        return {
            "k": jnp.zeros(shape, jnp.int8),
            "v": jnp.zeros(shape, jnp.int8),
            "ks": jnp.zeros(sshape, jnp.float32),
            "vs": jnp.zeros(sshape, jnp.float32),
        }
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def write_tokens(
    pool: dict[str, jnp.ndarray],
    k_new: jnp.ndarray,  # [L, B, Hkv, S, D] — heads-major cache layout
    v_new: jnp.ndarray,
    page_ids: np.ndarray,  # [B, S] physical page per token
    offsets: np.ndarray,  # [B, S] slot within page per token
    ks_new: jnp.ndarray | None = None,  # [L, B, Hkv, S, 1] (int8 pools)
    vs_new: jnp.ndarray | None = None,
) -> dict[str, jnp.ndarray]:
    """Scatter freshly computed K/V into their pages (vectorized).

    Quantized pools take the matching scale slices (both or neither) —
    the same [L, B, Hkv, S, 1] layout the dense int8 cache stores.
    """
    import jax.numpy as jnp

    L, B, H, S, D = k_new.shape
    pid = jnp.asarray(page_ids).reshape(-1)  # [B*S]
    off = jnp.asarray(offsets).reshape(-1)

    def flat(x):  # [L, B, H, S, *] → [B*S, L, H, *] (token-major updates)
        return jnp.transpose(x, (1, 3, 0, 2, 4)).reshape(
            B * S, L, H, x.shape[-1]
        )

    # pool[l, pid[n], :, off[n]] = new[n, l] for every layer l, token n.
    # Advanced indices (pid at dim 1, off at dim 3) are separated by the
    # head slice, so the token axis lands in front of the result — the
    # updates are built token-major to match.
    out = {
        "k": pool["k"].at[:, pid, :, off].set(flat(k_new)),
        "v": pool["v"].at[:, pid, :, off].set(flat(v_new)),
    }
    if "ks" in pool:
        if ks_new is None or vs_new is None:
            raise ValueError(
                "quantized pool requires ks_new/vs_new scale slices"
            )
        out["ks"] = pool["ks"].at[:, pid, :, off].set(flat(ks_new))
        out["vs"] = pool["vs"].at[:, pid, :, off].set(flat(vs_new))
    return out


def read_tokens(
    pool: dict[str, "jnp.ndarray"],
    page_ids: np.ndarray,  # [B, S] physical page per token
    offsets: np.ndarray,  # [B, S] slot within page per token
) -> dict[str, "jnp.ndarray"]:
    """Gather per-token K/V (and scales) back out of their pages.

    The exact inverse of ``write_tokens``: returns arrays in the
    heads-major dense-cache layout [L, B, Hkv, S, *]. Used to materialize
    a cached prefix's KV into a fresh admission's dense prefill cache
    (engine/scheduler.py) so only the suffix runs through the model.
    """
    import jax.numpy as jnp

    B, S = np.asarray(page_ids).shape
    pid = jnp.asarray(page_ids).reshape(-1)  # [B*S]
    off = jnp.asarray(offsets).reshape(-1)

    def gather(x):
        # x[l, pid[n], :, off[n]] → [B*S, L, H, *] (token axis in front,
        # same advanced-indexing rule write_tokens relies on), then back
        # to the cache layout [L, B, H, S, *].
        g = x[:, pid, :, off]
        L, H = x.shape[0], x.shape[2]
        return jnp.transpose(
            g.reshape(B, S, L, H, x.shape[-1]), (2, 0, 3, 1, 4)
        )

    return {k: gather(v) for k, v in pool.items()}


def token_positions_to_pages(
    allocator: PageAllocator, seq_ids: list[int], positions: np.ndarray
) -> tuple[np.ndarray, np.ndarray]:
    """Map per-row token positions [B, S] → (page_ids, offsets) [B, S]."""
    B, S = positions.shape
    page_ids = np.zeros((B, S), np.int32)
    offsets = np.zeros((B, S), np.int32)
    for i, sid in enumerate(seq_ids):
        table = allocator.table(sid)
        for j in range(S):
            pos = int(positions[i, j])
            page_ids[i, j] = table[pos // allocator.page_size]
            offsets[i, j] = pos % allocator.page_size
    return page_ids, offsets
