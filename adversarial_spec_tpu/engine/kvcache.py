"""Paged KV-cache manager: page allocator + device page pool.

Host-side bookkeeping (free list, per-sequence page tables) stays in numpy
— it is O(pages) integer work with data-dependent control flow that has no
business inside an XLA program — while the page pool itself lives on
device as two dense arrays [n_pages, Hkv, page_size, D] per layer group,
written with vectorized scatters and read by the paged Pallas kernel
(ops/pallas_paged.py).

Sizing: a debate round's opponents share the pool; ``n_pages`` bounds
total resident tokens across all rows, not per-row length — the property
that lets a 16k-context judge coexist with short critics (SURVEY §5
long-context obligation).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp
import numpy as np


class OutOfPages(RuntimeError):
    pass


@dataclass
class PagedCacheLayout:
    n_pages: int
    page_size: int
    n_layers: int
    n_kv_heads: int
    head_dim: int

    @property
    def tokens_capacity(self) -> int:
        return self.n_pages * self.page_size


class PageAllocator:
    """Free-list page allocator with per-sequence ordered page tables."""

    def __init__(self, n_pages: int, page_size: int):
        self.n_pages = n_pages
        self.page_size = page_size
        self._free = list(range(n_pages - 1, -1, -1))  # pop() → page 0 first
        self._tables: dict[int, list[int]] = {}
        self._lengths: dict[int, int] = {}

    @property
    def free_pages(self) -> int:
        return len(self._free)

    def new_sequence(self, seq_id: int) -> None:
        if seq_id in self._tables:
            raise ValueError(f"sequence {seq_id} already allocated")
        self._tables[seq_id] = []
        self._lengths[seq_id] = 0

    def extend(self, seq_id: int, n_tokens: int) -> list[int]:
        """Reserve room for n_tokens more; returns newly allocated pages."""
        table = self._tables[seq_id]
        length = self._lengths[seq_id]
        needed_pages = -(-(length + n_tokens) // self.page_size)
        new_pages = []
        while len(table) < needed_pages:
            if not self._free:
                # Roll back this call's allocations before failing.
                for p in new_pages:
                    table.remove(p)
                    self._free.append(p)
                raise OutOfPages(
                    f"paged KV cache exhausted: {self.n_pages} pages of "
                    f"{self.page_size} tokens all in use"
                )
            p = self._free.pop()
            table.append(p)
            new_pages.append(p)
        self._lengths[seq_id] = length + n_tokens
        return new_pages

    def length(self, seq_id: int) -> int:
        return self._lengths[seq_id]

    def table(self, seq_id: int) -> list[int]:
        return list(self._tables[seq_id])

    def free_sequence(self, seq_id: int) -> None:
        for p in self._tables.pop(seq_id):
            self._free.append(p)
        del self._lengths[seq_id]

    def table_array(self, seq_ids: list[int], max_pages: int) -> np.ndarray:
        """Batched page table [B, max_pages], -1-padded, for the kernel."""
        out = np.full((len(seq_ids), max_pages), -1, np.int32)
        for i, sid in enumerate(seq_ids):
            t = self._tables[sid]
            if len(t) > max_pages:
                raise ValueError(
                    f"sequence {sid} spans {len(t)} pages > {max_pages}"
                )
            out[i, : len(t)] = t
        return out


def init_page_pool(
    layout: PagedCacheLayout, dtype=jnp.bfloat16, kv_dtype: str = ""
) -> dict[str, jnp.ndarray]:
    """Device page pool: per-layer stacked K/V pages.

    ``kv_dtype="int8"``: pages store int8 K/V plus per-(token, head)
    f32 scale pages ("ks"/"vs", trailing dim 1) — the paged counterpart
    of the dense cache's int8 layout (models/transformer.py:init_cache).
    Presence of "ks" marks a quantized pool.
    """
    shape = (
        layout.n_layers,
        layout.n_pages,
        layout.n_kv_heads,
        layout.page_size,
        layout.head_dim,
    )
    if kv_dtype == "int8":
        sshape = shape[:-1] + (1,)
        return {
            "k": jnp.zeros(shape, jnp.int8),
            "v": jnp.zeros(shape, jnp.int8),
            "ks": jnp.zeros(sshape, jnp.float32),
            "vs": jnp.zeros(sshape, jnp.float32),
        }
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def write_tokens(
    pool: dict[str, jnp.ndarray],
    k_new: jnp.ndarray,  # [L, B, Hkv, S, D] — heads-major cache layout
    v_new: jnp.ndarray,
    page_ids: np.ndarray,  # [B, S] physical page per token
    offsets: np.ndarray,  # [B, S] slot within page per token
    ks_new: jnp.ndarray | None = None,  # [L, B, Hkv, S, 1] (int8 pools)
    vs_new: jnp.ndarray | None = None,
) -> dict[str, jnp.ndarray]:
    """Scatter freshly computed K/V into their pages (vectorized).

    Quantized pools take the matching scale slices (both or neither) —
    the same [L, B, Hkv, S, 1] layout the dense int8 cache stores.
    """
    L, B, H, S, D = k_new.shape
    pid = jnp.asarray(page_ids).reshape(-1)  # [B*S]
    off = jnp.asarray(offsets).reshape(-1)

    def flat(x):  # [L, B, H, S, *] → [B*S, L, H, *] (token-major updates)
        return jnp.transpose(x, (1, 3, 0, 2, 4)).reshape(
            B * S, L, H, x.shape[-1]
        )

    # pool[l, pid[n], :, off[n]] = new[n, l] for every layer l, token n.
    # Advanced indices (pid at dim 1, off at dim 3) are separated by the
    # head slice, so the token axis lands in front of the result — the
    # updates are built token-major to match.
    out = {
        "k": pool["k"].at[:, pid, :, off].set(flat(k_new)),
        "v": pool["v"].at[:, pid, :, off].set(flat(v_new)),
    }
    if "ks" in pool:
        if ks_new is None or vs_new is None:
            raise ValueError(
                "quantized pool requires ks_new/vs_new scale slices"
            )
        out["ks"] = pool["ks"].at[:, pid, :, off].set(flat(ks_new))
        out["vs"] = pool["vs"].at[:, pid, :, off].set(flat(vs_new))
    return out


def token_positions_to_pages(
    allocator: PageAllocator, seq_ids: list[int], positions: np.ndarray
) -> tuple[np.ndarray, np.ndarray]:
    """Map per-row token positions [B, S] → (page_ids, offsets) [B, S]."""
    B, S = positions.shape
    page_ids = np.zeros((B, S), np.int32)
    offsets = np.zeros((B, S), np.int32)
    for i, sid in enumerate(seq_ids):
        table = allocator.table(sid)
        for j in range(S):
            pos = int(positions[i, j])
            page_ids[i, j] = table[pos // allocator.page_size]
            offsets[i, j] = pos % allocator.page_size
    return page_ids, offsets
