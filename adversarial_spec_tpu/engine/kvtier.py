"""Tiered KV cache: host-RAM offload + persistent content-addressed store.

The device page pool (engine/kvcache.py) is tier 0 and dies twice over:
LRU pressure evicts a prefix block's page and the next admission re-pays
its prefill, and a process restart re-pays prefill for every hot system
prompt/spec document. Debate workloads are worst-case — every round
shares a giant document prefix across many opponents. This module adds
the two tiers below the pool:

- **Tier 1 — host RAM** (:class:`HostTier`): when the prefix cache
  LRU-evicts a leaf block, its KV pages demote to host buffers. The
  device→host copy is started at evict time (``copy_to_host_async``
  discipline — the scheduler passes a LAZY materializer, so the fetch
  resolves off the hot path) and the block re-promotes into a later
  admission's pages with an async ``device_put`` that overlaps the
  delta prefill. Bounded by ``--kv-host-mb``; LRU overflow spills to
  tier 2 (or drops when no store is armed).
- **Tier 2 — disk** (:class:`DiskStore`): a content-addressed store
  keyed by the radix block's CHAIN HASH (parent chain + block tokens —
  the same identity the radix trie realizes through dict hashing) plus
  a model/config fingerprint. Versioned header, atomic rename writes,
  sha-verified payloads, corrupt-entry quarantine. A restarted process
  — or a fleet with overlapping prompts — rehydrates hot prefixes
  instead of re-prefilling. Inserted blocks write through to the store
  (queued; flushed at drain end, off the serving path), so restart
  rehydration does not depend on eviction pressure ever having fired.

The tier state machine (every demoted block ends in EXACTLY ONE of
re-promote / spill / host-free; a consumed disk entry stays resident for
the next restart) is host-side and content-free, so the mock engine
drives the same machine deterministically on CPU with ``payload=None``
— hit ratios, swap counts, and SwapEvents pin in tier-1 without a TPU.

Process-wide config + stats follow the ``procconfig`` pattern shared
with ``interleave``/``spec``/``prefix_cache``: the CLI arms per round
(``--kv-host-mb``, ``--kv-store-dir``, ``--kv-flush-blocks``,
``--no-kv-tier``; env ``ADVSPEC_KV_HOST_MB`` / ``ADVSPEC_KV_STORE_DIR``
/ ``ADVSPEC_KV_FLUSH_BLOCKS`` / ``ADVSPEC_KV_TIER``) and snapshots into
``perf.kv_tier``. Deliberately imports no jax.
"""

from __future__ import annotations

import hashlib
import itertools
import json
import os
import threading
import time
from dataclasses import dataclass, field

import numpy as np

from adversarial_spec_tpu import obs as obs_mod
from adversarial_spec_tpu.engine import procconfig
from adversarial_spec_tpu.resilience import lockdep as lockdep_mod

DEFAULT_HOST_MB = 256

# -- config + stats ---------------------------------------------------------


@dataclass
class TierConfig:
    """Process-wide knobs, set once per CLI round (or by tests)."""

    enabled: bool = True
    # Host-RAM tier budget in MiB (0 disables tier 1).
    host_mb: int = DEFAULT_HOST_MB
    # Disk-store root directory ("" disables tier 2).
    store_dir: str = ""
    # Write-through flush threshold: flush the pending disk queue every
    # N queued blocks (0 = only at drain-end settle()). Mid-drain
    # flushes write ONLY already-resolved payloads — an unresolved lazy
    # materializer stays queued, so the no-sync-on-hot-path discipline
    # holds. Armed for cross-replica handoff (fleet disaggregation):
    # a decode replica can only adopt blocks that reached the shared
    # store before the drain ended.
    flush_blocks: int = 0


def env_enabled() -> bool:
    """The process default for the master switch (``ADVSPEC_KV_TIER``)."""
    return os.environ.get("ADVSPEC_KV_TIER", "1") != "0"


def env_host_mb() -> int:
    """The process default host budget (``ADVSPEC_KV_HOST_MB``)."""
    try:
        return max(0, int(os.environ.get("ADVSPEC_KV_HOST_MB", DEFAULT_HOST_MB)))
    except ValueError:
        return DEFAULT_HOST_MB


def env_store_dir() -> str:
    """The process default store root (``ADVSPEC_KV_STORE_DIR``)."""
    return os.environ.get("ADVSPEC_KV_STORE_DIR", "") or ""


def env_flush_blocks() -> int:
    """The process default flush threshold (``ADVSPEC_KV_FLUSH_BLOCKS``)."""
    try:
        return max(0, int(os.environ.get("ADVSPEC_KV_FLUSH_BLOCKS", "0")))
    except ValueError:
        return 0


@dataclass
class TierStats(procconfig.StatsBase):
    """Process-wide tier counters, aggregated across every batcher (and
    the mock engine's deterministic accounting).

    ``tier_lookups`` counts radix lookups that CONTINUED past the device
    tier (the prefix cache had tiers attached), so the per-tier hit
    rates measure how often the lower tiers rescued a device miss.
    Promotion (host→device) and rehydration (disk→device) are counted
    separately: the first is the pressure-thrash save, the second the
    restart/fleet save. ``recomputed_blocks`` counts promotions that
    LOST THE RACE (entry evicted/corrupt between lookup and promotion)
    and fell back to prefill — the correctness escape hatch, visible so
    a noisy store shows up in telemetry rather than as silent slowness.
    """

    tier_lookups: int = 0
    host_hits: int = 0  # lookups that matched >= 1 host-resident block
    disk_hits: int = 0  # lookups that matched >= 1 disk-resident block
    demoted_blocks: int = 0
    demoted_tokens: int = 0
    promoted_blocks: int = 0  # host -> device re-promotions
    promoted_tokens: int = 0
    rehydrated_blocks: int = 0  # disk -> device rehydrations
    rehydrated_tokens: int = 0
    recomputed_blocks: int = 0  # promotions lost the race -> prefilled
    spilled_blocks: int = 0  # host LRU overflow written through to disk
    host_freed_blocks: int = 0  # host LRU overflow dropped (no store)
    store_writes: int = 0
    store_corrupt: int = 0  # quarantined disk entries
    swap_in_s: float = 0.0  # promotion/rehydration wall (host+disk -> dev)
    swap_out_s: float = 0.0  # demotion/spill/store wall

    def record_lookup(self, host_blocks: int, disk_blocks: int) -> None:
        self.tier_lookups += 1
        if host_blocks:
            self.host_hits += 1
        if disk_blocks:
            self.disk_hits += 1
        if obs_mod.config().enabled:
            obs_mod.hot.tier_hit_ratio("host").set(
                round(self.host_hits / self.tier_lookups, 6)
            )
            obs_mod.hot.tier_hit_ratio("disk").set(
                round(self.disk_hits / self.tier_lookups, 6)
            )

    def snapshot(self) -> dict:
        out = self.as_dict()
        out["host_hit_rate"] = (
            round(self.host_hits / self.tier_lookups, 4)
            if self.tier_lookups
            else 0.0
        )
        out["disk_hit_rate"] = (
            round(self.disk_hits / self.tier_lookups, 4)
            if self.tier_lookups
            else 0.0
        )
        return out


_state = procconfig.ProcState(
    TierConfig(
        enabled=env_enabled(),
        host_mb=env_host_mb(),
        store_dir=env_store_dir(),
        flush_blocks=env_flush_blocks(),
    ),
    TierStats(),
    coerce={
        "host_mb": lambda v: max(0, int(v)),
        "flush_blocks": lambda v: max(0, int(v)),
    },
)
_config = _state.config
stats = _state.stats


def config() -> TierConfig:
    return _state.config


def configure(
    enabled: bool | None = None,
    host_mb: int | None = None,
    store_dir: str | None = None,
    flush_blocks: int | None = None,
) -> TierConfig:
    return _state.configure(
        enabled=enabled,
        host_mb=host_mb,
        store_dir=store_dir,
        flush_blocks=flush_blocks,
    )


def reset_stats() -> None:
    _state.reset_stats()


def snapshot() -> dict:
    """Stats + config, the ``perf.kv_tier`` payload."""
    return _state.snapshot()


def armed() -> bool:
    """True when the process config arms at least one lower tier."""
    return _config.enabled and (_config.host_mb > 0 or bool(_config.store_dir))


# -- content addressing -----------------------------------------------------


def chain_hash(parent: str, tokens) -> str:
    """Content address of one radix block: the chain ``(parent chain,
    block tokens)`` — the same identity the trie realizes through dict
    hashing, made stable across processes (the disk store's key).
    Tokens may be ints (real engines) or strings (the mock's 4-char
    chunks); both serialize through ``str``."""
    h = hashlib.sha256()
    h.update(parent.encode("ascii"))
    h.update(b"\x00")
    for t in tokens:
        h.update(str(t).encode("utf-8"))
        h.update(b"\x1f")
    return h.hexdigest()


def fingerprint(*parts) -> str:
    """Model/config fingerprint for the disk store: KV produced under a
    different model, dtype, page size, or layout must never rehydrate —
    the parts hash into the store's namespace directory."""
    h = hashlib.sha256()
    h.update(json.dumps([str(p) for p in parts]).encode("utf-8"))
    return h.hexdigest()[:16]


# -- tier 1: host RAM -------------------------------------------------------


@dataclass
class HostBlock:
    chain: str
    tokens: tuple
    # None (mock accounting), a dict of np arrays, or a ZERO-ARG LAZY
    # MATERIALIZER (the scheduler's demotion fetch: the device->host
    # copy was started at evict time; calling the closure resolves it —
    # free once the async copy has landed).
    payload: object
    n_tokens: int
    last_used: int = 0


class HostTier:
    """Bounded LRU of demoted KV blocks in host RAM.

    Capacity is byte-budgeted (``capacity_bytes`` / ``block_bytes`` —
    the owner computes bytes-per-block from the pool layout; the mock
    passes a nominal figure). The conservation invariant the chaos
    tests pin: every block ever demoted ends in EXACTLY ONE of
    resident / promoted / spilled / freed — ``check_invariants``
    raises on any bookkeeping drift."""

    def __init__(self, capacity_bytes: int, block_bytes: int):
        self.capacity_bytes = max(0, int(capacity_bytes))
        self.block_bytes = max(1, int(block_bytes))
        self._blocks: dict[str, HostBlock] = {}
        self._clock = 0
        # Conservation counters (lifetime, for check_invariants).
        self.demoted = 0
        self.promoted = 0
        self.spilled = 0
        self.freed = 0

    @property
    def resident_blocks(self) -> int:
        return len(self._blocks)

    @property
    def resident_bytes(self) -> int:
        return len(self._blocks) * self.block_bytes

    def put(self, chain: str, tokens, payload) -> list[HostBlock]:
        """Demote one block; returns the LRU blocks evicted to make
        room (the caller spills them to disk or frees them)."""
        self._clock += 1
        old = self._blocks.pop(chain, None)
        if old is not None:
            # Re-demotion of a chain already resident: the old copy is
            # replaced (content-identical by construction) — account it
            # freed so conservation holds.
            self.freed += 1
        self.demoted += 1
        self._blocks[chain] = HostBlock(
            chain=chain,
            tokens=tuple(tokens),
            payload=payload,
            n_tokens=len(tokens),
            last_used=self._clock,
        )
        evicted: list[HostBlock] = []
        while (
            self.resident_bytes > self.capacity_bytes
            and len(self._blocks) > 1
        ):
            lru = min(self._blocks.values(), key=lambda b: b.last_used)
            evicted.append(self._blocks.pop(lru.chain))
        if self.resident_bytes > self.capacity_bytes:
            # A single block over budget: nothing to keep.
            evicted.extend(self._blocks.values())
            self._blocks.clear()
        return evicted

    def get(self, chain: str) -> HostBlock | None:
        b = self._blocks.get(chain)
        if b is not None:
            self._clock += 1
            b.last_used = self._clock
        return b

    def take_promoted(self, chain: str) -> HostBlock | None:
        """Remove a block the caller just re-promoted into the device
        pool (terminal state: promoted). Called AFTER the device write
        lands, so a fault mid-promotion leaves the block resident —
        the tier is never corrupted by an aborted swap."""
        b = self._blocks.pop(chain, None)
        if b is not None:
            self.promoted += 1
        return b

    def note_spilled(self, n: int = 1) -> None:
        self.spilled += n

    def note_freed(self, n: int = 1) -> None:
        self.freed += n

    @staticmethod
    def materialize(block: HostBlock):
        """Resolve a lazy payload in place (the demotion fetch closure
        — by promotion/spill time the async copy has landed, so this is
        a free host read, not a device sync)."""
        if callable(block.payload):
            block.payload = block.payload()
        return block.payload

    def clear(self) -> None:
        self.freed += len(self._blocks)
        self._blocks.clear()

    def check_invariants(self) -> None:
        """Raise RuntimeError on bookkeeping drift: byte accounting,
        duplicate identity, or conservation (demoted blocks must all be
        accounted resident/promoted/spilled/freed)."""
        if len({b.chain for b in self._blocks.values()}) != len(self._blocks):
            raise RuntimeError("host tier holds duplicate chains")
        for chain, b in self._blocks.items():
            if b.chain != chain:
                raise RuntimeError(
                    f"host tier key {chain} holds block {b.chain}"
                )
        accounted = (
            len(self._blocks) + self.promoted + self.spilled + self.freed
        )
        if accounted != self.demoted:
            raise RuntimeError(
                f"host tier conservation violated: {self.demoted} demoted "
                f"!= {len(self._blocks)} resident + {self.promoted} "
                f"promoted + {self.spilled} spilled + {self.freed} freed"
            )


# -- tier 2: disk -----------------------------------------------------------

_MAGIC = b"ADVSPECKV"
_VERSION = 1


def _np_dtype(name: str):
    """Resolve a dtype name, including bfloat16 (ml_dtypes ships with
    jax; the store itself stays importable without it for non-bf16
    payloads)."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


class DiskStore:
    """Content-addressed on-disk KV block store (tier 2).

    Layout: ``<root>/<fingerprint>/<chain[:2]>/<chain>.kvb`` — the
    fingerprint namespaces by model/config so incompatible KV can never
    rehydrate. Entries are written to a temp name then ``os.replace``d
    (atomic on POSIX): a crashed writer leaves a ``.tmp`` orphan, never
    a torn entry. Every read verifies magic, version, fingerprint,
    chain, token content, and the payload sha; ANY failure quarantines
    the file (moved aside, counted) and reads as a miss — a corrupt
    entry costs one re-prefill, not a wrong transcript."""

    def __init__(self, root: str, fingerprint: str):
        self.root = root
        self.fingerprint = fingerprint
        self.dir = os.path.join(root, fingerprint)
        self.quarantine_dir = os.path.join(self.dir, "quarantine")
        os.makedirs(self.dir, exist_ok=True)
        # Serializes the replace-and-count section of put() across
        # THREADS sharing this instance; concurrent PROCESSES (fleet
        # replicas sharing one store dir) are already safe — each
        # writes a unique temp name and the replaces are atomic, so
        # the last identical copy wins and every instance's resident
        # count stays consistent with its own scan.
        self._put_lock = lockdep_mod.make_lock("DiskStore._put_lock")
        self._tmp_seq = itertools.count()
        self._resident = self._scan()

    def _scan(self) -> int:
        n = 0
        for sub in os.listdir(self.dir):
            p = os.path.join(self.dir, sub)
            if len(sub) == 2 and os.path.isdir(p):
                n += sum(1 for f in os.listdir(p) if f.endswith(".kvb"))
        return n

    @property
    def resident_entries(self) -> int:
        return self._resident

    def _path(self, chain: str) -> str:
        return os.path.join(self.dir, chain[:2], f"{chain}.kvb")

    def has(self, chain: str) -> bool:
        return os.path.exists(self._path(chain))

    def put(self, chain: str, tokens, payload: dict | None) -> bool:
        """Write one entry (idempotent: content-addressed, an existing
        entry is left alone). Returns True when a new entry landed."""
        path = self._path(chain)
        if os.path.exists(path):
            return False
        blobs: list[bytes] = []
        arrays = []
        if payload is not None:
            for name in sorted(payload):
                arr = np.ascontiguousarray(payload[name])
                raw = arr.tobytes()
                arrays.append(
                    {
                        "name": name,
                        "dtype": str(arr.dtype),
                        "shape": list(arr.shape),
                        "nbytes": len(raw),
                    }
                )
                blobs.append(raw)
        body = b"".join(blobs)
        header = json.dumps(
            {
                "fp": self.fingerprint,
                "chain": chain,
                "tokens": [
                    t if isinstance(t, (int, str)) else str(t)
                    for t in tokens
                ],
                "payload": payload is not None,
                "arrays": arrays,
                "sha": hashlib.sha256(body).hexdigest(),
            },
            separators=(",", ":"),
        ).encode("utf-8")
        os.makedirs(os.path.dirname(path), exist_ok=True)
        # The temp name is unique per (process, thread, call): two
        # concurrent writers of the SAME chain — two fleet replicas
        # writing through one store, or two threads of one engine —
        # must never interleave bytes into one temp file. Both finish
        # a complete, identical entry and both os.replace atomically;
        # the second replace installs identical content over the
        # first, so the store ends with exactly one valid entry and
        # no torn/quarantinable state.
        tmp = (
            f"{path}.{os.getpid()}.{threading.get_ident()}"
            f".{next(self._tmp_seq)}.tmp"
        )
        with open(tmp, "wb") as f:
            f.write(_MAGIC)
            f.write(bytes([_VERSION]))
            f.write(len(header).to_bytes(4, "little"))
            f.write(header)
            f.write(body)
        with self._put_lock:
            # Lost the race to a sibling thread: its entry already
            # landed and was counted — replacing with identical bytes
            # is harmless, but counting twice would drift the resident
            # ledger off the on-disk scan.
            existed = os.path.exists(path)
            os.replace(tmp, path)
            if existed:
                return False
            self._resident += 1
        return True

    def _quarantine(self, chain: str, reason: str) -> None:
        """Move a bad entry aside (never delete — it is evidence) and
        count it; the store keeps serving everything else."""
        path = self._path(chain)
        try:
            os.makedirs(self.quarantine_dir, exist_ok=True)
            os.replace(
                path, os.path.join(self.quarantine_dir, f"{chain}.kvb")
            )
            with self._put_lock:
                self._resident = max(0, self._resident - 1)
        except OSError:
            pass
        stats.store_corrupt += 1
        with self._put_lock:
            resident = self._resident
        obs_mod.emit(
            obs_mod.SwapEvent(
                op="quarantine",
                tier="disk",
                blocks=1,
                disk_resident=resident,
            )
        )

    def get(self, chain: str, tokens=None) -> tuple[tuple, dict | None] | None:
        """Read + fully verify one entry; ``tokens`` (when given) must
        match the stored block content. None = miss (absent or
        quarantined just now)."""
        path = self._path(chain)
        if not os.path.exists(path):
            return None
        try:
            with open(path, "rb") as f:
                if f.read(len(_MAGIC)) != _MAGIC:
                    raise ValueError("bad magic")
                version = f.read(1)
                if version != bytes([_VERSION]):
                    raise ValueError(f"unsupported version {version!r}")
                hlen = int.from_bytes(f.read(4), "little")
                if not 0 < hlen <= 1 << 24:
                    raise ValueError("implausible header length")
                header = json.loads(f.read(hlen).decode("utf-8"))
                if header.get("fp") != self.fingerprint:
                    raise ValueError("fingerprint mismatch")
                if header.get("chain") != chain:
                    raise ValueError("chain mismatch")
                stored = tuple(header.get("tokens", ()))
                if tokens is not None and stored != tuple(tokens):
                    raise ValueError("token content mismatch")
                body = f.read()
            if hashlib.sha256(body).hexdigest() != header.get("sha"):
                raise ValueError("payload sha mismatch")
            if not header.get("payload"):
                return stored, None
            payload: dict = {}
            off = 0
            for spec in header["arrays"]:
                raw = body[off : off + spec["nbytes"]]
                if len(raw) != spec["nbytes"]:
                    raise ValueError("truncated payload")
                payload[spec["name"]] = np.frombuffer(
                    raw, dtype=_np_dtype(spec["dtype"])
                ).reshape(spec["shape"])
                off += spec["nbytes"]
            if off != len(body):
                raise ValueError("trailing payload bytes")
            return stored, payload
        except (ValueError, KeyError, TypeError, json.JSONDecodeError, OSError) as e:
            self._quarantine(chain, str(e))
            return None


# -- the composed store -----------------------------------------------------


@dataclass
class TierHit:
    """One lower-tier block a tiered lookup matched, promotable into
    the admission being set up."""

    chain: str
    tokens: tuple
    source: str  # "host" | "disk"
    block: HostBlock | None = None  # host hits carry the entry


@dataclass
class _PendingStore:
    chain: str
    tokens: tuple
    payload: object  # dict | lazy materializer | None


# Outstanding LAZY payloads (each pinning one gathered device array)
# are bounded: past this many, the OLDEST resolve eagerly — their
# async copies landed long ago, so the fetch is a free host read, and
# the device memory demotion exists to relieve actually releases
# while pressure is still on.
_LAZY_RESOLVE_AFTER = 32


class TieredStore:
    """Tier 1 + tier 2 behind one interface; owns the swap stats and
    SwapEvent emission so the scheduler and the mock engine share one
    state machine (and one telemetry schema)."""

    def __init__(
        self,
        host: HostTier | None,
        disk: DiskStore | None,
        *,
        stats: TierStats | None = None,
    ):
        from collections import deque

        self.host = host
        self.disk = disk
        self.stats = stats if stats is not None else globals()["stats"]
        # Disk write-through queue, keyed by chain (content-addressed:
        # one pending write per block). File I/O happens at settle().
        self._pending: dict[str, _PendingStore] = {}
        # Holders (HostBlock / _PendingStore) whose payload is still a
        # lazy device-array materializer, oldest first.
        self._lazy = deque()

    def _note_lazy(self, holder) -> None:
        self._lazy.append(holder)
        while len(self._lazy) > _LAZY_RESOLVE_AFTER:
            h = self._lazy.popleft()
            if callable(h.payload):
                h.payload = h.payload()

    @property
    def host_resident(self) -> int:
        return self.host.resident_blocks if self.host is not None else 0

    @property
    def disk_resident(self) -> int:
        return self.disk.resident_entries if self.disk is not None else 0

    def _emit(self, op: str, tier: str, blocks: int, tokens: int, slot: int = -1) -> None:
        obs_mod.emit(
            obs_mod.SwapEvent(
                op=op,
                tier=tier,
                blocks=blocks,
                tokens=tokens,
                slot=slot,
                host_resident=self.host_resident,
                disk_resident=self.disk_resident,
            )
        )

    def _spill(self, evicted: list[HostBlock]) -> None:
        """Host LRU overflow: queue for disk write-through when a store
        is armed (terminal state: spilled; the file lands at settle —
        I/O never rides the serving path), else drop (freed). The
        evicted block is the LRU — its demotion copy resolved long ago,
        so materializing here is a free host read, and resolving now
        releases the gathered device array."""
        for b in evicted:
            if self.disk is not None:
                t0 = time.monotonic()
                payload = HostTier.materialize(b)
                self.host.note_spilled()
                self.stats.spilled_blocks += 1
                self.stats.swap_out_s += time.monotonic() - t0
                self.enqueue_store(b.chain, b.tokens, payload)
                self._emit("spill", "disk", 1, b.n_tokens)
            else:
                self.host.note_freed()
                self.stats.host_freed_blocks += 1
                self._emit("free", "host", 1, b.n_tokens)

    def demote(self, chain: str, tokens, payload, slot: int = -1) -> None:
        """One LRU-evicted radix block enters the lower tiers. Spill
        wall accumulates inside ``_spill`` — the demote window here is
        measured BEFORE spilling so ``swap_out_s`` never counts the
        same seconds twice."""
        t0 = time.monotonic()
        self.stats.demoted_blocks += 1
        self.stats.demoted_tokens += len(tokens)
        evicted: list[HostBlock] = []
        if self.host is not None:
            evicted = self.host.put(chain, tokens, payload)
            blk = self.host._blocks.get(chain)
            # blk is None when the block alone exceeds the host budget
            # (put's over-budget branch evicted it straight into
            # ``evicted``) — it spills/frees below like any other LRU
            # victim instead of being tracked as resident.
            if blk is not None and callable(payload):
                self._note_lazy(blk)
            self._emit("demote", "host", 1, len(tokens), slot)
        elif self.disk is not None:
            # Disk-only tiering: queue the write (the payload stays a
            # lazy handle — the gather was dispatched microseconds ago
            # and resolving it HERE would be a genuine host sync on
            # the serving path; settle resolves it off the hot path).
            self.enqueue_store(chain, tokens, payload)
            self._emit("demote", "disk", 1, len(tokens), slot)
        dt = time.monotonic() - t0
        self.stats.swap_out_s += dt
        if obs_mod.config().enabled:
            obs_mod.hot.swap_latency("out").observe(dt)
        if evicted:
            self._spill(evicted)

    def lookup_chain(self, chain: str, tokens) -> TierHit | None:
        """Host first (cheaper, warmer), then the disk tier — existence
        only; payload reads happen at promotion (``materialize``). A
        block queued for write-through but not yet flushed counts as
        disk-resident (the pending entry serves it)."""
        if self.host is not None:
            b = self.host.get(chain)
            if b is not None:
                return TierHit(
                    chain=chain, tokens=tuple(tokens), source="host", block=b
                )
        if self.disk is not None and (
            chain in self._pending or self.disk.has(chain)
        ):
            return TierHit(chain=chain, tokens=tuple(tokens), source="disk")
        return None

    def record_lookup(self, hits: list[TierHit]) -> None:
        self.stats.record_lookup(
            sum(1 for h in hits if h.source == "host"),
            sum(1 for h in hits if h.source == "disk"),
        )

    def materialize(self, hit: TierHit) -> tuple[bool, dict | None]:
        """Resolve a hit's payload for promotion. ``(False, None)``
        means the promotion LOST THE RACE (entry evicted, quarantined,
        or content mismatch since lookup) — the caller falls back to
        recomputing the block via plain prefill."""
        if hit.source == "host":
            b = (
                self.host.get(hit.chain) if self.host is not None else None
            )
            if b is None:
                self.stats.recomputed_blocks += 1
                return False, None
            return True, HostTier.materialize(b)
        p = self._pending.get(hit.chain)
        if p is not None:
            if callable(p.payload):
                p.payload = p.payload()
            return True, p.payload
        entry = self.disk.get(hit.chain, hit.tokens) if self.disk else None
        if entry is None:
            self.stats.recomputed_blocks += 1
            return False, None
        return True, entry[1]

    def consume(self, hit: TierHit, slot: int = -1, wall_s: float = 0.0) -> None:
        """The hit's KV landed in the device pool: finalize its state.
        Host entries leave the tier (terminal: promoted); disk entries
        STAY — the store is the persistent tier, and this block's next
        reader may be a restarted process."""
        n = len(hit.tokens)
        self.stats.swap_in_s += wall_s
        if hit.source == "host":
            self.host.take_promoted(hit.chain)
            self.stats.promoted_blocks += 1
            self.stats.promoted_tokens += n
            self._emit("promote", "host", 1, n, slot)
        else:
            self.stats.rehydrated_blocks += 1
            self.stats.rehydrated_tokens += n
            self._emit("rehydrate", "disk", 1, n, slot)
        if obs_mod.config().enabled and wall_s > 0.0:
            obs_mod.hot.swap_latency("in").observe(wall_s)

    def needs_store(self, chain: str) -> bool:
        """Would ``enqueue_store`` actually queue this chain? Callers
        whose payload fetch is EXPENSIVE (the scheduler's device
        gather) check this first so an already-stored/already-queued
        block never pays a discarded gather."""
        return (
            self.disk is not None
            and chain not in self._pending
            and not self.disk.has(chain)
        )

    def enqueue_store(self, chain: str, tokens, payload) -> None:
        """Queue one block for disk write-through (content-addressed:
        already-stored and already-queued chains are no-ops). Flushed by
        ``settle()`` at drain end — and, when ``flush_blocks`` arms the
        write-through threshold, every N queued blocks mid-drain (the
        fleet-handoff publication window). Threshold flushes write only
        ALREADY-RESOLVED payloads, so file I/O rides the serving path
        but a device sync never does."""
        if (
            self.disk is None
            or chain in self._pending
            or self.disk.has(chain)
        ):
            return
        entry = _PendingStore(chain, tuple(tokens), payload)
        self._pending[chain] = entry
        if callable(payload):
            self._note_lazy(entry)
        threshold = _config.flush_blocks
        if threshold > 0 and len(self._pending) >= threshold:
            self._flush_pending(force=False)

    def _flush_pending(self, force: bool = True) -> int:
        """Write queued blocks through to the disk store. ``force``
        (settle / handoff publication — the sanctioned sync points)
        resolves lazy payloads; a threshold flush (``force=False``)
        writes only blocks whose payload is already a plain value and
        leaves unresolved lazies queued — the serving path never pays a
        device sync for write-through. Returns entries written."""
        wrote = 0
        wrote_tokens = 0
        t0 = time.monotonic()
        pending = self._pending
        self._pending = {}
        if force:
            self._lazy.clear()
        for chain, p in pending.items():
            if not force and callable(p.payload):
                # Unresolved lazy: stays queued (and stays in _lazy —
                # the bounded resolve keeps draining it off-threshold).
                self._pending[chain] = p
                continue
            payload = p.payload() if callable(p.payload) else p.payload
            if self.disk is not None and self.disk.put(
                p.chain, p.tokens, payload
            ):
                wrote += 1
                wrote_tokens += len(p.tokens)
        if wrote:
            self.stats.store_writes += wrote
            self.stats.swap_out_s += time.monotonic() - t0
            self._emit("store", "disk", wrote, wrote_tokens)
        return wrote

    def settle(self) -> int:
        """Flush pending disk writes + resolve lazy host payloads (the
        sanctioned drain-end point: every async device→host copy
        started this drain has long resolved). Returns entries
        written."""
        wrote = self._flush_pending(force=True)
        if self.host is not None:
            for b in list(self.host._blocks.values()):
                HostTier.materialize(b)
        return wrote

    def publish_chains(self, chains, slot: int = -1) -> list[str]:
        """Prefill-side handoff publication: force-flush the pending
        queue (a sanctioned sync point, like ``settle`` — the prefill
        drain just ended) so the given chains are durable in the SHARED
        store, and return the sublist that actually is. Emits one
        ``ship`` SwapEvent for the durable blocks — the cross-replica
        half of the tier state machine's telemetry."""
        if self.disk is None:
            return []
        self._flush_pending(force=True)
        durable = [c for c in chains if self.disk.has(c)]
        if durable:
            self._emit("ship", "disk", len(durable), 0, slot)
        return durable

    def prefetch_chains(self, chains) -> int:
        """Decode-side prefetch hint: probe the store for chains a
        remote prefill shipped ahead of the admission that will adopt
        them (existence only — promotion into fresh pages happens in
        that admission's tiered lookup, overlapped with whatever the
        decode replica is doing now). Emits one ``prefetch`` SwapEvent;
        returns the probe's hit count."""
        if self.disk is None:
            return 0
        n = sum(
            1
            for c in chains
            if c in self._pending or self.disk.has(c)
        )
        self._emit("prefetch", "disk", n, 0)
        return n

    def check_invariants(self) -> None:
        if self.host is not None:
            self.host.check_invariants()
        if self.disk is not None:
            # One-sided on purpose: the store dir may be SHARED across
            # fleet replicas (that is its point — overlapping prefixes
            # rehydrate fleet-wide), so entries legitimately appear
            # that this instance never counted. Tracking MORE than the
            # scan finds is the local bookkeeping bug (double count /
            # phantom entry) this check exists to catch.
            resident = self.disk._scan()
            if resident < self.disk.resident_entries:
                raise RuntimeError(
                    f"disk store count drift: {self.disk.resident_entries} "
                    f"tracked vs {resident} on disk"
                )


def build_for(block_bytes: int, fingerprint_parts: tuple) -> TieredStore | None:
    """A TieredStore per the process config, or None when tiering is
    off. ``block_bytes`` is the host-budget unit (bytes one demoted
    block occupies — pool layout for real engines, nominal for the
    mock); ``fingerprint_parts`` namespace the disk store."""
    cfg = _config
    if not cfg.enabled:
        return None
    host = (
        HostTier(cfg.host_mb << 20, block_bytes) if cfg.host_mb > 0 else None
    )
    disk = (
        DiskStore(cfg.store_dir, fingerprint(*fingerprint_parts))
        if cfg.store_dir
        else None
    )
    if host is None and disk is None:
        return None
    return TieredStore(host, disk)
