"""Checkpoint materialization: HF safetensors → layer-stacked JAX pytrees.

TPU-native replacement for the reference's "model access" (API keys →
remote weights, scripts/providers.py:418-486): here access = reading HF
checkpoint dirs (``*.safetensors`` + config) into the transformer's
layer-stacked param pytree (models/transformer.py), transposing Linear
weights from torch's [out, in] to matmul-friendly [in, out] and stacking
per-layer tensors along a leading ``n_layers`` axis for scan-over-layers.

``checkpoint == "random"`` materializes synthetic weights of the family's
real shape (zero-egress test/bench path). Host RAM during load is bounded
to ONE stacked parameter in the target dtype: each stacked param is
assembled layer-by-layer into a single preallocated buffer (no per-layer
list, no np.stack double copy), placed on device via the caller's
``device_put`` hook, then freed before the next param is read (SURVEY §7
hard part (c): 70B within host RAM).
"""

from __future__ import annotations

import json
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np

from adversarial_spec_tpu.engine.checkpoint import transposed_head_flag
from adversarial_spec_tpu.models.config import ModelConfig, get_config
from adversarial_spec_tpu.models.transformer import Params, init_params

# Our layer-param name → HF per-layer tensor name (layers.{i} prefix added).
_HF_LAYER_MAP = {
    "attn_norm": "input_layernorm.weight",
    "wq": "self_attn.q_proj.weight",
    "wk": "self_attn.k_proj.weight",
    "wv": "self_attn.v_proj.weight",
    "wo": "self_attn.o_proj.weight",
    "bq": "self_attn.q_proj.bias",
    "bk": "self_attn.k_proj.bias",
    "bv": "self_attn.v_proj.bias",
    "ffn_norm": "post_attention_layernorm.weight",
    "w_gate": "mlp.gate_proj.weight",
    "w_up": "mlp.up_proj.weight",
    "w_down": "mlp.down_proj.weight",
    # Gemma-2 sandwich norms (HF names).
    "post_attn_norm": "post_attention_layernorm.weight",
    "ffn_norm_gemma2": "pre_feedforward_layernorm.weight",
    "post_ffn_norm": "post_feedforward_layernorm.weight",
}

_TRANSPOSE = {"wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down"}


class CheckpointConfigError(ValueError):
    """Registered architecture contradicts the checkpoint's config.json."""


def preflight_config(
    ckpt_dir: str | Path, cfg: ModelConfig, family: str
) -> None:
    """Cross-check the registered ModelConfig against the checkpoint's own
    ``config.json`` before any tensor is read.

    A mis-registered alias (wrong --family/--size for the directory it
    points at) would otherwise produce garbage logits with no error —
    shapes can coincide while rope_theta, GQA ratio, or tied embeddings
    differ. The reference fails fast with an actionable message at model
    access time (scripts/providers.py:418-486, key/alias preflight); this
    is the checkpoint-dir analog. A checkpoint without config.json (e.g.
    bare safetensors exports, test fixtures) is not checked.
    """
    path = Path(ckpt_dir) / "config.json"
    if not path.is_file():
        return
    try:
        hf = json.loads(path.read_text())
    except (OSError, json.JSONDecodeError) as e:
        raise CheckpointConfigError(
            f"unreadable config.json under {ckpt_dir}: {e}"
        ) from e

    problems: list[str] = []
    model_type = hf.get("model_type")
    if model_type is not None and str(model_type) != family:
        problems.append(
            f"model_type: checkpoint is {model_type!r}, "
            f"alias registered as family {family!r}"
        )

    scalar_checks = [
        ("hidden_size", "dim", cfg.dim),
        ("num_hidden_layers", "n_layers", cfg.n_layers),
        ("num_attention_heads", "n_heads", cfg.n_heads),
        ("num_key_value_heads", "n_kv_heads", cfg.n_kv_heads),
        ("intermediate_size", "ffn_dim", cfg.ffn_dim),
        ("vocab_size", "vocab_size", cfg.vocab_size),
        ("head_dim", "head_dim", cfg.head_dim),
        ("tie_word_embeddings", "tied_embeddings", cfg.tied_embeddings),
    ]
    # Qwen2 configs ship "sliding_window": 131072 with
    # "use_sliding_window": false — the declared window is inert, so
    # only compare when the checkpoint actually uses it.
    if hf.get("use_sliding_window", True):
        scalar_checks.append(
            ("sliding_window", "sliding_window", cfg.sliding_window)
        )
    for hf_key, field, want in scalar_checks:
        got = hf.get(hf_key)
        if got is None:
            continue
        try:
            if isinstance(want, bool):
                # Only a real JSON boolean (or 0/1) may match — bool([])
                # style coercion would silently pass malformed values.
                ok = (
                    isinstance(got, bool)
                    or (isinstance(got, int) and got in (0, 1))
                ) and bool(got) == want
            elif isinstance(want, float):
                ok = abs(float(got) - float(want)) < 1e-6
            else:
                ok = int(got) == want
        except (TypeError, ValueError):
            # A malformed value (string where a number belongs) is a
            # mismatch to report, never a crash.
            ok = False
        if not ok:
            problems.append(
                f"{hf_key}: checkpoint has {got!r}, registered config "
                f"({field}) has {want!r}"
            )

    theta = hf.get("rope_theta")
    if theta is not None:
        try:
            theta_mismatch = abs(float(theta) - cfg.rope_theta) > 1e-3
        except (TypeError, ValueError):
            theta_mismatch = True
        if theta_mismatch:
            problems.append(
                f"rope_theta: checkpoint has {theta!r}, registered config "
                f"has {cfg.rope_theta!r}"
            )

    rs = hf.get("rope_scaling")
    if rs is not None and not isinstance(rs, dict):
        problems.append(
            f"rope_scaling: checkpoint value {rs!r} is not an object"
        )
        rs = None
    rs_type = (rs or {}).get("rope_type", (rs or {}).get("type"))
    if rs and rs_type == "llama3":
        if cfg.rope_scaling is None:
            problems.append(
                "rope_scaling: checkpoint uses llama3 scaling "
                f"(factor={rs.get('factor')}), registered config is "
                "unscaled — long-context positions would be wrong"
            )
        else:
            want_f, want_lo, want_hi, want_orig = cfg.rope_scaling
            pairs = [
                ("factor", rs.get("factor"), want_f),
                ("low_freq_factor", rs.get("low_freq_factor"), want_lo),
                ("high_freq_factor", rs.get("high_freq_factor"), want_hi),
                (
                    "original_max_position_embeddings",
                    rs.get("original_max_position_embeddings"),
                    want_orig,
                ),
            ]
            for key, got, want in pairs:
                if got is None:
                    continue
                try:
                    pair_mismatch = abs(float(got) - want) > 1e-6
                except (TypeError, ValueError):
                    pair_mismatch = True
                if pair_mismatch:
                    problems.append(
                        f"rope_scaling.{key}: checkpoint has {got!r}, "
                        f"registered config has {want!r}"
                    )
    elif not rs and cfg.rope_scaling is not None:
        problems.append(
            "rope_scaling: registered config expects llama3 scaling "
            f"(factor={cfg.rope_scaling[0]}), checkpoint has none"
        )

    if problems:
        detail = "\n  - ".join(problems)
        raise CheckpointConfigError(
            f"checkpoint {ckpt_dir} does not match the registered "
            f"architecture for family {family!r}:\n  - {detail}\n"
            "Fix: re-register the alias with the family/size that matches "
            "this checkpoint (`registry` action, see `status`), or point "
            "it at the right directory. Loading anyway would produce "
            "garbage logits, not an error."
        )


def _open_safetensors(ckpt_dir: Path):
    """Return {tensor_name: (file, name)} across all shards."""
    from safetensors import safe_open

    index_path = ckpt_dir / "model.safetensors.index.json"
    files: dict[str, Path] = {}
    if index_path.is_file():
        index = json.loads(index_path.read_text())
        for name, fname in index["weight_map"].items():
            files[name] = ckpt_dir / fname
    else:
        shards = sorted(ckpt_dir.glob("*.safetensors"))
        if not shards:
            raise FileNotFoundError(f"no *.safetensors under {ckpt_dir}")
        for shard in shards:
            with safe_open(str(shard), framework="numpy") as f:
                for name in f.keys():
                    files[name] = shard
    return files


def _read_tensor(files: dict, name: str) -> np.ndarray:
    from safetensors import safe_open

    if name not in files:
        raise KeyError(f"tensor {name!r} missing from checkpoint")
    with safe_open(str(files[name]), framework="numpy") as f:
        return f.get_tensor(name)


def load_hf_checkpoint(
    ckpt_dir: str | Path,
    cfg: ModelConfig,
    family: str,
    dtype: jnp.dtype = jnp.bfloat16,
    device_put=None,
    transposed_head: bool | None = None,
) -> Params:
    """Read an HF checkpoint dir into the layer-stacked pytree.

    ``device_put(path_tuple, np_array) -> jax.Array`` lets the caller shard
    each tensor as it is read (defaults to plain jnp.asarray on the default
    device).

    ``transposed_head``: materialize the [D, V] head copy for tied
    configs (models/transformer.py:init_params). None reads the
    ADVSPEC_TRANSPOSED_HEAD env var (default on); set it to 0 on
    memory-tight fits to save the V·D bytes.
    """
    import ml_dtypes

    ckpt_dir = Path(ckpt_dir)
    preflight_config(ckpt_dir, cfg, family)
    files = _open_safetensors(ckpt_dir)
    put = device_put or (lambda path, arr: jnp.asarray(arr, dtype=dtype))
    np_dtype = np.dtype(
        {jnp.bfloat16: ml_dtypes.bfloat16}.get(dtype, np.dtype(dtype))
    )

    prefix = "model."

    def hf_name(layer_key: str) -> str:
        if family == "gemma2" and layer_key == "ffn_norm":
            return _HF_LAYER_MAP["ffn_norm_gemma2"]
        return _HF_LAYER_MAP[layer_key]

    def stack(layer_key: str) -> np.ndarray:
        """Assemble one layer-stacked param into a single preallocated
        target-dtype buffer — peak host RAM is this buffer plus one layer."""
        suffix = hf_name(layer_key)
        buf = None
        for i in range(cfg.n_layers):
            t = np.asarray(_read_tensor(files, f"{prefix}layers.{i}.{suffix}"))
            if layer_key in _TRANSPOSE:
                t = t.T  # torch Linear [out, in] → [in, out]
            if buf is None:
                buf = np.empty((cfg.n_layers,) + t.shape, np_dtype)
            buf[i] = t.astype(np_dtype)
        return buf

    layer_keys = [
        "attn_norm",
        "wq",
        "wk",
        "wv",
        "wo",
        "ffn_norm",
        "w_gate",
        "w_up",
        "w_down",
    ]
    if cfg.qkv_bias:
        layer_keys += ["bq", "bk", "bv"]
    if cfg.post_norms:
        layer_keys += ["post_attn_norm", "post_ffn_norm"]

    layers = {
        k: put(("layers", k), stack(k)) for k in layer_keys
    }
    embed_np = np.asarray(
        _read_tensor(files, f"{prefix}embed_tokens.weight")
    )
    params: Params = {
        "embed": put(("embed",), embed_np),
        "layers": layers,
        "final_norm": put(
            ("final_norm",), np.asarray(_read_tensor(files, f"{prefix}norm.weight"))
        ),
    }
    if transposed_head is None:
        transposed_head = transposed_head_flag()
    if not cfg.tied_embeddings:
        head = np.asarray(_read_tensor(files, "lm_head.weight")).T
        params["lm_head"] = put(("lm_head",), head)
    elif transposed_head:
        # Transposed [D, V] head copy for tied embeddings — the decode
        # hot path's head matmul at full bandwidth (see
        # models/transformer.py:init_params). np .T is a view of the
        # table already read for "embed"; `put` materializes it in the
        # target dtype/sharding.
        params["lm_head_t"] = put(("lm_head_t",), embed_np.T)
    return params


def materialize_params(
    checkpoint: str,
    family: str,
    size: str,
    dtype: jnp.dtype = jnp.bfloat16,
    seed: int = 0,
    max_seq_len: int = 0,
    device_put=None,
    quant: str = "",
) -> tuple[Params, ModelConfig]:
    """checkpoint == "random" → synthetic init; else HF safetensors dir.

    ``quant`` ("int8" / "int4", ops/quant.py) quantizes the matmul
    weights AT materialization, so every consumer (native-cache writer,
    residency estimate, serving path) sees one layout — the quantized
    shards are also what the weight-residency manager demotes to host
    RAM (engine/weightres.py), at a half/quarter of the bf16 bytes.
    """
    from adversarial_spec_tpu.ops.quant import quantize_params

    cfg = get_config(family, size, max_seq_len=max_seq_len)
    if checkpoint == "random":
        params = init_params(jax.random.key(seed), cfg, dtype=dtype)
        if device_put is not None:
            params = jax.tree_util.tree_map_with_path(
                lambda path, x: device_put(path, np.asarray(x)), params
            )
        return (quantize_params(params, fmt=quant) if quant else params), cfg
    params = load_hf_checkpoint(
        checkpoint, cfg, family, dtype=dtype, device_put=device_put
    )
    if quant:
        params = quantize_params(params, fmt=quant)
    return params, cfg
