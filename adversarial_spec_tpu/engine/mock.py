"""Scripted mock engine — the fake backend at the engine seam.

The reference's tests mock only the transport seam (``completion``,
``subprocess.run``) and run everything above it for real (SURVEY §4). The TPU
analog is this engine: it implements the same ``Engine`` interface as the TPU
engine, so the entire debate loop — CLI, rounds, parsing, convergence,
sessions, cost — runs unmodified on CPU with scripted critiques. It is also
BASELINE config 1 (1-round critique, 1 opponent, mock provider, CPU).

Model-id grammar (query params configure behavior):

- ``mock://agree``                      — replies [AGREE] immediately.
- ``mock://critic``                     — critiques forever, revising the spec.
- ``mock://critic?agree_after=3``       — critiques rounds 1-2, agrees from 3.
- ``mock://tasks``                      — emits structured [TASK] blocks
                                          (for export-tasks flows).
- ``mock://error``                      — permanent failure every call.
- ``mock://flaky?fail=2``               — transient failures on the first 2
                                          calls, then behaves like ``critic``.
- any id with ``&tps=N``                — simulates N tokens/sec decode speed
                                          in the reported usage (no sleeping).
- agreeing ids with ``&agree_tail=N``   — append N deterministic filler
                                          remarks AFTER the [AGREE] marker:
                                          the decode early cancellation
                                          exists to avoid paying for
                                          (bench.py --mode cancel).

Streaming parity works the same way (engine/streaming.py): a consumer
passed to ``chat`` receives the reply in fixed-width character chunks
(markers split across deliveries, like real token boundaries), and a
consumer returning False truncates the reply at that chunk boundary —
the transcript is the blocking reply's byte-identical prefix. The
cancel is accounted in ``perf.stream`` (tokens saved = the full reply's
remainder) and emits the scheduler's exact schema (CancelEvent, the
``cancelled`` lifecycle state, the request span closing with a
``cancelled`` phase), so the whole cancellation pipeline pins
deterministically on CPU.

The round number is recovered from the round template's "Debate round {N}"
header (prompts.REVIEW_PROMPT_TEMPLATE), the same information a real opponent
sees.

Prefix-cache parity: every chat request is routed through the SAME
``PageAllocator`` + ``PrefixCache`` machinery the TPU scheduler uses
(engine/prefix_cache.py) — the mock "tokenizer" chunks the prompt text
into fixed-width pieces, so hit-rates and tokens-saved are deterministic
on CPU and tier-1 tests can pin them without a TPU. There is no device
pool here: the cache tracks accounting only, and ``Usage.cached_tokens``
/ the process-wide stats reflect what a real engine would have skipped.

Tiered-KV parity works the same way (engine/kvtier.py): the mock's
prefix cache carries the SAME host/disk tiers the scheduler attaches —
LRU-evicted blocks demote (payload ``None``; the state machine is
content-free), tiered lookups continue past the device radix, promoted
and rehydrated blocks count as cached, and the disk store (keyed by a
mock-namespace fingerprint) persists across engine instances, so
restart-rehydration hit rates pin deterministically on CPU.

Trace parity works the same way (obs/trace.py): each request's chat
runs under its own ambient trace scope, so every event the accounting
emits stamps the round/opponent ids minted by the debate layer, and the
per-request span set (queued/prefill/decode under a ``request``
envelope) carries SYNTHETIC walls on the tokens/1024 second-scale —
the tools/trace_view.py waterfall and its checked decomposition pin
byte-deterministically on CPU, SLO breach capture included.

Weight-residency parity works the same way (engine/weightres.py): under
an EXPLICIT ``ADVSPEC_HBM_BUDGET_BYTES`` (the bench/test trigger — the
simulation stays off otherwise, so pre-residency mock event streams are
byte-identical), each distinct mock model id occupies a nominal 64 MiB
of "HBM": a round's model groups serve RESIDENT-FIRST, an over-budget
load demotes (or, with ``--no-weight-res``, frees) the LRU model, and a
demoted model's next turn promotes instead of re-loading — with
synthetic walls on exact binary fractions (load = bytes/1 GiB/s,
promote = load/8, demote = load/16), so the thrash-vs-resident
weight-load seconds, swap events, and the ``perf.weights`` payload pin
byte-deterministically on CPU.

Interleave parity works the same way (engine/interleave.py): the first
request of a ``chat`` batch prefills with nothing resident to overlap
(stalled), every later request's prefill rides the residents' decode
(overlapped, when the fused loop is enabled). Synthetic seconds are
``tokens / 1024`` — exact binary fractions, so the stalled + overlapped
== prefill invariant the CLI's ``perf.interleave`` block promises is
pinnable with ``==`` on CPU.
"""

from __future__ import annotations

import re
from urllib.parse import parse_qs, urlparse

from adversarial_spec_tpu import obs as obs_mod
from adversarial_spec_tpu.debate.usage import Usage
from adversarial_spec_tpu.engine import streaming as stream_mod
from adversarial_spec_tpu.engine import weightres as weightres_mod
from adversarial_spec_tpu.engine.types import ChatRequest, Completion, SamplingParams

_ROUND_RE = re.compile(r"Debate round (\d+)")

# Weight-residency simulation scale: nominal HBM bytes per distinct
# mock model, and the synthetic transfer rates (exact binary fractions
# so every derived wall pins with == on CPU). A "load" moves the bytes
# at 1 GiB/s, a promotion at 8 GiB/s (host RAM is that much closer than
# a checkpoint conversion), a demotion at 16 GiB/s (async gather).
_MODEL_BYTES = 64 << 20
_GIB = 1 << 30

# Streaming delivery granularity: the reply streams to the consumer in
# fixed-width character chunks. Width 5 on purpose — "[AGREE]" is 7
# characters, so the verdict marker routinely SPLITS across deliveries,
# which is exactly the case the incremental scanner
# (debate/parsing.StreamScanner) must handle.
_STREAM_CHUNK_CHARS = 5

# Mock prefix-cache geometry. A "token" is _TOKEN_CHARS characters of
# system+user text (matching _estimate_tokens' 4-chars-per-token rule, so
# cached_tokens is on the same scale as input_tokens); a page is
# _PAGE_TOKENS tokens — fine enough that a grown spec's unchanged head
# mostly re-hits, coarse enough to keep the radix index small.
_TOKEN_CHARS = 4
_PAGE_TOKENS = 16
_POOL_PAGES = 8192

_CRITIQUES = [
    "The error-handling section does not define behavior when the backing "
    "store is unavailable; specify a timeout, retry policy, and user-facing "
    "failure mode.",
    "Success metrics are unmeasurable as written; attach a concrete metric "
    "and measurement window to each goal.",
    "The API section omits versioning; define how breaking changes reach "
    "old clients.",
    "No capacity assumptions are stated; add expected request rate and data "
    "growth, and size the design against 10x those numbers.",
    "The rollout section lacks a rollback trigger; define the metric "
    "threshold that aborts the rollout.",
]


def _estimate_tokens(text: str) -> int:
    """Cheap whitespace-ish token estimate (parity: the reference estimates
    tokens for CLI providers that report none, scripts/models.py:274-454)."""
    return max(1, len(text) // 4)


class MockEngine:
    """Deterministic scripted engine; safe to share across calls."""

    def __init__(self) -> None:
        # Per-model-id call counter, for flaky/fail-N behaviors. Mutated
        # only from the (single-threaded) debate core.
        self._calls: dict[str, int] = {}
        # Prefix-cache accounting (lazy: only when the cache is enabled).
        self._allocator = None
        self._prefix = None
        self._seq = 0
        # Weight-residency accounting (lazy: only under an explicit
        # ADVSPEC_HBM_BUDGET_BYTES — see module docstring).
        self._weights = None

    @property
    def ledger(self):
        """The residency ledger (the engine-seam name the chaos/check
        paths share with TpuEngine); None until the simulation armed."""
        return self._weights

    def _sim_residency(self, requests: list[ChatRequest]) -> None:
        """Drive the weight-residency state machine for this chat's
        model groups, deterministically (see module docstring): groups
        serve resident-first, over-budget loads demote-or-free the LRU
        model, demoted models promote on their next turn. Accounting
        only — replies are computed per request in submission order
        either way, so transcripts are byte-identical with the
        simulation on, off, or thrashing."""
        budget = weightres_mod.mock_budget_bytes()
        if budget is None:
            return
        if self._weights is None:
            self._weights = weightres_mod.WeightLedger()
        led = self._weights
        models: list[str] = []
        for r in requests:
            if r.model not in models:
                models.append(r.model)
        models = led.resident_first(models)
        for gi, model in enumerate(models):
            if led.is_resident(model):
                led.touch(model)
                continue
            # Make room first (the engine's evict-before-materialize
            # rule): every over-budget resident demotes or frees.
            while (
                led.resident_models
                and (led.resident_models + 1) * _MODEL_BYTES > budget
            ):
                victim = led.lru_resident_alias()
                if victim is None:
                    break
                if weightres_mod.paging_armed():
                    led.demote_model(
                        victim,
                        None,
                        _MODEL_BYTES,
                        _MODEL_BYTES / (16 * _GIB),
                    )
                else:
                    led.free_model(victim)
            # Groups after the first ride the previous group's decode
            # (the engine's prefetch-thread overlap, deterministically).
            overlapped = gi > 0
            if led.is_host(model):
                led.promote_model(
                    model,
                    _MODEL_BYTES,
                    _MODEL_BYTES / (8 * _GIB),
                    overlapped=overlapped,
                )
            else:
                led.admit_load(
                    model, _MODEL_BYTES, _MODEL_BYTES / _GIB
                )

    def validate(self, model: str) -> str | None:
        if not model.startswith("mock://"):
            return f"not a mock model id: {model}"
        return None

    @staticmethod
    def _account_interleave(
        n_tokens: int, overlapped: bool, req_index: int = 0
    ) -> None:
        """Deterministic CPU mirror of the scheduler's fused-step
        telemetry: this request's prefill either stalled the (synthetic)
        batch or rode an earlier resident's decode. Synthetic seconds
        are tokens/1024 — exact in float, so perf.interleave's
        ``stalled + overlapped == prefill`` invariant pins with ==.

        Emits the SAME observability schema the real scheduler does
        (StepEvent + step/prefill/TTFT metrics), with the synthetic
        seconds as the observed values — so the whole obs pipeline
        (events JSONL, Prometheus text) pins byte-deterministically on
        CPU without a TPU in the loop."""
        from adversarial_spec_tpu.engine import interleave as interleave_mod

        overlapped = overlapped and interleave_mod.config().enabled
        synth_s = n_tokens / 1024.0
        interleave_mod.stats.record_prefill_time(
            synth_s, overlapped=overlapped
        )
        interleave_mod.stats.record_step(
            fused=overlapped, prefill_only=not overlapped
        )
        if obs_mod.config().enabled:
            obs_mod.hot.prefill_chunk.observe(synth_s)
            obs_mod.hot.ttft.observe(synth_s)
            obs_mod.emit(
                obs_mod.StepEvent(
                    kind="fused" if overlapped else "prefill",
                    n_live=req_index if overlapped else 0,
                    admission_slot=req_index,
                    prefill_tokens=n_tokens,
                )
            )

    @staticmethod
    def _account_spec(
        req: ChatRequest, text: str, req_index: int = 0
    ) -> None:
        """Deterministic CPU mirror of the scheduler's per-slot
        prompt-lookup speculation: step through this reply's token
        chunks exactly the way the batcher's verify loop would — draft
        γ tokens after the most recent [prev, cur] bigram match in the
        context (prompt + emitted so far), accept the longest prefix
        matching the actual continuation, emit accepted+1 — and record
        the SAME stats/events schema (``perf.spec``, SpecEvents, the
        tokens-per-step and acceptance histograms), so the whole
        speculation pipeline pins on CPU without a TPU. The mock
        "model" is greedy and its output IS the target distribution's
        argmax, so prompt-lookup acceptance here is exact string
        matching — high on the [SPEC] revision (a near-copy of the
        prompt), low on fresh prose, zero when the bigram never
        recurs.

        Tokenization here is whitespace words, NOT the prefix-cache
        accounting's fixed 4-char chunks: a fixed-offset chunking of
        the reply never aligns with the prompt's chunking of the same
        substring (the copy sits at an arbitrary offset mod 4), so
        chunk-wise acceptance would be identically zero. A real BPE
        re-tokenizes a copied substring to the same ids regardless of
        its byte offset — word splitting is the offset-stable mock of
        that property."""
        from adversarial_spec_tpu.engine import spec as spec_mod

        if not spec_mod.config().enabled:
            return
        gamma = spec_mod.config().gamma
        span = gamma + 1
        ctx = (req.system + "\n" + req.user).split()
        out = text.split()
        # Most-recent-bigram index over the growing context, the host
        # analog of speculative._draft's reverse scan. A bigram is
        # registered only once it is INTERIOR (a newer token landed
        # after it): the bigram ending at the context's final index IS
        # the query — indexing it too would make every lookup find
        # itself and every draft empty.
        last: dict[tuple[str, str], int] = {
            (ctx[m - 1], ctx[m]): m for m in range(1, len(ctx) - 1)
        }
        steps = drafted = accepted = 0
        i = 0
        obs_on = obs_mod.config().enabled
        while i < len(out):
            n_allowed = min(gamma, len(out) - i - 1)
            k = 0
            if n_allowed > 0 and len(ctx) >= 2:
                m = last.get((ctx[-2], ctx[-1]))
                if m is not None:
                    draft = ctx[m + 1 : m + 1 + gamma]
                    while (
                        k < n_allowed
                        and k < len(draft)
                        and draft[k] == out[i + k]
                    ):
                        k += 1
            n_emit = k + 1
            for tok in out[i : i + n_emit]:
                if len(ctx) >= 2:
                    last[(ctx[-2], ctx[-1])] = len(ctx) - 1
                ctx.append(tok)
            i += n_emit
            steps += 1
            drafted += n_allowed
            accepted += k
            spec_mod.stats.record_step(n_allowed, k, n_emit)
            # Synthetic step wall: ONE batched forward per verify step,
            # 1/1024 s (the same tokens/1024 second-scale the interleave
            # accounting uses), split by the position-share convention.
            spec_mod.stats.record_wall(
                (1 / 1024) / (span + 1), (1 / 1024) * span / (span + 1)
            )
            if obs_on:
                obs_mod.hot.spec_tokens_per_step.observe(float(n_emit))
                obs_mod.emit(
                    obs_mod.SpecEvent(
                        slot=req_index,
                        req_id=req_index,
                        drafted=n_allowed,
                        accepted=k,
                        emitted=n_emit,
                    )
                )
        if obs_on and drafted:
            obs_mod.hot.spec_acceptance.observe(accepted / drafted)

    @staticmethod
    def _emit_lifecycle(
        req_index: int,
        in_tokens: int,
        cached: int,
        out_tokens: int,
        span_id: str = "",
        cancelled: bool = False,
    ) -> None:
        """The scheduler's RequestEvent lifecycle, deterministically:
        queued → admitted → prefill → decode → finished, one synthetic
        slot per request, plus the scheduler's per-request causal-trace
        spans (queued/prefill/decode under a ``request`` envelope) with
        SYNTHETIC walls on the same tokens/1024 second-scale the
        interleave accounting uses — so the waterfall decomposition
        (prefill + decode == request service wall, the sum
        ``tools/trace_view.py`` checks) pins EXACTLY on CPU. Same
        schema, pinnable bytes. The SLO gates see the synthetic walls
        too, so breach capture pins without a TPU."""
        if not obs_mod.config().enabled:
            return
        transitions = (
            ("queued", in_tokens),
            ("admitted", in_tokens),
            ("prefill", in_tokens - cached),
            ("decode", out_tokens),
            ("cancelled" if cancelled else "finished", out_tokens),
        )
        prefill_s = (in_tokens - cached) / 1024.0
        decode_s = out_tokens / 1024.0
        # A cancelled request's envelope closes with the ``cancelled``
        # phase and its service wall SO FAR — still exactly
        # prefill + decode, so trace_view's decomposition check covers
        # cancelled requests (the scheduler's truncated span set).
        spans = (
            ("request", "begin", 0.0),
            ("queued", "begin", 0.0),
            ("queued", "end", 0.0),
            ("prefill", "begin", 0.0),
            ("prefill", "end", prefill_s),
            ("decode", "begin", 0.0),
            ("decode", "end", decode_s),
            (
                "request",
                "cancelled" if cancelled else "end",
                prefill_s + decode_s,
            ),
        )
        for state, tokens in transitions:
            obs_mod.emit(
                obs_mod.RequestEvent(
                    req_id=req_index,
                    state=state,
                    slot=req_index,
                    tokens=tokens,
                    cached_tokens=cached,
                    # Only the queue transition carries the arrival
                    # stamp (0.0 unless ADVSPEC_OBS_ARRIVALS armed —
                    # the byte-determinism pins see all zeros).
                    arrival_s=(
                        obs_mod.arrival_now() if state == "queued" else 0.0
                    ),
                )
            )
        for name, phase, wall in spans:
            obs_mod.emit(
                obs_mod.SpanEvent(
                    name=name,
                    phase=phase,
                    req_id=req_index,
                    slot=req_index,
                    wall_s=wall,
                    span_id=span_id,
                )
            )
        if not cancelled:
            # Cancelled requests count through advspec_cancelled_total
            # (emitted by the caller), not the finished outcome.
            obs_mod.hot.req_finished.inc()
        obs_mod.slo_check("ttft", span_id, prefill_s)
        obs_mod.slo_check("round", span_id, prefill_s + decode_s)

    def _ensure_prefix(self) -> None:
        """Build the allocator + prefix cache (and attach the KV tiers
        when armed) on first use — also reachable through ``prefetch``,
        so a COLD decode replica can probe the shared store before its
        first request ever admits."""
        from adversarial_spec_tpu.engine import prefix_cache as prefix_mod

        if self._prefix is not None:
            return
        from adversarial_spec_tpu.engine import kvtier as kvtier_mod
        from adversarial_spec_tpu.engine.kvcache import PageAllocator

        self._allocator = PageAllocator(_POOL_PAGES, _PAGE_TOKENS)
        self._prefix = prefix_mod.PrefixCache(
            self._allocator,
            max_pages=prefix_mod.config().max_pages,
        )
        if kvtier_mod.armed():
            # Same tier state machine as the scheduler, accounting
            # only: nominal block bytes (no KV exists here) and a
            # mock-namespace store fingerprint, so a real engine
            # can never rehydrate accounting-only entries.
            tiers = kvtier_mod.build_for(
                _PAGE_TOKENS * 64,
                ("mock", _TOKEN_CHARS, _PAGE_TOKENS),
            )
            if tiers is not None:
                self._prefix.attach_tiers(tiers)

    def _account_prefix(
        self,
        req: ChatRequest,
        overlapped: bool = False,
        req_index: int = 0,
    ) -> int:
        """Run this request's prompt through the real allocator + prefix
        cache (accounting only — no KV exists here) and return the token
        count served from cache. Counts prefilled/saved tokens into the
        process-wide stats either way, so cache-on/off runs compare."""
        from adversarial_spec_tpu.engine import prefix_cache as prefix_mod

        text = req.system + "\x1f" + req.user
        tokens = [
            text[i : i + _TOKEN_CHARS]
            for i in range(0, len(text), _TOKEN_CHARS)
        ]
        if not prefix_mod.config().enabled:
            prefix_mod.stats.record_prefill(len(tokens), 0)
            self._account_interleave(len(tokens), overlapped, req_index)
            return 0
        self._ensure_prefix()
        # The cap is per-round CLI config; follow it on a live cache.
        self._prefix.max_pages = prefix_mod.config().max_pages
        alloc, cache = self._allocator, self._prefix
        if cache.tiers is not None:
            matched, pages, tier_hits = cache.lookup_tiered(tokens)
        else:
            matched, pages = cache.lookup(tokens)
            tier_hits = []
        seq = self._seq
        self._seq += 1
        alloc.new_sequence(seq)
        try:
            from adversarial_spec_tpu.engine.kvcache import OutOfPages

            if matched:
                alloc.adopt(seq, pages, matched)
            try:
                cache.extend_evicting(seq, len(tokens) - matched)
            except OutOfPages:
                # Genuinely full even with an empty cache: account a
                # full prefill (a real engine would still serve the
                # request; only the reuse bookkeeping is skipped).
                prefix_mod.stats.record_prefill(len(tokens), 0)
                self._account_interleave(len(tokens), overlapped, req_index)
                return 0
            # Lower-tier blocks continuing the device match "promote":
            # the state machine is the scheduler's exactly — a hit that
            # lost the race (host LRU overflow between lookup and here)
            # degrades to accounted prefill.
            promoted = 0
            consumed = []
            for hit in tier_hits:
                ok, _payload = cache.tiers.materialize(hit)
                if not ok:
                    break
                promoted += len(hit.tokens)
                consumed.append(hit)
            # Consume BEFORE the radix insert (the scheduler's rule):
            # insert's cap enforcement may re-demote tail blocks into
            # the host tier, and consuming afterwards would pop them.
            for hit in consumed:
                cache.tiers.consume(hit, slot=req_index)
            n_full = len(tokens) // _PAGE_TOKENS
            if n_full:
                cache.insert(
                    tokens[: n_full * _PAGE_TOKENS],
                    alloc.table(seq)[:n_full],
                )
        finally:
            alloc.free_sequence(seq)
        if cache.tiers is not None:
            # The mock has no drive loop: settle (disk write-through of
            # the blocks just inserted) lands right here.
            cache.tiers.settle()
        cached = matched + promoted
        prefix_mod.stats.record_prefill(len(tokens) - cached, cached)
        self._account_interleave(len(tokens) - cached, overlapped, req_index)
        return cached

    @staticmethod
    def _chain_walk(req: ChatRequest) -> list[str]:
        """The request's full-page chain hashes, computed from the
        prompt text alone — exactly the chains ``lookup_tiered`` walks
        on the decode side, so they are the handoff hint's currency."""
        from adversarial_spec_tpu.engine import kvtier as kvtier_mod

        text = req.system + "\x1f" + req.user
        tokens = [
            text[i : i + _TOKEN_CHARS]
            for i in range(0, len(text), _TOKEN_CHARS)
        ]
        chains: list[str] = []
        chain = ""
        for b in range(len(tokens) // _PAGE_TOKENS):
            key = tuple(tokens[b * _PAGE_TOKENS : (b + 1) * _PAGE_TOKENS])
            chain = kvtier_mod.chain_hash(chain, key)
            chains.append(chain)
        return chains

    def prefill(
        self, requests: list[ChatRequest], params: SamplingParams
    ) -> list[dict]:
        """Disaggregated prefill — the handoff's shipping half: run
        admission + prefix/tier accounting ONLY (no reply decodes),
        settle the produced blocks write-through to the shared disk
        store, and return each request's durable chain hashes. The
        decode-side replica prefetches those chains and its first step
        starts from a tier hit; a request whose blocks did not all
        land reports only the durable prefix, so the router's
        adopt-vs-degrade decision is store-accurate."""
        out: list[dict] = []
        for i, req in enumerate(requests):
            with obs_mod.trace_scope(req.trace_id, req.span_id):
                cached = self._account_prefix(
                    req, overlapped=i > 0, req_index=i
                )
                chains = self._chain_walk(req)
                tiers = (
                    self._prefix.tiers if self._prefix is not None else None
                )
                durable = (
                    tiers.publish_chains(chains, slot=i)
                    if tiers is not None
                    else []
                )
                in_tokens = _estimate_tokens(req.system) + _estimate_tokens(
                    req.user
                )
                out.append(
                    {
                        "chains": list(durable),
                        "blocks": len(durable),
                        "tokens": in_tokens,
                        "cached": cached,
                        "new_tokens": max(in_tokens - cached, 0),
                    }
                )
        return out

    def prefetch(self, chains) -> int:
        """Decode-side handoff hint: how many of the shipped chains
        this engine's tier store can already serve (the promotion
        itself happens on the adopting request's own tiered lookup —
        this is the ahead-of-admission probe)."""
        from adversarial_spec_tpu.engine import prefix_cache as prefix_mod

        if not prefix_mod.config().enabled:
            return 0
        self._ensure_prefix()
        tiers = self._prefix.tiers
        if tiers is None:
            return 0
        return tiers.prefetch_chains(chains)

    def chat(
        self,
        requests: list[ChatRequest],
        params: SamplingParams,
        consumer=None,
    ) -> list[Completion]:
        # Request 0 prefills into an empty batch (stalled); every later
        # request's prefill would ride the residents' decode in the
        # fused scheduler loop (overlapped) — the deterministic CPU
        # analog of admit-while-decoding.
        if obs_mod.config().enabled:
            obs_mod.hot.mock_chat_requests.inc(len(requests))
        self._sim_residency(requests)
        return [
            self._one(
                req, params, overlapped=i > 0, req_index=i,
                consumer=consumer,
            )
            for i, req in enumerate(requests)
        ]

    def _one(
        self,
        req: ChatRequest,
        params: SamplingParams,
        overlapped: bool = False,
        req_index: int = 0,
        consumer=None,
    ) -> Completion:
        # The request's ambient trace scope: every event this request's
        # accounting emits (cache/tier/step/spec) stamps with its
        # trace/span, exactly as the scheduler scopes admissions.
        with obs_mod.trace_scope(req.trace_id, req.span_id):
            return self._one_traced(
                req, params, overlapped, req_index, consumer
            )

    @staticmethod
    def _stream_text(req_index: int, text: str, consumer) -> tuple[str, bool]:
        """Deterministic CPU mirror of the batcher's streaming
        delivery: the reply streams in ``_STREAM_CHUNK_CHARS``-wide
        chunks (each call the text SO FAR — the engine-seam contract),
        and a consumer returning False truncates the reply at that
        chunk boundary, so the transcript is the blocking reply's
        byte-identical prefix. Deliveries are accounted exactly the
        way the scheduler's ``_deliver_stream`` does — one
        ``record_delivery`` per callback that carried NEW (estimated)
        tokens — so ``perf.stream`` deliveries/streamed_tokens mean
        the same thing on both engines. Returns (possibly truncated
        text, cancelled?). A raising consumer disables streaming for
        the rest of the reply — the scheduler's containment rule."""
        pos = 0
        last_tokens = 0
        while pos < len(text):
            pos = min(pos + _STREAM_CHUNK_CHARS, len(text))
            cur_tokens = _estimate_tokens(text[:pos])
            if cur_tokens > last_tokens:
                stream_mod.stats.record_delivery(cur_tokens - last_tokens)
                last_tokens = cur_tokens
            try:
                keep = bool(consumer(req_index, text[:pos]))
            except Exception:
                return text, False
            if not keep:
                return text[:pos], True
        return text, False

    def _one_traced(
        self,
        req: ChatRequest,
        params: SamplingParams,
        overlapped: bool = False,
        req_index: int = 0,
        consumer=None,
    ) -> Completion:
        parsed = urlparse(req.model)
        behavior = parsed.netloc or parsed.path.lstrip("/")
        opts = {k: v[-1] for k, v in parse_qs(parsed.query).items()}
        self._calls[req.model] = self._calls.get(req.model, 0) + 1
        n_call = self._calls[req.model]

        m = _ROUND_RE.search(req.user)
        round_num = int(m.group(1)) if m else 1

        if behavior == "tasks":
            cached = self._account_prefix(req, overlapped, req_index)
            text = (
                "[TASK]\ntitle: Define data model\ndescription: Schema and "
                "migrations for the core entities.\npriority: critical\n"
                "dependencies:\nestimate: 1d\n[/TASK]\n"
                "[TASK]\ntitle: Implement API\ndescription: CRUD endpoints "
                "with validation and error handling.\npriority: high\n"
                "dependencies: Define data model\nestimate: 2d\n[/TASK]\n"
                "[TASK]\ntitle: Add observability\ndescription: Metrics, "
                "structured logs, and alerts for the API.\npriority: medium\n"
                "dependencies: Implement API\nestimate: 1d\n[/TASK]"
            )
            out_tokens = _estimate_tokens(text)
            in_tokens = _estimate_tokens(req.system) + _estimate_tokens(
                req.user
            )
            self._account_spec(req, text, req_index)
            self._emit_lifecycle(
                req_index, in_tokens, cached, out_tokens, req.span_id
            )
            return Completion(
                text=text,
                usage=Usage(
                    # system + user, like the critic branch: the prefix
                    # accounting covers both, and cached_tokens must
                    # stay a subset of input_tokens.
                    input_tokens=in_tokens,
                    output_tokens=out_tokens,
                    decode_tokens=out_tokens,
                    cached_tokens=cached,
                ),
            )
        if behavior == "error":
            return Completion(
                error=f"mock permanent failure (call {n_call})", transient=False
            )
        if behavior == "flaky":
            fail_n = int(opts.get("fail", "1"))
            if n_call <= fail_n:
                return Completion(
                    error=f"mock transient failure {n_call}/{fail_n}",
                    transient=True,
                )
            behavior = "critic"

        agree_after = int(opts.get("agree_after", "0"))
        cached = self._account_prefix(req, overlapped, req_index)
        if behavior == "agree" or (agree_after and round_num >= agree_after):
            text = "[AGREE]\nNo remaining objections; the document is ready."
            tail = int(opts.get("agree_tail", "0"))
            if tail > 0:
                # Deterministic verbosity AFTER the verdict marker —
                # exactly the decode early cancellation converts back
                # into served capacity (bench.py --mode cancel).
                text += "\n\nExtended remarks:" + "".join(
                    f"\n- remark {k}: the document remains acceptable "
                    "in every reviewed dimension."
                    for k in range(1, tail + 1)
                )
        else:
            crit = _CRITIQUES[(round_num - 1) % len(_CRITIQUES)]
            spec = _extract_document(req.user)
            revised = spec + f"\n\n## Revision note (round {round_num})\n" + crit
            text = (
                f"1. {crit}\n\n[SPEC]\n{revised}\n[/SPEC]"
            )

        full_tokens = min(_estimate_tokens(text), params.max_new_tokens)
        cancelled = False
        if consumer is not None and stream_mod.config().enabled:
            stream_mod.stats.record_request()
            text, cancelled = self._stream_text(req_index, text, consumer)
        out_tokens = min(_estimate_tokens(text), params.max_new_tokens)
        tps = float(opts.get("tps", "0"))
        in_tokens = _estimate_tokens(req.system) + _estimate_tokens(req.user)
        stream_saved = 0
        if cancelled:
            stream_saved = max(full_tokens - out_tokens, 0)
            stream_mod.stats.record_cancel(out_tokens, stream_saved)
            if obs_mod.config().enabled:
                obs_mod.hot.cancel("early_converge").inc()
                obs_mod.hot.cancel_tokens_saved.observe(float(stream_saved))
                obs_mod.emit(
                    obs_mod.CancelEvent(
                        req_id=req_index,
                        slot=req_index,
                        reason="early_converge",
                        tokens_emitted=out_tokens,
                        tokens_saved=stream_saved,
                        span_id=req.span_id,
                    )
                )
        # Speculation accounting runs over the DELIVERED text only: the
        # batcher never decodes past a cancel either.
        self._account_spec(req, text, req_index)
        self._emit_lifecycle(
            req_index, in_tokens, cached, out_tokens, req.span_id,
            cancelled=cancelled,
        )
        usage = Usage(
            input_tokens=in_tokens,
            output_tokens=out_tokens,
            decode_tokens=out_tokens,
            decode_time_s=out_tokens / tps if tps > 0 else 0.0,
            cached_tokens=cached,
        )
        return Completion(text=text, usage=usage, cancelled=cancelled)


def _extract_document(user_prompt: str) -> str:
    start = user_prompt.find("--- DOCUMENT ---")
    end = user_prompt.find("--- END DOCUMENT ---")
    if start == -1 or end == -1:
        return user_prompt.strip()
    return user_prompt[start + len("--- DOCUMENT ---") : end].strip()
