"""Scripted mock engine — the fake backend at the engine seam.

The reference's tests mock only the transport seam (``completion``,
``subprocess.run``) and run everything above it for real (SURVEY §4). The TPU
analog is this engine: it implements the same ``Engine`` interface as the TPU
engine, so the entire debate loop — CLI, rounds, parsing, convergence,
sessions, cost — runs unmodified on CPU with scripted critiques. It is also
BASELINE config 1 (1-round critique, 1 opponent, mock provider, CPU).

Model-id grammar (query params configure behavior):

- ``mock://agree``                      — replies [AGREE] immediately.
- ``mock://critic``                     — critiques forever, revising the spec.
- ``mock://critic?agree_after=3``       — critiques rounds 1-2, agrees from 3.
- ``mock://tasks``                      — emits structured [TASK] blocks
                                          (for export-tasks flows).
- ``mock://error``                      — permanent failure every call.
- ``mock://flaky?fail=2``               — transient failures on the first 2
                                          calls, then behaves like ``critic``.
- any id with ``&tps=N``                — simulates N tokens/sec decode speed
                                          in the reported usage (no sleeping).

The round number is recovered from the round template's "Debate round {N}"
header (prompts.REVIEW_PROMPT_TEMPLATE), the same information a real opponent
sees.
"""

from __future__ import annotations

import re
from urllib.parse import parse_qs, urlparse

from adversarial_spec_tpu.debate.usage import Usage
from adversarial_spec_tpu.engine.types import ChatRequest, Completion, SamplingParams

_ROUND_RE = re.compile(r"Debate round (\d+)")

_CRITIQUES = [
    "The error-handling section does not define behavior when the backing "
    "store is unavailable; specify a timeout, retry policy, and user-facing "
    "failure mode.",
    "Success metrics are unmeasurable as written; attach a concrete metric "
    "and measurement window to each goal.",
    "The API section omits versioning; define how breaking changes reach "
    "old clients.",
    "No capacity assumptions are stated; add expected request rate and data "
    "growth, and size the design against 10x those numbers.",
    "The rollout section lacks a rollback trigger; define the metric "
    "threshold that aborts the rollout.",
]


def _estimate_tokens(text: str) -> int:
    """Cheap whitespace-ish token estimate (parity: the reference estimates
    tokens for CLI providers that report none, scripts/models.py:274-454)."""
    return max(1, len(text) // 4)


class MockEngine:
    """Deterministic scripted engine; safe to share across calls."""

    def __init__(self) -> None:
        # Per-model-id call counter, for flaky/fail-N behaviors. Mutated
        # only from the (single-threaded) debate core.
        self._calls: dict[str, int] = {}

    def validate(self, model: str) -> str | None:
        if not model.startswith("mock://"):
            return f"not a mock model id: {model}"
        return None

    def chat(
        self, requests: list[ChatRequest], params: SamplingParams
    ) -> list[Completion]:
        return [self._one(req, params) for req in requests]

    def _one(self, req: ChatRequest, params: SamplingParams) -> Completion:
        parsed = urlparse(req.model)
        behavior = parsed.netloc or parsed.path.lstrip("/")
        opts = {k: v[-1] for k, v in parse_qs(parsed.query).items()}
        self._calls[req.model] = self._calls.get(req.model, 0) + 1
        n_call = self._calls[req.model]

        m = _ROUND_RE.search(req.user)
        round_num = int(m.group(1)) if m else 1

        if behavior == "tasks":
            text = (
                "[TASK]\ntitle: Define data model\ndescription: Schema and "
                "migrations for the core entities.\npriority: critical\n"
                "dependencies:\nestimate: 1d\n[/TASK]\n"
                "[TASK]\ntitle: Implement API\ndescription: CRUD endpoints "
                "with validation and error handling.\npriority: high\n"
                "dependencies: Define data model\nestimate: 2d\n[/TASK]\n"
                "[TASK]\ntitle: Add observability\ndescription: Metrics, "
                "structured logs, and alerts for the API.\npriority: medium\n"
                "dependencies: Implement API\nestimate: 1d\n[/TASK]"
            )
            out_tokens = _estimate_tokens(text)
            return Completion(
                text=text,
                usage=Usage(
                    input_tokens=_estimate_tokens(req.user),
                    output_tokens=out_tokens,
                    decode_tokens=out_tokens,
                ),
            )
        if behavior == "error":
            return Completion(
                error=f"mock permanent failure (call {n_call})", transient=False
            )
        if behavior == "flaky":
            fail_n = int(opts.get("fail", "1"))
            if n_call <= fail_n:
                return Completion(
                    error=f"mock transient failure {n_call}/{fail_n}",
                    transient=True,
                )
            behavior = "critic"

        agree_after = int(opts.get("agree_after", "0"))
        if behavior == "agree" or (agree_after and round_num >= agree_after):
            text = "[AGREE]\nNo remaining objections; the document is ready."
        else:
            crit = _CRITIQUES[(round_num - 1) % len(_CRITIQUES)]
            spec = _extract_document(req.user)
            revised = spec + f"\n\n## Revision note (round {round_num})\n" + crit
            text = (
                f"1. {crit}\n\n[SPEC]\n{revised}\n[/SPEC]"
            )

        out_tokens = min(_estimate_tokens(text), params.max_new_tokens)
        tps = float(opts.get("tps", "0"))
        usage = Usage(
            input_tokens=_estimate_tokens(req.system) + _estimate_tokens(req.user),
            output_tokens=out_tokens,
            decode_tokens=out_tokens,
            decode_time_s=out_tokens / tps if tps > 0 else 0.0,
        )
        return Completion(text=text, usage=usage)


def _extract_document(user_prompt: str) -> str:
    start = user_prompt.find("--- DOCUMENT ---")
    end = user_prompt.find("--- END DOCUMENT ---")
    if start == -1 or end == -1:
        return user_prompt.strip()
    return user_prompt[start + len("--- DOCUMENT ---") : end].strip()
