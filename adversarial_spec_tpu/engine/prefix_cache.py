"""Content-addressed cross-round prefix KV cache (host-side index).

The debate loop's dominant compute is redundant prefill: every round all
N opponents re-prefill the same spec+transcript prefix, and round R+1
re-prefills everything round R already computed (the transcript only
grows). This module is the host-side half of the fix — the device half
is the ref-counted page pool in engine/kvcache.py:

- Token streams are split into page-size-aligned BLOCKS and indexed in a
  radix trie keyed by exact block content (a block's identity is the
  chain ``(parent block, its tokens)``, i.e. a content-addressed chain
  hash realized through Python's dict hashing with full-content
  verification — no collision risk).
- Each cached block points at the physical page holding its KV. The
  cache holds one allocator reference per cached page; live sequences
  that adopt a prefix hold their own. Pages free only at refcount zero.
- ``lookup`` returns the longest cached prefix (whole blocks only);
  ``insert`` registers a finished admission's full blocks; ``evict_pages``
  drops least-recently-used LEAF blocks whose page no live sequence
  references — middle blocks are never evicted, keeping every cached
  chain contiguous.

Sharing is safe without copies because blocks are immutable once full
and every writer's positions lie strictly past its adopted prefix
(copy-on-write degenerates to copy-on-append for an append-only
transcript). A faulted slot merely drops its references; it can never
scribble into a shared page.

Process-wide config + stats live here too (the resilience/faults
pattern): the CLI arms them per round (``--prefix-cache``,
``--prefix-cache-pages``) and snapshots them into ``perf.prefix_cache``.
This module deliberately imports neither jax nor the device pool — the
mock engine uses it for deterministic CPU accounting.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

from adversarial_spec_tpu.engine import procconfig
from adversarial_spec_tpu.engine.kvcache import OutOfPages, PageAllocator
from adversarial_spec_tpu.engine.kvtier import chain_hash
from adversarial_spec_tpu import obs as obs_mod


@dataclass
class PrefixCacheConfig:
    """Process-wide knobs, set once per CLI round (or by tests)."""

    enabled: bool = True
    # Max pages the cache itself may hold references to; 0 = bounded only
    # by the pool (eviction then happens on allocation pressure alone).
    max_pages: int = 0


@dataclass
class PrefixCacheStats(procconfig.StatsBase):
    """Process-wide counters, aggregated across every cache instance
    (mock engine, each ContinuousBatcher, generate's shared-prefix
    prefill). ``reset`` zeroes in place so engines holding a reference
    keep counting into the same object."""

    lookups: int = 0
    hits: int = 0
    misses: int = 0
    cached_tokens: int = 0  # tokens matched by lookups
    prefilled_tokens: int = 0  # tokens actually run through prefill
    saved_tokens: int = 0  # forward tokens skipped thanks to reuse
    inserted_blocks: int = 0
    evicted_blocks: int = 0
    evicted_pages: int = 0

    def record_lookup(self, matched_tokens: int) -> None:
        self.lookups += 1
        if matched_tokens > 0:
            self.hits += 1
            self.cached_tokens += matched_tokens
        else:
            self.misses += 1
        # Every engine (TPU scheduler and the mock's CPU accounting)
        # funnels lookups through here — ONE emit site covers both.
        obs_mod.emit(
            obs_mod.CacheEvent(
                op="lookup",
                matched_tokens=matched_tokens,
                hit=matched_tokens > 0,
            )
        )
        if obs_mod.config().enabled:
            obs_mod.hot.hit_ratio.set(round(self.hits / self.lookups, 6))

    def record_prefill(self, computed_tokens: int, saved_tokens: int) -> None:
        self.prefilled_tokens += computed_tokens
        self.saved_tokens += saved_tokens

    def snapshot(self) -> dict:
        out = self.as_dict()
        out["hit_rate"] = round(self.hits / self.lookups, 4) if self.lookups else 0.0
        return out


_state = procconfig.ProcState(
    PrefixCacheConfig(
        enabled=os.environ.get("ADVSPEC_PREFIX_CACHE", "1") != "0"
    ),
    PrefixCacheStats(),
    # max_pages is config-only (the cap), not part of the perf payload.
    snapshot_fields=("enabled",),
)
_config = _state.config
stats = _state.stats


def config() -> PrefixCacheConfig:
    return _state.config


def configure(
    enabled: bool | None = None, max_pages: int | None = None
) -> PrefixCacheConfig:
    return _state.configure(enabled=enabled, max_pages=max_pages)


def reset_stats() -> None:
    _state.reset_stats()


def snapshot() -> dict:
    """Stats + config, the ``perf.prefix_cache`` payload."""
    return _state.snapshot()


@dataclass
class _Block:
    """One cached page-size block of tokens; a radix-trie node."""

    tokens: tuple
    page: int
    parent: "_Block | None"
    children: dict = field(default_factory=dict)
    last_used: int = 0
    # Content-addressed chain hash (engine/kvtier.py) — the block's
    # cross-process identity, stamped at insert when tiers are
    # attached; None on a tier-less cache (hashing skipped).
    chain: str | None = None


class PrefixCache:
    """Radix index of cached token blocks over one ``PageAllocator``.

    All methods are O(blocks touched); the cache is host-side bookkeeping
    only — page CONTENT lives wherever the caller keeps it (the device
    pool for real engines, nowhere for the mock engine's accounting).
    """

    def __init__(
        self,
        allocator: PageAllocator,
        page_size: int | None = None,
        *,
        max_pages: int = 0,
        stats: PrefixCacheStats | None = None,
    ):
        self.allocator = allocator
        self.page_size = page_size or allocator.page_size
        self.max_pages = max_pages
        self.stats = stats if stats is not None else globals()["stats"]
        self._root: dict[tuple, _Block] = {}
        self._by_page: dict[int, _Block] = {}
        self._clock = 0
        # Lower tiers (engine/kvtier.py), attached by the owner before
        # the first insert: LRU-evicted leaves demote into them, and
        # ``lookup_tiered`` continues the radix walk past the device
        # tier. ``_kv_fetch(page, n_tokens)`` (scheduler-installed)
        # returns a LAZY payload materializer for a page's KV — None on
        # accounting-only caches (the mock engine).
        self.tiers = None
        self._kv_fetch = None

    def attach_tiers(self, tiers, kv_fetch=None) -> None:
        """Arm the host/disk tiers. Must precede the first ``insert``
        (blocks are chain-stamped at insert; a block inserted tier-less
        has no cross-process identity and silently skips demotion)."""
        self.tiers = tiers
        self._kv_fetch = kv_fetch

    @property
    def cached_pages(self) -> int:
        return len(self._by_page)

    def _blocks(self, tokens) -> list[tuple]:
        ps = self.page_size
        n = len(tokens) // ps
        return [tuple(tokens[i * ps : (i + 1) * ps]) for i in range(n)]

    def lookup(self, tokens, record: bool = True) -> tuple[int, list[int]]:
        """Longest cached prefix of ``tokens``: (matched token count —
        always a page multiple — and the pages backing it, in order).

        ``record=False`` skips the stats (a caller that may DEFER the
        admission — scheduler pool-full retries — records once, with the
        actually-adopted count, when the admission really starts)."""
        self._clock += 1
        pages: list[int] = []
        children = self._root
        for key in self._blocks(tokens):
            node = children.get(key)
            if node is None:
                break
            node.last_used = self._clock
            pages.append(node.page)
            children = node.children
        matched = len(pages) * self.page_size
        if record:
            self.stats.record_lookup(matched)
        return matched, pages

    def lookup_tiered(
        self, tokens, record: bool = True
    ) -> tuple[int, list[int], list]:
        """``lookup`` continued past the device tier: after the radix
        walk stops, subsequent full blocks are matched against the host
        tier, then the disk store, by chain hash — the contiguous run
        of lower-tier blocks the admission can promote instead of
        prefilling. Returns ``(matched_tokens, pages, tier_hits)``;
        with no tiers attached it degenerates to ``lookup``."""
        self._clock += 1
        pages: list[int] = []
        hits: list = []
        children = self._root
        chain = ""
        blocks = self._blocks(tokens)
        depth = 0
        for key in blocks:
            node = children.get(key)
            if node is None:
                break
            node.last_used = self._clock
            if self.tiers is not None:
                # Reuse the chain stamped at insert — rehashing ~every
                # matched block per lookup (and per pool-full admission
                # retry) would be pure hot-path recomputation.
                chain = (
                    node.chain
                    if node.chain is not None
                    else chain_hash(chain, key)
                )
            pages.append(node.page)
            children = node.children
            depth += 1
        if self.tiers is not None:
            for key in blocks[depth:]:
                chain = chain_hash(chain, key)
                hit = self.tiers.lookup_chain(chain, key)
                if hit is None:
                    break
                hits.append(hit)
        matched = len(pages) * self.page_size
        if record:
            self.stats.record_lookup(matched)
            if self.tiers is not None:
                self.tiers.record_lookup(hits)
        return matched, pages, hits

    def insert(self, tokens, pages: list[int]) -> int:
        """Register the full blocks of ``tokens``; ``pages[i]`` is the
        allocator page holding block i's KV. Blocks already cached keep
        their existing page (first writer wins — content is identical by
        construction). Returns the number of newly cached blocks."""
        self._clock += 1
        blocks = self._blocks(tokens)
        if len(pages) < len(blocks):
            blocks = blocks[: len(pages)]
        added = 0
        children = self._root
        parent: _Block | None = None
        chain = ""
        for key, page in zip(blocks, pages):
            if self.tiers is not None:
                chain = chain_hash(chain, key)
            node = children.get(key)
            if node is None:
                node = _Block(
                    tokens=key,
                    page=page,
                    parent=parent,
                    chain=chain if self.tiers is not None else None,
                )
                # graftlint: disable=GL-REFCOUNT -- ownership transfer, not a leak: the ref is recorded in _by_page on the next line and released by _drop (LRU eviction / clear); nothing between can raise
                self.allocator.cache_ref(page)
                self._by_page[page] = node
                children[key] = node
                added += 1
                if self.tiers is not None and self.tiers.needs_store(chain):
                    # Disk write-through: queue the new block for the
                    # persistent store (flushed at drain end — file I/O
                    # off the serving path). The payload gather is
                    # dispatched NOW (the page is live and immutable
                    # here; by flush time it may be reused) but
                    # materializes lazily. needs_store first: a
                    # re-promoted/rehydrated block already queued or on
                    # disk must not pay a discarded gather.
                    self.tiers.enqueue_store(
                        chain,
                        key,
                        self._kv_fetch(page, len(key))
                        if self._kv_fetch is not None
                        else None,
                    )
            node.last_used = self._clock
            parent = node
            children = node.children
        self.stats.inserted_blocks += added
        if added:
            obs_mod.emit(obs_mod.CacheEvent(op="insert", blocks=added))
        if self.max_pages > 0 and self.cached_pages > self.max_pages:
            self._evict(self.cached_pages - self.max_pages, shared_ok=True)
        return added

    def _leaves(self) -> list[_Block]:
        return [b for b in self._by_page.values() if not b.children]

    def _drop(self, block: _Block) -> bool:
        """Remove one leaf block from the index and release the cache's
        page reference. Returns True if the page actually freed (no live
        sequence was sharing it).

        With tiers attached the block DEMOTES on its way out: its KV is
        gathered off the page BEFORE the reference drops (the page may
        return to the free list and be re-used by the very allocation
        that triggered this eviction — the gather is an independent
        copy, started async, materialized off the hot path), and the
        block enters the host tier keyed by its chain hash."""
        siblings = (
            block.parent.children if block.parent is not None else self._root
        )
        del siblings[block.tokens]
        del self._by_page[block.page]
        if self.tiers is not None and block.chain is not None:
            self.tiers.demote(
                block.chain,
                block.tokens,
                self._kv_fetch(block.page, len(block.tokens))
                if self._kv_fetch is not None
                else None,
            )
        freed = self.allocator.refcount(block.page) == 1
        self.allocator.cache_unref(block.page)
        self.stats.evicted_blocks += 1
        if freed:
            self.stats.evicted_pages += 1
        obs_mod.emit(
            obs_mod.CacheEvent(op="evict", blocks=1, pages=int(freed))
        )
        return freed

    def _evict(self, n_pages: int, shared_ok: bool) -> int:
        """Evict LRU leaves until ``n_pages`` pages were released.
        ``shared_ok=False`` (allocation pressure) only counts — and only
        touches — blocks whose page frees immediately; ``shared_ok=True``
        (cap enforcement) also drops blocks still referenced by live
        sequences (their pages free later, when the sequence does).

        One LRU-sorted pass per wave: dropping a leaf can turn its
        parent into a leaf, so waves repeat only while the target is
        short AND the previous wave made progress — O(blocks log blocks)
        per wave instead of a full rescan per released page."""
        released = 0
        while released < n_pages:
            wave = sorted(
                (
                    b
                    for b in self._leaves()
                    if shared_ok or self.allocator.refcount(b.page) == 1
                ),
                key=lambda b: b.last_used,
            )
            if not wave:
                break
            for victim in wave:
                if released >= n_pages:
                    break
                if victim.children:  # no longer a leaf is impossible;
                    continue  # defensive against future reentrancy
                if self._drop(victim) or shared_ok:
                    released += 1
        return released

    def evict_pages(self, n_pages: int) -> int:
        """Free ≥ ``n_pages`` pages back to the allocator if possible
        (called when an admission would otherwise hit OutOfPages).
        Returns how many pages were actually freed."""
        if n_pages <= 0:
            return 0
        return self._evict(n_pages, shared_ok=False)

    def extend_evicting(self, seq_id: int, n_tokens: int) -> None:
        """``allocator.extend`` with allocation pressure converted into
        LRU eviction of unreferenced cached blocks: reclaim exactly the
        shortfall and retry once, so the cache can never crowd out a
        live admission. The one reclaim policy both real engines and the
        mock's accounting share. Raises OutOfPages if the pool is full
        even with every cold block evicted."""
        try:
            self.allocator.extend(seq_id, n_tokens)
        except OutOfPages:
            need = (
                self.allocator.pages_needed(seq_id, n_tokens)
                - self.allocator.free_pages
            )
            if self.evict_pages(need) < need:
                raise
            self.allocator.extend(seq_id, n_tokens)

    def clear(self) -> None:
        """Drop every cached block (releasing all cache references)."""
        while self._by_page:
            for b in self._leaves():
                self._drop(b)
