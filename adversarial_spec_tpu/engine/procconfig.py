"""Process-wide config + stats switchboard (the ONE implementation).

Four subsystems follow the same pattern (born in ``resilience.faults``,
then re-implemented by hand in ``interleave``, ``spec``,
``prefix_cache``, and now ``kvtier``): a module-level config dataclass
the CLI arms once per round, a module-level stats dataclass every engine
instance records into, and four module functions — ``config()``,
``configure(...)``, ``reset_stats()``, ``snapshot()``. Before this
module each of them re-implemented the same three mechanics with subtle
copy drift risk:

- **configure**: per-field "skip None, else coerce and assign" loops;
- **reset**: zero every stats field IN PLACE so engines holding a
  reference keep counting into the same object;
- **snapshot**: stats fields + derived ratios + selected config fields,
  the module's ``perf.<name>`` payload.

:class:`StatsBase` carries reset/as_dict (subclasses override
``snapshot`` to add derived ratios); :class:`ProcState` carries the
configure/snapshot mechanics with per-field coercers (the knob
validation — γ's fail-at-the-knob check, the pipeline-depth clamp —
stays with the owning module, passed in as a callable). The modules
keep their explicit ``configure(...)`` signatures: discoverability and
call-site typos still fail loudly.

Deliberately imports no jax: every ported module is used by the mock
engine on CPU.
"""

from __future__ import annotations

from dataclasses import fields
from typing import Callable


class StatsBase:
    """Dataclass mixin for process-wide counters.

    ``reset`` zeroes in place (each field to its type's zero value) so
    engines holding a reference keep counting into the same object —
    the invariant every per-round CLI reset relies on.
    """

    def reset(self) -> None:
        for f in fields(self):
            setattr(self, f.name, type(getattr(self, f.name))())

    def as_dict(self) -> dict:
        return {f.name: getattr(self, f.name) for f in fields(self)}

    def snapshot(self) -> dict:
        """Override to append derived ratios after the raw fields."""
        return self.as_dict()


class ProcState:
    """One module's process-wide (config, stats) pair + the shared
    configure/snapshot mechanics."""

    def __init__(
        self,
        config,
        stats: StatsBase,
        *,
        coerce: dict[str, Callable] | None = None,
        snapshot_fields: tuple[str, ...] | None = None,
    ):
        self.config = config
        self.stats = stats
        self._coerce = dict(coerce or {})
        # Config fields appended to snapshot() (the perf payload);
        # default: every config field, in declaration order.
        self._snapshot_fields = (
            tuple(snapshot_fields)
            if snapshot_fields is not None
            else tuple(f.name for f in fields(config))
        )

    def configure(self, **kwargs):
        """Assign every non-None kwarg through its coercer (default: the
        current value's type — bool/int/float/str round-trip). Unknown
        names raise: a typo'd knob must fail loudly, not silently
        no-op."""
        for name, value in kwargs.items():
            if value is None:
                continue
            if not hasattr(self.config, name):
                raise AttributeError(
                    f"{type(self.config).__name__} has no knob {name!r}"
                )
            fn = self._coerce.get(name)
            if fn is None:
                fn = type(getattr(self.config, name))
            setattr(self.config, name, fn(value))
        return self.config

    def reset_stats(self) -> None:
        self.stats.reset()

    def snapshot(self) -> dict:
        """Stats (+ derived ratios) + the chosen config fields — the
        module's ``perf.<name>`` payload."""
        out = self.stats.snapshot()
        for name in self._snapshot_fields:
            out[name] = getattr(self.config, name)
        return out
