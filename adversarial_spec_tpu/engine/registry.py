"""Local model registry for the ``tpu://`` provider.

TPU-native replacement for the reference's provider registry + API keys +
Bedrock alias map (scripts/providers.py:57-185, 358-486; SURVEY §2.3): instead
of credentials for remote gateways, a registry entry describes how to
materialize a model locally — checkpoint path, family, tokenizer, mesh shape,
dtype. Aliasing (``tpu://llama3-8b`` → a checkpoint dir) mirrors Bedrock's
friendly-name aliasing; ``validate`` mirrors the per-model availability
preflight with actionable errors.

Built-in ``random-*`` entries materialize synthetic (randomly initialized)
checkpoints of real model-family shapes, so the full TPU path runs with zero
network egress — the test/bench story in an air-gapped environment.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field, asdict
from pathlib import Path

from adversarial_spec_tpu.obs.events import atomic_write_text

REGISTRY_PATH = Path.home() / ".config" / "adversarial-spec-tpu" / "registry.json"

TPU_PREFIX = "tpu://"

# The ``quant`` field's vocabulary ("" = full precision). Lives here —
# not in ops/quant.py, which implements the formats — so validation and
# the CLI stay jax-free (importing ops.quant pulls in jax.numpy).
QUANT_FORMATS = ("", "int8", "int4")


@dataclass
class ModelSpec:
    """Everything needed to materialize one model on the mesh."""

    alias: str
    family: str = "llama"  # llama | mistral | gemma2 | qwen2 — see models/
    checkpoint: str = "random"  # HF checkpoint dir, or "random" for synthetic
    tokenizer: str = ""  # tokenizer dir/file; "" = whitespace fallback
    size: str = "tiny"  # named config within the family (tiny/1b/8b/70b)
    dtype: str = "bfloat16"
    mesh: dict[str, int] = field(default_factory=dict)  # e.g. {"tp": 8}
    # 0 = keep the model config's native context length (e.g. 131072 for
    # llama-3.2 1b/3b); nonzero overrides it.
    max_seq_len: int = 0
    # "" = full precision; "int8" / "int4" = weight-only quantization
    # (ops/quant.py QUANT_FORMATS) — int4 packs two weights per byte,
    # the format that fits a multi-model opponent pool resident.
    quant: str = ""
    kv: str = "dense"  # "dense" | "paged" — KV-cache layout for decode
    kv_dtype: str = ""  # "" = model dtype, "int8" = quantized KV cache

    def to_dict(self) -> dict:
        return asdict(self)


# Synthetic entries available without any registry file or downloads.
_BUILTIN: dict[str, ModelSpec] = {
    spec.alias: spec
    for spec in [
        ModelSpec(alias="random-tiny", family="llama", size="tiny"),
        ModelSpec(alias="random-gemma-tiny", family="gemma2", size="tiny"),
        ModelSpec(alias="random-mistral-tiny", family="mistral", size="tiny"),
        ModelSpec(alias="random-qwen-tiny", family="qwen2", size="tiny"),
        ModelSpec(alias="random-1b", family="llama", size="1b"),
        ModelSpec(alias="random-3b", family="llama", size="3b"),
        ModelSpec(alias="random-8b", family="llama", size="8b"),
        ModelSpec(alias="random-70b", family="llama", size="70b", mesh={"tp": 8}),
    ]
}


def parse_tpu_model_id(model: str) -> str:
    """``tpu://alias`` → ``alias`` (raises on other schemes)."""
    if not model.startswith(TPU_PREFIX):
        raise ValueError(f"not a tpu:// model id: {model}")
    return model[len(TPU_PREFIX) :]


def load_registry(registry_path: Path | None = None) -> dict[str, ModelSpec]:
    """Built-ins merged with user entries (user entries win)."""
    path = Path(registry_path or REGISTRY_PATH)
    out = dict(_BUILTIN)
    if path.is_file():
        try:
            data = json.loads(path.read_text())
        except (json.JSONDecodeError, OSError):
            return out
        for alias, entry in data.items():
            known = {f for f in ModelSpec.__dataclass_fields__}
            fields = {k: v for k, v in entry.items() if k in known}
            fields["alias"] = alias
            out[alias] = ModelSpec(**fields)
    return out


def save_registry_entry(
    spec: ModelSpec, registry_path: Path | None = None
) -> Path:
    path = Path(registry_path or REGISTRY_PATH)
    path.parent.mkdir(parents=True, exist_ok=True)
    data = {}
    if path.is_file():
        try:
            data = json.loads(path.read_text())
        except (json.JSONDecodeError, OSError):
            data = {}
    data[spec.alias] = spec.to_dict()
    # tmp+replace (GL-ATOMIC): a crash mid-save must not tear the
    # registry every later ``tpu://`` resolve parses.
    atomic_write_text(str(path), json.dumps(data, indent=2))
    return path


def remove_registry_entry(
    alias: str, registry_path: Path | None = None
) -> bool:
    path = Path(registry_path or REGISTRY_PATH)
    if not path.is_file():
        return False
    data = json.loads(path.read_text())
    if alias not in data:
        return False
    del data[alias]
    # tmp+replace (GL-ATOMIC): same discipline as save_registry_entry.
    atomic_write_text(str(path), json.dumps(data, indent=2))
    return True


def resolve_model_spec(
    model: str, registry_path: Path | None = None
) -> ModelSpec:
    alias = parse_tpu_model_id(model)
    registry = load_registry(registry_path)
    if alias not in registry:
        known = ", ".join(sorted(registry))
        raise KeyError(
            f"unknown tpu model alias {alias!r}. Registered aliases: {known}. "
            f"Add one with: debate registry add-model {alias} "
            f"--checkpoint /path/to/hf/dir --family llama"
        )
    return registry[alias]


def validate_tpu_model(
    model: str,
    registry_path: Path | None = None,
    registry: dict[str, ModelSpec] | None = None,
) -> str | None:
    """None if servable, else an actionable error (exit-code-2 material).

    Pass a preloaded ``registry`` to avoid re-reading the registry file once
    per model when validating a batch.
    """
    try:
        if registry is not None:
            alias = parse_tpu_model_id(model)
            if alias not in registry:
                known = ", ".join(sorted(registry))
                raise KeyError(
                    f"unknown tpu model alias {alias!r}. Registered "
                    f"aliases: {known}"
                )
            spec = registry[alias]
        else:
            spec = resolve_model_spec(model, registry_path)
    except (ValueError, KeyError) as e:
        return str(e).strip("'\"")
    if spec.quant not in QUANT_FORMATS:
        return (
            f"model {model} registers unknown quantization "
            f"{spec.quant!r}; known: "
            + ", ".join(repr(q) for q in QUANT_FORMATS)
        )
    if spec.checkpoint != "random":
        ckpt = Path(spec.checkpoint)
        if not ckpt.exists():
            return (
                f"checkpoint for {model} not found at {ckpt}; update it with "
                f"debate registry add-model {spec.alias} --checkpoint <dir>"
            )
    return None
