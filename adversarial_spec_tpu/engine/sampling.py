"""Token sampling: greedy, temperature, top-k, top-p.

Split static/dynamic for XLA friendliness: ``greedy`` and ``top_k`` change
the traced graph (static), while ``temperature`` and ``top_p`` are runtime
scalars — changing them never recompiles the decode step.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def filtered_logits(
    logits: jnp.ndarray,  # [..., V] f32
    *,
    greedy: bool,
    top_k: int,
    temperature: jnp.ndarray,  # scalar f32
    top_p: jnp.ndarray,  # scalar f32
    use_top_p: bool = True,
) -> jnp.ndarray:
    """The post-filter logits whose softmax IS the sampling distribution.

    Exposed separately from ``sample_tokens`` because speculative decoding
    (engine/speculative.py) needs the target *distribution* per verified
    position for rejection sampling — acceptance tests and residual draws
    must use exactly what plain decode would sample from, or speculation
    changes the output distribution. Greedy (and temperature <= 0)
    degenerates to a one-hot at the argmax.
    """
    onehot = jnp.where(
        jnp.arange(logits.shape[-1])
        == jnp.argmax(logits, axis=-1, keepdims=True),
        0.0,
        -jnp.inf,
    )
    if greedy:
        return onehot

    # temperature == 0 degrades to greedy without retracing.
    safe_t = jnp.maximum(temperature, 1e-6)
    scaled = logits / safe_t

    if top_k > 0 and top_k < logits.shape[-1]:
        kth = jax.lax.top_k(scaled, top_k)[0][..., -1:]
        scaled = jnp.where(scaled < kth, -jnp.inf, scaled)

    if use_top_p:
        # Top-p (nucleus): drop tokens outside the smallest prefix of the
        # probability-sorted vocab whose mass exceeds top_p.
        sorted_logits = jnp.sort(scaled, axis=-1)[..., ::-1]
        sorted_probs = jax.nn.softmax(sorted_logits, axis=-1)
        cumulative = jnp.cumsum(sorted_probs, axis=-1)
        # Keep the first token whose cumulative crosses top_p.
        cutoff_mask = cumulative - sorted_probs > top_p
        cutoff_logit = jnp.min(
            jnp.where(cutoff_mask, jnp.inf, sorted_logits),
            axis=-1,
            keepdims=True,
        )
        scaled = jnp.where(scaled < cutoff_logit, -jnp.inf, scaled)

    return jnp.where(temperature <= 0.0, onehot, scaled)


def sample_tokens(
    logits: jnp.ndarray,  # [B, V] f32
    key: jax.Array,
    *,
    greedy: bool,
    top_k: int,
    temperature: jnp.ndarray,  # scalar f32
    top_p: jnp.ndarray,  # scalar f32
    use_top_p: bool = True,
) -> jnp.ndarray:
    """Sample one token per row. Returns [B] int32.

    ``use_top_p`` is a static switch: callers that know (at trace time)
    top_p >= 1 skip the full-vocab sort/cumsum entirely — it would be a
    semantic no-op that still costs a vocab-sized sort per decode step.
    """
    if greedy:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    filt = filtered_logits(
        logits,
        greedy=greedy,
        top_k=top_k,
        temperature=temperature,
        top_p=top_p,
        use_top_p=use_top_p,
    )
    # temperature <= 0: filtered_logits already degenerated to the argmax
    # one-hot, and categorical over a one-hot returns it deterministically.
    return jax.random.categorical(key, filt, axis=-1).astype(jnp.int32)
