"""Continuous batching scheduler over the paged KV pool.

SURVEY §7 step 3's full form ("continuous batching across opponents
sharing weights"): a slot-based scheduler that keeps one decode batch hot
while sequences of different lengths join and leave it —

- ``max_batch`` slots decode together as rows of one jitted program;
- a finished row's pages free immediately and a queued request is admitted
  into the empty slot at the next chunk boundary — its prompt chunks ride
  INSIDE the residents' decode program (``fused_prefill_decode_chunk``,
  Sarathi-style piggybacked chunked prefill), so admission never pauses
  the batch;
- per-row lengths/budgets/EOS are tracked as device arrays, so rows at
  different positions coexist in the same while_loop (per-row ``q_pos``
  drives page writes, RoPE positions, and window bounds).

Drive loop (engine/interleave.py holds the config + telemetry): the
default loop keeps up to two fused steps in flight and never calls a
blanket ``jax.block_until_ready`` — the host applies step N-1's fetched
``active`` flags (async device→host copy) while step N runs, overlapping
queue admission, prefix-cache radix lookups, page allocation, and result
collection with device compute. Sanctioned sync points, and ONLY these
(enforced by graftlint's GL-SYNC rule, which catches implicit syncs —
np.asarray/.item()/int()/truthiness on device values — as well as
explicit block_until_ready; docs/static_analysis.md): admission handoff
(``_finish_admission``), slot completion (token fetch), fault decisions,
and timeout expiry. ``interleave=False`` (CLI ``--no-interleave``,
``ADVSPEC_INTERLEAVE=0``) restores the legacy serialized loop — one
prefill dispatch, full sync, one decode dispatch, full sync — as the
escape hatch and bench baseline.

Inactive-slot safety: physical page 0 is a reserved TRASH page no
sequence owns. Allocator ids are shifted +1, the -1 "unmapped" sentinel
maps to 0, and inactive rows write their (masked, discarded) KV there —
a dead slot can never scribble into pages re-allocated to a newcomer.
Trash/unmapped pages are never read: every row's valid window
[pad, cur_len) ends before any unmapped logical slot.

Fault isolation: a fault at the decode-chunk, admission-prefill, or
page-allocation step evicts only the affected slot — its ``SchedResult``
carries the partial tokens plus ``error``/``fault_kind`` — frees its
pages, and leaves the rest of the batch decoding. Transient faults
(resilience/faults.py taxonomy) get one requeue before the partial result
is final, budgeted against the caller's existing deadline. The chaos
injector's ``scheduler_chunk`` and ``kv_alloc`` seams live here.

Per-request watchdog (``SchedRequest.deadline_s``, docs/resilience.md
"Durability and recovery"): both drive loops check per-request
deadlines once per iteration — pure host clock math — and evict an
over-deadline slot as ``FaultKind.TIMEOUT`` through the same shared
surgery, partial text delivered to its stream consumer, co-residents
untouched, no batcher-level requeue (the debate layer owns the single
hedged re-admission). Zero new sync points: the eviction rides the
decode-fault path's existing sanctioned fetches.

The round-synchronous debate path (engine/tpu.py) doesn't need this; it
serves multi-session workloads (several debates sharing one model) and is
exercised directly in tests/test_scheduler.py.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from adversarial_spec_tpu.engine.generate import (
    _prefill_chunk_impl,
    bucket_length,
    pad_batch,
    prefill_chunk,
)
from adversarial_spec_tpu.engine import interleave as interleave_mod
from adversarial_spec_tpu.engine import kvtier as kvtier_mod
from adversarial_spec_tpu.engine import prefix_cache as prefix_mod
from adversarial_spec_tpu.engine import spec as spec_mod
from adversarial_spec_tpu.engine import streaming as stream_mod
from adversarial_spec_tpu import obs as obs_mod
from adversarial_spec_tpu.engine.sampling import filtered_logits
from adversarial_spec_tpu.engine.speculative import (
    _draft,
    _rowwise_slice,
    _rowwise_write,
    accept_spans,
)
from adversarial_spec_tpu.engine.kvcache import (
    OutOfPages,
    PageAllocator,
    PagedCacheLayout,
    init_page_pool,
    read_tokens,
    write_tokens,
)
from adversarial_spec_tpu.engine.sampling import sample_tokens
from adversarial_spec_tpu.models.config import ModelConfig
from adversarial_spec_tpu.ops import quant
from adversarial_spec_tpu.models.transformer import (
    forward_paged_decode,
    init_cache,
)
from adversarial_spec_tpu.resilience import faults, injector

TRASH_PAGE = 0
# Admission prefill granularity — deliberately finer than generate.py's
# PREFILL_CHUNK (1024): smaller chunks mean decode chunks slot in between
# more often while a newcomer's prompt streams in.
ADMISSION_CHUNK = 512


@dataclass
class SchedRequest:
    req_id: int
    prompt_ids: list[int]
    max_new_tokens: int
    # Per-request watchdog deadline in seconds from submission (0 =
    # none). Checked by the drive loops' watchdog
    # (``_expire_request_deadlines``) — pure host clock math; the
    # eviction itself rides the decode-fault surgery's EXISTING
    # sanctioned fetches, so the watchdog adds zero new sync points.
    deadline_s: float = 0.0
    # Causal-trace ids (obs/trace.py), carried by value from the debate
    # round that issued this request; every flight-recorder event the
    # batcher emits for it is stamped with them (explicitly where the
    # emit site knows the request, via the ambient scope elsewhere).
    trace_id: str = ""
    span_id: str = ""
    # Host-side streaming consumer (engine/streaming.py): called at the
    # drive loop's existing fetch points with ALL token ids this
    # request has emitted so far (np.ndarray); return False to cancel
    # the request mid-decode (``_cancel_slot``). None = the blocking
    # path, byte-identical to pre-streaming behavior.
    on_tokens: object = None


@dataclass
class _Admission:
    """An in-flight admission: its prompt prefills one chunk per scheduler
    iteration (interleaved with resident rows' decode chunks) instead of
    stalling decode for the whole prompt.

    Two coordinate systems coexist (per admission, chosen at start):

    - padded (prefix cache off): tokens left-padded to the bucket, the
      original layout; KV slot = pad + logical position.
    - canonical (prefix cache on): tokens at slot = logical position,
      pad 0, right-padded to the bucket. The canonical layout is what
      makes page content layout-independent and therefore shareable: a
      token's K/V depends only on its logical position, so a block
      cached by one admission drops into any later one.
    """

    slot: int
    req: SchedRequest
    seq_id: int
    tokens: object  # [1, S] device array
    pads: object  # [1]
    cache: object  # 1-row dense cache being prefilled
    pos: int  # next chunk start
    S: int  # bucketed token-array length
    last_logits: object = None
    # Canonical-layout (prefix cache) bookkeeping:
    canonical: bool = False
    S_real: int = 0  # true prompt length (== S when padded)
    matched: int = 0  # tokens adopted from the cache (page multiple)
    prefill_end: int = 0  # prefill covers [pos0, prefill_end)
    prefill_s: float = 0.0  # this request's own prefill wall-clock
    # Set when a fused dispatch carrying this admission faulted: the
    # next chunk runs STANDALONE so a prefill-side error is attributed
    # to the admission (_abort_admission) instead of evicting another
    # resident every iteration; a decode-side fault already evicted its
    # slot, and fusion resumes after one clean standalone chunk.
    fuse_deferred: bool = False

    @property
    def remaining(self) -> int:
        return self.prefill_end - self.pos


@dataclass
class SchedResult:
    req_id: int
    tokens: np.ndarray  # generated ids (0 past the row's end)
    n_generated: int
    # Set when a fault evicted this request: ``tokens`` then holds the
    # PARTIAL decode up to the fault and ``fault_kind`` is the
    # resilience-taxonomy value (resilience/faults.py). None = clean.
    error: str | None = None
    fault_kind: str | None = None
    # Per-request perf split: prompt tokens served from the prefix cache
    # and the wall-clock this request's own admission prefill took (the
    # decode share is apportioned by the caller — engine/tpu.py).
    cached_tokens: int = 0
    prefill_time_s: float = 0.0
    # Per-request speculation telemetry: verify steps this row took part
    # in, eligible draft positions verified, and positions accepted
    # (acceptance rate = accepted / drafted). All zero with
    # --no-speculative.
    spec_steps: int = 0
    spec_drafted: int = 0
    spec_accepted: int = 0
    # This request's own decode wall: each drive-loop step's decode
    # share splits evenly over the rows live at dispatch, so the slot
    # sums reproduce the batcher's decode_time_s counter. Together with
    # prefill_time_s it IS the request's service wall — the end wall of
    # its ``request`` trace span (tools/trace_view.py checks the sum).
    decode_time_s: float = 0.0
    # Streaming early-convergence cancellation (engine/streaming.py):
    # ``cancelled`` marks a CLEAN mid-decode stop requested by the
    # consumer (``tokens`` holds the partial transcript, no error);
    # ``tokens_saved`` is the budget remainder never decoded.
    cancelled: bool = False
    tokens_saved: int = 0
    # Echo of the request's causal-trace ids.
    trace_id: str = ""
    span_id: str = ""


def _next_chunk_len(remaining: int) -> int:
    """Largest power-of-two chunk ≤ min(remaining, ADMISSION_CHUNK).

    Keeps compiled prefill-chunk shapes to a small fixed set (powers of
    two up to ADMISSION_CHUNK) while letting the canonical path start at
    an arbitrary page-aligned offset — cache granularity stays one PAGE,
    not one admission chunk.
    """
    c = ADMISSION_CHUNK
    while c > remaining:
        c //= 2
    return max(c, 1)


def _decode_chunk_impl(
    params,
    cfg: ModelConfig,
    pool,
    page_table: jnp.ndarray,  # [B, Pmax] physical ids (0 = trash/unmapped)
    cur_tok: jnp.ndarray,  # [B]
    cur_len: jnp.ndarray,  # [B] prompt+emitted tokens so far
    pad_lens: jnp.ndarray,  # [B]
    n_emitted: jnp.ndarray,  # [B]
    max_new: jnp.ndarray,  # [B] per-row budget
    active: jnp.ndarray,  # [B] bool
    out_buf: jnp.ndarray,  # [B, cap]
    eos_ids: jnp.ndarray,
    key: jax.Array,
    temperature: jnp.ndarray,
    top_p: jnp.ndarray,
    *,
    chunk: int,
    greedy: bool,
    top_k: int,
    use_top_p: bool = True,
    use_pallas: bool = False,
    use_pallas_matmul: bool = False,
    pallas_interpret: bool = False,
    mesh=None,
):
    """Up to ``chunk`` decode steps over whatever rows are active.

    This is THE paged decode loop — generate()'s round-synchronous paged
    path calls it too (with uniform initial state), and it is inlined
    into ``fused_prefill_decode_chunk`` — so the per-step write-page
    lookup, bounds, and sampling glue exist exactly once for the
    standalone and fused programs alike. ``scheduler_decode_chunk`` is
    this body jitted (with pool/out_buf donation); call the bare impl
    only from inside another traced program.
    """
    B = cur_tok.shape[0]
    page_size = pool["k"].shape[3]
    cap = out_buf.shape[1]
    rows = jnp.arange(B)

    def cond(state):
        i, active = state[0], state[6]
        return (i < chunk) & active.any()

    def body(state):
        i, cur, cur_len, n_emitted, pool, out_buf, active, key = state
        q_pos = cur_len - 1  # [B] logical slot of cur's KV
        write_page = jnp.where(
            active,
            page_table[rows, q_pos // page_size],
            TRASH_PAGE,
        )
        write_off = q_pos % page_size
        bounds = jnp.stack([pad_lens, q_pos + 1], axis=1).astype(jnp.int32)
        positions = (q_pos - pad_lens)[:, None]
        logits, pool = forward_paged_decode(
            params,
            cfg,
            cur[:, None],
            positions,
            pool,
            page_table,
            write_page,
            write_off,
            bounds,
            q_pos,
            use_pallas=use_pallas,
            use_pallas_matmul=use_pallas_matmul,
            pallas_interpret=pallas_interpret,
            mesh=mesh,
        )
        key, sub = jax.random.split(key)
        nxt = sample_tokens(
            logits[:, 0],
            sub,
            greedy=greedy,
            top_k=top_k,
            temperature=temperature,
            top_p=top_p,
            use_top_p=use_top_p,
        )
        is_eos = (nxt[:, None] == eos_ids[None, :]).any(axis=-1)
        nxt = jnp.where(active, nxt, 0)
        write_pos = jnp.minimum(n_emitted, cap - 1)
        out_buf = out_buf.at[rows, write_pos].set(
            jnp.where(active, nxt, out_buf[rows, write_pos])
        )
        n_emitted = n_emitted + active.astype(jnp.int32)
        cur_len = cur_len + active.astype(jnp.int32)
        done = (is_eos | (n_emitted >= max_new)) & active
        active = active & ~done
        return i + 1, nxt, cur_len, n_emitted, pool, out_buf, active, key

    state = (
        jnp.int32(0),
        cur_tok,
        cur_len,
        n_emitted,
        pool,
        out_buf,
        active,
        key,
    )
    _, cur, cur_len, n_emitted, pool, out_buf, active, _ = jax.lax.while_loop(
        cond, body, state
    )
    return pool, cur, cur_len, n_emitted, out_buf, active


# The public jitted entry point — the same body, not a hand-forwarded
# wrapper (a wrapper that forgot to thread a new kwarg would silently pin
# its default on one path only and break fused/standalone token parity).
scheduler_decode_chunk = partial(
    jax.jit,
    static_argnames=(
        "cfg",
        "chunk",
        "greedy",
        "top_k",
        "use_top_p",
        "use_pallas",
        "use_pallas_matmul",
        "pallas_interpret",
        "mesh",
    ),
    donate_argnames=("pool", "out_buf"),
)(_decode_chunk_impl)


@partial(
    jax.jit,
    static_argnames=(
        "cfg",
        "chunk",
        "greedy",
        "top_k",
        "use_top_p",
        "use_pallas",
        "use_pallas_matmul",
        "pallas_interpret",
        "mesh",
    ),
    donate_argnames=("adm_cache", "pool", "out_buf"),
)
def fused_prefill_decode_chunk(
    params,
    cfg: ModelConfig,
    adm_tokens: jnp.ndarray,  # [1, Sc] the admission's next prompt chunk
    adm_pads: jnp.ndarray,  # [1]
    adm_cache,  # 1-row dense cache being prefilled
    adm_cache_index: jnp.ndarray,  # scalar: slot of the chunk's 1st token
    pool,
    page_table: jnp.ndarray,
    cur_tok: jnp.ndarray,
    cur_len: jnp.ndarray,
    pad_lens: jnp.ndarray,
    n_emitted: jnp.ndarray,
    max_new: jnp.ndarray,
    active: jnp.ndarray,
    out_buf: jnp.ndarray,
    eos_ids: jnp.ndarray,
    key: jax.Array,
    temperature: jnp.ndarray,
    top_p: jnp.ndarray,
    *,
    chunk: int,
    greedy: bool,
    top_k: int,
    use_top_p: bool = True,
    use_pallas: bool = False,
    use_pallas_matmul: bool = False,
    pallas_interpret: bool = False,
    mesh=None,
):
    """ONE device program per scheduler iteration: the in-flight
    admission's prompt chunk AND every resident row's decode chunk
    (Sarathi-style piggybacked chunked prefill).

    The two halves touch disjoint state — the admission prefills into
    its private 1-row dense cache while residents decode against the
    paged pool (the admission's pages are only written at handoff, in
    ``_finish_admission``) — so fusing them is pure overlap: the
    newcomer's prompt math rides in the same dispatch instead of
    stalling the batch behind a separate program + host sync, and XLA is
    free to schedule the independent subgraphs together. Each half is
    the SAME traced body as its standalone program
    (``_prefill_chunk_impl`` / ``_decode_chunk_impl``), so greedy tokens
    are byte-identical either way. On sharded meshes the decode half
    carries the ``mesh`` down into ``forward_paged_decode`` exactly as
    ``scheduler_decode_chunk`` does (the dp-sharded wrapper —
    ``sharded_scheduler_decode_chunk`` — stays decode-only: admissions
    are a single-device batcher concern today).
    """
    adm_cache, adm_logits = _prefill_chunk_impl(
        params, cfg, adm_tokens, adm_pads, adm_cache, adm_cache_index
    )
    pool, cur, cur_len, n_emitted, out_buf, active = _decode_chunk_impl(
        params,
        cfg,
        pool,
        page_table,
        cur_tok,
        cur_len,
        pad_lens,
        n_emitted,
        max_new,
        active,
        out_buf,
        eos_ids,
        key,
        temperature,
        top_p,
        chunk=chunk,
        greedy=greedy,
        top_k=top_k,
        use_top_p=use_top_p,
        use_pallas=use_pallas,
        use_pallas_matmul=use_pallas_matmul,
        pallas_interpret=pallas_interpret,
        mesh=mesh,
    )
    return (
        adm_cache,
        adm_logits,
        pool,
        cur,
        cur_len,
        n_emitted,
        out_buf,
        active,
    )


def _spec_chunk_impl(
    params,
    cfg: ModelConfig,
    pool,
    page_table: jnp.ndarray,  # [B, Pmax] physical ids (0 = trash/unmapped)
    ctx_buf: jnp.ndarray,  # [B, C] prompt ++ emitted tokens (draft source)
    ctx_len: jnp.ndarray,  # [B] tokens valid in ctx_buf
    prev_tok: jnp.ndarray,  # [B] token before cur (bigram context)
    cur_tok: jnp.ndarray,  # [B]
    cur_len: jnp.ndarray,  # [B] prompt+emitted tokens so far
    pad_lens: jnp.ndarray,  # [B]
    n_emitted: jnp.ndarray,  # [B]
    max_new: jnp.ndarray,  # [B] per-row budget
    alloc_len: jnp.ndarray,  # [B] KV slots covered by allocated pages
    active: jnp.ndarray,  # [B] bool
    out_buf: jnp.ndarray,  # [B, cap]
    eos_ids: jnp.ndarray,
    key: jax.Array,
    temperature: jnp.ndarray,
    top_p: jnp.ndarray,
    *,
    gamma: int,
    greedy: bool,
    top_k: int,
    use_top_p: bool = True,
    use_pallas: bool = False,
    use_pallas_matmul: bool = False,
    pallas_interpret: bool = False,
    mesh=None,
):
    """ONE speculative step over whatever rows are active: draft up to γ
    tokens per row from that row's own context (prompt + generated so
    far — prompt-lookup, engine/speculative.py's bigram rule), run ONE
    batched multi-position verification forward over the paged pool, and
    accept a prefix by rejection sampling against the true sampling
    distribution (``accept_spans`` — the dense path's accept math, so
    greedy output stays byte-identical to plain decode).

    The verification forward IS ``forward_paged_decode`` — called
    span-native (tokens [B, γ+1], each position carrying its own write
    target and attention bounds), so the verify program shares the
    decode chunk's traced body the way ``fused_prefill_decode_chunk``
    shares the prefill's, and the Pallas route rides the multi-position
    paged kernel (ops/pallas_paged.py:paged_decode_attention_mq — one
    pass over the row's pages for the whole span, where the pre-PR-17
    batch-axis flatten re-gathered the pool γ+1 times). In-span
    causality comes from the bounds: position i's window ends at its own
    slot, and every span position's K/V is scattered before attention in
    each layer, so position i sees exactly [pad, cur_len+i).

    Rollback discipline: draft position k writes its K/V at slot
    ``cur_len-1+k`` only when the host's page allocation covers it AND
    the row's output budget could commit it (``n_allowed``); everything
    else lands on the trash page. Rejected drafts leave stale K/V above
    the accepted prefix — never read, because the row's next write
    region starts exactly there — and the host releases any page that
    no longer backs a committed token (``PageAllocator.truncate``) after
    fetching the accept counts. Emits 1..γ+1 tokens per active row;
    rows that cannot fit a draft (budget tail, pages short) degrade to a
    plain single-token step inside the SAME program, so the compiled
    shape is one per draft width γ.

    Returns the updated row state plus ``counts`` [5, B] (n_allowed,
    n_acc, n_emit, active, cur_len) — ONE stacked array so the drive
    loop's sanctioned accept fetch is a single host copy.
    """
    B = cur_tok.shape[0]
    page_size = pool["k"].shape[3]
    cap = out_buf.shape[1]
    C = ctx_buf.shape[1]
    span = gamma + 1
    rows = jnp.arange(B)
    j = jnp.arange(span)[None, :]  # [1, span]

    # Per-row draft positions eligible to COMMIT this step: bounded by
    # the output budget (the bonus token always needs one slot) and by
    # the KV slots the host has pages for.
    n_allowed = jnp.clip(
        jnp.minimum(max_new - n_emitted - 1, alloc_len - cur_len),
        0,
        gamma,
    )
    n_allowed = jnp.where(active, n_allowed, 0)

    # --- Draft from the row's own context (most recent bigram match). ---
    draft = _draft(ctx_buf, prev_tok, cur_tok, ctx_len, gamma)  # [B, γ]
    toks = jnp.concatenate([cur_tok[:, None], draft], axis=1)  # [B, span]
    q_pos = (cur_len - 1)[:, None] + jnp.arange(span)[None, :]  # [B, span]
    # Position 0 is cur (its slot is always covered: alloc_len ≥
    # cur_len); draft position k commits only while k ≤ n_allowed.
    writable = active[:, None] & (j <= n_allowed[:, None])
    safe_q = jnp.minimum(q_pos, page_table.shape[1] * page_size - 1)
    write_page = jnp.where(
        writable,
        page_table[rows[:, None], safe_q // page_size],
        TRASH_PAGE,
    )
    write_off = safe_q % page_size
    bounds = jnp.stack(
        [jnp.broadcast_to(pad_lens[:, None], q_pos.shape), q_pos + 1],
        axis=-1,
    ).astype(jnp.int32)  # [B, span, 2]
    positions = q_pos - pad_lens[:, None]

    # --- Verify: the paged forward, span-native ([B, γ+1] positions). ---
    logits, pool = forward_paged_decode(
        params,
        cfg,
        toks,
        positions,
        pool,
        page_table,
        write_page,
        write_off,
        bounds,
        q_pos,
        use_pallas=use_pallas,
        use_pallas_matmul=use_pallas_matmul,
        pallas_interpret=pallas_interpret,
        mesh=mesh,
    )

    # --- Accept by rejection sampling against the true distribution. ---
    filt = filtered_logits(
        logits,
        greedy=greedy,
        top_k=top_k,
        temperature=temperature,
        top_p=top_p,
        use_top_p=use_top_p,
    )  # [B, span, V]
    probs = jax.nn.softmax(filt, axis=-1)
    key, u_key, res_key = jax.random.split(key, 3)
    n_acc, bonus = accept_spans(
        probs, draft, n_allowed, u_key, res_key, greedy=greedy
    )
    emitted = jnp.concatenate(
        [draft, jnp.zeros((B, 1), draft.dtype)], axis=1
    )
    emitted = emitted.at[rows, n_acc].set(bonus)

    # --- EOS + per-row emit counts (EOS kept, zeros after). ---
    is_eos = (emitted[..., None] == eos_ids[None, None, :]).any(-1)
    eos_hits = is_eos & (j <= n_acc[:, None])
    any_eos = eos_hits.any(axis=1)
    first_eos = jnp.argmax(eos_hits, axis=1)
    n_emit = jnp.where(any_eos, first_eos + 1, n_acc + 1)
    n_emit = jnp.where(active, n_emit, 0)
    emitted = jnp.where(j < n_emit[:, None], emitted, 0)

    def append(buf, start_raw, width):
        """Write ``emitted[:n_emit]`` at per-row ``start_raw``, masked so
        every other slot keeps its current value (a clamped window near
        the buffer end must never smash earlier tokens)."""
        w_start = jnp.minimum(start_raw, width - span)
        d = start_raw - w_start  # [B] ≥ 0 in-window shift
        src = jnp.take_along_axis(
            emitted, jnp.clip(j - d[:, None], 0, span - 1), axis=1
        )
        current = _rowwise_slice(buf, w_start, span)
        mask = (
            active[:, None]
            & (j >= d[:, None])
            & (j < (d + n_emit)[:, None])
        )
        return _rowwise_write(buf, jnp.where(mask, src, current), w_start)

    out_buf = append(out_buf, jnp.minimum(n_emitted, cap - 1), cap)
    ctx_buf = append(ctx_buf, jnp.minimum(ctx_len, C - 1), C)

    new_cur = jnp.where(
        active, emitted[rows, jnp.maximum(n_emit - 1, 0)], cur_tok
    )
    new_prev = jnp.where(
        active,
        jnp.where(
            n_emit >= 2, emitted[rows, jnp.maximum(n_emit - 2, 0)], cur_tok
        ),
        prev_tok,
    )
    n_emitted = n_emitted + n_emit
    cur_len = cur_len + n_emit
    ctx_len = ctx_len + n_emit
    done = (any_eos | (n_emitted >= max_new)) & active
    active = active & ~done
    counts = jnp.stack(
        [n_allowed, n_acc, n_emit, active.astype(jnp.int32), cur_len]
    )
    return (
        pool,
        ctx_buf,
        ctx_len,
        new_prev,
        new_cur,
        cur_len,
        n_emitted,
        out_buf,
        active,
        counts,
    )


# The jitted verify program — the same body, not a hand-forwarded
# wrapper (the scheduler_decode_chunk convention: a wrapper that forgot
# to thread a kwarg would silently pin its default on one path only).
scheduler_spec_chunk = partial(
    jax.jit,
    static_argnames=(
        "cfg",
        "gamma",
        "greedy",
        "top_k",
        "use_top_p",
        "use_pallas",
        "use_pallas_matmul",
        "pallas_interpret",
        "mesh",
    ),
    donate_argnames=("pool", "out_buf", "ctx_buf"),
)(_spec_chunk_impl)


@partial(
    jax.jit,
    static_argnames=(
        "cfg",
        "gamma",
        "greedy",
        "top_k",
        "use_top_p",
        "use_pallas",
        "use_pallas_matmul",
        "pallas_interpret",
        "mesh",
    ),
    donate_argnames=("adm_cache", "pool", "out_buf", "ctx_buf"),
)
def fused_prefill_spec_chunk(
    params,
    cfg: ModelConfig,
    adm_tokens: jnp.ndarray,  # [1, Sc] the admission's next prompt chunk
    adm_pads: jnp.ndarray,  # [1]
    adm_cache,  # 1-row dense cache being prefilled
    adm_cache_index: jnp.ndarray,  # scalar: slot of the chunk's 1st token
    pool,
    page_table: jnp.ndarray,
    ctx_buf: jnp.ndarray,
    ctx_len: jnp.ndarray,
    prev_tok: jnp.ndarray,
    cur_tok: jnp.ndarray,
    cur_len: jnp.ndarray,
    pad_lens: jnp.ndarray,
    n_emitted: jnp.ndarray,
    max_new: jnp.ndarray,
    alloc_len: jnp.ndarray,
    active: jnp.ndarray,
    out_buf: jnp.ndarray,
    eos_ids: jnp.ndarray,
    key: jax.Array,
    temperature: jnp.ndarray,
    top_p: jnp.ndarray,
    *,
    gamma: int,
    greedy: bool,
    top_k: int,
    use_top_p: bool = True,
    use_pallas: bool = False,
    use_pallas_matmul: bool = False,
    pallas_interpret: bool = False,
    mesh=None,
):
    """``fused_prefill_decode_chunk``'s speculative sibling: the
    in-flight admission's prompt chunk AND every resident row's
    draft+verify step in ONE device program — a speculating slot rides
    the same dispatch as an in-flight admission, so turning speculation
    on never un-fuses chunked-prefill piggybacking. Each half is the
    SAME traced body as its standalone program (``_prefill_chunk_impl``
    / ``_spec_chunk_impl``), so greedy tokens are byte-identical either
    way."""
    adm_cache, adm_logits = _prefill_chunk_impl(
        params, cfg, adm_tokens, adm_pads, adm_cache, adm_cache_index
    )
    (
        pool,
        ctx_buf,
        ctx_len,
        prev_tok,
        cur_tok,
        cur_len,
        n_emitted,
        out_buf,
        active,
        counts,
    ) = _spec_chunk_impl(
        params,
        cfg,
        pool,
        page_table,
        ctx_buf,
        ctx_len,
        prev_tok,
        cur_tok,
        cur_len,
        pad_lens,
        n_emitted,
        max_new,
        alloc_len,
        active,
        out_buf,
        eos_ids,
        key,
        temperature,
        top_p,
        gamma=gamma,
        greedy=greedy,
        top_k=top_k,
        use_top_p=use_top_p,
        use_pallas=use_pallas,
        use_pallas_matmul=use_pallas_matmul,
        pallas_interpret=pallas_interpret,
        mesh=mesh,
    )
    return (
        adm_cache,
        adm_logits,
        pool,
        ctx_buf,
        ctx_len,
        prev_tok,
        cur_tok,
        cur_len,
        n_emitted,
        out_buf,
        active,
        counts,
    )


def sharded_scheduler_decode_chunk(
    mesh,
    params,
    cfg: ModelConfig,
    pool,
    page_table: jnp.ndarray,  # [B, Pmax] DEVICE-LOCAL physical ids
    cur_tok: jnp.ndarray,
    cur_len: jnp.ndarray,
    pad_lens: jnp.ndarray,
    n_emitted: jnp.ndarray,
    max_new: jnp.ndarray,
    active: jnp.ndarray,
    out_buf: jnp.ndarray,
    eos_ids: jnp.ndarray,
    key: jax.Array,
    temperature: jnp.ndarray,
    top_p: jnp.ndarray,
    **static_kw,
):
    """``scheduler_decode_chunk`` over a dp-sharded mesh.

    Paged decode scales over ``dp`` with ZERO cross-device page traffic:
    each device owns a slice of the page pool (pool axis 1 split over dp)
    holding its rows' pages plus its own trash page 0, and the page
    tables carry device-LOCAL physical ids (the caller lays pages out
    per-device — generate()'s paged setup). shard_map then runs the
    whole chunk loop independently per device; devices even early-exit
    their while_loops at different trip counts. tp/sp stay unsupported
    for paged (the kernel grid would need head sharding — dense decode
    covers those configs).

    Sampling keys are folded with the device index so rows on different
    devices draw independent randomness.
    """
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from adversarial_spec_tpu.parallel.mesh import DP

    rows = P(DP)
    pool_spec = jax.tree.map(lambda _: P(None, DP), pool)

    def local_chunk(
        params_l,
        pool_l,
        table_l,
        cur_l,
        len_l,
        pads_l,
        nem_l,
        maxn_l,
        act_l,
        out_l,
        eos_l,
        key_l,
        temp_l,
        tp_l,
    ):
        key_l = jax.random.fold_in(key_l, jax.lax.axis_index(DP))
        return scheduler_decode_chunk(
            params_l,
            cfg,
            pool_l,
            table_l,
            cur_l,
            len_l,
            pads_l,
            nem_l,
            maxn_l,
            act_l,
            out_l,
            eos_l,
            key_l,
            temp_l,
            tp_l,
            **static_kw,
        )

    return shard_map(
        local_chunk,
        mesh=mesh,
        in_specs=(
            P(),  # params replicated (dp-only gate: tp == 1)
            pool_spec,
            rows,  # page_table [B, Pmax]
            rows,
            rows,
            rows,
            rows,
            rows,
            rows,
            rows,  # out_buf [B, cap]
            P(),
            P(),
            P(),
            P(),
        ),
        out_specs=(pool_spec, rows, rows, rows, rows, rows),
        check_rep=False,
    )(
        params,
        pool,
        page_table,
        cur_tok,
        cur_len,
        pad_lens,
        n_emitted,
        max_new,
        active,
        out_buf,
        eos_ids,
        key,
        temperature,
        top_p,
    )


class ContinuousBatcher:
    """Admits requests into decode slots over one shared model + pool."""

    def __init__(
        self,
        params,
        cfg: ModelConfig,
        *,
        max_batch: int = 4,
        page_size: int = 64,
        capacity_tokens: int = 16384,
        max_new_cap: int = 1024,
        eos_ids: list[int] | None = None,
        greedy: bool = True,
        temperature: float = 0.7,
        top_k: int = 0,
        top_p: float = 1.0,
        seed: int = 0,
        chunk: int = 32,
        kv_dtype: str = "",
        prefix_cache: bool | None = None,
        interleave: bool | None = None,
        pipeline_depth: int | None = None,
        step_tokens: int = 0,
        speculative: bool | None = None,
        gamma: int | None = None,
        use_pallas_matmul: bool | None = None,
    ):
        self.params = params
        self.cfg = cfg
        self.B = max_batch
        # Replicated sharding of the params' mesh (None when params are
        # not mesh-sharded, e.g. direct CPU tests). Fresh admission
        # caches are committed to it at creation: an UNCOMMITTED fresh
        # cache and chunk 1's committed output otherwise present two jit
        # signatures for the same chunk length and XLA compiles the
        # whole prefill program twice — a genuine double compile the
        # retrace watch flagged on the first paged CLI drive.
        leaf = jax.tree_util.tree_leaves(params)[0]
        sh = getattr(leaf, "sharding", None)
        self._replicated = (
            jax.sharding.NamedSharding(sh.mesh, jax.sharding.PartitionSpec())
            if isinstance(sh, jax.sharding.NamedSharding)
            else None
        )
        self.page_size = page_size
        self.chunk = chunk
        self.kv_dtype = kv_dtype
        # Fused-step + pipelined drive loop (None = process config,
        # engine/interleave.py). ``step_tokens`` is the Sarathi-style
        # shared per-step token budget: a fused step's prompt chunk
        # shrinks so chunk_len + n_live·chunk stays under it. 0 = auto
        # (ADMISSION_CHUNK + max_batch·chunk — full-size prompt chunks
        # even with every slot decoding, i.e. legacy chunk sizes).
        cfg_il = interleave_mod.config()
        self.interleave = (
            cfg_il.enabled if interleave is None else bool(interleave)
        )
        self.pipeline_depth = max(
            1,
            min(
                cfg_il.pipeline_depth
                if pipeline_depth is None
                else int(pipeline_depth),
                interleave_mod.MAX_PIPELINE_DEPTH,
            ),
        )
        self.step_tokens = step_tokens or (
            ADMISSION_CHUNK + max_batch * chunk
        )
        # Per-slot prompt-lookup speculation (None = process config,
        # engine/spec.py): each decode step drafts up to γ tokens per
        # resident row from that row's own context and verifies them in
        # ONE multi-position forward (_spec_chunk_impl). γ is validated
        # at the knob (spec.configure / env read), so any value that
        # reaches here is ≥ 1.
        cfg_sp = spec_mod.config()
        self.speculative = (
            cfg_sp.enabled if speculative is None else bool(speculative)
        )
        self.gamma = self._clamp_gamma(
            cfg_sp.gamma if gamma is None else int(gamma), max_new_cap
        )
        self.greedy = greedy
        self.top_k = top_k
        self._temp = jnp.float32(temperature)
        self._top_p = jnp.float32(top_p)
        self._eos = jnp.asarray(
            sorted(set(eos_ids or [])) or [-1], jnp.int32
        )
        self._eos_np = np.asarray(sorted(set(eos_ids or [])) or [-1])
        self._use_top_p = float(top_p) < 1.0
        self._key = jax.random.key(seed)

        n_pages = -(-capacity_tokens // page_size)
        # Physical page 0 is the trash page; allocator ids shift +1.
        self.allocator = PageAllocator(n_pages, page_size)
        # Cross-round prefix KV cache over this pool (None = disabled).
        # The batcher OWNS the cache: its lifetime is the pool's, so a
        # batcher kept alive across rounds (engine/tpu.py) carries round
        # R's spec+transcript blocks into round R+1's admissions.
        if prefix_cache is None:
            prefix_cache = prefix_mod.config().enabled
        self.prefix_cache = (
            prefix_mod.PrefixCache(
                self.allocator,
                page_size,
                max_pages=prefix_mod.config().max_pages,
            )
            if prefix_cache
            else None
        )
        layout = PagedCacheLayout(
            n_pages=n_pages + 1,
            page_size=page_size,
            n_layers=cfg.n_layers,
            n_kv_heads=cfg.n_kv_heads,
            head_dim=cfg.head_dim,
        )
        self._dtype = jax.tree.leaves(params)[0].dtype
        self.pool = init_page_pool(
            layout, dtype=self._dtype, kv_dtype=kv_dtype
        )
        # Tiered KV (engine/kvtier.py): host-RAM demotion of LRU-evicted
        # prefix blocks + the persistent content-addressed disk store,
        # both below this pool. The host budget is denominated in real
        # page bytes; the store is namespaced by a model/config/layout
        # fingerprint so incompatible KV can never rehydrate. None when
        # tiering (or the prefix cache) is off.
        self.tiers = None
        if self.prefix_cache is not None and kvtier_mod.armed():
            kv_bytes = (
                1 if kv_dtype == "int8" else np.dtype(self._dtype).itemsize
            )
            block_bytes = (
                cfg.n_layers * cfg.n_kv_heads * page_size * cfg.head_dim
            ) * kv_bytes * 2
            if kv_dtype == "int8":  # per-(token, head) f32 scale pages
                block_bytes += cfg.n_layers * cfg.n_kv_heads * page_size * 4 * 2
            self.tiers = kvtier_mod.build_for(
                block_bytes,
                (cfg, page_size, kv_dtype, self._dtype),
            )
            if self.tiers is not None:
                self.prefix_cache.attach_tiers(
                    self.tiers, kv_fetch=self._fetch_page_kv
                )
        self.max_pages_per_seq = -(-(cfg.max_seq_len) // page_size)
        # Fused paged kernel on real TPUs; gather path elsewhere.
        self._use_pallas = jax.default_backend() == "tpu"
        self._pallas_interpret = jax.default_backend() == "cpu"
        # Fused dequant-matmul (ops/pallas_quant.py) whenever the params
        # actually carry quantized leaves: on real TPUs by default, or
        # opted in anywhere via ``use_pallas_matmul`` (CPU runs the same
        # kernels under interpret mode — the parity harness). A
        # full-precision checkpoint never routes through the kernels.
        if use_pallas_matmul is None:
            use_pallas_matmul = jax.default_backend() == "tpu"
        self._use_pallas_matmul = bool(use_pallas_matmul) and (
            quant.has_quantized_weights(params)
        )

        B, cap = self.B, max_new_cap
        self.cap = cap
        # Persistent per-row device state is COMMITTED to the params'
        # replicated sharding at creation (``_commit``, no-op off-mesh)
        # for the same reason fresh admission caches are: these arrays
        # are program inputs on the very first dispatch and donated
        # outputs ever after — an uncommitted fresh array and a
        # mesh-committed step output present two jit signatures for the
        # same program, and XLA compiles it twice (the retrace watch
        # caught exactly this on the engine's first paged spec drive:
        # ctx_len/prev_tok/cur_len/n_emitted/active flipped
        # UnspecifiedValue → NamedSharding between step 1 and step 2).
        self.page_table = self._commit(
            jnp.zeros((B, self.max_pages_per_seq), jnp.int32)
        )
        self.cur_tok = self._commit(jnp.zeros((B,), jnp.int32))
        # ≥1 so q_pos ≥ 0
        self.cur_len = self._commit(jnp.ones((B,), jnp.int32))
        self.pad_lens = self._commit(jnp.zeros((B,), jnp.int32))
        self.n_emitted = self._commit(jnp.zeros((B,), jnp.int32))
        self.max_new = self._commit(jnp.zeros((B,), jnp.int32))
        self.active = self._commit(jnp.zeros((B,), bool))
        self.out_buf = self._commit(jnp.zeros((B, cap), jnp.int32))
        # Host-trailing view of ``active``: the pipelined loop dispatches
        # against this snapshot (updated at admission handoff, fault
        # eviction, and step N-1's async fetch) instead of syncing on the
        # in-flight device state. A stale True only costs one no-op
        # dispatch whose while_loop exits immediately; fetches only ever
        # DEACTIVATE slots, and only when the slot's OWNERSHIP GENERATION
        # still matches the one recorded at dispatch — a slot freed and
        # re-admitted while a step was in flight bumps the generation, so
        # the old step's "this row finished" flag can never truncate the
        # newcomer that now owns the slot.
        self._active_np = np.zeros((B,), bool)
        self._slot_gen = [0] * B
        # Speculation state. ctx_buf is the DRAFT SOURCE: each row's
        # real (unpadded) prompt ids followed by everything it has
        # emitted — the prompt-lookup bigram scan runs over it on
        # device. Sized to the model context: submit() guarantees
        # bucketed prompt + budget fits max_seq_len, so prompt+emitted
        # always fits too. cur_len/row_len/n_emitted host views trail
        # the device via the per-step counts fetch; the host needs them
        # to manage draft page coverage (extend before dispatch,
        # truncate after the accept counts land).
        self._ctx_cap = cfg.max_seq_len
        self.ctx_buf = self._commit(
            jnp.zeros((B, self._ctx_cap), jnp.int32)
        )
        self.ctx_len = self._commit(jnp.zeros((B,), jnp.int32))
        self.prev_tok = self._commit(jnp.zeros((B,), jnp.int32))
        self._cur_len_np = np.ones((B,), np.int64)
        self._row_len_np = np.zeros((B,), np.int64)
        self._max_new_np = np.zeros((B,), np.int64)
        # Per-slot speculation telemetry [steps, drafted, accepted],
        # stamped onto SchedResult at completion/eviction.
        self._slot_spec: list[list[int]] = [[0, 0, 0] for _ in range(B)]

        self._slot_req: list[SchedRequest | None] = [None] * B
        self._slot_seq: list[int | None] = [None] * B
        # Streaming state (engine/streaming.py): the owner's consumer
        # callback and how many tokens it has been delivered so far —
        # deliveries happen at the drive loop's EXISTING fetch points
        # (no new sanctioned syncs), and a consumer returning False
        # triggers ``_cancel_slot``.
        self._slot_consumer: list = [None] * B
        self._slot_streamed: list[int] = [0] * B
        # Per-slot request telemetry, stamped at admission handoff.
        self._slot_cached: list[int] = [0] * B
        self._slot_prefill_s: list[float] = [0.0] * B
        # Per-slot causal-trace state: the owner's trace/span ids and
        # its accumulated decode wall (each step's decode share splits
        # evenly over the rows live at dispatch; the slot sums
        # reproduce decode_time_s).
        self._slot_trace: list[str] = [""] * B
        self._slot_span: list[str] = [""] * B
        self._slot_decode_s: list[float] = [0.0] * B
        # Host submit time per queued req_id: the 'queued' span's wall
        # (queue wait) measured at admission start.
        self._queued_t: dict[int, float] = {}
        # Per-request watchdog deadlines: req_id -> absolute monotonic
        # expiry, armed at submit for requests with ``deadline_s`` > 0.
        # The ABSOLUTE time survives a transient-fault requeue on
        # purpose — the watchdog bounds the request's total wall, not
        # its current residency. Entries clear when the request
        # finally resolves (finish/cancel/final fault/global timeout).
        self._deadline_t: dict[int, float] = {}
        self._admission: _Admission | None = None
        self._seq_counter = 0
        self.capacity_tokens = n_pages * page_size
        self.queue: list[SchedRequest] = []
        self.results: list[SchedResult] = []
        # req_ids that already consumed their one transient-fault requeue.
        # (Fault COUNTS live in the process-wide resilience.faults store —
        # one bookkeeping place, snapshotted by the CLI report.)
        self._retried: set[int] = set()
        # Wall-clock telemetry: admission prefills vs decode chunks.
        # decode_time_s feeds the engine's per-row usage attribution
        # (engine/tpu.py:_chat_continuous). Prefill time is split into
        # STALLED (the batch actually waited: standalone chunks with no
        # residents to overlap, and the admission-handoff scatter) vs
        # OVERLAPPED (the chunk rode inside a fused step while residents
        # decoded — hidden under compute). ``prefill_time_s`` is their
        # sum by construction; the same split feeds the process-wide
        # ``perf.interleave`` stats (engine/interleave.py).
        self.stalled_prefill_s = 0.0
        self.overlapped_prefill_s = 0.0
        self.decode_time_s = 0.0

    @property
    def prefill_time_s(self) -> float:
        """Total admission-prefill wall clock. Exactly the sum of the
        stalled and overlapped buckets — there is no third place prefill
        time can accumulate (the invariant ``perf.interleave`` pins)."""
        return self.stalled_prefill_s + self.overlapped_prefill_s

    def _record_prefill_time(self, seconds: float, *, overlapped: bool) -> None:
        if overlapped:
            self.overlapped_prefill_s += seconds
        else:
            self.stalled_prefill_s += seconds
        interleave_mod.stats.record_prefill_time(
            seconds, overlapped=overlapped
        )

    def reconfigure_sampling(
        self,
        *,
        greedy: bool | None = None,
        temperature: float | None = None,
        top_k: int | None = None,
        top_p: float | None = None,
        seed: int | None = None,
    ) -> None:
        """Retune sampling between rounds on a REUSED batcher (the pool,
        allocator, and prefix cache survive; only sampling state moves).
        Pass ``seed`` to reseed the PRNG stream for the new round."""
        if greedy is not None:
            self.greedy = greedy
        if top_k is not None:
            self.top_k = top_k
        if temperature is not None:
            self._temp = jnp.float32(temperature)
        if top_p is not None:
            self._top_p = jnp.float32(top_p)
            self._use_top_p = float(top_p) < 1.0
        if seed is not None:
            self._key = jax.random.key(seed)

    def reconfigure_speculative(
        self, enabled: bool | None = None, gamma: int | None = None
    ) -> None:
        """Retune speculation between DRAINS on a reused batcher (CLI
        rounds re-resolve the process config each invocation; the
        engine's persistent batcher must follow it). Only legal while no
        rows are resident: the admission path's page-reservation
        discipline (full budget up front vs lazy per-verify-step)
        depends on the flag, so flipping it under a live row would break
        the row's coverage contract. ``run_all`` drains fully, so the
        engine's call-seam is always idle."""
        if any(self._active_np) or any(
            r is not None for r in self._slot_req
        ):
            raise RuntimeError(
                "reconfigure_speculative on a batcher with resident rows"
            )
        if enabled is not None:
            self.speculative = bool(enabled)
            if self.speculative:
                # Re-enabling must re-walk the γ-vs-cap clamp: the
                # constructor may have degraded this batcher to plain
                # decode (cap <= 1 with self.gamma left unclamped), and
                # skipping the clamp here would let a span wider than
                # the output buffer reach the compiled program.
                self.gamma = self._clamp_gamma(self.gamma, self.cap)
        if gamma is not None:
            # Same knob validation as engine/spec.py — a γ that reaches
            # the compiled program is always ≥ 1.
            self.gamma = self._clamp_gamma(
                spec_mod._validate_gamma(int(gamma)), self.cap
            )

    def _clamp_gamma(self, gamma: int, cap: int) -> int:
        """Bound γ so a step's full span (γ drafts + the bonus token)
        fits the per-row output buffer: the spec chunk's masked append
        window is ``span`` wide, so ``span > cap`` would push the write
        window start negative (dynamic-slice clamping would then smash
        tokens at the buffer head). A 1-token cap leaves nothing to
        draft for — degrade to plain decode rather than compile a
        0-wide verify."""
        if cap <= 1:
            self.speculative = False
            return gamma
        return max(1, min(gamma, cap - 1))

    # -- admission ---------------------------------------------------------

    def submit(self, req: SchedRequest) -> None:
        """Reject infeasible requests up front with actionable errors —
        anything accepted here is guaranteed schedulable once enough
        resident sequences finish."""
        if req.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if req.max_new_tokens > self.cap:
            raise ValueError(
                f"max_new_tokens {req.max_new_tokens} exceeds scheduler "
                f"cap {self.cap}"
            )
        total = bucket_length(len(req.prompt_ids)) + req.max_new_tokens
        if total > self.cfg.max_seq_len:
            raise ValueError(
                f"prompt (bucketed) + budget = {total} tokens exceeds the "
                f"model context {self.cfg.max_seq_len}"
            )
        if total > self.capacity_tokens:
            raise ValueError(
                f"request needs {total} tokens but the pool holds only "
                f"{self.capacity_tokens}; raise capacity_tokens"
            )
        self.queue.append(req)
        if req.deadline_s > 0:
            import time

            self._deadline_t[req.req_id] = time.monotonic() + req.deadline_s
        if obs_mod.config().enabled:
            import time

            self._queued_t[req.req_id] = time.monotonic()
            obs_mod.emit(
                obs_mod.RequestEvent(
                    req_id=req.req_id,
                    state="queued",
                    tokens=len(req.prompt_ids),
                    trace_id=req.trace_id,
                    span_id=req.span_id,
                )
            )
            for name in ("request", "queued"):
                obs_mod.emit(
                    obs_mod.SpanEvent(
                        name=name,
                        phase="begin",
                        req_id=req.req_id,
                        trace_id=req.trace_id,
                        span_id=req.span_id,
                    )
                )

    def _commit(self, cache: dict) -> dict:
        """Commit a freshly created admission cache to the params'
        replicated mesh sharding (see ``_replicated`` in __init__); a
        no-op off-mesh."""
        if self._replicated is None:
            return cache
        return jax.device_put(cache, self._replicated)

    def _start_admission(self, slot: int, req: SchedRequest) -> bool:
        """Reserve pages and set up the chunked prefill for ``slot``;
        False if the pool is momentarily full (the request stays queued
        and retries after residents free pages). Any other failure —
        including an injected ``kv_alloc`` fault — propagates with the
        allocator state rolled back; ``_admit`` isolates it to this
        request."""
        injector.fire("kv_alloc", slot)
        if self.prefix_cache is not None:
            return self._start_admission_cached(slot, req)
        tokens_np, pads_np = pad_batch([req.prompt_ids], pad_id=0)
        S = tokens_np.shape[1]
        # Speculative rows reserve only the prompt + the first decode
        # write slot; draft headroom (and committed growth) is allocated
        # lazily per verify step and rolled back past the accepted
        # prefix (_prepare_spec_step / _apply_spec_counts). Plain rows
        # keep the full up-front reservation: every admitted request is
        # guaranteed to decode to its budget without further allocation.
        total = S + (1 if self.speculative else req.max_new_tokens)
        seq_id = self._seq_counter
        self.allocator.new_sequence(seq_id)
        try:
            self.allocator.extend(seq_id, total)
            self._admission = _Admission(
                slot=slot,
                req=req,
                seq_id=seq_id,
                tokens=jnp.asarray(tokens_np),
                pads=jnp.asarray(pads_np),
                cache=self._commit(
                    init_cache(
                        self.cfg, 1, S,
                        dtype=self._dtype, kv_dtype=self.kv_dtype,
                    )
                ),
                pos=0,
                S=S,
                S_real=S,
                prefill_end=S,
            )
        except OutOfPages:
            self.allocator.free_sequence(seq_id)
            return False
        except Exception:
            self.allocator.free_sequence(seq_id)
            raise
        self._seq_counter += 1
        obs_mod.emit(
            obs_mod.RequestEvent(
                req_id=req.req_id, state="admitted", slot=slot, tokens=S
            )
        )
        self._emit_admitted_spans(req, slot)
        return True

    def _extend_evicting(self, seq_id: int, n_tokens: int) -> None:
        """``allocator.extend`` that converts allocation pressure into
        prefix-cache LRU eviction before giving up (the shared reclaim
        policy lives on PrefixCache — one implementation for the
        scheduler and the mock engine's accounting alike)."""
        if self.prefix_cache is None:
            self.allocator.extend(seq_id, n_tokens)
        else:
            self.prefix_cache.extend_evicting(seq_id, n_tokens)

    # -- tiered KV swaps ---------------------------------------------------

    def _fetch_page_kv(self, page: int, n_tokens: int):
        """Demotion fetch: gather one evicted block's KV off its pool
        page into an INDEPENDENT device array (the page returns to the
        free list right after and may be re-used by the very allocation
        that triggered the eviction), start the device→host copy async
        (the ``copy_to_host_async`` discipline — no sanctioned sync is
        added to the drive loop), and hand the tier a lazy materializer:
        by the time the host tier spills/promotes/settles, the copy has
        long resolved and the fetch is a free host read."""
        phys = np.full((1, n_tokens), page + 1, np.int32)
        offs = np.arange(n_tokens, dtype=np.int32)[None, :]
        demote_kv = read_tokens(self.pool, phys, offs)
        for v in demote_kv.values():
            try:
                v.copy_to_host_async()
            except Exception:
                pass  # optional fast path only

        def materialize() -> dict:
            # graftlint: disable=GL-SYNC -- demotion materializer: resolved lazily at spill/promotion/settle time, long after the async copy started at evict time landed — a free host read, not a drive-loop stall
            return {k: np.asarray(demote_kv[k]) for k in demote_kv}

        return materialize

    def _promote_tier_blocks(
        self, slot: int, seq_id: int, ids, matched: int, tier_hits: list
    ) -> int:
        """Promote a contiguous run of lower-tier blocks into this
        admission's freshly reserved pages: host→device ``device_put``
        + pool scatter per block, dispatched WITHOUT a host sync so the
        transfers overlap the admission's delta prefill chunks. Each
        target page is swap-pinned around its scatter (a fault
        mid-promotion must never leave an in-flight write against a
        freed page — ``PageAllocator.check_invariants`` enforces it).

        A hit whose entry vanished since lookup (host LRU overflow, a
        quarantined disk read — the promotion "lost the race") stops
        the run; the remaining tokens fall back to plain prefill, which
        is always correct. Returns the promoted token count; the
        promoted blocks are re-inserted into the radix index so
        co-admitted opponents share them immediately."""
        import time

        tiers = self.tiers
        ps = self.page_size
        consumed: list = []
        payloads: list[dict] = []
        t0 = time.monotonic()
        for hit in tier_hits:
            injector.fire("kv_swap", slot)
            ok, payload = tiers.materialize(hit)
            if not ok or payload is None:
                break  # lost the race: prefill recomputes from here
            consumed.append(hit)
            payloads.append(payload)
        if not consumed:
            return 0
        done = len(consumed) * ps
        table = self.allocator.table(seq_id)
        pages = [
            table[(matched + i * ps) // ps] for i in range(len(consumed))
        ]
        # ONE batched host→device transfer + pool scatter for the whole
        # promoted run: a per-block write_tokens would copy the full
        # pool per block on the eager path. Target pages stay
        # swap-pinned for the duration (a fault mid-scatter must never
        # leave an in-flight write against a freed page).
        phys = np.repeat(np.asarray(pages, np.int32) + 1, ps)[None, :]
        offs = np.tile(np.arange(ps, dtype=np.int32), len(consumed))[None, :]
        promo_kv = {
            k: jnp.asarray(np.concatenate([p[k] for p in payloads], axis=3))
            for k in payloads[0]
        }
        pinned: list[int] = []
        try:
            for page in pages:
                self.allocator.swap_pin(page)
                pinned.append(page)
            self.pool = write_tokens(
                self.pool,
                promo_kv["k"],
                promo_kv["v"],
                phys,
                offs,
                ks_new=promo_kv.get("ks"),
                vs_new=promo_kv.get("vs"),
            )
        finally:
            for page in pinned:
                self.allocator.swap_unpin(page)
        # Consume BEFORE the radix re-insert: insert's cap enforcement
        # may LRU-evict tail blocks straight back into the host tier,
        # and consuming afterwards would pop those freshly re-demoted
        # entries (emptying the tier the next admission needs).
        per = (time.monotonic() - t0) / len(consumed)
        for hit in consumed:
            tiers.consume(hit, slot=slot, wall_s=per)
        self.prefix_cache.insert(
            list(ids[: matched + done]),
            table[: (matched + done) // ps],
        )
        return done

    def _start_admission_cached(self, slot: int, req: SchedRequest) -> bool:
        """Prefix-cache admission: adopt the longest cached prefix and
        set up a CANONICAL-layout (pad 0, slot == logical position)
        prefill of only the remainder.

        The token array is right-padded to the usual power-of-two bucket
        (compiled shapes unchanged) but prefill only covers
        [matched, page_ceil(S_real)) — the bucket's garbage tail is never
        computed or attended (forward's causal mask stops at
        cache_index). The last prompt token is always re-run even on a
        full-prefix hit: its logits seed sampling.
        """
        ids = req.prompt_ids
        S_real = len(ids)
        ps = self.page_size
        # record=False: a pool-full deferral retries this whole method
        # every scheduler iteration — stats count once, on success, with
        # the clamped (actually adopted) match.
        if self.tiers is not None:
            matched, pages, tier_hits = self.prefix_cache.lookup_tiered(
                ids, record=False
            )
        else:
            matched, pages = self.prefix_cache.lookup(ids, record=False)
            tier_hits = []
        # Keep at least the last token to prefill (logits source).
        limit = ((S_real - 1) // ps) * ps
        matched = min(matched, limit)
        pages = pages[: matched // ps]
        tier_hits = tier_hits[: (limit - matched) // ps]
        S = bucket_length(S_real)
        prefill_end = min(-(-S_real // ps) * ps, S)
        tokens_np = np.zeros((1, S), np.int32)
        tokens_np[0, :S_real] = np.asarray(ids, np.int32)
        seq_id = self._seq_counter
        self.allocator.new_sequence(seq_id)
        try:
            if matched:
                self.allocator.adopt(seq_id, pages, matched)
            # Same lazy-reservation rule as the padded path: prompt + 1
            # under speculation, full budget otherwise.
            self._extend_evicting(
                seq_id,
                (S_real - matched)
                + (1 if self.speculative else req.max_new_tokens),
            )
            # Lower-tier blocks continuing the device match promote into
            # the pages the extend just reserved — async host→device
            # writes that overlap the delta prefill below; a hit that
            # lost the race degrades to prefill (chaos seam: kv_swap).
            promoted = (
                self._promote_tier_blocks(
                    slot, seq_id, ids, matched, tier_hits
                )
                if tier_hits
                else 0
            )
            total = matched + promoted
            cache = self._commit(
                init_cache(
                    self.cfg, 1, S, dtype=self._dtype, kv_dtype=self.kv_dtype
                )
            )
            if total:
                # Materialize the adopted + promoted prefix KV into the
                # dense admission cache so the delta's attention sees it
                # (the promoted blocks' scatter was dispatched above;
                # this gather queues after it — no host sync).
                table = (
                    np.asarray(
                        self.allocator.table(seq_id)[: total // ps],
                        np.int32,
                    )
                    + 1
                )  # physical ids
                slots = np.arange(total, dtype=np.int32)[None, :]
                gathered = read_tokens(
                    self.pool, table[slots // ps], slots % ps
                )
                for k in cache:
                    cache[k] = (
                        cache[k].at[:, :, :, :total, :].set(gathered[k])
                    )
            self._admission = _Admission(
                slot=slot,
                req=req,
                seq_id=seq_id,
                tokens=jnp.asarray(tokens_np),
                pads=jnp.zeros((1,), jnp.int32),
                cache=cache,
                pos=total,
                S=S,
                canonical=True,
                S_real=S_real,
                matched=total,
                prefill_end=prefill_end,
            )
        except OutOfPages:
            self.allocator.free_sequence(seq_id)
            return False
        except Exception:
            self.allocator.free_sequence(seq_id)
            raise
        self._seq_counter += 1
        self.prefix_cache.stats.record_lookup(matched)
        if self.tiers is not None:
            self.tiers.record_lookup(tier_hits)
        obs_mod.emit(
            obs_mod.RequestEvent(
                req_id=req.req_id,
                state="admitted",
                slot=slot,
                tokens=S_real,
                cached_tokens=total,
            )
        )
        self._emit_admitted_spans(req, slot)
        return True

    def _emit_admitted_spans(self, req: SchedRequest, slot: int) -> None:
        """Trace-span bookkeeping at admission start: the 'queued' span
        ends (wall = the measured queue wait) and the 'prefill' span
        opens. Called by both admission variants under the request's
        ambient scope (``_admit``)."""
        if not obs_mod.config().enabled:
            return
        import time

        t0 = self._queued_t.pop(req.req_id, None)
        wait = (time.monotonic() - t0) if t0 is not None else 0.0
        obs_mod.emit(
            obs_mod.SpanEvent(
                name="queued",
                phase="end",
                req_id=req.req_id,
                slot=slot,
                wall_s=wait,
                trace_id=req.trace_id,
                span_id=req.span_id,
            )
        )
        obs_mod.emit(
            obs_mod.SpanEvent(
                name="prefill",
                phase="begin",
                req_id=req.req_id,
                slot=slot,
                trace_id=req.trace_id,
                span_id=req.span_id,
            )
        )

    def _advance_admission(self) -> None:
        """One STANDALONE prefill chunk of the in-flight admission —
        used when no resident row is decoding (nothing to fuse with) and
        by the legacy serialized loop. The fused path dispatches through
        ``_dispatch_fused`` instead, where the chunk rides the decode
        program and its time lands in the OVERLAPPED bucket."""
        import time

        adm = self._admission
        t0 = time.monotonic()
        chunk_len = _next_chunk_len(adm.remaining)
        adm.cache, adm.last_logits = prefill_chunk(
            self.params,
            self.cfg,
            adm.tokens[:, adm.pos : adm.pos + chunk_len],
            adm.pads,
            adm.cache,
            jnp.int32(adm.pos),
        )
        adm.pos += chunk_len
        # Block before stamping: async dispatch would otherwise push this
        # chunk's device time into the NEXT decode chunk's blocked wait,
        # billing resident rows for the newcomer's prefill. A standalone
        # chunk is a genuine stall, so this sync is sanctioned (GL-SYNC
        # allowlists this method in [tool.graftlint]).
        jax.block_until_ready(adm.last_logits)
        elapsed = time.monotonic() - t0
        self._record_prefill_time(elapsed, overlapped=False)
        adm.prefill_s += elapsed
        interleave_mod.stats.record_step(fused=False, prefill_only=True)
        prefix_mod.stats.record_prefill(chunk_len, 0)
        if obs_mod.config().enabled:
            obs_mod.retrace.observe(
                "prefill_chunk", ("prefill", chunk_len, adm.S),
                fn=prefill_chunk,
            )
            obs_mod.hot.prefill_chunk.observe(elapsed)
            obs_mod.emit(
                obs_mod.StepEvent(
                    kind="prefill",
                    n_live=int(sum(self._active_np)),
                    admission_slot=adm.slot,
                    prefill_tokens=chunk_len,
                )
            )
            obs_mod.emit(
                obs_mod.RequestEvent(
                    req_id=adm.req.req_id,
                    state="prefill",
                    slot=adm.slot,
                    tokens=chunk_len,
                )
            )
        if adm.pos >= adm.prefill_end:
            self._finish_admission()

    def _finish_admission(self) -> None:
        """Prefill done: scatter the dense cache into this sequence's
        pages (+1 shift: page 0 is trash) and activate the slot.

        ``self._admission`` stays set until the slot takes ownership of
        the sequence below: the pool scatter and first-token sampling are
        real device work that can fault, and ``_abort_admission`` needs
        the admission record to free its pages and resolve its request.
        """
        import time

        t0 = time.monotonic()
        adm = self._admission
        slot, req, seq_id, S = adm.slot, adm.req, adm.seq_id, adm.S
        cache, last_logits = adm.cache, adm.last_logits
        # graftlint: disable=GL-SYNC -- admission handoff is a sanctioned sync point: the pool scatter below needs host pads
        pads_np = np.asarray(adm.pads)
        table = np.asarray(self.allocator.table(seq_id), np.int32) + 1
        if adm.canonical:
            if adm.prefill_end > adm.S_real:
                # The final chunk's last slot is bucket garbage; re-run
                # the last REAL token (identical KV rewrite — same token,
                # position, and visible prefix) purely for its logits.
                cache, last_logits = prefill_chunk(
                    self.params,
                    self.cfg,
                    adm.tokens[:, adm.S_real - 1 : adm.S_real],
                    adm.pads,
                    cache,
                    jnp.int32(adm.S_real - 1),
                )
                if obs_mod.config().enabled:
                    # Same jitted callable as the chunked-prefill site:
                    # every dispatch must be observed or the cache-size
                    # probe misattributes this site's compiles to the
                    # other as phantom "unexpected recompiles".
                    obs_mod.retrace.observe(
                        "prefill_chunk", ("prefill", 1, adm.S),
                        fn=prefill_chunk,
                    )
            # Scatter only the delta: slots [matched, S_real). Adopted
            # prefix pages already hold [0, matched) and must never be
            # rewritten (shared, copy-on-append discipline).
            scat = np.arange(adm.matched, adm.S_real, dtype=np.int32)
        else:
            scat = np.arange(S, dtype=np.int32)
        slots = scat[None, :]
        page_ids = table[slots // self.page_size]
        offsets = slots % self.page_size
        lo, hi = int(scat[0]), int(scat[-1]) + 1
        self.pool = write_tokens(
            self.pool,
            cache["k"][..., lo:hi, :],
            cache["v"][..., lo:hi, :],
            page_ids,
            offsets,
            ks_new=cache["ks"][..., lo:hi, :] if "ks" in cache else None,
            vs_new=cache["vs"][..., lo:hi, :] if "ks" in cache else None,
        )

        self._key, sub = jax.random.split(self._key)
        first = sample_tokens(
            last_logits,
            sub,
            greedy=self.greedy,
            top_k=self.top_k,
            temperature=self._temp,
            top_p=self._top_p,
            use_top_p=self._use_top_p,
        )[0]

        row_table = np.zeros((self.max_pages_per_seq,), np.int32)
        row_table[: len(table)] = table
        self.page_table = self.page_table.at[slot].set(jnp.asarray(row_table))
        self.cur_tok = self.cur_tok.at[slot].set(first)
        # Canonical rows live at pad 0 with their true length; padded
        # rows keep the bucketed length + left pad. Per-row pad_lens and
        # cur_len let both layouts coexist in one decode batch.
        row_len = adm.S_real if adm.canonical else S
        self.cur_len = self.cur_len.at[slot].set(row_len + 1)
        self.pad_lens = self.pad_lens.at[slot].set(
            0 if adm.canonical else int(pads_np[0])
        )
        self.out_buf = self.out_buf.at[slot].set(0)
        self.out_buf = self.out_buf.at[slot, 0].set(first)
        # Admission handoff is a sanctioned sync point: ``first`` was
        # fetched above, blocking on every step in flight.
        interleave_mod.stats.record_sync()
        obs_mod.record_sync("admission_handoff")
        # graftlint: disable=GL-SYNC -- admission handoff is a sanctioned sync point: the first sampled token decides slot activation (and seeds the slot's stream delivery)
        first_np = np.asarray(first)
        first_is_eos = bool(np.isin(first_np, self._eos_np))
        self.n_emitted = self.n_emitted.at[slot].set(1)
        self.max_new = self.max_new.at[slot].set(req.max_new_tokens)
        row_active = (req.max_new_tokens > 1) and not first_is_eos
        self.active = self.active.at[slot].set(row_active)
        self._active_np[slot] = row_active
        self._slot_gen[slot] += 1  # new owner: expire in-flight flags
        if self.speculative:
            # Seed the draft source: the row's REAL (unpadded) prompt
            # ids followed by its first sampled token. ctx coordinates
            # are independent of the KV layout — padded rows draft from
            # the same clean token stream canonical rows do.
            ids_np = np.asarray(req.prompt_ids, np.int32)
            row_ctx = np.zeros((self._ctx_cap,), np.int32)
            row_ctx[: len(ids_np)] = ids_np
            self.ctx_buf = self.ctx_buf.at[slot].set(jnp.asarray(row_ctx))
            self.ctx_buf = self.ctx_buf.at[slot, len(ids_np)].set(first)
            self.ctx_len = self.ctx_len.at[slot].set(len(ids_np) + 1)
            self.prev_tok = self.prev_tok.at[slot].set(
                int(ids_np[-1]) if len(ids_np) else 0
            )
            self._cur_len_np[slot] = row_len + 1
            self._row_len_np[slot] = row_len
            self._max_new_np[slot] = req.max_new_tokens
        # Unconditional: a reused batcher whose speculation was flipped
        # OFF between drains must not stamp the previous occupant's
        # counts onto this request's SchedResult ('all zero with
        # --no-speculative' is the field contract).
        self._slot_spec[slot] = [0, 0, 0]
        if adm.canonical and self.prefix_cache is not None:
            # Cache this prompt's full blocks (the already-adopted prefix
            # re-inserts as a no-op; only new tail blocks take refs).
            n_full = adm.S_real // self.page_size
            if n_full:
                self.prefix_cache.insert(
                    list(req.prompt_ids[: n_full * self.page_size]),
                    self.allocator.table(seq_id)[:n_full],
                )
            prefix_mod.stats.record_prefill(0, adm.matched)
        # Ownership handoff: from here the slot (not the admission)
        # accounts for the sequence.
        self._admission = None
        self._slot_req[slot] = req
        self._slot_seq[slot] = seq_id
        self._slot_cached[slot] = adm.matched
        self._slot_trace[slot] = req.trace_id
        self._slot_span[slot] = req.span_id
        self._slot_decode_s[slot] = 0.0
        self._slot_consumer[slot] = req.on_tokens
        self._slot_streamed[slot] = 0
        if req.on_tokens is not None:
            stream_mod.stats.record_request()
        elapsed = time.monotonic() - t0
        # The handoff (pool scatter + first-token sample + sync) is time
        # the batch genuinely waits on: stalled, in both loop modes.
        self._record_prefill_time(elapsed, overlapped=False)
        self._slot_prefill_s[slot] = adm.prefill_s + elapsed
        if obs_mod.config().enabled:
            # TTFT as the batcher sees it: this request's own prefill
            # wall (stalled + overlapped chunks) through the handoff
            # that produced its first sampled token.
            obs_mod.hot.ttft.observe(self._slot_prefill_s[slot])
            obs_mod.hot.pool_util.set(
                round(
                    1.0
                    - self.allocator.free_pages / self.allocator.n_pages,
                    6,
                )
            )
            obs_mod.emit(
                obs_mod.RequestEvent(
                    req_id=req.req_id,
                    state="decode",
                    slot=slot,
                    tokens=1,
                    cached_tokens=adm.matched,
                )
            )
            # Trace spans: prefill closes with this request's OWN
            # prefill wall (stalled + overlapped chunks + handoff —
            # exactly SchedResult.prefill_time_s), decode opens. The
            # TTFT SLO gate sees the same wall the ttft histogram does;
            # a breach arms the once-per-request trace-scoped capture.
            obs_mod.emit(
                obs_mod.SpanEvent(
                    name="prefill",
                    phase="end",
                    req_id=req.req_id,
                    slot=slot,
                    wall_s=self._slot_prefill_s[slot],
                    trace_id=req.trace_id,
                    span_id=req.span_id,
                )
            )
            obs_mod.emit(
                obs_mod.SpanEvent(
                    name="decode",
                    phase="begin",
                    req_id=req.req_id,
                    slot=slot,
                    trace_id=req.trace_id,
                    span_id=req.span_id,
                )
            )
            obs_mod.slo_check(
                "ttft", req.span_id, self._slot_prefill_s[slot]
            )
        # First-token stream delivery: ``first`` was already fetched
        # for the EOS check above, so this rides the handoff sync. A
        # consumer that cancels on the very first token (its marker is
        # a single token, or the prompt itself decided the verdict)
        # stops the row before it ever joins a decode step.
        if req.on_tokens is not None:
            keep = self._deliver_stream(slot, 1, first_np.reshape(1))
            if not keep and row_active:
                self._cancel_slot(slot, 1, first_np.reshape(1))
                return
        if not row_active:
            self._finish_slot(slot)

    def _admit(self) -> None:
        """Fill free slots from the queue. Single-chunk (short) prompts
        admit to completion immediately so a burst of requests fills the
        batch BEFORE the next decode chunk, and so a newcomer occupies
        its slot within one scheduler iteration (slot-targeted fault
        injection and eviction surgery rely on that timing). The stall
        this costs is bounded by ONE admission chunk — the common
        cross-round case is a prefix-cache-hit delta far under it. The
        first MULTI-chunk prompt stays in flight and its remaining
        chunks ride the residents' fused steps (one chunked admission at
        a time)."""
        # Host bookkeeping only — no device sync: a slot without an
        # owner is never live (_finish_slot / fault eviction / timeout
        # all clear the trailing view before releasing the slot), so the
        # pipelined loop can admit while a step is still in flight.
        for slot in range(self.B):
            if self._admission is not None or not self.queue:
                return
            if self._slot_req[slot] is None and not self._active_np[slot]:
                # The request's ambient trace scope: cache lookups, tier
                # promotions, and retrace observations this admission
                # causes stamp with ITS trace/span (obs/trace.py).
                req0 = self.queue[0]
                try:
                    with obs_mod.trace_scope(req0.trace_id, req0.span_id):
                        started = self._start_admission(slot, req0)
                except Exception as e:
                    # Fault isolation: only this request is affected —
                    # the batch keeps decoding and admission continues
                    # with the next queued request. Faults that know
                    # their seam (injected kv_swap mid-promotion) keep
                    # it; everything else faulted reserving pages.
                    self._fault_request(
                        self.queue.pop(0),
                        e,
                        getattr(e, "seam", "kv_alloc") or "kv_alloc",
                        slot=slot,
                    )
                    continue
                if not started:
                    # Pool full right now — the request stays queued
                    # (FIFO) until residents free pages.
                    return
                self.queue.pop(0)
                try:
                    # Short prefills (≤ one ADMISSION_CHUNK of work left —
                    # possibly several sub-chunk pieces on the canonical
                    # path) admit to completion immediately.
                    with obs_mod.trace_scope(req0.trace_id, req0.span_id):
                        while (
                            self._admission is not None
                            and self._admission.slot == slot
                            and self._admission.remaining <= ADMISSION_CHUNK
                        ):
                            self._advance_admission()
                except Exception as e:
                    self._abort_admission(e)

    # -- fault containment -------------------------------------------------

    def _fault_request(
        self,
        req: SchedRequest,
        exc: BaseException,
        seam: str,
        tokens: np.ndarray | None = None,
        n: int = 0,
        cached_tokens: int = 0,
        prefill_time_s: float = 0.0,
        slot: int = -1,
        pages_freed: int = 0,
        spec_counts: tuple[int, int, int] = (0, 0, 0),
        decode_time_s: float = 0.0,
    ) -> None:
        """Resolve one faulted request: requeue once if the fault is
        transient (OOM/device-loss/preemption/timeout) and this req_id
        hasn't been retried yet — budgeted against the caller's existing
        deadline, since the requeue drains through the same run_all loop
        — else finalize with the partial tokens + fault metadata. Every
        event here stamps the INJURED request's trace/span explicitly
        (the ambient scope may belong to a co-resident admission), so
        the auto-dump's JSONL resolves the fault to its victim."""
        kind = faults.classify(exc)
        faults.record(kind, seam)
        requeued = kind.transient and req.req_id not in self._retried
        obs_mod.emit(
            obs_mod.FaultEvent(
                seam=seam,
                kind=kind.value,
                slot=slot,
                req_id=req.req_id,
                pages_freed=pages_freed,
                requeued=requeued,
                error=f"{type(exc).__name__}: {exc}",
                trace_id=req.trace_id,
                span_id=req.span_id,
            )
        )
        if requeued:
            self._retried.add(req.req_id)
            self.queue.append(req)
            if obs_mod.config().enabled:
                import time

                self._queued_t[req.req_id] = time.monotonic()
            obs_mod.emit(
                obs_mod.RequestEvent(
                    req_id=req.req_id,
                    state="queued",
                    tokens=len(req.prompt_ids),
                    trace_id=req.trace_id,
                    span_id=req.span_id,
                )
            )
            obs_mod.emit(
                obs_mod.SpanEvent(
                    name="queued",
                    phase="begin",
                    req_id=req.req_id,
                    trace_id=req.trace_id,
                    span_id=req.span_id,
                )
            )
            return
        # Final resolution: the watchdog stops tracking this request.
        self._deadline_t.pop(req.req_id, None)
        obs_mod.emit(
            obs_mod.RequestEvent(
                req_id=req.req_id,
                state="evicted",
                slot=slot,
                tokens=n,
                cached_tokens=cached_tokens,
                trace_id=req.trace_id,
                span_id=req.span_id,
            )
        )
        if obs_mod.config().enabled:
            obs_mod.hot.req_evicted.inc()
            # Close the request's trace envelope with what it actually
            # consumed — an evicted request still waterfalls.
            obs_mod.emit(
                obs_mod.SpanEvent(
                    name="request",
                    phase="end",
                    req_id=req.req_id,
                    slot=slot,
                    wall_s=prefill_time_s + decode_time_s,
                    trace_id=req.trace_id,
                    span_id=req.span_id,
                )
            )
        # The whole point of the flight recorder: when a fault evicts,
        # the last N events (reconstructing what the batcher was doing)
        # land on disk IMMEDIATELY, before any further unwind.
        obs_mod.autodump("fault")
        self.results.append(
            SchedResult(
                req_id=req.req_id,
                tokens=(
                    tokens if tokens is not None else np.zeros((0,), np.int32)
                ),
                n_generated=n,
                error=f"{type(exc).__name__}: {exc}",
                fault_kind=kind.value,
                cached_tokens=cached_tokens,
                prefill_time_s=prefill_time_s,
                spec_steps=spec_counts[0],
                spec_drafted=spec_counts[1],
                spec_accepted=spec_counts[2],
                decode_time_s=decode_time_s,
                trace_id=req.trace_id,
                span_id=req.span_id,
            )
        )

    def _abort_admission(self, exc: BaseException) -> None:
        """The in-flight admission's prefill faulted: free its pages and
        resolve its request; resident rows are untouched."""
        adm = self._admission
        self._admission = None
        if adm is None:
            # The fault landed after the slot already took ownership
            # (tail of _finish_admission): there is no admission record
            # to unwind here, so don't mask the original fault.
            raise exc
        free0 = self.allocator.free_pages
        self.allocator.free_sequence(adm.seq_id)
        if obs_mod.config().enabled:
            obs_mod.emit(
                obs_mod.SpanEvent(
                    name="prefill",
                    phase="end",
                    req_id=adm.req.req_id,
                    slot=adm.slot,
                    wall_s=adm.prefill_s,
                    trace_id=adm.req.trace_id,
                    span_id=adm.req.span_id,
                )
            )
        self._fault_request(
            adm.req,
            exc,
            "admission",
            cached_tokens=adm.matched,
            prefill_time_s=adm.prefill_s,
            slot=adm.slot,
            pages_freed=self.allocator.free_pages - free0,
        )

    def _handle_decode_fault(self, exc: BaseException) -> None:
        """A decode chunk faulted: evict ONE slot, keep the rest.

        The victim is the slot the fault names (injected faults carry
        one), else the occupied slot with the longest resident sequence
        — the best heuristic for a real OOM, since it owns the most
        pages. If the fault destroyed the donated device state (a real
        mid-execution abort invalidates the donated pool/out_buf), slot
        surgery is impossible — re-raise and let the engine degrade the
        whole group (the pre-isolation behavior).
        """
        try:
            # graftlint: disable=GL-SYNC -- fault decision point: eviction surgery needs host lengths to pick the victim
            cur_len_np = np.asarray(self.cur_len)
            # graftlint: disable=GL-SYNC -- fault decision point: probes whether the donated device state survived the fault
            np.asarray(self.out_buf[:, :1])  # probe the donated buffer
        except Exception:
            raise exc from None
        slot = getattr(exc, "slot", None)
        if (
            slot is None
            or not 0 <= slot < self.B
            or self._slot_req[slot] is None
        ):
            occupied = [
                s for s in range(self.B) if self._slot_req[s] is not None
            ]
            if not occupied:
                raise exc
            slot = max(occupied, key=lambda s: int(cur_len_np[s]))
        # graftlint: disable=GL-SYNC -- fault decision point: the victim's partial tokens must be rescued before the slot is freed
        n = int(self.n_emitted[slot])
        # graftlint: disable=GL-SYNC -- fault decision point (partial-token rescue, same sanctioned sync as the count above)
        partial = np.asarray(self.out_buf[slot, :n])
        # Faults that know their seam keep it (the watchdog's
        # deadline evictions report at seam "watchdog"; injected
        # scheduler_chunk faults already carry that name).
        seam = getattr(exc, "seam", None) or "scheduler_chunk"
        self._evict_slot(slot, exc, seam, n, partial)

    def _evict_slot(
        self,
        slot: int,
        exc: BaseException,
        seam: str,
        n: int,
        partial: np.ndarray,
    ) -> None:
        """Shared slot-eviction surgery for both fault paths
        (``_handle_decode_fault``, ``_evict_spec_row``) — callers differ
        only in victim choice and where the partial-token rescue comes
        from. Eviction only drops this slot's REFERENCES: pages shared
        with the prefix cache (or other admissions) survive untouched —
        a faulted slot can never invalidate co-residents' prefix blocks;
        for a speculating row ``free_sequence`` drops its committed
        pages AND any in-flight draft pages."""
        req = self._slot_req[slot]
        st = self._slot_spec[slot]
        # The partial transcript reaches the stream consumer BEFORE the
        # slot frees: an evicted request's caller gets every token the
        # budget bought (the watchdog's contract — partial text
        # delivered, then the timeout fault). The cancel return is
        # moot; the slot is going away regardless.
        self._deliver_stream(slot, n, partial)
        pages_freed = self._release_slot(slot)
        interleave_mod.stats.record_sync()  # fault decision point
        obs_mod.record_sync("fault")
        if obs_mod.config().enabled:
            # The victim's decode span closes with its accumulated
            # share before the request envelope does (_fault_request).
            obs_mod.emit(
                obs_mod.SpanEvent(
                    name="decode",
                    phase="end",
                    req_id=req.req_id,
                    slot=slot,
                    wall_s=self._slot_decode_s[slot],
                    trace_id=req.trace_id,
                    span_id=req.span_id,
                )
            )
        self._fault_request(
            req,
            exc,
            seam,
            tokens=partial,
            n=n,
            cached_tokens=self._slot_cached[slot],
            prefill_time_s=self._slot_prefill_s[slot],
            slot=slot,
            pages_freed=pages_freed,
            spec_counts=(st[0], st[1], st[2]),
            decode_time_s=self._slot_decode_s[slot],
        )

    def _release_slot(self, slot: int) -> int:
        """THE slot-release surgery, shared by fault eviction
        (``_evict_slot``) and cancellation (``_cancel_slot``) — one
        implementation so a new release invariant cannot be added to
        one path and forgotten on the other (the PR 6 lesson, where the
        two fault paths had already drifted apart). Drops the slot's
        sequence references (pages shared with the prefix cache or
        other admissions survive untouched; for a speculating row this
        covers committed AND in-flight draft pages), clears ownership
        and streaming state, deactivates the device row, zeroes its
        page-table row, and bumps the ownership generation so any
        in-flight flags/counts/deliveries for the old owner expire.
        Returns the pages actually freed."""
        free0 = self.allocator.free_pages
        self.allocator.free_sequence(self._slot_seq[slot])
        self._slot_req[slot] = None
        self._slot_seq[slot] = None
        self._slot_consumer[slot] = None
        self._slot_streamed[slot] = 0
        self.active = self.active.at[slot].set(False)
        self._active_np[slot] = False
        self._slot_gen[slot] += 1
        self.page_table = self.page_table.at[slot].set(0)
        return self.allocator.free_pages - free0

    # -- streaming + cancellation ------------------------------------------

    def _stream_armed(self, slots) -> bool:
        """True when any of ``slots`` has a streaming consumer — the
        gate for the extra (same-sync-point) token fetches below."""
        return any(self._slot_consumer[s] is not None for s in slots)

    def _deliver_stream(self, slot: int, n: int, tokens) -> bool:
        """Deliver this slot's tokens-so-far to its streaming consumer
        (pure host callback — no device work, no sync). Returns False
        when the consumer asked for cancellation. A consumer that
        RAISES is disabled for the rest of the request and the row
        decodes to its budget — a broken callback must not corrupt the
        batcher or take co-residents down with it."""
        cb = self._slot_consumer[slot]
        if cb is None or n <= self._slot_streamed[slot]:
            return True
        new = n - self._slot_streamed[slot]
        self._slot_streamed[slot] = n
        stream_mod.stats.record_delivery(new)
        try:
            return bool(cb(np.asarray(tokens[:n])))
        except Exception:
            self._slot_consumer[slot] = None
            return True

    def _stream_entry(
        self, emitted_np: np.ndarray, out_np: np.ndarray, live_slots
    ) -> None:
        """Stream one fetched step's tokens to every live consumer and
        cancel the rows whose consumers are done. ``live_slots`` are
        (slot, generation) pairs recorded at dispatch — the same guard
        ``_fetch_entry`` uses, so a freed-and-readmitted slot can never
        have an old step's tokens delivered to its new owner."""
        for slot, gen in live_slots:
            if (
                gen != self._slot_gen[slot]
                or self._slot_req[slot] is None
                or self._slot_consumer[slot] is None
            ):
                continue
            n = int(emitted_np[slot])
            keep = self._deliver_stream(slot, n, out_np[slot])
            if not keep and self._active_np[slot]:
                # Still decoding: stop paying for the rest of the
                # budget. (An already-finished row resolves through
                # _collect with nothing left to save.)
                self._cancel_slot(slot, n, out_np[slot, :n])

    def _cancel_slot(
        self,
        slot: int,
        n: int,
        tokens,
        reason: str = "early_converge",
    ) -> None:
        """First-class mid-decode cancellation: the consumer has read
        everything the debate will ever use, so the request stops HERE
        — a clean result carrying the partial transcript, not a fault.

        The slot frees through the same reference-drop surgery fault
        eviction uses — ``_release_slot``, the ONE shared
        implementation — (pages shared with the prefix cache survive;
        for a speculating row the per-step counts fetch already rolled
        draft pages back past the accepted prefix via
        ``PageAllocator.truncate``, so ``free_sequence`` drops exactly
        the committed coverage), and the freed capacity re-admits
        queued work at the next ``_admit``. Before the refs drop, the
        computed KV is SALVAGED: the full pages covering
        prompt + emitted tokens insert into the prefix cache, so a
        later admission sharing the prefix adopts instead of
        re-prefilling (the canonical layout makes page content
        position-pure, hence cacheable mid-request).

        In-flight steps may still write this row's KV tail: device
        programs execute in dispatch order, so those stale writes land
        BEFORE any later owner's data (the fault-eviction discipline),
        and the inserted pages end strictly below every position an
        in-flight step can touch — full pages cover at most
        prompt + n - 1 tokens (the last emitted token's KV is only
        written when it is consumed), while in-flight writes start at
        or past that boundary, i.e. in the first NON-inserted page.
        The ownership-generation bump expires any in-flight flags or
        spec counts for the slot.
        """
        req = self._slot_req[slot]
        seq = self._slot_seq[slot]
        # Budget remainder: how much reserved decode capacity the
        # cancel returned to the pool. An UPPER bound on the decode
        # actually avoided — where EOS would have landed is unknowable
        # once we stop decoding (the mock, which scripts its own reply,
        # reports the exact remainder instead; engine/streaming.py).
        saved = max(int(req.max_new_tokens) - n, 0)
        if self.prefix_cache is not None:
            covered = len(req.prompt_ids) + max(n - 1, 0)
            n_full = covered // self.page_size
            if n_full:
                ids = list(req.prompt_ids) + [
                    int(t) for t in tokens[: max(n - 1, 0)]
                ]
                self.prefix_cache.insert(
                    ids[: n_full * self.page_size],
                    self.allocator.table(seq)[:n_full],
                )
        st = self._slot_spec[slot]
        cached = self._slot_cached[slot]
        prefill_s = self._slot_prefill_s[slot]
        decode_s = self._slot_decode_s[slot]
        self._release_slot(slot)
        self._deadline_t.pop(req.req_id, None)
        stream_mod.stats.record_cancel(n, saved)
        self.results.append(
            SchedResult(
                req_id=req.req_id,
                tokens=np.asarray(tokens[:n], np.int32),
                n_generated=n,
                cancelled=True,
                tokens_saved=saved,
                cached_tokens=cached,
                prefill_time_s=prefill_s,
                spec_steps=st[0],
                spec_drafted=st[1],
                spec_accepted=st[2],
                decode_time_s=decode_s,
                trace_id=req.trace_id,
                span_id=req.span_id,
            )
        )
        if obs_mod.config().enabled:
            obs_mod.hot.cancel(reason).inc()
            obs_mod.hot.cancel_tokens_saved.observe(float(saved))
            if self.speculative and st[1]:
                obs_mod.hot.spec_acceptance.observe(st[2] / st[1])
            obs_mod.hot.pool_util.set(
                round(
                    1.0
                    - self.allocator.free_pages / self.allocator.n_pages,
                    6,
                )
            )
            obs_mod.emit(
                obs_mod.RequestEvent(
                    req_id=req.req_id,
                    state="cancelled",
                    slot=slot,
                    tokens=n,
                    cached_tokens=cached,
                    trace_id=req.trace_id,
                    span_id=req.span_id,
                )
            )
            obs_mod.emit(
                obs_mod.CancelEvent(
                    req_id=req.req_id,
                    slot=slot,
                    reason=reason,
                    tokens_emitted=n,
                    tokens_saved=saved,
                    trace_id=req.trace_id,
                    span_id=req.span_id,
                )
            )
            # Truncated span set: decode closes with the slot's
            # accumulated share, the request envelope closes with
            # phase ``cancelled`` and the service wall SO FAR — still
            # exactly prefill + decode, so tools/trace_view.py's
            # decomposition check holds for cancelled requests too.
            obs_mod.emit(
                obs_mod.SpanEvent(
                    name="decode",
                    phase="end",
                    req_id=req.req_id,
                    slot=slot,
                    wall_s=decode_s,
                    trace_id=req.trace_id,
                    span_id=req.span_id,
                )
            )
            obs_mod.emit(
                obs_mod.SpanEvent(
                    name="request",
                    phase="cancelled",
                    req_id=req.req_id,
                    slot=slot,
                    wall_s=prefill_s + decode_s,
                    trace_id=req.trace_id,
                    span_id=req.span_id,
                )
            )
            # A cancelled request still consumed service: the round SLO
            # judges the wall it actually paid, exactly as
            # ``_finish_slot`` does (and as the mock does for cancelled
            # lifecycles) — a breach that happens to end in a cancel
            # must still count and self-capture.
            obs_mod.slo_check("round", req.span_id, prefill_s + decode_s)

    # -- completion --------------------------------------------------------

    def _finish_slot(self, slot: int) -> None:
        # Slot completion is a sanctioned sync point: the token fetch
        # below blocks on the step in flight (the row itself is frozen —
        # its values read identically from any later state).
        interleave_mod.stats.record_sync()
        obs_mod.record_sync("slot_complete")
        self._active_np[slot] = False  # invariant: no owner ⇒ not live
        req = self._slot_req[slot]
        # graftlint: disable=GL-SYNC -- slot completion is a sanctioned sync point: the row is frozen, its count/tokens read identically from any later state
        n = int(self.n_emitted[slot])
        # graftlint: disable=GL-SYNC -- slot completion token fetch (same sanctioned point as the count above)
        row = np.asarray(self.out_buf[slot, :n])
        st = self._slot_spec[slot]
        # Final-tail stream delivery: an EOS/budget-terminated row hands
        # its consumer the last tokens here (a late cancel is moot —
        # the row is already done, nothing left to save).
        self._deliver_stream(slot, n, row)
        self.results.append(
            SchedResult(
                req_id=req.req_id,
                tokens=row,
                n_generated=n,
                cached_tokens=self._slot_cached[slot],
                prefill_time_s=self._slot_prefill_s[slot],
                spec_steps=st[0],
                spec_drafted=st[1],
                spec_accepted=st[2],
                decode_time_s=self._slot_decode_s[slot],
                trace_id=req.trace_id,
                span_id=req.span_id,
            )
        )
        if self.speculative and st[1] and obs_mod.config().enabled:
            # Per-request acceptance rate at completion — the obs
            # histogram the ISSUE's serving headline reads from.
            obs_mod.hot.spec_acceptance.observe(st[2] / st[1])
        # The shared release surgery (also fault eviction's and
        # cancellation's): beyond the ref drop it clears _slot_seq —
        # the hand-rolled version left it stale — and keeps every
        # release invariant in one place.
        self._release_slot(slot)
        self._deadline_t.pop(req.req_id, None)
        if obs_mod.config().enabled:
            obs_mod.hot.req_finished.inc()
            obs_mod.hot.pool_util.set(
                round(
                    1.0
                    - self.allocator.free_pages / self.allocator.n_pages,
                    6,
                )
            )
            obs_mod.emit(
                obs_mod.RequestEvent(
                    req_id=req.req_id,
                    state="finished",
                    slot=slot,
                    tokens=n,
                    cached_tokens=self._slot_cached[slot],
                    trace_id=req.trace_id,
                    span_id=req.span_id,
                )
            )
            # Close the trace spans: decode with the slot's accumulated
            # decode share, the request envelope with prefill + decode
            # (its SERVICE wall — the sum tools/trace_view.py checks
            # against the stage walls, and the value the per-request
            # round SLO gate judges).
            service_s = (
                self._slot_prefill_s[slot] + self._slot_decode_s[slot]
            )
            obs_mod.emit(
                obs_mod.SpanEvent(
                    name="decode",
                    phase="end",
                    req_id=req.req_id,
                    slot=slot,
                    wall_s=self._slot_decode_s[slot],
                    trace_id=req.trace_id,
                    span_id=req.span_id,
                )
            )
            obs_mod.emit(
                obs_mod.SpanEvent(
                    name="request",
                    phase="end",
                    req_id=req.req_id,
                    slot=slot,
                    wall_s=service_s,
                    trace_id=req.trace_id,
                    span_id=req.span_id,
                )
            )
            obs_mod.slo_check("round", req.span_id, service_s)

    def _collect(self, active_np: np.ndarray | None = None) -> None:
        """Resolve finished slots. The legacy loop passes nothing (full
        device sync); the pipelined loop passes its trailing host
        snapshot so collection never blocks on the step in flight — a
        row inactive at step N-1 is frozen (masked writes, no count
        advance), so its tokens/counters read the same from any later
        state."""
        if active_np is None:
            # graftlint: disable=GL-SYNC -- full fetch only on the legacy loop and timeout-expiry paths (the pipelined loop always passes its trailing host snapshot)
            active_np = np.asarray(self.active)
        for slot in range(self.B):
            if self._slot_req[slot] is not None and not active_np[slot]:
                self._finish_slot(slot)

    # -- main loop ---------------------------------------------------------

    def run_all(self, timeout_s: float = 0.0) -> list[SchedResult]:
        """Drain the queue: admit, step (fused prefill+decode), collect,
        repeat — pipelined two steps deep by default
        (``interleave=False`` restores the legacy serialized loop).

        ``timeout_s`` > 0 is a best-effort wall-clock budget (parity with
        generate()'s deadline, checked between chunks): on expiry, resident
        rows finish with whatever they have emitted and queued requests
        return zero tokens rather than blocking the caller.

        Fault isolation invariant: every submitted ``req_id`` gets exactly
        one ``SchedResult`` — a fault on one slot evicts that slot only
        (partial tokens + ``fault_kind`` on its result, one requeue first
        when transient) while co-resident rows keep decoding.
        """
        if self.interleave:
            self._drive_pipelined(timeout_s)
        else:
            self._drive_legacy(timeout_s)
        if self.tiers is not None:
            # Drain-end settle: flush queued disk write-through entries
            # and resolve lazy demotion payloads — every async
            # device→host copy started this drain has resolved by now,
            # so this is host work (file I/O + free fetches), never a
            # serving-path stall.
            self.tiers.settle()
        out = sorted(self.results, key=lambda r: r.req_id)
        # Drain per-run state: a batcher kept alive across rounds (the
        # prefix cache's raison d'être) must not replay old results.
        self.results = []
        self._retried.clear()
        return out

    def _has_work(self) -> bool:
        return bool(
            self.queue
            or self._admission is not None
            or any(r is not None for r in self._slot_req)
        )

    def _expire_timeout(self) -> None:
        """Deadline hit: the in-flight admission unwinds (pages freed —
        including dropping refs on any adopted cached prefix; its request
        reports with the queue), resident rows finish with whatever the
        chunk in flight emitted, and every queued request resolves with
        zero tokens instead of blocking the caller."""
        interleave_mod.stats.record_sync()  # timeout decision point
        obs_mod.record_sync("timeout")
        if self._admission is not None:
            adm = self._admission
            self._admission = None
            self.allocator.free_sequence(adm.seq_id)
            self.queue.insert(0, adm.req)  # report with the queue
        self.active = jnp.zeros_like(self.active)
        self._active_np[:] = False
        self._collect()
        for req in self.queue:
            self.results.append(
                SchedResult(
                    req_id=req.req_id,
                    tokens=np.zeros((0,), np.int32),
                    n_generated=0,
                    trace_id=req.trace_id,
                    span_id=req.span_id,
                )
            )
            obs_mod.emit(
                obs_mod.RequestEvent(
                    req_id=req.req_id,
                    state="timeout",
                    trace_id=req.trace_id,
                    span_id=req.span_id,
                )
            )
            if obs_mod.config().enabled:
                obs_mod.hot.req_timeout.inc()
                obs_mod.emit(
                    obs_mod.SpanEvent(
                        name="request",
                        phase="end",
                        req_id=req.req_id,
                        trace_id=req.trace_id,
                        span_id=req.span_id,
                    )
                )
        self.queue.clear()
        # Queue-wait bookkeeping dies with the queue: a req_id reused
        # by a later drain must not inherit this round's submit time.
        # Per-request deadlines likewise — everything just resolved.
        self._queued_t.clear()
        self._deadline_t.clear()
        # Deadline evictions are triage material exactly like faults:
        # dump what the batcher was doing when the budget ran out.
        obs_mod.autodump("timeout")

    def _watchdog_exc(self, req: SchedRequest, where: str) -> TimeoutError:
        exc = TimeoutError(
            "DEADLINE_EXCEEDED: per-request watchdog deadline "
            f"{req.deadline_s:g}s expired ({where}, req {req.req_id})"
        )
        exc.seam = "watchdog"
        # The request's total budget is spent: no batcher-level requeue
        # (it would re-expire on arrival) — the single hedged
        # re-admission with a TIGHTENED budget is the debate layer's
        # decision (run_round), where the breaker can veto it.
        self._retried.add(req.req_id)
        return exc

    def _expire_request_deadlines(self) -> None:
        """Per-request watchdog (``SchedRequest.deadline_s``): called
        once per drive-loop iteration in BOTH loops, pure host clock
        math on the fast path (one dict check when no deadline is
        armed). An over-deadline RESIDENT row evicts through the
        decode-fault surgery — ``_handle_decode_fault`` → ``_evict_slot``
        → ``_release_slot`` — whose EXISTING sanctioned fetches rescue
        the partial tokens and deliver them to the stream consumer, so
        the watchdog introduces zero new sync points and co-residents
        keep decoding. An over-deadline in-flight ADMISSION aborts
        (pages freed, request resolved at the admission seam); an
        over-deadline QUEUED request resolves with zero tokens — a
        watchdog must also cover work that never got scheduled."""
        if not self._deadline_t:
            return
        import time

        now = time.monotonic()
        for slot in range(self.B):
            req = self._slot_req[slot]
            if req is None:
                continue
            dl = self._deadline_t.get(req.req_id)
            if dl is None or now <= dl:
                continue
            exc = self._watchdog_exc(req, "mid-decode")
            exc.slot = slot
            self._handle_decode_fault(exc)
        adm = self._admission
        if adm is not None:
            dl = self._deadline_t.get(adm.req.req_id)
            if dl is not None and now > dl:
                self._abort_admission(
                    self._watchdog_exc(adm.req, "mid-prefill")
                )
        expired = [
            r
            for r in self.queue
            if self._deadline_t.get(r.req_id, now) < now
        ]
        for req in expired:
            self.queue.remove(req)
            self._deadline_t.pop(req.req_id, None)
            self._fault_request(
                req, self._watchdog_exc(req, "queued"), "watchdog"
            )

    # -- pipelined drive loop ---------------------------------------------

    def _fused_chunk_len(
        self, remaining: int, n_live: int, width: int | None = None
    ) -> int:
        """Prompt-chunk length for a fused step: largest power of two
        that fits the shared per-step token budget after the live rows'
        decode work is accounted (Sarathi-style — the newcomer's
        prefill shrinks before resident latency does). ``width`` is the
        per-row token budget of the riding step: the decode-chunk
        length normally, γ+1 verify positions under speculation."""
        w = self.chunk if width is None else width
        cap = min(ADMISSION_CHUNK, max(self.step_tokens - n_live * w, 1))
        c = ADMISSION_CHUNK
        while c > cap or c > remaining:
            c //= 2
        return max(c, 1)

    def _dispatch_fused(self, adm: _Admission, chunk_len: int) -> None:
        """Issue ONE device program advancing the admission's prompt
        chunk and all live rows' decode chunk; no host sync."""
        self._key, sub = jax.random.split(self._key)
        injector.fire("scheduler_chunk")
        (
            adm_cache,
            adm_logits,
            self.pool,
            self.cur_tok,
            self.cur_len,
            self.n_emitted,
            self.out_buf,
            self.active,
        ) = fused_prefill_decode_chunk(
            self.params,
            self.cfg,
            adm.tokens[:, adm.pos : adm.pos + chunk_len],
            adm.pads,
            adm.cache,
            jnp.int32(adm.pos),
            self.pool,
            self.page_table,
            self.cur_tok,
            self.cur_len,
            self.pad_lens,
            self.n_emitted,
            self.max_new,
            self.active,
            self.out_buf,
            self._eos,
            sub,
            self._temp,
            self._top_p,
            chunk=self.chunk,
            greedy=self.greedy,
            top_k=self.top_k,
            use_top_p=self._use_top_p,
            use_pallas=self._use_pallas,
            use_pallas_matmul=self._use_pallas_matmul,
            pallas_interpret=self._pallas_interpret,
        )
        adm.cache, adm.last_logits = adm_cache, adm_logits
        adm.pos += chunk_len
        interleave_mod.stats.record_step(fused=True)
        prefix_mod.stats.record_prefill(chunk_len, 0)
        if obs_mod.config().enabled:
            obs_mod.retrace.observe(
                "fused_prefill_decode_chunk",
                ("fused", chunk_len, adm.S, self.B, self.cap, self.chunk),
                fn=fused_prefill_decode_chunk,
            )

    def _dispatch_decode(self) -> None:
        """Issue one decode-only chunk program; no host sync."""
        self._key, sub = jax.random.split(self._key)
        injector.fire("scheduler_chunk")
        (
            self.pool,
            self.cur_tok,
            self.cur_len,
            self.n_emitted,
            self.out_buf,
            self.active,
        ) = scheduler_decode_chunk(
            self.params,
            self.cfg,
            self.pool,
            self.page_table,
            self.cur_tok,
            self.cur_len,
            self.pad_lens,
            self.n_emitted,
            self.max_new,
            self.active,
            self.out_buf,
            self._eos,
            sub,
            self._temp,
            self._top_p,
            chunk=self.chunk,
            greedy=self.greedy,
            top_k=self.top_k,
            use_top_p=self._use_top_p,
            use_pallas=self._use_pallas,
            use_pallas_matmul=self._use_pallas_matmul,
            pallas_interpret=self._pallas_interpret,
        )
        interleave_mod.stats.record_step(fused=False)
        if obs_mod.config().enabled:
            obs_mod.retrace.observe(
                "scheduler_decode_chunk",
                ("decode", self.B, self.cap, self.chunk, self.greedy),
                fn=scheduler_decode_chunk,
            )

    # -- speculative stepping ----------------------------------------------

    def _evict_spec_row(
        self, slot: int, exc: BaseException, seam: str
    ) -> None:
        """A speculative step could not secure this row's next KV slot
        (genuine pool exhaustion after prefix-cache LRU eviction) or an
        injected ``kv_alloc`` fault fired mid-decode: evict ONLY this
        row (``_evict_slot``) while co-resident rows keep decoding."""
        # Emitted count comes from the host view (trailing the counts
        # fetch) — no device sync needed for the count itself.
        n = int(self._cur_len_np[slot] - self._row_len_np[slot])
        # graftlint: disable=GL-SYNC -- fault decision point: the victim's partial tokens must be rescued before the slot is freed
        partial = np.asarray(self.out_buf[slot, :n])
        self._evict_slot(slot, exc, seam, n, partial)

    def _prepare_spec_step(self, live: list[int]) -> jnp.ndarray:
        """Size page coverage for ONE speculative step over ``live``
        rows and return the per-row draft bound (the device program's
        ``alloc_len``).

        Coverage discipline (the append/rollback contract with
        ``_apply_spec_counts``):

        - extend each row to ``cur_len + min(γ+1, budget left)`` KV
          slots — the full draft span, through the prefix cache's
          LRU-evicting extend so cache pages yield to live decode;
        - under genuine pressure fall back to ``cur_len + 1`` (the next
          mandatory single-token write), degrading the row to a plain
          step INSIDE the same compiled program (``n_allowed`` clamps
          to 0); if even that page cannot be found, evict the row with
          a classified OOM (transient → one requeue);
        - the device receives ``covered_tokens - 1`` as its draft
          bound: the −1 reserves the slot the step's LAST emitted token
          (bonus or rejection draw) will need for its own KV write next
          step, so the post-step length fix-up in
          ``_apply_spec_counts`` NEVER has to allocate — rollback is
          the only page operation after a verify, and it cannot fail.

        The device page table is re-pushed from the allocator's
        authoritative host tables every step: draft pages released by
        one row's rollback may have been re-acquired by another row
        since the last push, so tail entries can go stale across steps
        (never within one — writes/reads are bounded by ``alloc_len``).
        """
        span = self.gamma + 1
        alloc = np.zeros((self.B,), np.int64)
        for slot in list(live):
            seq = self._slot_seq[slot]
            cl = int(self._cur_len_np[slot])
            remaining = int(self._max_new_np[slot]) - (
                cl - int(self._row_len_np[slot])
            )
            length = self.allocator.length(seq)
            want = cl + min(span, max(remaining, 1))
            try:
                injector.fire("kv_alloc", slot)
                if want > length:
                    # This row's trace scope: a cache eviction / tier
                    # demotion its extend forces stamps with the
                    # request that caused the pressure.
                    with obs_mod.trace_scope(
                        self._slot_trace[slot], self._slot_span[slot]
                    ):
                        self._extend_evicting(seq, want - length)
            except OutOfPages:
                try:
                    if cl + 1 > length:
                        with obs_mod.trace_scope(
                            self._slot_trace[slot], self._slot_span[slot]
                        ):
                            self._extend_evicting(seq, cl + 1 - length)
                except OutOfPages as e:
                    self._evict_spec_row(slot, e, "kv_alloc")
                    live.remove(slot)
                    continue
            except Exception as e:
                # Injected/bug fault at the alloc seam: isolate to this
                # row, co-residents keep decoding.
                self._evict_spec_row(slot, e, "kv_alloc")
                live.remove(slot)
                continue
            alloc[slot] = self.allocator.covered_tokens(seq) - 1
        tables = np.zeros((self.B, self.max_pages_per_seq), np.int32)
        for slot in live:
            t = self.allocator.table(self._slot_seq[slot])
            tables[slot, : len(t)] = np.asarray(t, np.int32) + 1
        # Committed like every other persistent row-state creation
        # (GL-COMMIT): the re-pushed table is a program input next
        # dispatch, and an uncommitted fresh array vs the committed
        # step output is two jit signatures — the PR 6 double-compile
        # class, which this site reintroduced on the spec path.
        self.page_table = self._commit(jnp.asarray(tables))
        return jnp.asarray(alloc, jnp.int32)

    def _dispatch_spec(
        self, alloc_len: jnp.ndarray, adm: _Admission | None, chunk_len: int
    ) -> jnp.ndarray:
        """Issue ONE speculative device program — every live row's
        draft+verify step, optionally fused with the in-flight
        admission's next prompt chunk — and return the stacked per-row
        counts array (still on device; the drive loop fetches it as the
        sanctioned spec sync)."""
        self._key, sub = jax.random.split(self._key)
        injector.fire("scheduler_chunk")
        if adm is not None:
            (
                adm_cache,
                adm_logits,
                self.pool,
                self.ctx_buf,
                self.ctx_len,
                self.prev_tok,
                self.cur_tok,
                self.cur_len,
                self.n_emitted,
                self.out_buf,
                self.active,
                counts,
            ) = fused_prefill_spec_chunk(
                self.params,
                self.cfg,
                adm.tokens[:, adm.pos : adm.pos + chunk_len],
                adm.pads,
                adm.cache,
                jnp.int32(adm.pos),
                self.pool,
                self.page_table,
                self.ctx_buf,
                self.ctx_len,
                self.prev_tok,
                self.cur_tok,
                self.cur_len,
                self.pad_lens,
                self.n_emitted,
                self.max_new,
                alloc_len,
                self.active,
                self.out_buf,
                self._eos,
                sub,
                self._temp,
                self._top_p,
                gamma=self.gamma,
                greedy=self.greedy,
                top_k=self.top_k,
                use_top_p=self._use_top_p,
                use_pallas=self._use_pallas,
                use_pallas_matmul=self._use_pallas_matmul,
                pallas_interpret=self._pallas_interpret,
            )
            adm.cache, adm.last_logits = adm_cache, adm_logits
            adm.pos += chunk_len
            interleave_mod.stats.record_step(fused=True)
            prefix_mod.stats.record_prefill(chunk_len, 0)
            if obs_mod.config().enabled:
                obs_mod.retrace.observe(
                    "fused_prefill_spec_chunk",
                    (
                        "fused_spec",
                        chunk_len,
                        adm.S,
                        self.gamma,
                        self.B,
                        self.cap,
                    ),
                    fn=fused_prefill_spec_chunk,
                )
        else:
            (
                self.pool,
                self.ctx_buf,
                self.ctx_len,
                self.prev_tok,
                self.cur_tok,
                self.cur_len,
                self.n_emitted,
                self.out_buf,
                self.active,
                counts,
            ) = scheduler_spec_chunk(
                self.params,
                self.cfg,
                self.pool,
                self.page_table,
                self.ctx_buf,
                self.ctx_len,
                self.prev_tok,
                self.cur_tok,
                self.cur_len,
                self.pad_lens,
                self.n_emitted,
                self.max_new,
                alloc_len,
                self.active,
                self.out_buf,
                self._eos,
                sub,
                self._temp,
                self._top_p,
                gamma=self.gamma,
                greedy=self.greedy,
                top_k=self.top_k,
                use_top_p=self._use_top_p,
                use_pallas=self._use_pallas,
                use_pallas_matmul=self._use_pallas_matmul,
                pallas_interpret=self._pallas_interpret,
            )
            interleave_mod.stats.record_step(fused=False)
            if obs_mod.config().enabled:
                obs_mod.retrace.observe(
                    "scheduler_spec_chunk",
                    ("spec", self.gamma, self.B, self.cap, self.greedy),
                    fn=scheduler_spec_chunk,
                )
        return counts

    def _apply_spec_counts(
        self, counts_np: np.ndarray, live_slots: tuple
    ) -> None:
        """Apply one fetched spec step's per-row counts to the host
        state: advance the trailing cur_len/active views, ROLL BACK
        draft pages past each row's accepted prefix
        (``PageAllocator.truncate`` — the pages the step reserved but
        the rejection sampler didn't commit), and record telemetry.
        Rows whose ownership generation changed since dispatch are
        skipped — the multi-token analog of ``_fetch_entry``'s guard (a
        freed-and-readmitted slot must not have the old step's counts
        corrupt its new owner's bookkeeping)."""
        for slot, gen in live_slots:
            if gen != self._slot_gen[slot] or self._slot_seq[slot] is None:
                continue
            n_allowed = int(counts_np[0, slot])
            n_acc = int(counts_np[1, slot])
            n_emit = int(counts_np[2, slot])
            act = bool(counts_np[3, slot])
            new_cl = int(counts_np[4, slot])
            seq = self._slot_seq[slot]
            length = self.allocator.length(seq)
            released = 0
            if new_cl > length:
                # Fully accepted span: a pure length bump within the
                # pages already held (the draft bound's −1 reserve
                # guarantees coverage) — never allocates, cannot fail.
                self.allocator.extend(seq, new_cl - length)
            else:
                released = len(self.allocator.truncate(seq, new_cl))
            self._cur_len_np[slot] = new_cl
            st = self._slot_spec[slot]
            st[0] += 1
            st[1] += n_allowed
            st[2] += n_acc
            spec_mod.stats.record_step(n_allowed, n_acc, n_emit)
            if released:
                spec_mod.stats.record_rollback(released)
            if obs_mod.config().enabled:
                obs_mod.hot.spec_tokens_per_step.observe(float(n_emit))
                obs_mod.emit(
                    obs_mod.SpecEvent(
                        slot=slot,
                        req_id=self._slot_req[slot].req_id,
                        drafted=n_allowed,
                        accepted=n_acc,
                        emitted=n_emit,
                        rolled_back_pages=released,
                        trace_id=self._slot_trace[slot],
                        span_id=self._slot_span[slot],
                    )
                )
            self._active_np[slot] = act

    @staticmethod
    def _entry_ready(entry: tuple) -> bool:
        """True when a step's flags have already resolved on device —
        fetching them is then free (no stall). Conservative False when
        the runtime can't say."""
        try:
            return bool(entry[0].is_ready())
        except Exception:
            return False

    def _fetch_entry(self, entry: tuple) -> None:
        """Apply one completed step's flags to the trailing host view.
        Fetches only DEACTIVATE, and only rows whose slot still belongs
        to the request that was live at dispatch (generation match) — a
        slot freed and re-admitted mid-flight must not have the old
        row's completion flag truncate its new owner.

        When streaming is armed the entry additionally carries the
        step's emitted counts and an out_buf SNAPSHOT (out_buf itself
        is donated to the next dispatch; the snapshot is an independent
        device copy taken at dispatch time): their fetch rides the SAME
        resolved/depth-bound point as the flags — this is exactly how
        decoded tokens already land on host every step, so the stream
        consumer adds no new sanctioned sync."""
        active_ref, emitted_ref, out_ref, live_slots = entry
        # graftlint: disable=GL-SYNC -- pipelined fetch: called only when the entry resolved (is_ready) or at the depth bound, the double buffer's one sanctioned blocking point
        act = np.asarray(active_ref)
        for s, gen in live_slots:
            if gen == self._slot_gen[s] and not act[s]:
                self._active_np[s] = False
        if emitted_ref is None:
            return
        # graftlint: disable=GL-SYNC -- stream token fetch riding the same resolved/depth-bound entry fetch as the flags above (no new sync point; the async copy started at dispatch)
        emitted_np = np.asarray(emitted_ref)
        # graftlint: disable=GL-SYNC -- stream token fetch (the out_buf snapshot in the same entry; see above)
        out_np = np.asarray(out_ref)
        self._stream_entry(emitted_np, out_np, live_slots)

    def _drive_pipelined(self, timeout_s: float) -> None:
        """Admit → dispatch (fused when an admission and live rows
        coexist) → fetch the step before last → collect; the host's own
        work (queue admission, radix lookups, page allocation,
        collection) overlaps the step in flight. Host syncs happen only
        at admission handoff, slot completion, fault decisions, and
        timeout expiry — never as a blanket per-chunk barrier."""
        import time
        from collections import deque

        deadline = time.monotonic() + timeout_s if timeout_s > 0 else None
        inflight: deque[tuple] = deque()  # (active_ref, live_slots)
        while self._has_work():
            if deadline is not None and time.monotonic() > deadline:
                # Entries in flight resolve through the same lazy arrays
                # _collect reads; their per-step flags are moot now.
                inflight.clear()
                self._expire_timeout()
                break
            # Per-request watchdog: evict over-deadline work before
            # admitting/dispatching more (host clock math; evictions
            # ride the fault surgery's existing sanctioned fetches).
            self._expire_request_deadlines()
            self._admit()
            adm = self._admission
            live = [s for s in range(self.B) if self._active_np[s]]
            t0 = time.monotonic()
            fused_share = 0.0
            dispatched = False
            # Speculation: each iteration's "decode work" becomes one
            # γ-draft + verify program per live row, and the host MUST
            # learn each row's accepted length before it can dispatch
            # the next step (draft pages roll back, coverage re-sizes,
            # flags advance per-row) — so the spec path runs one step
            # deep with a sanctioned counts fetch per iteration instead
            # of the double buffer; the γ+1 tokens a step can emit are
            # what buy that sync back.
            spec = self.speculative
            width = (self.gamma + 1) if spec else self.chunk
            spec_counts = None
            spec_slots: tuple = ()
            # Fuse only the LEADING prefill chunks (strictly more work
            # left after this chunk): the FINAL chunk runs standalone so
            # the handoff happens before this iteration's decode chunk
            # and the newcomer joins it immediately — fusing the last
            # chunk would push the join one chunk later, fragmenting
            # decode into extra programs for every admission (measured
            # net-negative: the join lag costs more than the one
            # remaining stall saves). Corollary: a fused step never
            # finishes a prefill; every handoff happens inside
            # _advance_admission.
            chunk_len = (
                self._fused_chunk_len(adm.remaining, len(live), width)
                if adm is not None and live
                else 0
            )
            ride = (
                adm is not None
                and live
                and not adm.fuse_deferred
                and chunk_len < adm.remaining
            )
            if spec and live and (ride or adm is None):
                # Coverage sizing for the step dispatched below. The
                # standalone-admission branch prepares AFTER its
                # handoff instead (the handoff may activate a new row,
                # and preparing here too would repeat the per-row
                # extend walk and a second full page-table push).
                alloc_len = self._prepare_spec_step(live)
            if ride:
                try:
                    # Fused dispatches run under the riding admission's
                    # trace scope so its retrace/compile observations
                    # attribute to the request that shaped the program.
                    with obs_mod.trace_scope(
                        adm.req.trace_id, adm.req.span_id
                    ):
                        if spec:
                            spec_slots = tuple(
                                (s, self._slot_gen[s]) for s in live
                            )
                            spec_counts = self._dispatch_spec(
                                alloc_len, adm, chunk_len
                            )
                        else:
                            self._dispatch_fused(adm, chunk_len)
                    # Telemetry attribution for the fused program: the
                    # halves aren't separately measurable without a
                    # profiler, so split this iteration's wall clock by
                    # token share (prompt tokens vs the decode/verify
                    # half's upper bound) — deterministic given host
                    # state.
                    fused_share = chunk_len / (
                        chunk_len + len(live) * width
                    )
                    dispatched = True
                except Exception as e:
                    # A dispatch-time fault (chaos seam, trace error) is
                    # treated as decode-side surgery: the admission's
                    # state refs still point at the step before and it
                    # stays in flight; older in-flight entries stay
                    # valid (they can only deactivate). Defer the NEXT
                    # chunk to the standalone path so a fault that
                    # actually originates in the prefill half aborts the
                    # admission there instead of evicting another
                    # innocent resident every iteration.
                    adm.fuse_deferred = True
                    spec_counts = None
                    self._handle_decode_fault(e)
            else:
                if adm is not None:
                    # Final chunk, nothing live to ride, or the last
                    # fused dispatch carrying this admission faulted: a
                    # standalone (stalled) chunk, timed + recorded
                    # inside _advance_admission — which also performs
                    # the handoff when the prefill completes, so the
                    # new row is live for the decode dispatch below.
                    try:
                        with obs_mod.trace_scope(
                            adm.req.trace_id, adm.req.span_id
                        ):
                            self._advance_admission()
                        adm.fuse_deferred = False
                    except Exception as e:
                        self._abort_admission(e)
                    live = [
                        s for s in range(self.B) if self._active_np[s]
                    ]
                    if spec and live:
                        # The handoff may have activated a new row;
                        # its coverage must be sized before it joins
                        # the verify step.
                        alloc_len = self._prepare_spec_step(live)
                    # Restart the clock: the standalone chunk's seconds
                    # are already in the stalled-prefill bucket — the
                    # decode dt below must not re-count them (their sum
                    # is what the engine subtracts from total wall).
                    t0 = time.monotonic()
                if live:
                    try:
                        if spec:
                            spec_slots = tuple(
                                (s, self._slot_gen[s]) for s in live
                            )
                            spec_counts = self._dispatch_spec(
                                alloc_len, None, 0
                            )
                        else:
                            self._dispatch_decode()
                        dispatched = True
                    except Exception as e:
                        spec_counts = None
                        self._handle_decode_fault(e)
            if dispatched and spec:
                depth = 1
                step_sync = "spec_counts"
                counts_np = None
                if spec_counts is not None:
                    try:
                        # Start the copy before the blocking fetch —
                        # marginal, but free.
                        spec_counts.copy_to_host_async()
                    except Exception:
                        pass  # optional fast path only
                    try:
                        # The spec path's ONE sanctioned per-step sync:
                        # the host cannot size the next step's page
                        # coverage, roll rejected drafts back, or
                        # advance per-row flags without the accepted
                        # counts. A [5, B] int fetch — the γ+1 tokens
                        # the step can emit amortize it.
                        # graftlint: disable=GL-SYNC -- spec accept fetch: the host must know each row's accepted length to roll draft pages back and size the next step's coverage (the one sanctioned speculative sync)
                        counts_np = np.asarray(spec_counts)
                    except Exception as e:
                        # An async device fault surfaces at the fetch:
                        # same eviction surgery as dispatch-time.
                        self._handle_decode_fault(e)
                    interleave_mod.stats.record_sync()
                    obs_mod.record_sync("spec_counts")
                    if counts_np is not None:
                        self._apply_spec_counts(counts_np, spec_slots)
                        if self._stream_armed(
                            s for s, _ in spec_slots
                        ):
                            # Stream delivery at the spec path's ONE
                            # sanctioned per-step sync: the counts
                            # fetch above already blocked on this
                            # step, so the token fetch adds no new
                            # sync point (out_buf is the step's live
                            # output here — its donation happens at
                            # the NEXT dispatch). Emitted counts come
                            # from the host views _apply_spec_counts
                            # just advanced.
                            # graftlint: disable=GL-SYNC -- stream token fetch at the sanctioned spec_counts sync (the counts fetch above already blocked on this step)
                            out_np = np.asarray(self.out_buf)
                            self._stream_entry(
                                self._cur_len_np - self._row_len_np,
                                out_np,
                                spec_slots,
                            )
                dt = time.monotonic() - t0
                span = self.gamma + 1
                if fused_share > 0.0:
                    p = dt * fused_share
                    self._record_prefill_time(p, overlapped=True)
                    adm.prefill_s += p
                    self.decode_time_s += dt - p
                    spec_dt = dt - p
                else:
                    self.decode_time_s += dt
                    spec_dt = dt
                if live:
                    # Per-request decode attribution: this step's decode
                    # wall splits evenly over the rows live at dispatch
                    # (slot sums reproduce decode_time_s — the 'decode'
                    # trace span's wall).
                    dec_share = spec_dt / len(live)
                    for s in live:
                        self._slot_decode_s[s] += dec_share
                # Draft/verify wall split by position share: the bigram
                # scan costs about one forward position against the
                # span's γ+1 (SpecStats' deterministic convention).
                spec_mod.stats.record_wall(
                    spec_dt / (span + 1), spec_dt * span / (span + 1)
                )
                if obs_mod.config().enabled:
                    obs_mod.hot.step_wall.observe(dt)
                    if live:
                        # Per-row inter-token latency from the tokens
                        # the step ACTUALLY emitted (the fetched
                        # counts), not the optimistic γ+1 program
                        # width — near-zero acceptance must not report
                        # a γ+1-fold rosier latency than delivered.
                        emitted = (
                            sum(
                                int(counts_np[2, s])
                                for s, _ in spec_slots
                            )
                            if counts_np is not None
                            else 0
                        )
                        obs_mod.hot.inter_token.observe(
                            dt * len(live) / max(emitted, 1)
                        )
                    obs_mod.emit(
                        obs_mod.StepEvent(
                            kind=(
                                "fused_spec"
                                if fused_share > 0.0
                                else "spec"
                            ),
                            n_live=len(live),
                            admission_slot=(
                                adm.slot if fused_share > 0.0 else -1
                            ),
                            prefill_tokens=(
                                chunk_len if fused_share > 0.0 else 0
                            ),
                            decode_chunk=width,
                            pipeline_depth=depth,
                            sync_reason=step_sync,
                            # The riding admission's span; batch-level
                            # otherwise (trace stamps from ambient).
                            span_id=(
                                adm.req.span_id
                                if fused_share > 0.0
                                else ""
                            ),
                            trace_id=(
                                adm.req.trace_id
                                if fused_share > 0.0
                                else ""
                            ),
                        )
                    )
            elif dispatched:
                # Streaming consumers ride the double buffer: the entry
                # carries the step's emitted counts plus an out_buf
                # SNAPSHOT (jnp.copy — out_buf itself is donated to the
                # next dispatch, so a raw ref would be deleted before
                # the depth-bound fetch; the copy is a device-side op
                # that overlaps compute and only exists while a
                # consumer is attached).
                streaming = self._stream_armed(live)
                entry = (
                    self.active,
                    self.n_emitted if streaming else None,
                    jnp.copy(self.out_buf) if streaming else None,
                    tuple((s, self._slot_gen[s]) for s in live),
                )
                for ref in entry[:3]:
                    if ref is None:
                        continue
                    try:
                        # Start the device→host copy now; the fetch one
                        # iteration later should find it resolved.
                        ref.copy_to_host_async()
                    except Exception:
                        pass  # optional fast path only
                inflight.append(entry)
                depth = len(inflight)
                step_sync = ""
                try:
                    # Retire completed steps ADAPTIVELY: any entry whose
                    # flags already resolved (is_ready — free to fetch)
                    # applies now, so completions/slot-frees are seen
                    # with zero lag whenever the device keeps up (CPU:
                    # effectively every iteration). Only force a
                    # blocking fetch at the depth bound — that is the
                    # double buffer proper, and it only engages when the
                    # device is genuinely still executing step N-1.
                    while inflight and (
                        len(inflight) >= self.pipeline_depth
                        or self._entry_ready(inflight[0])
                    ):
                        if not self._entry_ready(inflight[0]):
                            # Depth bound forced a genuinely blocking
                            # fetch — the double buffer's one sanctioned
                            # blocking point, made runtime-visible.
                            obs_mod.record_sync("depth_fetch")
                            step_sync = "depth_fetch"
                        self._fetch_entry(inflight.popleft())
                except Exception as e:
                    # An async device fault surfaces at the fetch, one
                    # step late: same eviction surgery as dispatch-time.
                    inflight.clear()
                    self._handle_decode_fault(e)
                dt = time.monotonic() - t0
                if fused_share > 0.0:
                    p = dt * fused_share
                    self._record_prefill_time(p, overlapped=True)
                    adm.prefill_s += p
                    self.decode_time_s += dt - p
                    dec_dt = dt - p
                else:
                    self.decode_time_s += dt
                    dec_dt = dt
                if live:
                    # Per-request decode attribution (see the spec
                    # branch): even split over rows live at dispatch.
                    dec_share = dec_dt / len(live)
                    for s in live:
                        self._slot_decode_s[s] += dec_share
                if obs_mod.config().enabled:
                    obs_mod.hot.step_wall.observe(dt)
                    if live:
                        obs_mod.hot.inter_token.observe(dt / self.chunk)
                    obs_mod.emit(
                        obs_mod.StepEvent(
                            kind="fused" if fused_share > 0.0 else "decode",
                            n_live=len(live),
                            admission_slot=(
                                adm.slot if fused_share > 0.0 else -1
                            ),
                            prefill_tokens=(
                                chunk_len if fused_share > 0.0 else 0
                            ),
                            decode_chunk=self.chunk,
                            pipeline_depth=depth,
                            sync_reason=step_sync,
                            span_id=(
                                adm.req.span_id
                                if fused_share > 0.0
                                else ""
                            ),
                            trace_id=(
                                adm.req.trace_id
                                if fused_share > 0.0
                                else ""
                            ),
                        )
                    )
            self._collect(self._active_np)

    # -- legacy serialized loop -------------------------------------------

    def _drive_legacy(self, timeout_s: float) -> None:
        """The pre-fusion loop (escape hatch + bench baseline): one
        prompt-chunk dispatch, full host sync, one decode dispatch, full
        host sync, every iteration."""
        import time

        deadline = time.monotonic() + timeout_s if timeout_s > 0 else None
        while self._has_work():
            if deadline is not None and time.monotonic() > deadline:
                self._expire_timeout()
                break
            # Per-request watchdog (same placement as the pipelined
            # loop): this loop full-syncs every chunk anyway.
            self._expire_request_deadlines()
            self._admit()
            if self._admission is not None:
                # One prompt chunk, then fall through to a decode chunk —
                # resident rows keep emitting while the newcomer prefills.
                adm = self._admission
                try:
                    with obs_mod.trace_scope(
                        adm.req.trace_id, adm.req.span_id
                    ):
                        self._advance_admission()
                except Exception as e:
                    self._abort_admission(e)
            if bool(self.active.any()):
                t_dec = time.monotonic()
                if self.speculative:
                    # Legacy + speculation: fully serialized draft/
                    # verify steps — dispatch one γ-wide program, block
                    # on the counts, roll rejected draft pages back.
                    # Same per-row desync bookkeeping as the pipelined
                    # path, without the async fetch machinery.
                    self._active_np[:] = np.asarray(self.active)
                    live = [
                        s for s in range(self.B) if self._active_np[s]
                    ]
                    alloc_len = self._prepare_spec_step(live)
                    width = self.gamma + 1
                    if live:
                        live_slots = tuple(
                            (s, self._slot_gen[s]) for s in live
                        )
                        counts_np = None
                        try:
                            counts = self._dispatch_spec(
                                alloc_len, None, 0
                            )
                            counts_np = np.asarray(counts)
                            self._apply_spec_counts(
                                counts_np, live_slots
                            )
                            if self._stream_armed(live):
                                # Stream + cancel at the legacy spec
                                # step's full sync (this whole loop is
                                # serialized by design).
                                self._stream_entry(
                                    self._cur_len_np
                                    - self._row_len_np,
                                    np.asarray(self.out_buf),
                                    live_slots,
                                )
                        except Exception as e:
                            self._handle_decode_fault(e)
                        finally:
                            dt = time.monotonic() - t_dec
                            self.decode_time_s += dt
                            if live:
                                dec_share = dt / len(live)
                                for s in live:
                                    self._slot_decode_s[s] += dec_share
                            spec_mod.stats.record_wall(
                                dt / (width + 1),
                                dt * width / (width + 1),
                            )
                            if obs_mod.config().enabled:
                                obs_mod.record_sync("legacy_step")
                                obs_mod.hot.step_wall.observe(dt)
                                # Actual per-row emission, as in the
                                # pipelined loop — γ+1 is the program
                                # width, not the delivered tokens.
                                emitted = (
                                    sum(
                                        int(counts_np[2, s])
                                        for s, _ in live_slots
                                    )
                                    if counts_np is not None
                                    else 0
                                )
                                obs_mod.hot.inter_token.observe(
                                    dt * len(live) / max(emitted, 1)
                                )
                                obs_mod.emit(
                                    obs_mod.StepEvent(
                                        kind="spec",
                                        n_live=len(live),
                                        decode_chunk=width,
                                        sync_reason="legacy_step",
                                    )
                                )
                else:
                    live = [
                        s
                        for s in range(self.B)
                        if self._slot_req[s] is not None
                    ]
                    try:
                        self._dispatch_decode()
                        jax.block_until_ready(self.active)
                    except Exception as e:
                        self._handle_decode_fault(e)
                    finally:
                        dt = time.monotonic() - t_dec
                        self.decode_time_s += dt
                        if live:
                            dec_share = dt / len(live)
                            for s in live:
                                self._slot_decode_s[s] += dec_share
                        if obs_mod.config().enabled:
                            obs_mod.record_sync("legacy_step")
                            obs_mod.hot.step_wall.observe(dt)
                            obs_mod.hot.inter_token.observe(dt / self.chunk)
                            obs_mod.emit(
                                obs_mod.StepEvent(
                                    kind="decode",
                                    n_live=int(sum(self._active_np)),
                                    decode_chunk=self.chunk,
                                    sync_reason="legacy_step",
                                )
                            )
                    if self._stream_armed(live):
                        # Stream + cancel at the legacy step's full
                        # sync (this loop blocks every chunk anyway).
                        self._active_np[:] = np.asarray(self.active)
                        self._stream_entry(
                            np.asarray(self.n_emitted),
                            np.asarray(self.out_buf),
                            tuple((s, self._slot_gen[s]) for s in live),
                        )
            self._collect()
        self._active_np[:] = np.asarray(self.active)
