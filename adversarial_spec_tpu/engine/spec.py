"""Speculative-decoding config and telemetry (process-wide, host side).

Prompt-lookup speculation exists in two places: the dense ``generate()``
path (engine/speculative.py, the original implementation) and per-slot
in the paged ContinuousBatcher (engine/scheduler.py — draft from the
row's own context, ONE multi-position verification forward over the
paged pool, rejection-sampled accept). This module is the one
switchboard both consult, following the established
``resilience.faults`` / ``prefix_cache`` / ``interleave`` pattern:

- **config**: ``enabled`` (CLI ``--speculative/--no-speculative``, env
  ``ADVSPEC_SPECULATIVE``, default on) and ``gamma`` — the draft length
  per speculative step (CLI ``--gamma``, env ``ADVSPEC_GAMMA``, default
  8). γ is validated AT THE KNOB: γ < 1 raises here, with the same
  actionable message the old import-time check in speculative.py gave,
  instead of failing deep inside a traced accept loop. Unlike the old
  import-time constant, ``configure(gamma=...)`` retunes a live process
  (tests, the tpu_ladder γ sweep) without a reimport.
- **stats**: per-round speculation counters both real engines and the
  mock's deterministic CPU accounting record into. ``reset`` zeroes in
  place so engines holding a reference keep counting into the same
  object. ``snapshot()`` is the CLI's ``perf.spec`` payload.

Deliberately imports no jax: the mock engine uses it on CPU. The
config/stats mechanics live in ``engine/procconfig.py`` (shared with
``interleave``, ``prefix_cache``, ``kvtier``); γ's fail-at-the-knob
validation stays here, passed in as the coercer.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

from adversarial_spec_tpu.engine import procconfig

DEFAULT_GAMMA = 8


def _validate_gamma(gamma: int) -> int:
    if gamma < 1:
        # Fail at the knob, not deep inside a traced accept loop (γ=0
        # would index draft[:, -1] and run 1-wide verifies that are pure
        # overhead). The env read fires at import, so the remedy is to
        # fix the env var, not a kwarg.
        raise ValueError(
            f"ADVSPEC_GAMMA must be >= 1, got {gamma}; unset ADVSPEC_GAMMA "
            "(and pass speculative=False if the goal was disabling "
            "speculation)"
        )
    return gamma


def env_enabled() -> bool:
    """The process default for the master switch (``ADVSPEC_SPECULATIVE``)."""
    return os.environ.get("ADVSPEC_SPECULATIVE", "1") != "0"


def env_gamma() -> int:
    """The process default draft length (``ADVSPEC_GAMMA``), validated."""
    return _validate_gamma(
        int(os.environ.get("ADVSPEC_GAMMA", str(DEFAULT_GAMMA)))
    )


@dataclass
class SpecConfig:
    """Process-wide knobs, set once per CLI round (or by tests)."""

    enabled: bool = True
    gamma: int = DEFAULT_GAMMA


@dataclass
class SpecStats(procconfig.StatsBase):
    """Process-wide speculation counters, aggregated across every
    batcher drain (and the mock engine's deterministic accounting).

    ``drafted_tokens`` counts draft positions that could actually have
    committed (per-row ``n_allowed`` — the budget/page-clamped draft
    width), so ``accepted / drafted`` is a true acceptance rate, not
    diluted by positions that were never eligible. ``emitted_tokens``
    additionally counts each step's bonus/rejection token.

    The draft/verify wall split is attributed by position share of the
    fused draft+verify program (the draft's bigram scan costs about one
    forward position against the span's γ+1): measuring the halves
    separately would need a profiler — the same deterministic-share
    convention the fused prefill+decode step uses.
    """

    # PER-ROW verify steps: +1 per LIVE row per dispatched program (B
    # co-resident rows ⇒ +B per program), so emitted/spec_steps is a
    # true per-row tokens-per-step. Program dispatch counts live in the
    # retrace watch / StepEvents, not here.
    spec_steps: int = 0
    drafted_tokens: int = 0  # eligible draft positions verified
    accepted_tokens: int = 0  # draft positions accepted
    emitted_tokens: int = 0  # tokens emitted by spec steps (incl. bonus)
    rolled_back_pages: int = 0  # draft pages released by rollback
    draft_time_s: float = 0.0
    verify_time_s: float = 0.0

    def record_step(self, drafted: int, accepted: int, emitted: int) -> None:
        self.spec_steps += 1
        self.drafted_tokens += drafted
        self.accepted_tokens += accepted
        self.emitted_tokens += emitted

    def record_wall(self, draft_s: float, verify_s: float) -> None:
        self.draft_time_s += draft_s
        self.verify_time_s += verify_s

    def record_rollback(self, pages: int) -> None:
        self.rolled_back_pages += pages

    def snapshot(self) -> dict:
        out = self.as_dict()
        out["acceptance_rate"] = (
            round(self.accepted_tokens / self.drafted_tokens, 4)
            if self.drafted_tokens
            else 0.0
        )
        out["tokens_per_step"] = (
            round(self.emitted_tokens / self.spec_steps, 4)
            if self.spec_steps
            else 0.0
        )
        return out


_state = procconfig.ProcState(
    SpecConfig(enabled=env_enabled(), gamma=env_gamma()),
    SpecStats(),
    coerce={"gamma": lambda g: _validate_gamma(int(g))},
)
_config = _state.config
stats = _state.stats


def config() -> SpecConfig:
    return _state.config


def configure(
    enabled: bool | None = None, gamma: int | None = None
) -> SpecConfig:
    return _state.configure(enabled=enabled, gamma=gamma)


def reset_stats() -> None:
    _state.reset_stats()


def snapshot() -> dict:
    """Stats + config, the ``perf.spec`` payload."""
    return _state.snapshot()
