"""Prompt-lookup speculative decoding — batched, any sampling mode.

The debate workload's dominant output is a ``[SPEC]...[/SPEC]`` revision —
a near-copy of the input document with edits. That makes *prompt-lookup*
drafting (LLMA / prompt-lookup decoding: match the last n-gram of the
generated text against the context and draft the tokens that followed it
there) exceptionally effective: long runs of the revision are verbatim
context spans, so most drafts verify and the model emits several tokens
per forward pass instead of one. No draft model, no extra weights — the
draft source is the prompt *plus the text generated so far* (revision
notes repeat across rounds, so generated text matters).

One step, per batch row: draft γ tokens from the most recent n-gram match;
run ONE verification forward over [cur, d_0..d_{γ-1}] (γ+1 positions, the
same KV-cached forward prefill chunks use, with per-row cache slots since
rows desynchronize); accept drafts by REJECTION SAMPLING against the true
sampling distribution (engine/sampling.py:filtered_logits):

    draft token d_i is a delta distribution, so accept with probability
    p_i(d_i) (u < p catches both: greedy p is one-hot → exact argmax
    match); on the first rejection sample from the residual p with d_i
    zeroed and renormalized — the marginal at every position is exactly p,
    so speculation is *distribution-preserving* at any temperature and
    bit-identical to plain decode when greedy.

Cache discipline: the verification forward writes γ+1 KV slots per row at
that row's own offset; rejected drafts leave stale KV above slot
cache_index+n_acc, but the row's next write region starts exactly there
(new cache_index = old + n_emit) and layer writes land before attention,
so stale slots are never read.

Because rows accept different draft counts, they desynchronize — after any
speculative phase the tail must finish on ``rowwise_decode_steps`` (per-row
cache slots), not the shared-slot loop in engine/generate.py.

Scope: dense KV cache, on any non-sp mesh — single device; dp-only
meshes via the ``*_dp`` shard_mapped wrappers below (rows shard over
dp, each device runs its own accept loop — per-row desync never
crosses devices); tp and mixed dp×tp meshes via one GSPMD-partitioned
accept loop (``mesh=`` on the entry points: heads shard over tp inside
the verification forward, the compiler inserts the collectives).
Multi-host dp meshes work too: generate()'s surrounding control flow
only fetches replicated scalars. sp decode meshes are the one
exclusion (ring-resharded caches; plain chunked decode serves them).
On TPU the verification forward runs the MULTI-QUERY fused kernel
(ops/pallas_decode.py:decode_attention_mq — the whole γ+1 span in one
pass over the KV cache) and the tail loop the single-query kernel, so
speculation no longer costs the fused-attention path (round-1's
shortcut). int8 KV composes: the MQ kernel reads int8 tiles and
dequantizes in-kernel.

EOS contract (mirror of generate._sample_step — change BOTH together):
the EOS token itself is kept in the output; slots after it emit 0.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from adversarial_spec_tpu.engine import spec as spec_config
from adversarial_spec_tpu.engine.sampling import (
    filtered_logits,
    sample_tokens,
)
from adversarial_spec_tpu.models.config import ModelConfig
from adversarial_spec_tpu.models.transformer import Cache, Params, forward

# Draft length per speculative step. Larger γ emits more tokens per
# verification forward when drafts match (revision-heavy [SPEC] output)
# but wastes a γ+1-wide forward when they miss; 8 is the prior, the
# ladder's gamma sweep (tpu_ladder.py) measures the crossover on chip.
# The knob LIVES in engine/spec.py now (``ADVSPEC_GAMMA`` / ``--gamma``,
# reconfigurable per round without a reimport); this module-level value
# is the import-time snapshot kept for callers that treat γ as a
# constant — importing it validates the env var exactly as before
# (spec.env_gamma fails fast on γ < 1).
GAMMA = spec_config.config().gamma


def _rowwise_slice(buf: jnp.ndarray, starts: jnp.ndarray, size: int):
    """[B, N] gathered at per-row starts → [B, size]."""
    return jax.vmap(
        lambda row, s: jax.lax.dynamic_slice(row, (s,), (size,))
    )(buf, starts)


def _rowwise_write(buf: jnp.ndarray, vals: jnp.ndarray, starts: jnp.ndarray):
    """Write [B, size] into [B, N] at per-row starts."""
    return jax.vmap(
        lambda row, v, s: jax.lax.dynamic_update_slice(row, v, (s,))
    )(buf, vals, starts)


def accept_spans(
    probs: jnp.ndarray,  # [B, γ+1, V] filtered target distribution
    draft: jnp.ndarray,  # [B, γ]
    n_allowed: jnp.ndarray,  # [B] draft positions eligible to commit
    u_key: jax.Array,
    res_key: jax.Array,
    *,
    greedy: bool,
):
    """THE accept math — rejection-sample a per-row accept length against
    the true sampling distribution, shared verbatim by the dense path
    (``speculative_decode_steps``) and the paged ContinuousBatcher's
    verify step (engine/scheduler.py), so greedy output stays
    byte-identical to plain decode on both.

    ``n_allowed`` caps how many draft positions may commit this step
    (the dense path passes a constant γ; the batcher clamps per row by
    output budget and allocated pages). Positions at or past the cap are
    FORCED rejections — crucially, a forced stop draws the bonus token
    from the FULL distribution at that position, not the residual:
    zeroing a draft token the coin never rejected would bias the
    marginal (and break greedy parity whenever the draft equals the
    argmax). Returns ``(n_acc [B], bonus [B])``.
    """
    B, gamma = draft.shape
    rows = jnp.arange(B)
    p_draft = jnp.take_along_axis(
        probs[:, :-1], draft[..., None], axis=-1
    )[..., 0]  # [B, γ] target prob of each draft token
    u = jax.random.uniform(u_key, (B, gamma))
    pos = jnp.arange(gamma)[None, :]
    # greedy: p ∈ {0,1} ⇒ exact argmax match
    accept = (u < p_draft) & (pos < n_allowed[:, None])
    n_acc = jnp.sum(
        jnp.cumprod(accept.astype(jnp.int32), axis=1), axis=1
    )  # [B]

    # --- The bonus token: residual draw at a NATURAL rejection point,
    # a fresh full-distribution draw when the allowed span ran out. ---
    at = probs[rows, n_acc]  # [B, V] distribution at emit position
    rejected = n_acc < n_allowed
    rej_draft = draft[rows, jnp.minimum(n_acc, gamma - 1)]
    # Residual: zero the rejected draft token, renormalize. Marginal
    # over (accept, residual) is exactly `at` — see module docstring.
    res = at.at[rows, rej_draft].set(
        jnp.where(rejected, 0.0, at[rows, rej_draft])
    )
    res = res / jnp.maximum(res.sum(-1, keepdims=True), 1e-30)
    bonus = jax.random.categorical(
        res_key, jnp.log(jnp.maximum(res, 1e-30)), axis=-1
    ).astype(jnp.int32)
    if greedy:
        # Bit-identical contract: no RNG in the greedy path. The
        # residual of a one-hot is one-hot ⇒ argmax, computed directly.
        bonus = jnp.argmax(res, axis=-1).astype(jnp.int32)
    return n_acc, bonus


def _draft(context, prev, cur, limits, gamma):
    """Most recent [prev, cur] bigram match in each row's context.

    context: [B, N] prompt ++ generated-so-far (zeros beyond ``limits``);
    limits: [B] one past the last real context token. Returns draft
    [B, gamma] — the tokens that followed the match (zeros when none;
    drafts never affect correctness, only acceptance rate).
    """
    B, N = context.shape
    pos = jnp.arange(N - 1)[None, :]
    match = (
        (context[:, :-1] == prev[:, None])
        & (context[:, 1:] == cur[:, None])
        # The bigram AND at least one drafted token must be real context.
        & (pos + 2 < limits[:, None])
    )
    best = jnp.max(jnp.where(match, pos, -1), axis=1)  # [B]
    has_match = best >= 0
    d_start = jnp.clip(best + 2, 0, N - gamma)
    draft = _rowwise_slice(context, d_start, gamma)
    return jnp.where(has_match[:, None], draft, jnp.zeros_like(draft))


@partial(
    jax.jit,
    static_argnames=(
        "cfg",
        "prompt_len",
        "iters",
        "gamma",
        "greedy",
        "top_k",
        "use_top_p",
        "use_pallas",
        "pallas_interpret",
        "mesh",
    ),
    donate_argnames=("cache", "out_buf"),
)
def speculative_decode_steps(
    params: Params,
    cfg: ModelConfig,
    cache: Cache,
    prompt_tokens: jnp.ndarray,  # [B, S] left-padded prompts (draft source)
    prev_tokens: jnp.ndarray,  # [B] token before cur (n-gram context)
    cur_tokens: jnp.ndarray,  # [B] last emitted token per row
    pad_lens: jnp.ndarray,  # [B]
    finished: jnp.ndarray,  # [B] bool
    out_buf: jnp.ndarray,  # [B, max_new]
    steps: jnp.ndarray,  # [B] per-row decode step (out_buf position)
    stop_at: jnp.ndarray,  # scalar: decode no further than this step
    eos_ids: jnp.ndarray,  # [E]
    key: jax.Array,
    temperature: jnp.ndarray,
    top_p: jnp.ndarray,
    *,
    prompt_len: int,
    iters: int,
    gamma: int = GAMMA,
    greedy: bool = False,
    top_k: int = 0,
    use_top_p: bool = True,
    use_pallas: bool = False,
    pallas_interpret: bool = False,
    mesh=None,
):
    """Up to ``iters`` speculative rounds over whichever rows still fit a
    full γ+1 span.

    ``mesh`` (tp path): a single-host mesh whose tensor-parallel degree
    shards the layer matmuls via GSPMD — this whole function runs as ONE
    partitioned program (devices stay in lockstep, which tp requires
    anyway; collectives come from the compiler, not manual psums). The
    verify forward's attention takes the jnp path (the MQ kernel is
    single-device; GSPMD shards its heads axis), and the dp-only case
    uses the ``*_dp`` shard_map wrappers below instead (independent
    per-device accept loops beat a lockstep global loop when devices
    don't have to communicate).

    Returns (cache, prev, cur, finished, out_buf, steps, n_iters,
    n_emitted_total, n_row_iters) — the caller finishes budget-capped
    rows with ``rowwise_decode_steps`` and can use n_emitted_total /
    n_row_iters (exact per-active-row emit rate: n_row_iters counts
    active rows summed over iterations) to turn speculation OFF when
    drafts aren't matching (each rejected round costs a γ+1-wide forward
    to emit one token).
    """
    B, S = prompt_tokens.shape
    T = cache["k"].shape[3]  # [L, B, Hkv, T, D]
    max_new = out_buf.shape[1]
    kv_base = jnp.arange(T)[None, :] >= pad_lens[:, None]
    span = gamma + 1
    rows = jnp.arange(B)
    bound = jnp.minimum(stop_at, max_new)

    def active_rows(steps, finished):
        return ~finished & (steps + span <= bound)

    def cond(state):
        it, steps, finished = state[0], state[1], state[6]
        return (it < iters) & active_rows(steps, finished).any()

    def body(state):
        (
            it,
            steps,
            prev,
            cur,
            cache,
            out_buf,
            finished,
            key,
            n_emit_tot,
            n_row_iters,
        ) = state
        active = active_rows(steps, finished)

        # --- Draft from prompt ++ generated text (most recent match). ---
        context = jnp.concatenate([prompt_tokens, out_buf], axis=1)
        draft = _draft(context, prev, cur, prompt_len + steps, gamma)

        # --- Verify: one forward over [cur, draft] at per-row slots. ---
        toks = jnp.concatenate([cur[:, None], draft], axis=1)  # [B, γ+1]
        cache_index = prompt_len + steps - 1  # [B]
        positions = (
            cache_index[:, None]
            + jnp.arange(span, dtype=jnp.int32)[None, :]
            - pad_lens[:, None]
        )
        logits, cache = forward(
            params,
            cfg,
            toks,
            positions,
            cache,
            cache_index,
            kv_base,
            use_pallas_decode=use_pallas,
            pallas_interpret=pallas_interpret,
            mesh=mesh,
        )
        # The true per-position sampling distribution (one-hot if greedy).
        filt = filtered_logits(
            logits,
            greedy=greedy,
            top_k=top_k,
            temperature=temperature,
            top_p=top_p,
            use_top_p=use_top_p,
        )  # [B, γ+1, V]
        probs = jax.nn.softmax(filt, axis=-1)

        # --- Rejection-sample the accept length per row (accept_spans —
        # the same shared math the batcher's verify step runs; a full-γ
        # n_allowed makes the cap term an identity here). ---
        key, u_key, res_key = jax.random.split(key, 3)
        n_acc, bonus = accept_spans(
            probs,
            draft,
            jnp.full((B,), gamma, jnp.int32),
            u_key,
            res_key,
            greedy=greedy,
        )

        emitted = jnp.concatenate(
            [draft, jnp.zeros((B, 1), draft.dtype)], axis=1
        )
        emitted = emitted.at[rows, n_acc].set(bonus)

        # --- EOS + per-row emit counts (EOS kept, zeros after). ---
        is_eos = (emitted[..., None] == eos_ids[None, None, :]).any(-1)
        j = jnp.arange(span)[None, :]
        eos_hits = is_eos & (j <= n_acc[:, None])
        any_eos = eos_hits.any(axis=1)
        first_eos = jnp.argmax(eos_hits, axis=1)
        n_emit = jnp.where(any_eos, first_eos + 1, n_acc + 1)
        n_emit = jnp.where(active, n_emit, 0)
        emitted = jnp.where(j < n_emit[:, None], emitted, 0)

        # Inactive rows write their existing slots back (no-op write —
        # a clamped zero-write could smash a budget-capped row's tail).
        w_start = jnp.minimum(steps, max_new - span)
        current = _rowwise_slice(out_buf, w_start, span)
        out_buf = _rowwise_write(
            out_buf,
            jnp.where(active[:, None], emitted, current),
            w_start,
        )

        finished = finished | (any_eos & active)
        new_cur = jnp.where(
            active, emitted[rows, jnp.maximum(n_emit - 1, 0)], cur
        )
        new_prev = jnp.where(
            active,
            jnp.where(n_emit >= 2, emitted[rows, n_emit - 2], cur),
            prev,
        )
        return (
            it + 1,
            steps + n_emit,
            new_prev,
            new_cur,
            cache,
            out_buf,
            finished,
            key,
            n_emit_tot + n_emit.sum(),
            n_row_iters + active.sum(),
        )

    state = (
        jnp.int32(0),
        steps,
        prev_tokens,
        cur_tokens,
        cache,
        out_buf,
        finished,
        key,
        jnp.int32(0),
        jnp.int32(0),
    )
    (
        it,
        steps,
        prev,
        cur,
        cache,
        out_buf,
        finished,
        key,
        n_emit_tot,
        n_row_iters,
    ) = jax.lax.while_loop(cond, body, state)
    return (
        cache,
        prev,
        cur,
        finished,
        out_buf,
        steps,
        it,
        n_emit_tot,
        n_row_iters,
    )


@partial(
    jax.jit,
    static_argnames=(
        "cfg",
        "prompt_len",
        "chunk",
        "greedy",
        "top_k",
        "use_top_p",
        "use_pallas",
        "pallas_interpret",
        "mesh",
    ),
    donate_argnames=("cache", "out_buf"),
)
def rowwise_decode_steps(
    params: Params,
    cfg: ModelConfig,
    cache: Cache,
    cur_tokens: jnp.ndarray,  # [B]
    pad_lens: jnp.ndarray,  # [B]
    finished: jnp.ndarray,  # [B] bool
    out_buf: jnp.ndarray,  # [B, max_new]
    steps: jnp.ndarray,  # [B] per-row decode step
    stop_at: jnp.ndarray,  # scalar
    eos_ids: jnp.ndarray,
    key: jax.Array,
    temperature: jnp.ndarray,
    top_p: jnp.ndarray,
    *,
    prompt_len: int,
    chunk: int,
    greedy: bool,
    top_k: int,
    use_top_p: bool = True,
    use_pallas: bool = False,
    pallas_interpret: bool = False,
    mesh=None,
):
    """Plain single-token decode with PER-ROW cache slots.

    The tail loop after any speculative phase: rows desynchronize there
    (different accepted draft counts), so the shared-slot
    ``decode_chunk_steps`` can no longer drive them. Same sampling and
    EOS semantics as generate._sample_step. ``mesh``: tp via GSPMD, same
    contract as speculative_decode_steps (the S=1 forward routes the
    fused kernel through its shard_map wrapper on such meshes).
    """
    B = cur_tokens.shape[0]
    T = cache["k"].shape[3]  # [L, B, Hkv, T, D]
    max_new = out_buf.shape[1]
    kv_base = jnp.arange(T)[None, :] >= pad_lens[:, None]
    rows = jnp.arange(B)
    bound = jnp.minimum(stop_at, max_new)

    def active_rows(steps, finished):
        return ~finished & (steps < bound)

    def cond(state):
        it, steps, finished = state[0], state[1], state[4]
        return (it < chunk) & active_rows(steps, finished).any()

    def body(state):
        it, steps, cur, cache, finished, out_buf, key = state
        active = active_rows(steps, finished)
        cache_index = prompt_len + steps - 1  # [B]
        positions = (cache_index - pad_lens)[:, None]
        logits, cache = forward(
            params,
            cfg,
            cur[:, None],
            positions,
            cache,
            cache_index,
            kv_base,
            use_pallas_decode=use_pallas,
            pallas_interpret=pallas_interpret,
            mesh=mesh,
        )
        key, sub = jax.random.split(key)
        nxt = sample_tokens(
            logits[:, 0],
            sub,
            greedy=greedy,
            top_k=top_k,
            temperature=temperature,
            top_p=top_p,
            use_top_p=use_top_p,
        )
        is_eos = (nxt[:, None] == eos_ids[None, :]).any(axis=-1)
        nxt = jnp.where(finished, 0, nxt)
        idx = jnp.minimum(steps, max_new - 1)
        vals = jnp.where(active, nxt, out_buf[rows, idx])
        out_buf = out_buf.at[rows, idx].set(vals)
        finished = finished | (is_eos & active)
        steps = steps + active.astype(jnp.int32)
        cur = jnp.where(active, nxt, cur)
        return it + 1, steps, cur, cache, finished, out_buf, key

    state = (jnp.int32(0), steps, cur_tokens, cache, finished, out_buf, key)
    it, steps, cur, cache, finished, out_buf, key = jax.lax.while_loop(
        cond, body, state
    )
    return cache, cur, finished, out_buf, steps


def speculative_decode_steps_dp(
    mesh,
    params,
    cfg,
    cache,
    prompt_tokens,
    prev_tokens,
    cur_tokens,
    pad_lens,
    finished,
    out_buf,
    steps,
    stop_at,
    eos_ids,
    key,
    temperature,
    top_p,
    **static_kw,
):
    """``speculative_decode_steps`` with rows sharded over a dp-only mesh.

    dp-only (tp = sp = 1): inside shard_map the layer matmuls see full
    weights (replicated), so no manual tp collectives are needed. The
    engine gates on ``mesh.size == mesh.shape[DP]``.
    """
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from adversarial_spec_tpu.parallel.mesh import DP

    row_arrays = (
        prompt_tokens,
        prev_tokens,
        cur_tokens,
        pad_lens,
        finished,
        out_buf,
        steps,
    )
    rowspec = tuple(P(DP, *([None] * (a.ndim - 1))) for a in row_arrays)
    cache_spec = jax.tree.map(
        lambda x: P(None, DP, *([None] * (x.ndim - 2))), cache
    )
    param_spec = jax.tree.map(lambda _: P(), params)

    def local(params_l, cache_l, prompt_l, prev_l, cur_l, pads_l, fin_l,
              out_l, steps_l, stop_at_l, eos_l, key_l, temp_l, tp_l):
        key_l = jax.random.fold_in(key_l, jax.lax.axis_index(DP))
        (
            cache_o, prev_o, cur_o, fin_o, out_o, steps_o,
            it, n_emit, n_row_iters,
        ) = speculative_decode_steps(
            params_l, cfg, cache_l, prompt_l, prev_l, cur_l, pads_l,
            fin_l, out_l, steps_l, stop_at_l, eos_l, key_l, temp_l, tp_l,
            **static_kw,
        )
        return (
            cache_o, prev_o, cur_o, fin_o, out_o, steps_o,
            jax.lax.pmax(it, DP),
            jax.lax.psum(n_emit, DP),
            jax.lax.psum(n_row_iters, DP),
        )

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(param_spec, cache_spec, *rowspec,
                  P(), P(), P(), P(), P()),
        out_specs=(cache_spec, rowspec[1], rowspec[2], rowspec[4],
                   rowspec[5], rowspec[6], P(), P(), P()),
        check_rep=False,
    )(params, cache, *row_arrays, stop_at, eos_ids, key, temperature,
      top_p)


def rowwise_decode_steps_dp(
    mesh,
    params,
    cfg,
    cache,
    cur_tokens,
    pad_lens,
    finished,
    out_buf,
    steps,
    stop_at,
    eos_ids,
    key,
    temperature,
    top_p,
    **static_kw,
):
    """``rowwise_decode_steps`` with rows sharded over a dp-only mesh."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from adversarial_spec_tpu.parallel.mesh import DP

    row_arrays = (cur_tokens, pad_lens, finished, out_buf, steps)
    rowspec = tuple(P(DP, *([None] * (a.ndim - 1))) for a in row_arrays)
    cache_spec = jax.tree.map(
        lambda x: P(None, DP, *([None] * (x.ndim - 2))), cache
    )
    param_spec = jax.tree.map(lambda _: P(), params)

    def local(params_l, cache_l, cur_l, pads_l, fin_l, out_l, steps_l,
              stop_at_l, eos_l, key_l, temp_l, tp_l):
        key_l = jax.random.fold_in(key_l, jax.lax.axis_index(DP))
        return rowwise_decode_steps(
            params_l, cfg, cache_l, cur_l, pads_l, fin_l, out_l, steps_l,
            stop_at_l, eos_l, key_l, temp_l, tp_l, **static_kw,
        )

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(param_spec, cache_spec, *rowspec,
                  P(), P(), P(), P(), P()),
        out_specs=(cache_spec, rowspec[0], rowspec[2], rowspec[3],
                   rowspec[4]),
        check_rep=False,
    )(params, cache, *row_arrays, stop_at, eos_ids, key, temperature,
      top_p)
