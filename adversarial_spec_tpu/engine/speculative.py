"""Prompt-lookup speculative decoding (greedy, single-row).

The debate workload's dominant output is a ``[SPEC]...[/SPEC]`` revision —
a near-copy of the input document with edits. That makes *prompt-lookup*
drafting (LLMA / prompt-lookup decoding: match the last n-gram of the
generated text against the prompt and draft the tokens that followed it
there) exceptionally effective: long runs of the revision are verbatim
prompt spans, so most drafts verify and the model emits several tokens per
forward pass instead of one. No draft model, no extra weights — the draft
source is the prompt itself.

One step: draft γ tokens from the best (most recent) n-gram match; run ONE
verification forward over [cur, d_0..d_{γ-1}] (γ+1 positions, the same
KV-cached forward prefill chunks use); accept the longest prefix of drafts
that equals the greedy argmax chain; emit the accepted tokens plus the
model's own next token (always ≥1 token of progress, bit-identical to
plain greedy decode by construction).

Cache discipline: the verification forward writes γ+1 KV slots; rejected
drafts leave stale KV above slot cache_index+n_acc, but the next step's
write region starts exactly there (new cache_index = old + n_emit) and
layer writes land before attention, so stale slots are never read.

Scope (v1): greedy sampling, one row (B=1 — BASELINE config 2's
single-opponent critique), dense KV cache, jnp attention (generate()
forces the tail decode off the Pallas kernel so one attention
implementation governs the whole call — near-tie argmaxes must not
diverge between verify and tail). Exact-output parity with plain greedy
decode on the same attention path is the correctness contract (tested).

EOS contract (mirror of generate._sample_step — change BOTH together):
the EOS token itself is kept in the output; slots after it emit 0.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from adversarial_spec_tpu.models.config import ModelConfig
from adversarial_spec_tpu.models.transformer import Cache, Params, forward

GAMMA = 8  # draft length per step


@partial(
    jax.jit,
    static_argnames=("cfg", "prompt_len", "chunk", "gamma"),
    donate_argnames=("cache", "out_buf"),
)
def speculative_decode_steps(
    params: Params,
    cfg: ModelConfig,
    cache: Cache,
    prompt_tokens: jnp.ndarray,  # [1, S] the left-padded prompt (draft source)
    prev_token: jnp.ndarray,  # [] token before cur (n-gram context)
    cur_token: jnp.ndarray,  # [] last emitted token
    pad_lens: jnp.ndarray,  # [1]
    finished: jnp.ndarray,  # [1] bool
    out_buf: jnp.ndarray,  # [1, max_new]
    start_step: jnp.ndarray,  # scalar
    stop_at: jnp.ndarray,  # scalar
    eos_ids: jnp.ndarray,  # [E]
    *,
    prompt_len: int,
    chunk: int,
    gamma: int = GAMMA,
):
    """Run speculative greedy steps while ≥ γ+1 output slots remain.

    Returns (cache, prev, cur, finished, out_buf, step, n_iters) — the
    caller finishes any tail with the plain single-token loop, and can use
    step-progress / n_iters (mean tokens emitted per verification forward)
    to turn speculation OFF when drafts aren't matching (each rejected
    round costs a γ+1-wide forward to emit one token).
    """
    S = prompt_tokens.shape[1]
    T = cache["k"].shape[2]
    max_new = out_buf.shape[1]
    pt = prompt_tokens[0]
    kv_base = jnp.arange(T)[None, :] >= pad_lens[:, None]
    draft_span = gamma + 1

    def cond(state):
        step, finished = state[0], state[5]
        # The full span must fit the output budget; the chunk bound only
        # paces how much work one host call performs.
        fits = step + draft_span <= jnp.minimum(stop_at, max_new)
        return fits & (step < start_step + chunk) & ~finished.all()

    def body(state):
        step, prev, cur, cache, out_buf, finished, n_iters = state

        # --- Draft: most recent prompt position following [prev, cur]. ---
        match = (pt[:-1] == prev) & (pt[1:] == cur)  # [S-1]
        pos = jnp.arange(S - 1)
        best = jnp.max(jnp.where(match, pos, -1))
        has_match = best >= 0
        d_start = jnp.clip(best + 2, 0, S - gamma)
        draft = jax.lax.dynamic_slice(pt, (d_start,), (gamma,))
        draft = jnp.where(has_match, draft, jnp.zeros_like(draft))

        # --- Verify: one forward over [cur, draft]. ---
        toks = jnp.concatenate([cur[None], draft])[None]  # [1, γ+1]
        cache_index = prompt_len + step - 1
        positions = (
            cache_index
            + jnp.arange(draft_span, dtype=jnp.int32)[None, :]
            - pad_lens[:, None]
        )
        logits, cache = forward(
            params, cfg, toks, positions, cache, cache_index, kv_base
        )
        greedy_chain = jnp.argmax(logits[0], axis=-1).astype(jnp.int32)

        # --- Accept the longest verified prefix, emit + bonus token. ---
        matches = draft == greedy_chain[:-1]  # [γ]
        n_acc = jnp.sum(jnp.cumprod(matches.astype(jnp.int32)))
        emitted = jnp.concatenate([draft, jnp.zeros((1,), draft.dtype)])
        emitted = emitted.at[n_acc].set(greedy_chain[n_acc])

        is_eos = (emitted[:, None] == eos_ids[None, :]).any(axis=-1)
        j = jnp.arange(draft_span)
        eos_hits = is_eos & (j <= n_acc)
        any_eos = eos_hits.any()
        first_eos = jnp.argmax(eos_hits)
        n_emit = jnp.where(any_eos, first_eos + 1, n_acc + 1)
        emitted = jnp.where(j < n_emit, emitted, 0)

        out_buf = jax.lax.dynamic_update_slice(
            out_buf, emitted[None], (0, step)
        )
        finished = finished | any_eos
        new_cur = emitted[n_emit - 1]
        new_prev = jnp.where(n_emit >= 2, emitted[n_emit - 2], cur)
        return (
            step + n_emit,
            new_prev,
            new_cur,
            cache,
            out_buf,
            finished,
            n_iters + 1,
        )

    state = (
        start_step,
        prev_token,
        cur_token,
        cache,
        out_buf,
        finished,
        jnp.int32(0),
    )
    step, prev, cur, cache, out_buf, finished, n_iters = jax.lax.while_loop(
        cond, body, state
    )
    return cache, prev, cur, finished, out_buf, step, n_iters
