"""Streaming-token config and telemetry (process-wide, host side).

The engine seam streams per-request tokens to a HOST-SIDE consumer
(engine/types.py ``StreamConsumer``): the ContinuousBatcher delivers
each request's tokens-so-far at the drive loop's existing fetch points
(the pipelined loop's async entry fetch, the speculative path's
per-step counts sync, admission handoff, slot completion — no new
sanctioned sync points), and a consumer returning ``False`` cancels
the request mid-decode: its spans close with a ``cancelled`` phase,
the computed KV's full pages are salvaged into the prefix cache, its
pages and slot free through the same reference-drop surgery fault
eviction uses, and the freed capacity re-admits queued work
immediately (docs/streaming.md).

The debate layer's early-convergence consumer (debate/core.py) is the
first user: an opponent's critique is only needed until ``[AGREE]``
(or a section marker — parsing.EARLY_CANCEL_MARKERS) appears, so
everything decoded past the marker is waste the matched-ceiling debate
study (PAPERS.md) says buys nothing — round COUNT, not round length,
drives quality. This module is the switchboard both engines (batcher
and the mock's deterministic CPU accounting) consult and record into,
following the ``interleave`` / ``spec`` / ``prefix_cache`` pattern:

- **config**: ``enabled`` (CLI ``--stream/--no-stream``, env
  ``ADVSPEC_STREAM``, default on) gates token delivery;
  ``early_cancel`` (CLI ``--early-cancel/--no-early-cancel``, env
  ``ADVSPEC_EARLY_CANCEL``, default on) additionally arms the debate
  layer's marker-driven cancellation. Stream off = the blocking path,
  byte-identical end to end; stream on = transcripts byte-identical
  UP TO each cancellation point (greedy decode is deterministic and
  cancellation only truncates).
- **stats**: per-round streaming counters; ``snapshot()`` is the CLI's
  ``perf.stream`` payload. ``saved_fraction`` is the headline the
  cancel bench pins: tokens the round did NOT decode over the tokens
  it would have decoded without cancellation.

Deliberately imports no jax: the mock engine uses it on CPU. The
config/stats mechanics live in ``engine/procconfig.py``.
"""

from __future__ import annotations

import inspect
import os
from dataclasses import dataclass

from adversarial_spec_tpu.engine import procconfig


def env_enabled() -> bool:
    """The process default for the master switch (``ADVSPEC_STREAM``)."""
    return os.environ.get("ADVSPEC_STREAM", "1") != "0"


def env_early_cancel() -> bool:
    """The process default for marker-driven cancellation
    (``ADVSPEC_EARLY_CANCEL``)."""
    return os.environ.get("ADVSPEC_EARLY_CANCEL", "1") != "0"


@dataclass
class StreamConfig:
    """Process-wide knobs, set once per CLI round (or by tests)."""

    enabled: bool = True
    early_cancel: bool = True


@dataclass
class StreamStats(procconfig.StatsBase):
    """Process-wide streaming counters, aggregated across every batcher
    drain (and the mock engine's deterministic accounting).

    ``streamed_tokens`` counts tokens DELIVERED through consumers (a
    cancelled request contributes only its emitted prefix), so
    ``tokens_saved / (streamed_tokens + tokens_saved)`` — the snapshot's
    ``saved_fraction`` — is the fraction of the round's streamed decode
    the cancellations avoided paying for.

    ``tokens_saved`` semantics per engine: the REAL batcher records the
    budget remainder (``max_new_tokens − emitted``) — the reserved
    decode capacity the cancel returned to the pool, an UPPER bound on
    the decode actually avoided, since where EOS would have landed is
    unknowable once decoding stops. The MOCK engine scripts its own
    reply, so it records the exact remainder of the reply the consumer
    never read; its ``saved_fraction`` (the cancel bench's headline) is
    therefore exact, not an upper bound.
    """

    requests_streamed: int = 0
    deliveries: int = 0  # consumer callbacks that carried new tokens
    streamed_tokens: int = 0  # tokens delivered through consumers
    cancels: int = 0
    cancelled_emitted_tokens: int = 0  # tokens emitted before each cancel
    tokens_saved: int = 0  # budget tokens never decoded thanks to cancel

    def record_request(self) -> None:
        self.requests_streamed += 1

    def record_delivery(self, n_tokens: int) -> None:
        self.deliveries += 1
        self.streamed_tokens += n_tokens

    def record_cancel(self, emitted: int, saved: int) -> None:
        self.cancels += 1
        self.cancelled_emitted_tokens += emitted
        self.tokens_saved += saved

    def snapshot(self) -> dict:
        out = self.as_dict()
        denom = self.streamed_tokens + self.tokens_saved
        out["saved_fraction"] = (
            round(self.tokens_saved / denom, 4) if denom else 0.0
        )
        return out


_state = procconfig.ProcState(
    StreamConfig(enabled=env_enabled(), early_cancel=env_early_cancel()),
    StreamStats(),
)
_config = _state.config
stats = _state.stats


def config() -> StreamConfig:
    return _state.config


def configure(
    enabled: bool | None = None, early_cancel: bool | None = None
) -> StreamConfig:
    return _state.configure(enabled=enabled, early_cancel=early_cancel)


def reset_stats() -> None:
    _state.reset_stats()


def snapshot() -> dict:
    """Stats + config, the ``perf.stream`` payload."""
    return _state.snapshot()


def armed() -> bool:
    """True when the debate layer should build early-cancel consumers:
    streaming AND marker cancellation both enabled."""
    return _state.config.enabled and _state.config.early_cancel


def consumer_supported(engine) -> bool:
    """True when the engine's ``chat`` accepts the streaming
    ``consumer`` kwarg (the Engine protocol's streaming extension).
    Inspected rather than assumed so test fakes and out-of-tree engines
    with the original 2-argument signature keep working unmodified —
    they simply serve the blocking path."""
    try:
        return "consumer" in inspect.signature(engine.chat).parameters
    except (TypeError, ValueError):
        return False
