"""Tokenization for the tpu:// engine.

Two implementations behind one duck-typed interface (``encode``, ``decode``,
``bos_id``, ``eos_ids``, ``pad_id``, ``vocab_size``):

- ``HFTokenizer`` wraps a ``tokenizer.json`` via the ``tokenizers`` library
  (ships with transformers) for real checkpoints.
- ``ByteTokenizer`` is a 3-special + 256-byte vocabulary used by synthetic
  ``random-*`` models, so the full engine path (chat templating → encode →
  decode loop → detokenize) runs with zero downloads in an air-gapped
  environment.

Chat templating is deliberately minimal and family-agnostic: a plain-text
system/user/assistant scaffold. Instruction-tuned checkpoints get their
family template via ``CHAT_TEMPLATES`` keyed on the registry family.
"""

from __future__ import annotations

from pathlib import Path

PAD_ID = 0
BOS_ID = 1
EOS_ID = 2
_BYTE_OFFSET = 3


class ByteTokenizer:
    """UTF-8 bytes → ids [3, 259); specials 0/1/2 = pad/bos/eos."""

    vocab_size = 259
    bos_id = BOS_ID
    pad_id = PAD_ID

    @property
    def eos_ids(self) -> list[int]:
        return [EOS_ID]

    def encode(self, text: str, add_bos: bool = True) -> list[int]:
        ids = [b + _BYTE_OFFSET for b in text.encode("utf-8")]
        return [BOS_ID] + ids if add_bos else ids

    def decode(self, ids) -> str:
        # Ids past the byte range can appear when a model's vocab is padded
        # wider than 259 (synthetic checkpoints) — skip them.
        data = bytes(
            int(i) - _BYTE_OFFSET
            for i in ids
            if _BYTE_OFFSET <= int(i) < _BYTE_OFFSET + 256
        )
        return data.decode("utf-8", errors="replace")


class HFTokenizer:
    """Wraps a HuggingFace ``tokenizer.json`` (tokenizers library)."""

    def __init__(self, path: str):
        from tokenizers import Tokenizer  # deferred heavy import

        p = Path(path)
        if p.is_dir():
            p = p / "tokenizer.json"
        self._tok = Tokenizer.from_file(str(p))
        self.vocab_size = self._tok.get_vocab_size()
        # Note: Qwen-2 has no BOS at all — <|im_start|> is a chat-turn
        # delimiter already present in the template, not a BOS candidate.
        self.bos_id = self._special_id(["<|begin_of_text|>", "<s>", "<bos>"])
        self.pad_id = 0
        # Collect EVERY terminator present: instruct models end turns with
        # chat-turn markers (<|eot_id|>, <end_of_turn>, <|im_end|>) rather
        # than the document EOS, and decode must stop on any of them.
        vocab = self._tok.get_vocab()
        self.eos_ids = [
            vocab[c]
            for c in (
                "<|end_of_text|>",
                "</s>",
                "<eos>",
                "<|im_end|>",
                "<|eot_id|>",
                "<end_of_turn>",
            )
            if c in vocab
        ]

    def _special_id(self, candidates: list[str]) -> int | None:
        vocab = self._tok.get_vocab()
        for c in candidates:
            if c in vocab:
                return vocab[c]
        return None

    def encode(self, text: str, add_bos: bool = True) -> list[int]:
        ids = self._tok.encode(text, add_special_tokens=False).ids
        if add_bos and self.bos_id is not None:
            return [self.bos_id] + ids
        return ids

    def decode(self, ids) -> str:
        return self._tok.decode([int(i) for i in ids], skip_special_tokens=True)


GENERIC_CHAT_TEMPLATE = (
    "### System\n{system}\n\n### User\n{user}\n\n### Assistant\n"
)

CHAT_TEMPLATES: dict[str, str] = {
    "llama": (
        "<|start_header_id|>system<|end_header_id|>\n\n{system}<|eot_id|>"
        "<|start_header_id|>user<|end_header_id|>\n\n{user}<|eot_id|>"
        "<|start_header_id|>assistant<|end_header_id|>\n\n"
    ),
    "mistral": "[INST] {system}\n\n{user} [/INST]",
    "gemma2": (
        "<start_of_turn>user\n{system}\n\n{user}<end_of_turn>\n"
        "<start_of_turn>model\n"
    ),
    "qwen2": (
        "<|im_start|>system\n{system}<|im_end|>\n"
        "<|im_start|>user\n{user}<|im_end|>\n"
        "<|im_start|>assistant\n"
    ),
}


def apply_chat_template(
    family: str, system: str, user: str, instruct: bool
) -> str:
    """Render one (system, user) turn to the family's prompt format."""
    template = CHAT_TEMPLATES.get(family) if instruct else None
    if template is None:
        template = GENERIC_CHAT_TEMPLATE
    return template.format(system=system or "", user=user)


def load_tokenizer(tokenizer_path: str):
    """Tokenizer factory: path → HFTokenizer, empty → ByteTokenizer."""
    if tokenizer_path:
        return HFTokenizer(tokenizer_path)
    return ByteTokenizer()
