"""The ``tpu://`` engine: local JAX inference over the device mesh.

The reference's L1 transport (litellm HTTP to remote APIs,
scripts/models.py:607-678) becomes: registry alias → checkpoint
materialized as a sharded param pytree on a {dp,tp,sp} mesh → batched
prefill + chunked decode (engine/generate.py). The thread-per-opponent
fan-out (models.py:699) becomes rows of one batch: every request for the
same model in a ``chat`` call decodes as one XLA program.

Heterogeneous opponent pools (SURVEY §7 hard part (b)): requests are
grouped by model alias; groups run sequentially with an LRU of loaded
models (weight swap). Same-model opponents — the common debate setup —
always batch.

Failure semantics (parity with reference retry/degrade policy,
models.py:46-47, 538-555): per-group exceptions are captured into
``Completion.error``; OOM/transient device errors are marked transient so
the debate core's backoff retries them; a failed group never kills the
round.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

import jax
import jax.numpy as jnp

from adversarial_spec_tpu.debate.usage import Usage
from adversarial_spec_tpu.engine import registry as registry_mod
from adversarial_spec_tpu.engine.generate import generate
from adversarial_spec_tpu.engine.loader import materialize_params
from adversarial_spec_tpu.engine.registry import ModelSpec
from adversarial_spec_tpu.engine.tokenizer import (
    apply_chat_template,
    load_tokenizer,
)
from adversarial_spec_tpu.engine.types import ChatRequest, Completion, SamplingParams
from adversarial_spec_tpu.models.config import ModelConfig
from adversarial_spec_tpu.parallel.mesh import (
    make_mesh,
    maybe_initialize_distributed,
)
from adversarial_spec_tpu.parallel.sharding import make_device_put

# Loaded models kept resident before weight-swap eviction (LRU).
MAX_RESIDENT_MODELS = 2

_DTYPES = {
    "bfloat16": jnp.bfloat16,
    "float32": jnp.float32,
    "float16": jnp.float16,
}

_TRANSIENT_MARKERS = (
    "RESOURCE_EXHAUSTED",
    "OUT_OF_RANGE",
    "UNAVAILABLE",
    "DEADLINE_EXCEEDED",
)


def _trim_prompt(ids: list[int], limit: int) -> list[int]:
    """Trim to ``limit`` tokens keeping the first token (BOS/template
    head) and the most recent tail — one definition for every serving
    path."""
    if limit > 0 and len(ids) > limit:
        return ids[:1] + ids[len(ids) - (limit - 1) :]
    return ids


@dataclass
class LoadedModel:
    spec: ModelSpec
    cfg: ModelConfig
    params: dict
    tokenizer: object
    mesh: object
    last_used: float = 0.0


class TpuEngine:
    """Serves every ``tpu://`` alias; caches loaded models (weight swap)."""

    def __init__(self) -> None:
        self._models: dict[str, LoadedModel] = {}

    def validate(self, model: str) -> str | None:
        return registry_mod.validate_tpu_model(model)

    # -- model residency ---------------------------------------------------

    def _load(self, alias: str) -> LoadedModel:
        if alias in self._models:
            lm = self._models[alias]
            lm.last_used = time.monotonic()
            return lm
        spec = registry_mod.resolve_model_spec(f"tpu://{alias}")
        dtype = _DTYPES.get(spec.dtype, jnp.bfloat16)
        # Make room BEFORE materializing: otherwise N+1 full param sets
        # coexist in HBM during the swap.
        self._evict_to(MAX_RESIDENT_MODELS - 1)
        maybe_initialize_distributed()
        mesh = make_mesh(spec.mesh)
        params, cfg = self._materialize(spec, dtype, mesh)
        tokenizer = load_tokenizer(spec.tokenizer)
        lm = LoadedModel(
            spec=spec,
            cfg=cfg,
            params=params,
            tokenizer=tokenizer,
            mesh=mesh,
            last_used=time.monotonic(),
        )
        self._models[alias] = lm
        return lm

    def _materialize(self, spec: ModelSpec, dtype, mesh):
        """Params via the fastest available source: native Orbax cache
        (converted once, restored straight into target shardings) →
        HF safetensors conversion (then cached) → synthetic init."""
        from adversarial_spec_tpu.engine import checkpoint as ckpt_mod
        from adversarial_spec_tpu.models.config import get_config
        from adversarial_spec_tpu.models.transformer import init_params
        from adversarial_spec_tpu.ops.quant import quantize_params
        from adversarial_spec_tpu.parallel.sharding import param_shardings

        import shutil
        import sys

        quantize = spec.quant == "int8"
        cfg = get_config(spec.family, spec.size, max_seq_len=spec.max_seq_len)
        cache_path = None
        if spec.checkpoint != "random":
            cache_path = ckpt_mod.cache_dir_for(
                spec.checkpoint,
                spec.family,
                spec.size,
                spec.dtype,
                spec.quant,
                tied_embeddings=cfg.tied_embeddings,
            )
        if cache_path is not None and ckpt_mod.has_native(cache_path):
            # Cache is an optimization in BOTH directions: a corrupt or
            # layout-incompatible cache falls back to HF conversion
            # instead of permanently breaking the model.
            try:
                # The restore template must match the layout the cache was
                # SAVED with: same transposed-head flag reading as
                # load_hf_checkpoint and the cache fingerprint (a toggled
                # env selects a different cache dir rather than failing
                # restore against this template).
                t_head = ckpt_mod.transposed_head_flag()

                def build():
                    p = init_params(
                        jax.random.key(0), cfg, dtype,
                        transposed_head=t_head,
                    )
                    return quantize_params(p) if quantize else p

                shapes = jax.eval_shape(build)
                shardings = param_shardings(mesh, shapes)
                abstract = jax.tree.map(
                    lambda s, sh: jax.ShapeDtypeStruct(
                        s.shape, s.dtype, sharding=sh
                    ),
                    shapes,
                    shardings,
                )
                return ckpt_mod.load_native(cache_path, abstract), cfg
            except Exception as e:
                print(
                    f"warning: native checkpoint cache unreadable "
                    f"({e}); reconverting from HF",
                    file=sys.stderr,
                )
                shutil.rmtree(cache_path, ignore_errors=True)

        params, cfg = materialize_params(
            spec.checkpoint,
            spec.family,
            spec.size,
            dtype=dtype,
            max_seq_len=spec.max_seq_len,
            device_put=make_device_put(mesh, dtype),
        )
        if quantize:
            params = quantize_params(params)
        if cache_path is not None:
            try:  # write side is best-effort too
                ckpt_mod.save_native(params, cache_path)
            except Exception as e:
                print(
                    f"warning: native checkpoint cache write failed: {e}",
                    file=sys.stderr,
                )
        return params, cfg

    def _evict_to(self, keep: int) -> None:
        while len(self._models) > keep:
            oldest = min(self._models, key=lambda a: self._models[a].last_used)
            del self._models[oldest]

    # -- serving -----------------------------------------------------------

    def chat(
        self, requests: list[ChatRequest], params: SamplingParams
    ) -> list[Completion]:
        # Group by alias: same-model opponents batch into one decode.
        groups: dict[str, list[int]] = {}
        for i, req in enumerate(requests):
            alias = registry_mod.parse_tpu_model_id(req.model)
            groups.setdefault(alias, []).append(i)

        out: list[Completion | None] = [None] * len(requests)
        for alias, indices in groups.items():
            batch = [requests[i] for i in indices]
            try:
                completions = self._chat_one_model(alias, batch, params)
            except Exception as e:  # degrade, never raise (parity: ref)
                msg = f"{type(e).__name__}: {e}"
                transient = any(m in msg for m in _TRANSIENT_MARKERS)
                completions = [
                    Completion(error=msg, transient=transient)
                    for _ in batch
                ]
            for i, comp in zip(indices, completions):
                out[i] = comp
        return [c for c in out if c is not None]

    def _chat_one_model(
        self, alias: str, batch: list[ChatRequest], params: SamplingParams
    ) -> list[Completion]:
        lm = self._load(alias)
        tok = lm.tokenizer
        instruct = lm.spec.checkpoint != "random"

        prompts = []
        for req in batch:
            text = apply_chat_template(
                lm.spec.family, req.system, req.user, instruct
            )
            ids = tok.encode(text)
            # Reserve room for generation within the model's context.
            prompts.append(
                _trim_prompt(ids, lm.cfg.max_seq_len - params.max_new_tokens)
            )

        # Paged single-device specs serve through the continuous batcher:
        # opponents occupy decode slots, early-EOS rows free their pages
        # mid-round, and queued requests (opponent pools larger than the
        # slot count) admit into freed slots without waiting for the whole
        # batch — the multi-session serving path NOTES.md round 2 left
        # unwired. Sharded meshes keep the round-synchronous generate()
        # (its paged path shards the pool over dp), as do budgets so large
        # that no bucketed prompt passes the batcher's context check (the
        # dense path has no such check and still serves them).
        from adversarial_spec_tpu.engine.generate import MIN_BUCKET

        fits_batcher = (
            lm.cfg.max_seq_len - params.max_new_tokens >= MIN_BUCKET
        )
        if lm.spec.kv == "paged" and lm.mesh.size == 1 and fits_batcher:
            return self._chat_continuous(lm, prompts, params)

        t0 = time.monotonic()
        with lm.mesh:
            result = generate(
                lm.params,
                lm.cfg,
                prompts,
                max_new_tokens=params.max_new_tokens,
                eos_ids=list(tok.eos_ids),
                pad_id=tok.pad_id,
                greedy=params.greedy,
                temperature=params.temperature,
                top_k=params.top_k,
                top_p=params.top_p,
                seed=params.seed,
                timeout_s=params.timeout_s,
                mesh=lm.mesh,
                paged=lm.spec.kv == "paged",
                kv_dtype=lm.spec.kv_dtype,
            )
        total_time = time.monotonic() - t0

        # Per-row attribution: decode time proportional to each row's
        # actual decoded tokens (an early-EOS row consumed fewer decode
        # steps than a full-budget row); the prefill/overhead remainder
        # splits evenly (prefill is genuinely shared batch work). Row
        # sums reproduce the call totals exactly.
        tok_total = float(result.n_generated.sum())
        prefill_share = (total_time - result.decode_time_s) / len(batch)
        completions = []
        for row, req in enumerate(batch):
            n = int(result.n_generated[row])
            frac = (n / tok_total) if tok_total > 0 else 1.0 / len(batch)
            decode_share = result.decode_time_s * frac
            text = tok.decode(result.tokens[row, :n])
            completions.append(
                Completion(
                    text=text,
                    usage=Usage(
                        input_tokens=len(prompts[row]),
                        output_tokens=n,
                        device_time_s=prefill_share + decode_share,
                        decode_tokens=n,
                        decode_time_s=decode_share,
                    ),
                )
            )
        return completions

    def _chat_continuous(
        self, lm: LoadedModel, prompts: list[list[int]], params: SamplingParams
    ) -> list[Completion]:
        """Serve one model's requests through the ContinuousBatcher.

        Pool capacity is bucketed to a power of two so repeat rounds of
        similar size reuse the compiled chunk program (pool shape is a
        jit constant).
        """
        from adversarial_spec_tpu.engine.generate import bucket_length
        from adversarial_spec_tpu.engine.scheduler import (
            ContinuousBatcher,
            SchedRequest,
        )

        import os

        tok = lm.tokenizer
        # The batcher checks bucket_length(prompt) + budget against the
        # model context; the engine-level trim above only bounded the RAW
        # length, so a near-limit prompt would round up past the context
        # and error the whole group. Re-trim against the bucketed length.
        max_prompt = lm.cfg.max_seq_len - params.max_new_tokens
        while max_prompt > 1 and bucket_length(max_prompt) > max_prompt:
            nxt = bucket_length(max_prompt) // 2
            if nxt >= max_prompt:  # at the minimum bucket already
                break
            max_prompt = nxt
        prompts = [_trim_prompt(p, max_prompt) for p in prompts]
        # Pool capacity covers CONCURRENT residency (the max_batch largest
        # requests), not the whole queue — finished rows free their pages
        # and queued requests admit into them; sizing by the queue total
        # would make pool HBM scale with round size, which is exactly what
        # paging exists to avoid.
        n_slots = min(len(prompts), 8)
        per_req = sorted(
            (bucket_length(len(p)) + params.max_new_tokens for p in prompts),
            reverse=True,
        )
        need = sum(per_req[:n_slots])
        capacity = 2048
        while capacity < need:
            capacity *= 2

        t0 = time.monotonic()
        with lm.mesh:
            batcher = ContinuousBatcher(
                lm.params,
                lm.cfg,
                max_batch=n_slots,
                capacity_tokens=capacity,
                max_new_cap=params.max_new_tokens,
                eos_ids=list(tok.eos_ids),
                greedy=params.greedy,
                temperature=params.temperature,
                top_k=params.top_k,
                top_p=params.top_p,
                # seed=None means fresh entropy (as generate() does) —
                # pinning 0 would make every unseeded round sample
                # identically.
                seed=(
                    params.seed
                    if params.seed is not None
                    else int.from_bytes(os.urandom(4), "little")
                ),
                # Same KV precision on both serving paths: the
                # round-synchronous fallback passes spec.kv_dtype to
                # generate(); the batcher must honor it too (int8
                # pages + scale pages).
                kv_dtype=lm.spec.kv_dtype,
            )
            for i, ids in enumerate(prompts):
                batcher.submit(
                    SchedRequest(
                        req_id=i,
                        prompt_ids=ids,
                        max_new_tokens=params.max_new_tokens,
                    )
                )
            results = batcher.run_all(timeout_s=params.timeout_s)
        total_time = time.monotonic() - t0

        # Same attribution scheme as the dense path: decode time splits
        # by decoded tokens, the prefill/overhead remainder evenly.
        tok_total = float(sum(r.n_generated for r in results)) or 1.0
        overhead = total_time - batcher.decode_time_s
        completions = []
        for r in results:  # sorted by req_id == prompt order
            frac = r.n_generated / tok_total
            decode_share = batcher.decode_time_s * frac
            completions.append(
                Completion(
                    text=tok.decode(r.tokens[: r.n_generated]),
                    usage=Usage(
                        input_tokens=len(prompts[r.req_id]),
                        output_tokens=r.n_generated,
                        device_time_s=overhead / len(results) + decode_share,
                        decode_tokens=r.n_generated,
                        decode_time_s=decode_share,
                    ),
                )
            )
        return completions
