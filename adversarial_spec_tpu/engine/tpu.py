"""The ``tpu://`` engine: local JAX inference over the device mesh.

The reference's L1 transport (litellm HTTP to remote APIs,
scripts/models.py:607-678) becomes: registry alias → checkpoint
materialized as a sharded param pytree on a {dp,tp,sp} mesh → batched
prefill + chunked decode (engine/generate.py). The thread-per-opponent
fan-out (models.py:699) becomes rows of one batch: every request for the
same model in a ``chat`` call decodes as one XLA program.

Heterogeneous opponent pools (SURVEY §7 hard part (b)): requests are
grouped by model alias; groups run sequentially with an LRU of loaded
models (weight swap). Same-model opponents — the common debate setup —
always batch.

Failure semantics (parity with reference retry/degrade policy,
models.py:46-47, 538-555): per-group exceptions are classified through the
resilience fault taxonomy (resilience/faults.py) and captured into
``Completion.error``; OOM/device-loss/preemption/timeout are marked
transient so the debate core's backoff retries them; a failed group never
kills the round. The chaos injector's ``generate`` and ``checkpoint_load``
seams live here.
"""

from __future__ import annotations

import math
import os
import sys
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from adversarial_spec_tpu import obs as obs_mod
from adversarial_spec_tpu.debate.usage import Usage
from adversarial_spec_tpu.engine import interleave as interleave_mod
from adversarial_spec_tpu.engine import kvtier as kvtier_mod
from adversarial_spec_tpu.engine import prefix_cache as prefix_mod
from adversarial_spec_tpu.engine import registry as registry_mod
from adversarial_spec_tpu.engine import spec as spec_mod
from adversarial_spec_tpu.engine import streaming as stream_mod
from adversarial_spec_tpu.engine import weightres as weightres_mod
from adversarial_spec_tpu.engine.generate import (
    MIN_BUCKET,
    bucket_length,
    generate,
)
from adversarial_spec_tpu.engine.loader import materialize_params
from adversarial_spec_tpu.engine.scheduler import (
    ContinuousBatcher,
    SchedRequest,
)
from adversarial_spec_tpu.engine.registry import ModelSpec
from adversarial_spec_tpu.engine.tokenizer import (
    apply_chat_template,
    load_tokenizer,
)
from adversarial_spec_tpu.engine.types import ChatRequest, Completion, SamplingParams
from adversarial_spec_tpu.models.config import ModelConfig
from adversarial_spec_tpu.parallel.mesh import (
    make_mesh,
    maybe_initialize_distributed,
)
from adversarial_spec_tpu.parallel.sharding import make_device_put
from adversarial_spec_tpu.resilience import faults, injector
from adversarial_spec_tpu.resilience import lockdep as lockdep_mod

_GIB = 1 << 30


def hbm_budget_bytes() -> int:
    """Per-chip byte budget for resident model weights.

    Residency is BYTE-budgeted, not count-budgeted: two 8B bf16 models
    (~32 GB) exceed a v5e chip's 16 GB HBM, so a fixed two-model LRU
    would OOM on exactly the mix-families setup SKILL.md recommends.
    The budget is the device's reported HBM limit (falling back to a
    v5e-sized 16 GiB when the backend reports none, e.g. CPU) times a
    0.75 headroom factor — the reserve covers KV cache, activations,
    and the transient peak while a swap is in flight. Override with
    ADVSPEC_HBM_BUDGET_BYTES (read per decision, so tests and operators
    can retune a live engine).
    """
    env = os.environ.get("ADVSPEC_HBM_BUDGET_BYTES")
    if env:
        return int(env)
    limit = 0
    try:
        stats = jax.devices()[0].memory_stats() or {}
        limit = int(stats.get("bytes_limit", 0))
    except Exception:
        limit = 0
    if limit <= 0:
        limit = 16 * _GIB
    return int(limit * 0.75)


def per_chip_param_bytes(params) -> int:
    """Per-chip bytes a (possibly sharded) param pytree occupies.

    Uses each leaf's sharding to count ONE device's shard — tp/sp-sharded
    weights divide across the mesh, dp-replicated ones do not. Works on
    concrete arrays and eval_shape/ShapeDtypeStruct trees alike; no data
    is fetched.
    """
    total = 0
    for leaf in jax.tree.leaves(params):
        shape = leaf.shape
        sharding = getattr(leaf, "sharding", None)
        if sharding is not None and hasattr(sharding, "shard_shape"):
            try:
                shape = sharding.shard_shape(shape)
            except Exception:
                pass
        total += math.prod(shape) * np.dtype(leaf.dtype).itemsize
    return total


_DTYPES = {
    "bfloat16": jnp.bfloat16,
    "float32": jnp.float32,
    "float16": jnp.float16,
}

def _trim_prompt(ids: list[int], limit: int) -> list[int]:
    """Trim to ``limit`` tokens keeping the first token (BOS/template
    head) and the most recent tail — one definition for every serving
    path."""
    if limit > 0 and len(ids) > limit:
        return ids[:1] + ids[len(ids) - (limit - 1) :]
    return ids


@dataclass
class HostWeights:
    """A demoted model's host-resident shards plus everything needed to
    re-activate it with one committed ``device_put`` (the weight
    ledger's opaque payload — engine/weightres.py). ``shardings`` is
    the ORIGINAL params' sharding tree: promotion restores the exact
    jit signature the model compiled under, so re-promotion compiles
    nothing (the PR 5/6 committed-sharding discipline applied to
    params)."""

    spec: ModelSpec
    cfg: ModelConfig
    tokenizer: object
    mesh: object
    np_params: dict
    shardings: dict
    bytes_device: int


@dataclass
class LoadedModel:
    spec: ModelSpec
    cfg: ModelConfig
    params: dict
    tokenizer: object
    mesh: object
    last_used: float = 0.0
    bytes_per_chip: int = 0
    prefetched: bool = False  # loaded ahead of use by _maybe_prefetch
    # Persistent ContinuousBatcher (paged single-device serving): kept
    # alive ACROSS chat calls so its page pool + prefix cache carry one
    # round's spec/transcript KV into the next round's admissions —
    # the cross-round half of the prefix cache. Rebuilt when the shape
    # key (slots, capacity, budget, kv dtype, cache enablement) changes.
    batcher: object = None
    batcher_key: tuple | None = None


class TpuEngine:
    """Serves every ``tpu://`` alias; caches loaded models (weight swap).

    Residency is byte-budgeted against per-chip HBM (hbm_budget_bytes),
    and heterogeneous rounds overlap the NEXT group's weight load with
    the CURRENT group's decode (one background loader thread): device
    transfers are async, so the swap rides under compute instead of
    serializing after it (SURVEY §7 hard part (b)).
    """

    def __init__(self) -> None:
        self._models: dict[str, LoadedModel] = {}
        self._lock = lockdep_mod.make_lock("TpuEngine._lock")
        self._inflight: dict[str, Future] = {}
        # Estimated bytes of loads currently MATERIALIZING (foreground
        # or prefetch): counted alongside _models in every budget sum so
        # two concurrent loads can't each conclude they fit alone.
        self._loading: dict[str, int] = {}
        # The weight-residency state machine (engine/weightres.py):
        # resident/host/freed bookkeeping, eviction pins (mid-decode
        # models are acquire_weights-pinned, never victims), and the
        # host payloads evicted models demote into instead of paying a
        # full re-materialization on their next turn.
        self.ledger = weightres_mod.WeightLedger()
        # Demotions whose device→host gather is still in flight: the
        # victim is already out of _models (budget math stops counting
        # it) but not yet committed to the ledger's host tier. A load
        # of THAT alias must wait for the commit (then promote) instead
        # of racing a cold re-materialization against the gather;
        # loads of every other alias never block on the transfer.
        self._demoting: dict[str, threading.Event] = {}
        self.prefetch_hits = 0  # prefetched loads actually consumed

    def _committed_bytes_locked(self) -> int:
        """Resident + materializing bytes. Caller holds self._lock."""
        return sum(
            m.bytes_per_chip for m in self._models.values()
        ) + sum(self._loading.values())

    def validate(self, model: str) -> str | None:
        return registry_mod.validate_tpu_model(model)

    # -- model residency ---------------------------------------------------

    def _load(self, alias: str) -> LoadedModel:
        with self._lock:
            lm = self._models.get(alias)
            if lm is not None:
                # A completed prefetch pops its own _inflight entry
                # under the same lock that publishes the model, but
                # clear defensively on every hit so a stale future can
                # never shadow (or resurrect) an evicted model.
                self._inflight.pop(alias, None)
            fut = self._inflight.get(alias)
        if lm is not None:
            if lm.prefetched:
                self.prefetch_hits += 1
                lm.prefetched = False
            lm.last_used = time.monotonic()
            return lm
        if fut is not None:
            try:
                lm = fut.result()
            except Exception:
                lm = None  # prefetch died: retry on the caller's thread
            with self._lock:
                self._inflight.pop(alias, None)
            if lm is not None:
                self.prefetch_hits += 1
                lm.prefetched = False
                lm.last_used = time.monotonic()
                return lm
        self._wait_demoting(alias)
        if self.ledger.is_host(alias):
            # Demoted weights are host-resident: re-activate with one
            # committed device_put instead of a full materialization.
            return self._promote_sync(alias)
        return self._load_sync(alias)

    def _load_sync(
        self,
        alias: str,
        prefetched: bool = False,
        estimate: int | None = None,
        evict: bool = True,
        reserved: bool = False,  # caller already put alias in _loading
    ) -> LoadedModel:
        spec = registry_mod.resolve_model_spec(f"tpu://{alias}")
        dtype = _DTYPES.get(spec.dtype, jnp.bfloat16)
        maybe_initialize_distributed()
        mesh = make_mesh(spec.mesh)
        # Make room BEFORE materializing — otherwise both param sets
        # coexist in HBM during the swap. The estimate comes from
        # eval_shape + the real sharding rules, so it is exact. The
        # prefetch path passes evict=False (it already fit-checked and
        # must never evict on someone else's behalf) and its estimate
        # (no duplicate eval_shape trace).
        if estimate is None:
            estimate = self._estimate_per_chip_bytes(spec, dtype, mesh)
        if evict:
            # Eviction, the final fit check, and the reservation happen
            # under ONE lock hold (reserve_as) so two concurrent loads
            # can't both conclude they fit alone.
            self._evict_for(estimate, reserve_as=alias)
        elif not reserved:
            with self._lock:
                self._loading[alias] = estimate
        try:
            t_load = time.monotonic()
            params, cfg = self._materialize(spec, dtype, mesh)
            tokenizer = load_tokenizer(spec.tokenizer)
            if obs_mod.config().enabled:
                obs_mod.metrics.counter(
                    "advspec_model_loads_total",
                    help="model materializations (foreground + prefetch)",
                ).inc()
                obs_mod.metrics.histogram(
                    "advspec_model_load_seconds",
                    help="checkpoint materialization + tokenizer wall",
                ).observe(time.monotonic() - t_load)
            lm = LoadedModel(
                spec=spec,
                cfg=cfg,
                params=params,
                tokenizer=tokenizer,
                mesh=mesh,
                last_used=time.monotonic(),
                bytes_per_chip=per_chip_param_bytes(params) or estimate,
                prefetched=prefetched,
            )
            with self._lock:
                # Publish and retire the in-flight marker atomically: a
                # concurrent _load sees the alias in exactly one of
                # _models / _inflight, never neither.
                self._models[alias] = lm
                self._inflight.pop(alias, None)
            self.ledger.admit_load(
                alias, lm.bytes_per_chip, time.monotonic() - t_load
            )
            return lm
        finally:
            with self._lock:
                self._loading.pop(alias, None)

    def _estimate_per_chip_bytes(self, spec: ModelSpec, dtype, mesh) -> int:
        """Per-chip weight bytes the alias WILL occupy, before loading.

        eval_shape over the same builder _materialize uses (init +
        optional int8 quantization), mapped through the real sharding
        rules — no memory is touched.
        """
        from adversarial_spec_tpu.models.config import get_config
        from adversarial_spec_tpu.models.transformer import init_params
        from adversarial_spec_tpu.ops.quant import quantize_params
        from adversarial_spec_tpu.parallel.sharding import param_shardings

        cfg = get_config(spec.family, spec.size, max_seq_len=spec.max_seq_len)

        def build():
            p = init_params(jax.random.key(0), cfg, dtype)
            return quantize_params(p, fmt=spec.quant) if spec.quant else p

        shapes = jax.eval_shape(build)
        shardings = param_shardings(mesh, shapes)
        abstract = jax.tree.map(
            lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
            shapes,
            shardings,
        )
        return per_chip_param_bytes(abstract)

    def _evict_for(
        self, needed_bytes: int, reserve_as: str | None = None
    ) -> None:
        """Evict LRU models until ``needed_bytes`` fits in the budget.

        Pinned aliases (mid-decode) are never victims. If everything
        evictable is gone and the budget still doesn't fit, proceed and
        let the device's own OOM surface as a transient error (the
        debate core retries after backoff) — a hard refusal here would
        also block single models legitimately larger than the estimate.
        """
        budget = hbm_budget_bytes()
        while True:
            with self._lock:
                resident = self._committed_bytes_locked()
                if not self._models or resident + needed_bytes <= budget:
                    break
                victims = [
                    a for a in self._models if not self.ledger.pinned(a)
                ]
                if not victims:
                    break
                oldest = min(
                    victims, key=lambda a: self._models[a].last_used
                )
                lm, ev = self._pop_for_demotion_locked(oldest)
            # The device→host gather runs OUTSIDE the engine lock: a
            # concurrent hit on an already-resident model must not
            # stall behind a GB-scale transfer. Budget math is already
            # right — the pop removed the victim from the committed
            # sum, and the _demoting event (registered under the same
            # lock hold) makes a racing load of the VICTIM wait for
            # the ledger commit instead of cold-loading against it.
            self._demote_popped(oldest, lm, ev)
        with self._lock:
            resident = self._committed_bytes_locked()
            if reserve_as is not None:
                # Reserve atomically with the final fit check: a
                # concurrent load's check now sees these bytes.
                self._loading[reserve_as] = needed_bytes
        if resident + needed_bytes > budget:
            print(
                f"warning: model needs {needed_bytes >> 20} MiB with "
                f"{resident >> 20} MiB pinned-resident, budget "
                f"{budget >> 20} MiB — loading anyway (OOM will retry "
                "as transient)",
                file=sys.stderr,
            )

    def _pop_for_demotion_locked(
        self, alias: str
    ) -> tuple[LoadedModel, threading.Event]:
        """Take one model out of the loaded dict for demotion. The
        batcher's device state (pool pages, row buffers) goes with the
        weights: a demoted model must hold ZERO HBM, and an unbounded
        per-model batcher cache is a leak in a long-lived serve daemon
        (its KV survives only through the tiered store's write-through,
        which already flushed at drain end). Caller holds
        ``self._lock``; the returned event is registered under the same
        hold, so a racing load of this alias observes the model in
        exactly one of _models / _demoting / the ledger's host tier."""
        lm = self._models.pop(alias)
        lm.batcher = None
        lm.batcher_key = None
        ev = threading.Event()
        self._demoting[alias] = ev
        return lm, ev

    def _demote_popped(
        self, alias: str, lm: LoadedModel, ev: threading.Event
    ) -> None:
        """Finish one eviction outside the engine lock. With weight
        paging armed the (typically quantized) shards demote to the
        host tier — the device→host copies are STARTED async for every
        leaf before any is resolved, so the gather overlaps itself;
        with paging off this is the classic free-and-reload
        eviction."""
        try:
            if not weightres_mod.paging_armed():
                self.ledger.free_model(alias)
                return
            t0 = time.monotonic()
            for leaf in jax.tree.leaves(lm.params):
                try:
                    leaf.copy_to_host_async()
                except AttributeError:  # non-jax leaf (tests)
                    pass
            np_params = jax.tree.map(np.asarray, lm.params)
            shardings = jax.tree.map(
                lambda x: getattr(x, "sharding", None), lm.params
            )
            holder = HostWeights(
                spec=lm.spec,
                cfg=lm.cfg,
                tokenizer=lm.tokenizer,
                mesh=lm.mesh,
                np_params=np_params,
                shardings=shardings,
                bytes_device=lm.bytes_per_chip,
            )
            bytes_host = sum(
                leaf.nbytes for leaf in jax.tree.leaves(np_params)
            )
            self.ledger.demote_model(
                alias, holder, bytes_host, time.monotonic() - t0
            )
        finally:
            with self._lock:
                self._demoting.pop(alias, None)
            ev.set()

    def _wait_demoting(self, alias: str) -> None:
        """Block until an in-flight demotion of ``alias`` (if any)
        commits to the ledger — the racing loader then promotes the
        freshly demoted shards instead of cold-loading against the
        gather. Never blocks for other aliases."""
        with self._lock:
            ev = self._demoting.get(alias)
        if ev is not None:
            ev.wait()

    def _promote_sync(
        self,
        alias: str,
        prefetched: bool = False,
        evict: bool = True,
        reserved: bool = False,
    ) -> LoadedModel:
        """Re-activate a host-demoted model: one async ``device_put``
        of the saved shards into their ORIGINAL shardings (committed —
        promoted params present the same jit signature the model
        compiled under, so nothing recompiles), dispatched without
        blocking so a prefetch-thread promotion overlaps the current
        model's decode. A fault mid-swap (the ``weight_swap`` chaos
        seam fires here) leaves the host entry untouched: only the
        waiting admission degrades, and the swap is declared
        (``swap_fault`` WeightEvent), never silent."""
        holder = self.ledger.peek_host(alias)
        if holder is None or not isinstance(holder.payload, HostWeights):
            return self._load_sync(
                alias, prefetched=prefetched, reserved=reserved
            )
        hw: HostWeights = holder.payload
        try:
            injector.fire("weight_swap")
            if evict:
                self._evict_for(hw.bytes_device, reserve_as=alias)
            elif not reserved:
                with self._lock:
                    self._loading[alias] = hw.bytes_device
            t0 = time.monotonic()
            params = jax.tree.map(
                lambda arr, sh: (
                    jax.device_put(arr, sh) if sh is not None
                    else jnp.asarray(arr)
                ),
                hw.np_params,
                hw.shardings,
            )
            lm = LoadedModel(
                spec=hw.spec,
                cfg=hw.cfg,
                params=params,
                tokenizer=hw.tokenizer,
                mesh=hw.mesh,
                last_used=time.monotonic(),
                bytes_per_chip=hw.bytes_device,
                prefetched=prefetched,
            )
            with self._lock:
                self._models[alias] = lm
                self._inflight.pop(alias, None)
            self.ledger.promote_model(
                alias,
                hw.bytes_device,
                time.monotonic() - t0,
                overlapped=prefetched,
            )
            return lm
        except BaseException:
            # Conservation: the host entry was never consumed — the
            # next _load retries the promotion; the fault evicts only
            # the admission that was waiting on this swap.
            self.ledger.note_swap_fault(alias)
            raise
        finally:
            with self._lock:
                self._loading.pop(alias, None)

    def check_residency_invariants(self) -> None:
        """Ledger conservation plus the ledger↔engine mirror: the
        ledger's resident set must be exactly the engine's loaded-model
        dict, and no demoted model may still hold a batcher (chaos
        drills and tests call this after every drill step)."""
        # Settle in-flight demotions first: mid-gather a victim is
        # transiently in neither _models nor the host tier (by design),
        # which is drift only if it persists past the commit.
        with self._lock:
            pending = list(self._demoting.values())
        for ev in pending:
            ev.wait()
        self.ledger.check_invariants()
        with self._lock:
            resident = set(self.ledger.resident_aliases())
            loaded = set(self._models)
        if resident != loaded:
            raise RuntimeError(
                f"weight ledger/engine drift: ledger resident "
                f"{sorted(resident)} != loaded models {sorted(loaded)}"
            )

    def _maybe_prefetch(self, alias: str) -> None:
        """Queue a background load of ``alias`` (non-blocking).

        All real work — spec resolution, the eval_shape estimate, the
        fit check, materialization — happens on the loader thread, so
        the serving path pays only two dict probes. chat() calls this
        AFTER the current group's model is loaded and pinned, so the
        fit check sees the full resident set.
        """
        with self._lock:
            if alias in self._models or alias in self._inflight:
                return
            fut: Future = Future()
            self._inflight[alias] = fut
        # A DAEMON thread, not a ThreadPoolExecutor: pool threads are
        # non-daemon and concurrent.futures joins them at interpreter
        # exit, so a prefetch wedged on a dead TPU tunnel (this
        # environment's signature failure mode) would hang the CLI at
        # exit. A daemon thread dies with the process instead; the
        # future carries results/exceptions exactly as before.
        def _work() -> None:
            try:
                fut.set_result(self._prefetch_task(alias))
            except BaseException as e:  # future owns error delivery
                fut.set_exception(e)

        try:
            threading.Thread(
                target=_work, daemon=True, name=f"advspec-prefetch-{alias}"
            ).start()
        except BaseException as e:
            # start() failing (thread exhaustion) must not leave a
            # forever-pending future registered — later loads would
            # block on it without timeout.
            with self._lock:
                self._inflight.pop(alias, None)
            fut.set_exception(e)

    def _prefetch_task(self, alias: str) -> LoadedModel | None:
        """Background half of _maybe_prefetch.

        Prefetch never evicts (the active model is mid-decode and
        pinned; evicting idle models during someone else's decode is a
        policy decision the foreground loader makes with better
        information): if the alias doesn't fit beside everything
        resident, give up — the load then serializes at use time,
        exactly as before prefetching existed. Exceptions stay in the
        future; the foreground _load falls back to a sync load and owns
        error reporting.
        """
        try:
            # A demotion of this alias may still be gathering: wait for
            # its ledger commit (cheap — this is the background thread)
            # so the prefetch promotes the shards instead of racing a
            # cold load against the transfer.
            self._wait_demoting(alias)
            host_entry = self.ledger.peek_host(alias)
            if host_entry is not None and isinstance(
                host_entry.payload, HostWeights
            ):
                # Host-demoted weights: the prefetch is a PROMOTION —
                # the async host→device transfer rides under the
                # current model's decode, which is the entire point of
                # overlapped swap (swap-overlap fraction in
                # perf.weights counts exactly these).
                estimate = host_entry.payload.bytes_device
            else:
                host_entry = None
                spec = registry_mod.resolve_model_spec(f"tpu://{alias}")
                dtype = _DTYPES.get(spec.dtype, jnp.bfloat16)
                mesh = make_mesh(spec.mesh)
                estimate = self._estimate_per_chip_bytes(spec, dtype, mesh)
            with self._lock:
                fits = (
                    self._committed_bytes_locked() + estimate
                    <= hbm_budget_bytes()
                )
                if fits:
                    # Reserve atomically with the check: a concurrent
                    # foreground load's budget math must see these
                    # bytes before this thread starts materializing.
                    self._loading[alias] = estimate
            if fits and host_entry is not None:
                return self._promote_sync(
                    alias, prefetched=True, evict=False, reserved=True
                )
            if fits:
                return self._load_sync(
                    alias,
                    prefetched=True,
                    estimate=estimate,
                    evict=False,
                    reserved=True,
                )
            return None
        finally:
            # _load_sync pops the markers when it publishes; pop here
            # for the not-fits and exception exits (including a raise
            # before _load_sync's own try/finally) so a dead future or
            # stale reservation never blocks later loads of this alias.
            with self._lock:
                if not isinstance(self._models.get(alias), LoadedModel):
                    self._inflight.pop(alias, None)
                    self._loading.pop(alias, None)

    def _materialize(self, spec: ModelSpec, dtype, mesh):
        """Params via the fastest available source: native Orbax cache
        (converted once, restored straight into target shardings) →
        HF safetensors conversion (then cached) → synthetic init."""
        from adversarial_spec_tpu.engine import checkpoint as ckpt_mod
        from adversarial_spec_tpu.models.config import get_config
        from adversarial_spec_tpu.models.transformer import init_params
        from adversarial_spec_tpu.ops.quant import quantize_params
        from adversarial_spec_tpu.parallel.sharding import param_shardings

        import shutil
        import sys

        injector.fire("checkpoint_load")
        quantize = bool(spec.quant)
        cfg = get_config(spec.family, spec.size, max_seq_len=spec.max_seq_len)
        cache_path = None
        if spec.checkpoint != "random":
            cache_path = ckpt_mod.cache_dir_for(
                spec.checkpoint,
                spec.family,
                spec.size,
                spec.dtype,
                spec.quant,
                tied_embeddings=cfg.tied_embeddings,
            )
        if cache_path is not None and ckpt_mod.has_native(cache_path):
            # Cache is an optimization in BOTH directions: a corrupt or
            # layout-incompatible cache falls back to HF conversion
            # instead of permanently breaking the model.
            try:
                # The restore template must match the layout the cache was
                # SAVED with: same transposed-head flag reading as
                # load_hf_checkpoint and the cache fingerprint (a toggled
                # env selects a different cache dir rather than failing
                # restore against this template).
                t_head = ckpt_mod.transposed_head_flag()

                def build():
                    p = init_params(
                        jax.random.key(0), cfg, dtype,
                        transposed_head=t_head,
                    )
                    return (
                        quantize_params(p, fmt=spec.quant)
                        if quantize
                        else p
                    )

                shapes = jax.eval_shape(build)
                shardings = param_shardings(mesh, shapes)
                abstract = jax.tree.map(
                    lambda s, sh: jax.ShapeDtypeStruct(
                        s.shape, s.dtype, sharding=sh
                    ),
                    shapes,
                    shardings,
                )
                return ckpt_mod.load_native(cache_path, abstract), cfg
            except Exception as e:
                print(
                    f"warning: native checkpoint cache unreadable "
                    f"({e}); reconverting from HF",
                    file=sys.stderr,
                )
                shutil.rmtree(cache_path, ignore_errors=True)

        params, cfg = materialize_params(
            spec.checkpoint,
            spec.family,
            spec.size,
            dtype=dtype,
            max_seq_len=spec.max_seq_len,
            device_put=make_device_put(mesh, dtype),
            quant=spec.quant,
        )
        if cache_path is not None:
            try:  # write side is best-effort too
                ckpt_mod.save_native(params, cache_path)
            except Exception as e:
                print(
                    f"warning: native checkpoint cache write failed: {e}",
                    file=sys.stderr,
                )
        return params, cfg

    # -- serving -----------------------------------------------------------

    def chat(
        self,
        requests: list[ChatRequest],
        params: SamplingParams,
        consumer=None,
    ) -> list[Completion]:
        if obs_mod.config().enabled:
            obs_mod.metrics.counter(
                "advspec_engine_chat_requests_total",
                help="chat requests by serving engine",
                engine="tpu",
            ).inc(len(requests))
        # Group by alias: same-model opponents batch into one decode.
        groups: dict[str, list[int]] = {}
        for i, req in enumerate(requests):
            alias = registry_mod.parse_tpu_model_id(req.model)
            groups.setdefault(alias, []).append(i)

        # Residency-aware group order: serve the groups whose weights
        # are ALREADY resident before any group that forces a swap —
        # under a pool-larger-than-HBM budget this turns "one swap per
        # group" into "at most (pool − resident) swaps per round".
        # Groups decode independently, so reordering cannot change any
        # row's greedy tokens; the output list is re-indexed by the
        # original request positions either way.
        aliases = self.ledger.resident_first(list(groups))
        groups = {a: groups[a] for a in aliases}
        out: list[Completion | None] = [None] * len(requests)
        for gi, (alias, indices) in enumerate(groups.items()):
            batch = [requests[i] for i in indices]
            # The caller's stream consumer indexes rows of ITS batch;
            # re-map each group's row back through the group indices.
            group_consumer = None
            if consumer is not None:
                def group_consumer(row, text, _c=consumer, _ix=tuple(indices)):
                    return _c(_ix[row], text)
            try:
                completions = self._chat_one_model(
                    alias,
                    batch,
                    params,
                    # Overlap the next group's weight load with this
                    # group's decode (async transfers ride under
                    # compute). Launched inside _chat_one_model, after
                    # this group's model is loaded and pinned, so the
                    # prefetch fit check sees the full resident set.
                    prefetch_next=(
                        aliases[gi + 1] if gi + 1 < len(aliases) else None
                    ),
                    consumer=group_consumer,
                )
            except Exception as e:  # degrade, never raise (parity: ref)
                msg = f"{type(e).__name__}: {e}"
                kind = faults.classify(e)
                # Injected faults know their seam; real ones are counted
                # where caught.
                faults.record(kind, getattr(e, "seam", "generate"))
                obs_mod.emit(
                    obs_mod.FaultEvent(
                        seam=getattr(e, "seam", "generate"),
                        kind=kind.value,
                        error=msg,
                        # Group-level failure: the round's trace, no
                        # single victim span.
                        trace_id=batch[0].trace_id if batch else "",
                    )
                )
                obs_mod.autodump("fault")
                completions = [
                    Completion(error=msg, transient=kind.transient)
                    for _ in batch
                ]
            for i, comp in zip(indices, completions):
                out[i] = comp
        return [c for c in out if c is not None]

    def _chat_one_model(
        self,
        alias: str,
        batch: list[ChatRequest],
        params: SamplingParams,
        prefetch_next: str | None = None,
        consumer=None,
    ) -> list[Completion]:
        # Pin BEFORE loading: from the moment this model can be resident
        # it must not be an eviction/demotion victim of a concurrent
        # background load (eviction only drops the dict entry; a
        # foreground reference would keep the bytes alive while the
        # budget math believes them freed). acquire/release is the
        # ledger's refcount pair — GL-REFCOUNT enforces the
        # try/finally shape.
        self.ledger.acquire_weights(alias)
        try:
            lm = self._load(alias)
            if prefetch_next is not None:
                self._stage_next(prefetch_next)
            injector.fire("generate")
            return self._chat_loaded(lm, batch, params, consumer)
        finally:
            self.ledger.release_weights(alias)

    def _stage_next(self, alias: str) -> None:
        """Make the NEXT group's swap overlap this group's decode: when
        the next model is host-demoted and HBM is full, demote the LRU
        resident NOW (the current group's model is pinned and can't be
        the victim) so the background promotion fits — without this,
        a budget-saturated pool can never overlap a promotion, because
        the prefetch thread refuses to evict on anyone's behalf. Only
        the cheap host-resident case stages eagerly (its byte estimate
        is already known); cold loads keep the fit-check-only prefetch
        policy."""
        entry = self.ledger.peek_host(alias)
        if entry is not None and isinstance(entry.payload, HostWeights):
            needed = entry.payload.bytes_device
            with self._lock:
                fits = (
                    self._committed_bytes_locked() + needed
                    <= hbm_budget_bytes()
                )
            if not fits:
                self._evict_for(needed)
        self._maybe_prefetch(alias)

    def _chat_loaded(
        self,
        lm: LoadedModel,
        batch: list[ChatRequest],
        params: SamplingParams,
        consumer=None,
    ) -> list[Completion]:
        tok = lm.tokenizer
        instruct = lm.spec.checkpoint != "random"

        prompts = []
        for req in batch:
            text = apply_chat_template(
                lm.spec.family, req.system, req.user, instruct
            )
            ids = tok.encode(text)
            # Reserve room for generation within the model's context.
            prompts.append(
                _trim_prompt(ids, lm.cfg.max_seq_len - params.max_new_tokens)
            )

        # Paged single-device specs serve through the continuous batcher:
        # opponents occupy decode slots, early-EOS rows free their pages
        # mid-round, and queued requests (opponent pools larger than the
        # slot count) admit into freed slots without waiting for the whole
        # batch — the multi-session serving path NOTES.md round 2 left
        # unwired. Sharded meshes keep the round-synchronous generate()
        # (its paged path shards the pool over dp), as do budgets so large
        # that no bucketed prompt passes the batcher's context check (the
        # dense path has no such check and still serves them).
        fits_batcher = (
            lm.cfg.max_seq_len - params.max_new_tokens >= MIN_BUCKET
        )
        if lm.spec.kv == "paged" and lm.mesh.size == 1 and fits_batcher:
            return self._chat_continuous(lm, prompts, params, batch, consumer)
        # The round-synchronous generate() fallback has no per-request
        # token stream (one fused program decodes the whole batch to
        # budget): consumers are served the blocking result only —
        # streaming and early cancellation are batcher-path features
        # (docs/streaming.md).

        t0 = time.monotonic()
        with lm.mesh:
            result = generate(
                lm.params,
                lm.cfg,
                prompts,
                max_new_tokens=params.max_new_tokens,
                eos_ids=list(tok.eos_ids),
                pad_id=tok.pad_id,
                greedy=params.greedy,
                temperature=params.temperature,
                top_k=params.top_k,
                top_p=params.top_p,
                seed=params.seed,
                timeout_s=params.timeout_s,
                mesh=lm.mesh,
                paged=lm.spec.kv == "paged",
                kv_dtype=lm.spec.kv_dtype,
            )
        total_time = time.monotonic() - t0

        # Per-row attribution: decode time proportional to each row's
        # actual decoded tokens (an early-EOS row consumed fewer decode
        # steps than a full-budget row); the prefill/overhead remainder
        # splits evenly (prefill is genuinely shared batch work). Row
        # sums reproduce the call totals exactly.
        tok_total = float(result.n_generated.sum())
        prefill_share = (total_time - result.decode_time_s) / len(batch)
        completions = []
        for row, req in enumerate(batch):
            n = int(result.n_generated[row])
            frac = (n / tok_total) if tok_total > 0 else 1.0 / len(batch)
            decode_share = result.decode_time_s * frac
            text = tok.decode(result.tokens[row, :n])
            completions.append(
                Completion(
                    text=text,
                    usage=Usage(
                        input_tokens=len(prompts[row]),
                        output_tokens=n,
                        device_time_s=prefill_share + decode_share,
                        decode_tokens=n,
                        decode_time_s=decode_share,
                        # Batch prefill is shared work; an even split is
                        # the honest per-row attribution.
                        prefill_time_s=result.prefill_time_s / len(batch),
                    ),
                )
            )
        return completions

    def _chat_continuous(
        self,
        lm: LoadedModel,
        prompts: list[list[int]],
        params: SamplingParams,
        batch: list[ChatRequest] | None = None,
        consumer=None,
    ) -> list[Completion]:
        """Serve one model's requests through the ContinuousBatcher.

        Pool capacity is bucketed to a power of two so repeat rounds of
        similar size reuse the compiled chunk program (pool shape is a
        jit constant). ``batch`` carries the callers' ChatRequests so
        each SchedRequest inherits its causal trace/span ids — the hop
        that ties a debate round to the device steps that served it.
        """
        tok = lm.tokenizer
        # The batcher checks bucket_length(prompt) + budget against the
        # model context; the engine-level trim above only bounded the RAW
        # length, so a near-limit prompt would round up past the context
        # and error the whole group. Re-trim against the bucketed length.
        max_prompt = lm.cfg.max_seq_len - params.max_new_tokens
        while max_prompt > 1 and bucket_length(max_prompt) > max_prompt:
            nxt = bucket_length(max_prompt) // 2
            if nxt >= max_prompt:  # at the minimum bucket already
                break
            max_prompt = nxt
        prompts = [_trim_prompt(p, max_prompt) for p in prompts]
        # Pool capacity covers CONCURRENT residency (the max_batch largest
        # requests), not the whole queue — finished rows free their pages
        # and queued requests admit into them; sizing by the queue total
        # would make pool HBM scale with round size, which is exactly what
        # paging exists to avoid.
        n_slots = min(len(prompts), 8)
        per_req = sorted(
            (bucket_length(len(p)) + params.max_new_tokens for p in prompts),
            reverse=True,
        )
        need = sum(per_req[:n_slots])
        capacity = 2048
        while capacity < need:
            capacity *= 2

        seed = (
            params.seed
            if params.seed is not None
            # seed=None means fresh entropy (as generate() does) —
            # pinning 0 would make every unseeded round sample
            # identically.
            else int.from_bytes(os.urandom(4), "little")
        )
        batcher_key = (
            n_slots,
            capacity,
            params.max_new_tokens,
            lm.spec.kv_dtype,
            prefix_mod.config().enabled,
            prefix_mod.config().max_pages,
            # The batcher snapshots these at construction: a persisted
            # batcher must rebuild when the operator flips the drive
            # loop (--no-interleave) or the pipeline depth per round.
            interleave_mod.config().enabled,
            interleave_mod.config().pipeline_depth,
            # Tiered-KV knobs likewise: flipping --no-kv-tier, the host
            # budget, or the store dir between rounds must rebuild the
            # tiers (and re-fingerprint the store) rather than keep
            # serving under the old config.
            kvtier_mod.config().enabled,
            kvtier_mod.config().host_mb,
            kvtier_mod.config().store_dir,
        )
        t0 = time.monotonic()
        try:
            results, decode_time = self._run_batcher(
                lm, batcher_key, prompts, params, seed, batch, consumer
            )
        except BaseException:
            # An escaping exception (decode fault whose donated-state
            # probe failed, submit validation mid-loop, timeout plumbing)
            # leaves the batcher mid-drain: stale results, occupied
            # slots, leaked sequences. Reusing it next round would
            # replay that corruption — drop it; the next call rebuilds.
            lm.batcher = None
            lm.batcher_key = None
            raise
        total_time = time.monotonic() - t0

        # Same attribution scheme as the dense path: decode time splits
        # by decoded tokens, the prefill/overhead remainder evenly. No
        # double-billing under the fused loop: the batcher PARTITIONS
        # each fused step's wall clock between its decode counter and
        # the riding admission's prefill_time_s (token-share split), so
        # ``overhead`` (= total - decode) contains every prefill second
        # exactly once and a row's decode_share never re-counts time
        # already attributed to another row's admission.
        tok_total = float(sum(r.n_generated for r in results)) or 1.0
        overhead = total_time - decode_time
        completions = []
        for r in results:  # sorted by req_id == prompt order
            frac = r.n_generated / tok_total
            decode_share = decode_time * frac
            completions.append(
                Completion(
                    # Fault-evicted rows keep their partial decode in
                    # ``text`` (diagnostic value) but carry the error so
                    # the debate core's retry/degrade policy applies.
                    # Cancelled rows are CLEAN partials: the consumer
                    # read everything it needed before stopping them.
                    text=tok.decode(r.tokens[: r.n_generated]),
                    error=r.error,
                    cancelled=r.cancelled,
                    transient=(
                        r.fault_kind is not None
                        and faults.FaultKind(r.fault_kind).transient
                    ),
                    usage=Usage(
                        input_tokens=len(prompts[r.req_id]),
                        output_tokens=r.n_generated,
                        device_time_s=overhead / len(results) + decode_share,
                        decode_tokens=r.n_generated,
                        decode_time_s=decode_share,
                        cached_tokens=r.cached_tokens,
                        prefill_time_s=r.prefill_time_s,
                    ),
                )
            )
        return completions

    @staticmethod
    def _make_stream_callback(tok, consumer, row):
        """Incremental detokenization for one request: the batcher
        hands ALL emitted ids so far (monotone supersets); decode the
        full prefix each delivery — a partial multi-byte token decodes
        differently once its continuation arrives, and HF detokenizers
        are not concatenative in general (metaspace/whitespace joining),
        so suffix-diffing could hand the consumer text the blocking
        path never produces, breaking the seam's byte-parity guarantee.
        The full re-decode is a DELIBERATE O(n²/chunk) host cost:
        deliveries happen once per fetched chunk (not per token), n is
        capped by max_new_tokens, and it is paid only while a consumer
        is attached — cheap against the 32 model forwards each chunk
        represents. Returning False asks the batcher to cancel the
        request mid-decode."""

        def on_tokens(token_ids) -> bool:
            return bool(consumer(row, tok.decode(token_ids)))

        return on_tokens

    def _run_batcher(
        self, lm, batcher_key, prompts, params, seed, batch=None,
        consumer=None,
    ):
        """Acquire (reuse or build) the model's persistent batcher and
        drain this call's requests through it.

        Returns ``(results, decode_time_s)`` where the decode time is
        THIS call's delta on the (cumulative) batcher counter. The
        watermark is per-call local state — engine-instance storage
        would be shared mutable telemetry that misattributes decode time
        whenever two drains interleave on one engine."""
        tok = lm.tokenizer
        n_slots, capacity = batcher_key[0], batcher_key[1]
        with lm.mesh:
            if lm.batcher is not None and lm.batcher_key == batcher_key:
                # Round R+1 reuses round R's batcher: same compiled chunk
                # programs AND a warm prefix cache (the shared
                # spec+transcript prefix admits as a page-table adopt +
                # delta prefill instead of a full re-prefill).
                batcher = lm.batcher
                batcher.reconfigure_sampling(
                    greedy=params.greedy,
                    temperature=params.temperature,
                    top_k=params.top_k,
                    top_p=params.top_p,
                    seed=seed,
                )
                # Speculation knobs re-resolve from the process config
                # every drain (one CLI invocation = one round; a later
                # round's --no-speculative/--gamma must reach the
                # persistent batcher). The batcher is idle here —
                # run_all drains fully — so the flip is legal.
                sp = spec_mod.config()
                batcher.reconfigure_speculative(
                    enabled=sp.enabled, gamma=sp.gamma
                )
            else:
                batcher = ContinuousBatcher(
                    lm.params,
                    lm.cfg,
                    max_batch=n_slots,
                    capacity_tokens=capacity,
                    max_new_cap=params.max_new_tokens,
                    eos_ids=list(tok.eos_ids),
                    greedy=params.greedy,
                    temperature=params.temperature,
                    top_k=params.top_k,
                    top_p=params.top_p,
                    seed=seed,
                    # Same KV precision on both serving paths: the
                    # round-synchronous fallback passes spec.kv_dtype to
                    # generate(); the batcher must honor it too (int8
                    # pages + scale pages).
                    kv_dtype=lm.spec.kv_dtype,
                )
                lm.batcher = batcher
                lm.batcher_key = batcher_key
            # Per-round telemetry delta: the persistent batcher's
            # counters accumulate across rounds.
            decode_t0 = batcher.decode_time_s
            stream_on = consumer is not None and stream_mod.config().enabled
            for i, ids in enumerate(prompts):
                src = batch[i] if batch is not None else None
                batcher.submit(
                    SchedRequest(
                        req_id=i,
                        prompt_ids=ids,
                        max_new_tokens=params.max_new_tokens,
                        # Per-request watchdog: a hung/slow request is
                        # evicted as TIMEOUT at this deadline while
                        # co-residents keep decoding (0 = disabled).
                        deadline_s=params.request_deadline_s,
                        # Trace propagation: the opponent request's ids
                        # ride into per-slot batcher state so every
                        # event of every device step resolves back to
                        # the debate round that caused it.
                        trace_id=src.trace_id if src is not None else "",
                        span_id=src.span_id if src is not None else "",
                        on_tokens=(
                            self._make_stream_callback(tok, consumer, i)
                            if stream_on
                            else None
                        ),
                    )
                )
            results = batcher.run_all(timeout_s=params.timeout_s)
            return results, batcher.decode_time_s - decode_t0
