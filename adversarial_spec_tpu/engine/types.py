"""Engine interface types.

The engine interface is *batched by design*: one ``chat`` call takes N
requests and may execute them as N rows of a single sharded decode. This is
the TPU-native replacement for the reference's thread-per-model fan-out
(scripts/models.py:681-722) — concurrency moves from Python threads into the
batch dimension of one XLA program (SURVEY §2.3).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Protocol, runtime_checkable

from adversarial_spec_tpu.debate.usage import Usage

# Streaming consumer at the engine seam (docs/streaming.md): called
# with (request index within the chat batch, the full response text
# decoded SO FAR — each call a superset of the last, so a marker split
# across token boundaries is always eventually visible in one string).
# Return False to cancel that request mid-decode; the engine resolves
# it with the partial text (byte-identical to the blocking path up to
# the cancellation point) and ``Completion.cancelled`` set. Engines
# whose ``chat`` lacks the ``consumer`` parameter simply serve the
# blocking path (debate/core.py inspects before passing one).
StreamConsumer = Callable[[int, str], bool]


@dataclass(frozen=True)
class SamplingParams:
    """Decode-time sampling configuration (one set per chat call)."""

    max_new_tokens: int = 1024
    temperature: float = 0.7
    top_p: float = 1.0
    top_k: int = 0
    greedy: bool = False
    seed: int | None = None
    # Best-effort wall-clock budget for one chat call; engines stop decoding
    # (returning what they have) when exceeded. 0 = unlimited.
    timeout_s: float = 0.0
    # Per-REQUEST watchdog deadline in seconds, measured from submission
    # to the serving engine (0 = disabled). Where ``timeout_s`` bounds
    # the whole call and expires EVERY resident row at once, this bounds
    # one hung/slow request: the ContinuousBatcher evicts an
    # over-deadline slot as ``FaultKind.TIMEOUT`` through the shared
    # release surgery — partial text delivered to its stream consumer,
    # co-residents unaffected — and the debate layer answers with a
    # single breaker-aware hedged re-admission on a tightened budget
    # (docs/resilience.md "Durability and recovery").
    request_deadline_s: float = 0.0


@dataclass(frozen=True)
class ChatRequest:
    """One opponent's prompt: model id + system/user messages."""

    model: str
    system: str
    user: str
    # Opaque metadata echoed back on the completion (e.g. persona label).
    tag: str = ""
    # Causal-trace ids (obs/trace.py): the debate round that issued this
    # request and this request's own span. Minted by the debate layer,
    # carried by value down the serving stack so every flight-recorder
    # event an engine emits resolves back to one round + opponent.
    trace_id: str = ""
    span_id: str = ""
    # Fleet placement key (fleet/hashring.py): one stable id per
    # DEBATE (not per round — the point is that every round of the
    # same debate consistent-hashes onto the replica already holding
    # its prefix KV). Stamped by the debate layer; "" falls back to
    # hashing the model id (no cross-round affinity, still sticky
    # within a batch).
    affinity_key: str = ""


@dataclass
class Completion:
    """One model's completion; ``error`` set instead of raising so a batch
    can partially fail (parity: reference captures errors into
    ModelResponse.error, scripts/models.py:553-555, 676-678)."""

    text: str = ""
    error: str | None = None
    # Transient errors are retried by the caller; permanent ones are not.
    transient: bool = False
    # Set when a streaming consumer cancelled this request mid-decode
    # (early convergence): ``text`` holds the partial transcript up to
    # the cancellation point — a CLEAN result, not an error (the
    # consumer read everything it needed).
    cancelled: bool = False
    usage: Usage = field(default_factory=Usage)

    @property
    def ok(self) -> bool:
        return self.error is None


@runtime_checkable
class Engine(Protocol):
    """Minimal engine surface the debate core depends on."""

    def chat(
        self,
        requests: list[ChatRequest],
        params: SamplingParams,
        consumer: StreamConsumer | None = None,
    ) -> list[Completion]:
        """Complete every request; must return len(requests) completions.

        ``consumer`` (optional capability — callers probe for the
        parameter via ``streaming.consumer_supported`` before passing
        one) streams each request's decoded-text-so-far to the host and
        lets it cancel mid-decode; with ``None`` the call is the
        original blocking path, byte-identical to pre-streaming."""
        ...

    def validate(self, model: str) -> str | None:
        """Return None if ``model`` is servable, else an actionable error
        message (parity: credential preflight, reference
        scripts/providers.py:418-486)."""
        ...
