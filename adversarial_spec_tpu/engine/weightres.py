"""Opponent-pool weight residency: LRU weight paging over one mesh.

A debate round fans one document out to N *different* opponent models,
but HBM holds few full-precision weight sets: before this module the
engine's only answer to pressure was dropping the LRU model entirely
and re-materializing it from checkpoint on its next turn — the full
conversion/restore cost, once per swap, every round, exactly on the
paper's core workload (parallel multi-model critique). This module is
the kvtier demote/promote pattern applied to PARAMS:

- **Demote** — an evicted model's (typically quantized — int8/int4
  weigh 2-4x less than bf16) shards move to a byte-budgeted host-RAM
  tier instead of being freed; the device→host copies are started
  asynchronously at evict time. The model's batcher (page pool, prefix
  cache) is dropped with the device weights — batcher state is HBM too,
  and an unbounded per-model batcher cache is a leak in a long-lived
  serve daemon.
- **Promote** — a host-resident model re-activates with one
  ``device_put`` of the saved shards into their ORIGINAL shardings
  (the committed-sharding discipline: promoted params present the same
  jit signature as the originals, so re-promotion compiles nothing),
  dispatched asynchronously so the transfer overlaps the CURRENT
  model's decode via the engine's prefetch thread.
- **Coalesce** — the engine serves a round's same-model requests as one
  group and orders groups RESIDENT-FIRST, and the serve daemon's stride
  scheduler pulls same-model units out of a tenant's queue into the
  running dispatch, so a swap happens only after the resident models'
  work is exhausted — a swap is a declared, traced event
  (:class:`~adversarial_spec_tpu.obs.events.WeightEvent`,
  ``advspec_weight_resident_models``,
  ``advspec_weight_swap_seconds{direction}``), never an inferred one.

The ledger here is the state machine (every model admitted to the
device tier ends in EXACTLY ONE of resident / host / freed — the
conservation invariant ``check_invariants`` raises on) and is
deliberately jax-free and clock-free: payloads are opaque holders the
TPU engine fills with host arrays (``None`` for the mock engine, which
drives the same machine deterministically with synthetic byte counts
and synthetic walls), and every wall second is PASSED IN by the caller,
so mock residency telemetry pins byte-identically on CPU.

Process-wide config + stats follow the ``procconfig`` pattern shared
with ``interleave``/``spec``/``prefix_cache``/``kvtier``: the CLI arms
per round (``--weight-res/--no-weight-res``, ``--weight-host-mb``; env
``ADVSPEC_WEIGHT_RES`` / ``ADVSPEC_WEIGHT_HOST_MB``) and snapshots into
``perf.weights``.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass

from adversarial_spec_tpu import obs as obs_mod
from adversarial_spec_tpu.engine import procconfig
from adversarial_spec_tpu.resilience import lockdep as lockdep_mod

DEFAULT_HOST_MB = 2048

# -- config + stats ---------------------------------------------------------


@dataclass
class WeightResConfig:
    """Process-wide knobs, set once per CLI round (or by tests)."""

    # Master switch: off = evictions FREE the weights (naive
    # evict-reload — the bench's control arm), on = evictions demote to
    # the host tier and re-activation promotes.
    enabled: bool = True
    # Host-RAM budget in MiB for demoted weight shards (0 disables the
    # host tier; demotion then degrades to free).
    host_mb: int = DEFAULT_HOST_MB


def env_enabled() -> bool:
    """The process default for the master switch (``ADVSPEC_WEIGHT_RES``)."""
    return os.environ.get("ADVSPEC_WEIGHT_RES", "1") != "0"


def env_host_mb() -> int:
    """The process default host budget (``ADVSPEC_WEIGHT_HOST_MB``)."""
    try:
        return max(
            0, int(os.environ.get("ADVSPEC_WEIGHT_HOST_MB", DEFAULT_HOST_MB))
        )
    except ValueError:
        return DEFAULT_HOST_MB


@dataclass
class WeightStats(procconfig.StatsBase):
    """Process-wide residency counters, aggregated across every engine
    (and the mock's deterministic accounting).

    ``load_s`` is the cost residency exists to avoid (full checkpoint
    materializations); ``promote_s`` the cost it pays instead — the
    bench headline compares ``load_s + promote_s`` resident-vs-thrash.
    ``promotions_overlapped`` counts promotions that rode another
    model's decode (the prefetch thread), so the swap-overlap fraction
    is ``promotions_overlapped / promotions``.
    """

    loads: int = 0  # full (cold) materializations
    load_s: float = 0.0
    demotions: int = 0  # device -> host
    demote_s: float = 0.0
    promotions: int = 0  # host -> device re-activations
    promote_s: float = 0.0
    promotions_overlapped: int = 0  # promotions riding another's decode
    freed_models: int = 0  # evictions that freed instead of demoting
    swap_faults: int = 0  # promotions aborted by a fault mid-swap
    coalesced_groups: int = 0  # chat rounds reordered resident-first
    coalesced_units: int = 0  # serve units pulled ahead to dodge a swap
    preload_hints: int = 0  # warm-replica residency hints (autoscale)

    def snapshot(self) -> dict:
        out = self.as_dict()
        out["weight_load_wall_s"] = round(self.load_s + self.promote_s, 6)
        out["swap_overlap_fraction"] = (
            round(self.promotions_overlapped / self.promotions, 4)
            if self.promotions
            else 0.0
        )
        out["reload_avoided_rate"] = (
            round(self.promotions / (self.promotions + self.loads), 4)
            if (self.promotions + self.loads)
            else 0.0
        )
        return out


_state = procconfig.ProcState(
    WeightResConfig(enabled=env_enabled(), host_mb=env_host_mb()),
    WeightStats(),
    coerce={"host_mb": lambda v: max(0, int(v))},
)
_config = _state.config
stats = _state.stats


def config() -> WeightResConfig:
    return _state.config


def configure(
    enabled: bool | None = None, host_mb: int | None = None
) -> WeightResConfig:
    return _state.configure(enabled=enabled, host_mb=host_mb)


def reset_stats() -> None:
    _state.reset_stats()


def snapshot() -> dict:
    """Stats + config, the ``perf.weights`` payload."""
    return _state.snapshot()


def paging_armed() -> bool:
    """True when evictions demote to host RAM instead of freeing."""
    return _config.enabled and _config.host_mb > 0


def preload_hint(models) -> int:
    """Residency preload hint for a replica being WARMED before ring
    admission (fleet/autoscale.py): the hottest models from the serve
    scheduler's model mix, in hotness order. Deliberately advisory —
    the ledger's one admission surgery still runs on first serve, so
    conservation invariants are untouched; the hint's value is that
    the warming replica builds its engines (and, on the TPU engine, its
    checkpoints materialize) while the replica is NOT routable, moving
    the cold-load wall off the first routed request. Counted so the
    elasticity drills can assert warming actually happened. Returns
    the hint count recorded."""
    n = len(list(models))
    if n:
        stats.preload_hints += n
    return n


def mock_budget_bytes() -> int | None:
    """The mock engine's residency trigger: it drives the ledger only
    under an EXPLICIT ``ADVSPEC_HBM_BUDGET_BYTES`` (the bench and tests
    arm it); without one the simulation is off and mock event streams
    stay byte-identical to their pre-residency pins."""
    env = os.environ.get("ADVSPEC_HBM_BUDGET_BYTES")
    if not env:
        return None
    try:
        return int(env)
    except ValueError:
        return None


# -- the residency ledger ---------------------------------------------------

RESIDENT = "resident"
HOST = "host"


@dataclass
class ModelEntry:
    """One model's residency record. ``payload`` is opaque to the
    ledger: the TPU engine stores a host-weights holder (np shards +
    shardings + spec/config/tokenizer), the mock stores ``None``."""

    alias: str
    state: str  # RESIDENT | HOST
    bytes_device: int = 0
    bytes_host: int = 0
    payload: object = None
    last_used: int = 0
    pins: int = 0


class WeightLedger:
    """The weight-residency state machine (one per engine instance;
    stats aggregate into the process-wide module counters).

    Conservation invariant (the chaos drill's contract): every model
    ever demoted ends in EXACTLY ONE of re-promoted / still host-
    resident / freed — an aborted promotion leaves the host entry
    untouched (the engine commits the transition only AFTER the device
    transfer is dispatched), so a fault mid-swap costs one retry, never
    a lost or double-counted model.

    Every ``_entries`` transition funnels through ONE surgery
    (:meth:`_retire_model`) plus the one admission path
    (:meth:`_admit_model`) — graftlint's fourth GL-LIFECYCLE machine
    enforces exactly that shape statically.
    """

    def __init__(self, stats_obj: WeightStats | None = None):
        self._entries: dict[str, ModelEntry] = {}
        # Pins taken before the model finished loading (the engine pins
        # FIRST so a concurrent eviction can never victimize a model
        # that is about to serve); merged into the entry at admission.
        self._pre_pins: dict[str, int] = {}
        self._clock = 0
        self._lock = lockdep_mod.make_lock("WeightLedger._lock")
        self.stats = stats_obj if stats_obj is not None else stats
        # Conservation counters (lifetime).
        self.admitted = 0  # loads + promotions into the device tier
        self.demoted = 0
        self.promoted = 0  # host entries re-admitted to the device
        self.freed_host = 0  # host entries dropped (budget/clear)
        self.freed_resident = 0  # device entries freed without demoting

    # -- queries ------------------------------------------------------

    def state(self, alias: str) -> str | None:
        with self._lock:
            e = self._entries.get(alias)
            return e.state if e is not None else None

    def is_resident(self, alias: str) -> bool:
        return self.state(alias) == RESIDENT

    def is_host(self, alias: str) -> bool:
        return self.state(alias) == HOST

    def peek_host(self, alias: str) -> ModelEntry | None:
        """The host entry a promotion will materialize from (left in
        place — the transition commits via :meth:`promote_model` only
        after the device transfer is dispatched, so an aborted swap
        leaves the tier intact)."""
        with self._lock:
            e = self._entries.get(alias)
            return e if e is not None and e.state == HOST else None

    def resident_aliases(self) -> list[str]:
        with self._lock:
            return [
                a for a, e in self._entries.items() if e.state == RESIDENT
            ]

    def host_aliases(self) -> list[str]:
        with self._lock:
            return [a for a, e in self._entries.items() if e.state == HOST]

    @property
    def resident_models(self) -> int:
        with self._lock:
            return sum(
                1 for e in self._entries.values() if e.state == RESIDENT
            )

    @property
    def host_models(self) -> int:
        with self._lock:
            return sum(1 for e in self._entries.values() if e.state == HOST)

    @property
    def host_bytes(self) -> int:
        with self._lock:
            return self._host_bytes_locked()

    def _host_bytes_locked(self) -> int:
        """Caller must hold ``_lock`` (plain Lock — not re-entrant)."""
        return sum(
            e.bytes_host for e in self._entries.values() if e.state == HOST
        )

    def lru_resident_alias(self) -> str | None:
        """The least-recently-used unpinned resident model (the next
        eviction victim), or None when everything resident is pinned."""
        with self._lock:
            cands = [
                e
                for e in self._entries.values()
                if e.state == RESIDENT and e.pins == 0
            ]
            if not cands:
                return None
            return min(cands, key=lambda e: e.last_used).alias

    def resident_first(self, aliases: list[str]) -> list[str]:
        """Stable resident-first order for one round's model groups —
        THE coalescing policy both engines share (a swap is allowed
        only after the resident models' queued work is exhausted).
        Counts the reorder into ``coalesced_groups`` when it changed
        anything; groups decode independently, so reordering cannot
        change any row's greedy tokens."""
        if len(aliases) <= 1:
            return list(aliases)
        order = sorted(
            range(len(aliases)),
            key=lambda i: (not self.is_resident(aliases[i]), i),
        )
        if order != list(range(len(aliases))):
            self.stats.coalesced_groups += 1
        return [aliases[i] for i in order]

    def touch(self, alias: str) -> None:
        with self._lock:
            e = self._entries.get(alias)
            if e is not None:
                self._clock += 1
                e.last_used = self._clock

    # -- pins (graftlint refcount pair: acquire_weights=release_weights)

    def acquire_weights(self, alias: str) -> None:
        """Pin a model against eviction for the duration of its serve
        (mid-decode weights must never be a demotion victim). Balanced
        by :meth:`release_weights` on every path (try/finally at the
        call site — GL-REFCOUNT enforces the shape)."""
        with self._lock:
            e = self._entries.get(alias)
            if e is not None:
                e.pins += 1
            else:
                self._pre_pins[alias] = self._pre_pins.get(alias, 0) + 1

    def release_weights(self, alias: str) -> None:
        with self._lock:
            e = self._entries.get(alias)
            if e is not None and e.pins > 0:
                e.pins -= 1
                return
            if self._pre_pins.get(alias):
                self._pre_pins[alias] -= 1
                if not self._pre_pins[alias]:
                    del self._pre_pins[alias]

    def pinned(self, alias: str) -> bool:
        with self._lock:
            e = self._entries.get(alias)
            if e is not None and e.pins > 0:
                return True
            return bool(self._pre_pins.get(alias))

    # -- transitions --------------------------------------------------

    def _emit(self, op: str, alias: str, nbytes: int, wall_s: float) -> None:
        if not obs_mod.config().enabled:
            return
        obs_mod.hot.weight_resident.set(self.resident_models)
        obs_mod.emit(
            obs_mod.WeightEvent(
                op=op,
                alias=alias,
                nbytes=nbytes,
                wall_s=wall_s,
                resident=self.resident_models,
                host=self.host_models,
            )
        )

    def _admit_model(
        self, alias: str, bytes_device: int, payload: object = None
    ) -> ModelEntry:
        """The ONE admission path into the device tier (load and
        promote both land here). Merges any pin taken before the load
        finished."""
        self._clock += 1
        pins = self._pre_pins.pop(alias, 0)
        entry = ModelEntry(
            alias=alias,
            state=RESIDENT,
            bytes_device=bytes_device,
            payload=payload,
            last_used=self._clock,
            pins=pins,
        )
        self._entries[alias] = entry
        self.admitted += 1
        return entry

    def _retire_model(self, alias: str, dest: str) -> ModelEntry | None:
        """THE release surgery: the only code that takes an entry out
        of its current state. ``dest``: ``host`` (demotion — the caller
        already attached the host payload via :meth:`demote_model`),
        ``promoted`` (host entry re-admitted by ``promote_model``),
        ``freed`` (dropped from either state). Conservation counters
        update here and nowhere else."""
        entry = self._entries.get(alias)
        if entry is None:
            return None
        if dest == HOST:
            entry.state = HOST
            entry.bytes_device = 0
            self.demoted += 1
            return entry
        popped = self._entries.pop(alias)
        if dest == "promoted":
            self.promoted += 1
        elif popped.state == HOST:
            self.freed_host += 1
        else:
            self.freed_resident += 1
        return popped

    def admit_load(
        self, alias: str, bytes_device: int, wall_s: float = 0.0
    ) -> None:
        """A cold materialization finished: the model is resident.

        Two racing loads of one alias both publish (the engine's
        ``_models`` dict tolerates the overwrite); the SECOND admission
        retires the first through the surgery so conservation stays
        exact — one admission resident, one freed, never two counted
        against one entry."""
        with self._lock:
            prior = self._entries.get(alias)
            popped = (
                self._retire_model(alias, "freed")
                if prior is not None
                else None
            )
            entry = self._admit_model(alias, bytes_device)
            if popped is not None:
                entry.pins += popped.pins
        self.stats.loads += 1
        self.stats.load_s += wall_s
        self._emit("load", alias, bytes_device, wall_s)
        if obs_mod.config().enabled and wall_s > 0.0:
            obs_mod.hot.weight_swap_latency("load").observe(wall_s)

    def demote_model(
        self,
        alias: str,
        payload: object,
        bytes_host: int,
        wall_s: float = 0.0,
        host_budget_bytes: int | None = None,
    ) -> list[str]:
        """Resident → host: the eviction that keeps the shards. Returns
        the aliases of host-tier LRU victims freed to fit the budget
        (oldest first; the demoted model itself is freed when it alone
        exceeds the budget)."""
        freed: list[str] = []
        with self._lock:
            entry = self._retire_model(alias, HOST)
            if entry is None:
                return freed
            entry.payload = payload
            entry.bytes_host = bytes_host
            budget = (
                host_budget_bytes
                if host_budget_bytes is not None
                else _config.host_mb << 20
            )
            while self._host_bytes_locked() > budget:
                victims = [
                    e
                    for e in self._entries.values()
                    if e.state == HOST
                ]
                if not victims:
                    break
                lru = min(victims, key=lambda e: e.last_used)
                self._retire_model(lru.alias, "freed")
                freed.append(lru.alias)
        self.stats.demotions += 1
        self.stats.demote_s += wall_s
        self._emit("demote", alias, bytes_host, wall_s)
        if obs_mod.config().enabled and wall_s > 0.0:
            obs_mod.hot.weight_swap_latency("out").observe(wall_s)
        for victim in freed:
            self.stats.freed_models += 1
            self._emit("free", victim, 0, 0.0)
        return freed

    def promote_model(
        self,
        alias: str,
        bytes_device: int,
        wall_s: float = 0.0,
        overlapped: bool = False,
    ) -> None:
        """Host → resident, called AFTER the device transfer was
        dispatched (a fault before this call leaves the host entry
        untouched — the aborted-swap contract)."""
        with self._lock:
            prior = self._entries.get(alias)
            # Two racing promotions both pass peek_host before either
            # commits: the loser finds the alias already RESIDENT and
            # must retire that admission as freed, not count a second
            # promotion against the single demotion.
            dest = (
                "promoted"
                if prior is None or prior.state == HOST
                else "freed"
            )
            popped = self._retire_model(alias, dest)
            pins = popped.pins if popped is not None else 0
            entry = self._admit_model(alias, bytes_device)
            entry.pins += pins
        self.stats.promotions += 1
        self.stats.promote_s += wall_s
        if overlapped:
            self.stats.promotions_overlapped += 1
        self._emit("promote", alias, bytes_device, wall_s)
        if obs_mod.config().enabled and wall_s > 0.0:
            obs_mod.hot.weight_swap_latency("in").observe(wall_s)

    def free_model(self, alias: str) -> None:
        """Either state → freed (eviction with paging off, host budget
        overflow handled by demote, or explicit teardown)."""
        with self._lock:
            popped = self._retire_model(alias, "freed")
        if popped is not None:
            self.stats.freed_models += 1
            self._emit("free", alias, 0, 0.0)

    def note_swap_fault(self, alias: str) -> None:
        """A promotion aborted mid-swap: the host entry is untouched
        (conservation holds), the fault is counted and declared."""
        self.stats.swap_faults += 1
        self._emit("swap_fault", alias, 0, 0.0)

    def clear(self) -> None:
        """Engine teardown: free everything through the surgery."""
        with self._lock:
            for alias in list(self._entries):
                self._retire_model(alias, "freed")

    def check_invariants(self) -> None:
        """Raise RuntimeError on bookkeeping drift: state vocabulary,
        pin sanity, and conservation (every demotion accounted host /
        promoted / freed; every admission accounted resident / demoted /
        freed)."""
        with self._lock:
            resident = host = 0
            for alias, e in self._entries.items():
                if e.alias != alias:
                    raise RuntimeError(
                        f"weight ledger key {alias} holds entry {e.alias}"
                    )
                if e.state == RESIDENT:
                    resident += 1
                elif e.state == HOST:
                    host += 1
                else:
                    raise RuntimeError(
                        f"weight ledger entry {alias} in unknown state "
                        f"{e.state!r}"
                    )
                if e.pins < 0:
                    raise RuntimeError(
                        f"weight ledger entry {alias} has negative pins"
                    )
            if self.demoted != host + self.promoted + self.freed_host:
                raise RuntimeError(
                    f"weight ledger demotion conservation violated: "
                    f"{self.demoted} demoted != {host} host + "
                    f"{self.promoted} promoted + {self.freed_host} freed"
                )
            if self.admitted != (
                resident + self.demoted + self.freed_resident
            ):
                raise RuntimeError(
                    f"weight ledger admission conservation violated: "
                    f"{self.admitted} admitted != {resident} resident + "
                    f"{self.demoted} demoted + "
                    f"{self.freed_resident} freed"
                )
