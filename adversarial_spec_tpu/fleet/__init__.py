"""Fleet layer: replicated engines behind a prefix-affinity router.

One engine process is a single point of failure — a dead process loses
every in-flight round and all device/host KV residency. This package is
ROADMAP item 3's first half: N engine replicas behind a router that

- **routes by prefix affinity** (fleet/hashring.py): the debate's
  affinity key consistent-hashes onto a replica, so every round of the
  same debate lands where its prefix KV already lives, and a membership
  change moves only ~1/N of the keyspace;
- **fails over on health + breakers** (fleet/router.py): per-replica
  heartbeats and per-(replica, model) circuit breakers
  (resilience/breaker.py ``replica_key``) drain a slow or dead replica
  — queued and in-flight requests re-route to the next replica on the
  ring;
- **recovers through the shared store**: replicas share the PR 7
  content-addressed disk store, so a failed-over request rehydrates its
  prefix KV on the new replica instead of re-prefilling, and the PR 10
  round journal keeps already-completed opponents from re-issuing.

Replicas come in two transports (fleet/replica.py): ``inproc`` wraps a
fresh engine instance in this process (deterministic, tier-1-testable),
``worker`` runs one per subprocess (``python -m
adversarial_spec_tpu.fleet.worker``) — the SIGKILL-able topology the
replica-kill chaos harness drives (``tools/chaos_run.py
--replica-kill``).

Process-wide config + stats follow the ``procconfig`` pattern shared
with ``interleave``/``spec``/``kvtier``: the CLI arms per round
(``--fleet``, ``--fleet-replicas``; env ``ADVSPEC_FLEET`` /
``ADVSPEC_FLEET_REPLICAS`` / ``ADVSPEC_FLEET_TRANSPORT``) and snapshots
into ``perf.fleet``. Deliberately imports no jax — the mock fleet runs
entirely on CPU.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

from adversarial_spec_tpu.engine import procconfig

DEFAULT_REPLICAS = 2
TRANSPORTS = ("inproc", "worker")

# Elasticity defaults (fleet/autoscale.py). The fractions are of the
# PER-REPLICA backlog capacity (serve's max_backlog_tokens): scale-out
# arms at 0.6 — deliberately BELOW the serve brownout-enter fraction
# (0.75) so capacity is already being added when brownout would start
# shedding batch admissions; scale-in arms only when backlog would fit
# comfortably in one fewer replica. Streaks + cooldown are the
# hysteresis pair: a decision needs N consecutive ticks AND a quiet
# period since the last membership change, so an oscillating load
# trace cannot flap the ring.
DEFAULT_MIN_REPLICAS = 1
DEFAULT_MAX_REPLICAS = 4
DEFAULT_SCALE_OUT_FRACTION = 0.6
DEFAULT_SCALE_IN_FRACTION = 0.15
DEFAULT_SCALE_OUT_TICKS = 2
DEFAULT_SCALE_IN_TICKS = 5
DEFAULT_SCALE_COOLDOWN_S = 5.0
DEFAULT_SCALE_INTERVAL_S = 0.25
DEFAULT_SPAWN_RETRIES = 3

# Disaggregation defaults (fleet/handoff.py + role-aware routing).
# ``prefill_replicas`` > 0 splits the fleet: that many founders carry
# the "prefill" role, the rest "decode", and admissions whose estimated
# prefill exceeds ``handoff_threshold_tokens`` run admission+prefill on
# a prefill replica, publish the produced blocks to the shared store,
# and decode on a decode replica that adopts them from a tier hit.
# 0 keeps the symmetric (un-roled) fleet. The per-role min/max bound
# the role-aware autoscaler.
DEFAULT_PREFILL_REPLICAS = 0
DEFAULT_HANDOFF_THRESHOLD_TOKENS = 256
DEFAULT_MIN_PREFILL_REPLICAS = 1
DEFAULT_MAX_PREFILL_REPLICAS = 2


def env_enabled() -> bool:
    """The process default for the master switch (``ADVSPEC_FLEET``).
    Default OFF: a single engine stays the shipped topology until the
    operator opts a process into the fleet."""
    return os.environ.get("ADVSPEC_FLEET", "0") == "1"


def env_replicas() -> int:
    """The process default replica count (``ADVSPEC_FLEET_REPLICAS``)."""
    try:
        return max(1, int(os.environ.get("ADVSPEC_FLEET_REPLICAS", DEFAULT_REPLICAS)))
    except ValueError:
        return DEFAULT_REPLICAS


def env_transport() -> str:
    """The process default transport (``ADVSPEC_FLEET_TRANSPORT``)."""
    t = os.environ.get("ADVSPEC_FLEET_TRANSPORT", "inproc")
    return t if t in TRANSPORTS else "inproc"


def env_autoscale() -> bool:
    """The process default for elasticity (``ADVSPEC_FLEET_AUTOSCALE``).
    Default OFF: membership stays fixed until the operator opts in."""
    return os.environ.get("ADVSPEC_FLEET_AUTOSCALE", "0") == "1"


def _env_int(name: str, default: int, floor: int = 0) -> int:
    try:
        return max(floor, int(os.environ.get(name, default)))
    except ValueError:
        return default


def _env_float(name: str, default: float, floor: float = 0.0) -> float:
    try:
        return max(floor, float(os.environ.get(name, default)))
    except ValueError:
        return default


def env_min_replicas() -> int:
    """The elastic floor (``ADVSPEC_FLEET_MIN``)."""
    return _env_int("ADVSPEC_FLEET_MIN", DEFAULT_MIN_REPLICAS, floor=1)


def env_max_replicas() -> int:
    """The elastic ceiling (``ADVSPEC_FLEET_MAX``)."""
    return _env_int("ADVSPEC_FLEET_MAX", DEFAULT_MAX_REPLICAS, floor=1)


def env_scale_cooldown_s() -> float:
    """Post-membership-change quiet period
    (``ADVSPEC_FLEET_SCALE_COOLDOWN_S``)."""
    return _env_float(
        "ADVSPEC_FLEET_SCALE_COOLDOWN_S", DEFAULT_SCALE_COOLDOWN_S
    )


def env_scale_interval_s() -> float:
    """Autoscaler tick period (``ADVSPEC_FLEET_SCALE_INTERVAL_S``)."""
    return _env_float(
        "ADVSPEC_FLEET_SCALE_INTERVAL_S", DEFAULT_SCALE_INTERVAL_S
    )


def env_prefill_replicas() -> int:
    """Prefill-role founder count (``ADVSPEC_FLEET_PREFILL_REPLICAS``;
    0 = symmetric fleet, no disaggregation)."""
    return _env_int(
        "ADVSPEC_FLEET_PREFILL_REPLICAS", DEFAULT_PREFILL_REPLICAS
    )


def env_handoff_threshold_tokens() -> int:
    """Estimated-prefill-token threshold above which an admission
    routes prefill-first (``ADVSPEC_FLEET_HANDOFF_THRESHOLD``)."""
    return _env_int(
        "ADVSPEC_FLEET_HANDOFF_THRESHOLD",
        DEFAULT_HANDOFF_THRESHOLD_TOKENS,
    )


@dataclass
class FleetConfig:
    """Process-wide knobs, set once per CLI round (or by tests)."""

    enabled: bool = False
    replicas: int = DEFAULT_REPLICAS
    # "inproc" (fresh engine instances in this process) or "worker"
    # (one subprocess per replica — SIGKILL-able, the chaos topology).
    transport: str = "inproc"
    # Per-request transport deadline for worker replicas, seconds: a
    # worker that stays silent this long is treated as dead and its
    # in-flight requests fail over (0 = wait forever).
    request_timeout_s: float = 30.0
    # Elasticity (fleet/autoscale.py): backlog-driven membership. The
    # autoscaler only runs when the serve daemon owns a scheduler to
    # read pressure from; these knobs shape its decisions everywhere
    # (daemon loop, chaos drills, bench arms).
    autoscale: bool = False
    min_replicas: int = DEFAULT_MIN_REPLICAS
    max_replicas: int = DEFAULT_MAX_REPLICAS
    scale_out_fraction: float = DEFAULT_SCALE_OUT_FRACTION
    scale_in_fraction: float = DEFAULT_SCALE_IN_FRACTION
    scale_out_ticks: int = DEFAULT_SCALE_OUT_TICKS
    scale_in_ticks: int = DEFAULT_SCALE_IN_TICKS
    scale_cooldown_s: float = DEFAULT_SCALE_COOLDOWN_S
    scale_interval_s: float = DEFAULT_SCALE_INTERVAL_S
    # Bounded spawn retry (fleet/replica.py spawn_replica): attempts
    # past the first before a typed SpawnFailed aborts the scale-out.
    spawn_retries: int = DEFAULT_SPAWN_RETRIES
    # Disaggregation (fleet/handoff.py): founders carrying the
    # "prefill" role (0 = symmetric fleet), the estimated-prefill
    # threshold that routes an admission prefill-first, and the
    # per-role membership bounds the role-aware autoscaler honors.
    prefill_replicas: int = DEFAULT_PREFILL_REPLICAS
    handoff_threshold_tokens: int = DEFAULT_HANDOFF_THRESHOLD_TOKENS
    min_prefill_replicas: int = DEFAULT_MIN_PREFILL_REPLICAS
    max_prefill_replicas: int = DEFAULT_MAX_PREFILL_REPLICAS


def _coerce_transport(value) -> str:
    v = str(value)
    if v not in TRANSPORTS:
        # Fail AT THE KNOB (the γ precedent): a typo'd transport must
        # not silently fall back to inproc mid-deployment.
        raise ValueError(
            f"unknown fleet transport {v!r}; known: {', '.join(TRANSPORTS)}"
        )
    return v


@dataclass
class FleetStats(procconfig.StatsBase):
    """Process-wide fleet counters, aggregated across every router the
    process builds (one per config generation).

    ``affinity_hits`` counts requests served by the ring's PRIMARY
    choice for their key; ``routed_requests − affinity_hits`` is the
    hop traffic (breaker-open skips + failover re-routes), so
    ``affinity_hit_rate`` is the headline the fleet bench compares
    against random routing. ``reissued_requests`` counts requests that
    were re-routed after their replica died mid-flight — the work a
    replica loss costs; ``duplicated_completions`` counts completions
    that arrived for an already-resolved request and MUST stay zero
    (the lose-a-replica-lose-nothing invariant the chaos harness
    pins)."""

    routed_requests: int = 0
    affinity_hits: int = 0
    failover_hops: int = 0
    breaker_skips: int = 0
    reissued_requests: int = 0
    completed_requests: int = 0
    duplicated_completions: int = 0
    replicas_spawned: int = 0
    replicas_retired: int = 0
    heartbeats: int = 0
    heartbeat_failures: int = 0
    # Elasticity (fleet/autoscale.py): membership changes that
    # completed, spawn attempts that exhausted their bounded retry
    # (``SpawnFailed`` — each one also enters cooldown, so the counter
    # bounds how hot a broken spawn path can loop), and decisions the
    # hysteresis/cooldown pair suppressed (the anti-flap ledger the
    # oscillating-load test pins).
    scale_outs: int = 0
    scale_ins: int = 0
    spawn_failures: int = 0
    flaps_suppressed: int = 0
    # Disaggregation (fleet/handoff.py): cross-replica KV handoffs by
    # terminal outcome. ``handoff_adopted`` = the decode replica's
    # first step started from a tier hit on the shipped blocks;
    # ``handoff_degraded`` = the lost-race fallback (store miss,
    # quarantine, partial publish) re-prefilled locally —
    # byte-identical transcript, just slower; ``handoff_abandoned`` =
    # the prefill side died before publication. ``handoff_shipped_
    # blocks`` counts blocks made durable for a handoff.
    handoff_attempts: int = 0
    handoff_adopted: int = 0
    handoff_degraded: int = 0
    handoff_abandoned: int = 0
    handoff_shipped_blocks: int = 0

    def snapshot(self) -> dict:
        out = self.as_dict()
        out["affinity_hit_rate"] = (
            round(self.affinity_hits / self.routed_requests, 4)
            if self.routed_requests
            else 0.0
        )
        out["handoff_hit_rate"] = (
            round(self.handoff_adopted / self.handoff_attempts, 4)
            if self.handoff_attempts
            else 0.0
        )
        return out


_state = procconfig.ProcState(
    FleetConfig(
        enabled=env_enabled(),
        replicas=env_replicas(),
        transport=env_transport(),
        autoscale=env_autoscale(),
        min_replicas=env_min_replicas(),
        max_replicas=env_max_replicas(),
        scale_cooldown_s=env_scale_cooldown_s(),
        scale_interval_s=env_scale_interval_s(),
        prefill_replicas=env_prefill_replicas(),
        handoff_threshold_tokens=env_handoff_threshold_tokens(),
    ),
    FleetStats(),
    coerce={
        "replicas": lambda v: max(1, int(v)),
        "transport": _coerce_transport,
        "min_replicas": lambda v: max(1, int(v)),
        "max_replicas": lambda v: max(1, int(v)),
        "scale_out_ticks": lambda v: max(1, int(v)),
        "scale_in_ticks": lambda v: max(1, int(v)),
        "scale_cooldown_s": lambda v: max(0.0, float(v)),
        "scale_interval_s": lambda v: max(0.0, float(v)),
        "spawn_retries": lambda v: max(0, int(v)),
        "prefill_replicas": lambda v: max(0, int(v)),
        "handoff_threshold_tokens": lambda v: max(0, int(v)),
        "min_prefill_replicas": lambda v: max(1, int(v)),
        "max_prefill_replicas": lambda v: max(1, int(v)),
    },
)
_config = _state.config
stats = _state.stats


def config() -> FleetConfig:
    return _state.config


def configure(
    enabled: bool | None = None,
    replicas: int | None = None,
    transport: str | None = None,
    request_timeout_s: float | None = None,
    autoscale: bool | None = None,
    min_replicas: int | None = None,
    max_replicas: int | None = None,
    scale_out_fraction: float | None = None,
    scale_in_fraction: float | None = None,
    scale_out_ticks: int | None = None,
    scale_in_ticks: int | None = None,
    scale_cooldown_s: float | None = None,
    scale_interval_s: float | None = None,
    spawn_retries: int | None = None,
    prefill_replicas: int | None = None,
    handoff_threshold_tokens: int | None = None,
    min_prefill_replicas: int | None = None,
    max_prefill_replicas: int | None = None,
) -> FleetConfig:
    return _state.configure(
        enabled=enabled,
        replicas=replicas,
        transport=transport,
        request_timeout_s=request_timeout_s,
        autoscale=autoscale,
        min_replicas=min_replicas,
        max_replicas=max_replicas,
        scale_out_fraction=scale_out_fraction,
        scale_in_fraction=scale_in_fraction,
        scale_out_ticks=scale_out_ticks,
        scale_in_ticks=scale_in_ticks,
        scale_cooldown_s=scale_cooldown_s,
        scale_interval_s=scale_interval_s,
        spawn_retries=spawn_retries,
        prefill_replicas=prefill_replicas,
        handoff_threshold_tokens=handoff_threshold_tokens,
        min_prefill_replicas=min_prefill_replicas,
        max_prefill_replicas=max_prefill_replicas,
    )


def reset_stats() -> None:
    _state.reset_stats()


def snapshot() -> dict:
    """Stats + config, the ``perf.fleet`` payload."""
    return _state.snapshot()


def armed() -> bool:
    """True when requests should route through the fleet: >= 2 replicas
    (a 1-replica fleet is just an engine with extra steps, served by
    the plain dispatch path) — OR an elastic fleet whose CEILING admits
    a second replica, because an autoscaled fleet may legitimately
    start at one replica and grow."""
    if not _config.enabled:
        return False
    if _config.autoscale and _config.max_replicas >= 2:
        return True
    return _config.replicas >= 2


def disagg_armed() -> bool:
    """True when the fleet is split into prefill/decode roles: a
    routable fleet with at least one prefill-role founder AND at least
    one decode replica left over."""
    return (
        armed()
        and _config.prefill_replicas > 0
        and _config.replicas > _config.prefill_replicas
    )


# -- the process fleet engine ----------------------------------------------
# Built lazily on first armed dispatch, rebuilt when the knobs that
# shape the topology change (the TpuEngine batcher_key precedent), and
# torn down explicitly by tests / the worker-transport harnesses.

_engine = None
_engine_key = None


def _topology_key():
    """(founder count, rebuild key) for the current config. Elastic
    founders start inside [floor, ceiling] — typically AT the floor,
    growing on demand (the bench's elastic arm). The prefill-role
    founder count shapes the topology too: flipping disaggregation on
    or off rebuilds the fleet with the roles re-tagged."""
    n = _config.replicas
    if _config.autoscale:
        n = max(_config.min_replicas, min(n, _config.max_replicas))
    return n, (
        n,
        _config.autoscale,
        _config.transport,
        _config.request_timeout_s,
        _config.prefill_replicas,
    )


def fleet_engine():
    """The process-wide FleetEngine for the current config (lazy; a
    config change retires the old fleet and builds a fresh one)."""
    global _engine, _engine_key
    n, key = _topology_key()
    if _engine is not None and _engine_key != key:
        _engine.shutdown()
        _engine = None
    if _engine is None:
        from adversarial_spec_tpu.fleet.router import FleetEngine

        _engine = FleetEngine(
            replicas=n,
            transport=_config.transport,
            request_timeout_s=_config.request_timeout_s,
            prefill_replicas=_config.prefill_replicas,
        )
        _engine_key = key
    return _engine


def install_engine(engine) -> None:
    """Replace the process fleet engine with a caller-built topology
    (harnesses and tests that need explicit worker envs / log dirs /
    kill triggers). The installed engine serves until the topology
    knobs change or ``shutdown_fleet`` runs."""
    global _engine, _engine_key
    if _engine is not None and _engine is not engine:
        _engine.shutdown()
    _engine = engine
    _engine_key = _topology_key()[1]


def shutdown_fleet() -> None:
    """Tear down the process fleet (tests; worker harness cleanup)."""
    global _engine, _engine_key
    if _engine is not None:
        _engine.shutdown()
    _engine = None
    _engine_key = None
