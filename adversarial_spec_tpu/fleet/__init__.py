"""Fleet layer: replicated engines behind a prefix-affinity router.

One engine process is a single point of failure — a dead process loses
every in-flight round and all device/host KV residency. This package is
ROADMAP item 3's first half: N engine replicas behind a router that

- **routes by prefix affinity** (fleet/hashring.py): the debate's
  affinity key consistent-hashes onto a replica, so every round of the
  same debate lands where its prefix KV already lives, and a membership
  change moves only ~1/N of the keyspace;
- **fails over on health + breakers** (fleet/router.py): per-replica
  heartbeats and per-(replica, model) circuit breakers
  (resilience/breaker.py ``replica_key``) drain a slow or dead replica
  — queued and in-flight requests re-route to the next replica on the
  ring;
- **recovers through the shared store**: replicas share the PR 7
  content-addressed disk store, so a failed-over request rehydrates its
  prefix KV on the new replica instead of re-prefilling, and the PR 10
  round journal keeps already-completed opponents from re-issuing.

Replicas come in two transports (fleet/replica.py): ``inproc`` wraps a
fresh engine instance in this process (deterministic, tier-1-testable),
``worker`` runs one per subprocess (``python -m
adversarial_spec_tpu.fleet.worker``) — the SIGKILL-able topology the
replica-kill chaos harness drives (``tools/chaos_run.py
--replica-kill``).

Process-wide config + stats follow the ``procconfig`` pattern shared
with ``interleave``/``spec``/``kvtier``: the CLI arms per round
(``--fleet``, ``--fleet-replicas``; env ``ADVSPEC_FLEET`` /
``ADVSPEC_FLEET_REPLICAS`` / ``ADVSPEC_FLEET_TRANSPORT``) and snapshots
into ``perf.fleet``. Deliberately imports no jax — the mock fleet runs
entirely on CPU.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

from adversarial_spec_tpu.engine import procconfig

DEFAULT_REPLICAS = 2
TRANSPORTS = ("inproc", "worker")


def env_enabled() -> bool:
    """The process default for the master switch (``ADVSPEC_FLEET``).
    Default OFF: a single engine stays the shipped topology until the
    operator opts a process into the fleet."""
    return os.environ.get("ADVSPEC_FLEET", "0") == "1"


def env_replicas() -> int:
    """The process default replica count (``ADVSPEC_FLEET_REPLICAS``)."""
    try:
        return max(1, int(os.environ.get("ADVSPEC_FLEET_REPLICAS", DEFAULT_REPLICAS)))
    except ValueError:
        return DEFAULT_REPLICAS


def env_transport() -> str:
    """The process default transport (``ADVSPEC_FLEET_TRANSPORT``)."""
    t = os.environ.get("ADVSPEC_FLEET_TRANSPORT", "inproc")
    return t if t in TRANSPORTS else "inproc"


@dataclass
class FleetConfig:
    """Process-wide knobs, set once per CLI round (or by tests)."""

    enabled: bool = False
    replicas: int = DEFAULT_REPLICAS
    # "inproc" (fresh engine instances in this process) or "worker"
    # (one subprocess per replica — SIGKILL-able, the chaos topology).
    transport: str = "inproc"
    # Per-request transport deadline for worker replicas, seconds: a
    # worker that stays silent this long is treated as dead and its
    # in-flight requests fail over (0 = wait forever).
    request_timeout_s: float = 30.0


def _coerce_transport(value) -> str:
    v = str(value)
    if v not in TRANSPORTS:
        # Fail AT THE KNOB (the γ precedent): a typo'd transport must
        # not silently fall back to inproc mid-deployment.
        raise ValueError(
            f"unknown fleet transport {v!r}; known: {', '.join(TRANSPORTS)}"
        )
    return v


@dataclass
class FleetStats(procconfig.StatsBase):
    """Process-wide fleet counters, aggregated across every router the
    process builds (one per config generation).

    ``affinity_hits`` counts requests served by the ring's PRIMARY
    choice for their key; ``routed_requests − affinity_hits`` is the
    hop traffic (breaker-open skips + failover re-routes), so
    ``affinity_hit_rate`` is the headline the fleet bench compares
    against random routing. ``reissued_requests`` counts requests that
    were re-routed after their replica died mid-flight — the work a
    replica loss costs; ``duplicated_completions`` counts completions
    that arrived for an already-resolved request and MUST stay zero
    (the lose-a-replica-lose-nothing invariant the chaos harness
    pins)."""

    routed_requests: int = 0
    affinity_hits: int = 0
    failover_hops: int = 0
    breaker_skips: int = 0
    reissued_requests: int = 0
    completed_requests: int = 0
    duplicated_completions: int = 0
    replicas_spawned: int = 0
    replicas_retired: int = 0
    heartbeats: int = 0
    heartbeat_failures: int = 0

    def snapshot(self) -> dict:
        out = self.as_dict()
        out["affinity_hit_rate"] = (
            round(self.affinity_hits / self.routed_requests, 4)
            if self.routed_requests
            else 0.0
        )
        return out


_state = procconfig.ProcState(
    FleetConfig(
        enabled=env_enabled(),
        replicas=env_replicas(),
        transport=env_transport(),
    ),
    FleetStats(),
    coerce={
        "replicas": lambda v: max(1, int(v)),
        "transport": _coerce_transport,
    },
)
_config = _state.config
stats = _state.stats


def config() -> FleetConfig:
    return _state.config


def configure(
    enabled: bool | None = None,
    replicas: int | None = None,
    transport: str | None = None,
    request_timeout_s: float | None = None,
) -> FleetConfig:
    return _state.configure(
        enabled=enabled,
        replicas=replicas,
        transport=transport,
        request_timeout_s=request_timeout_s,
    )


def reset_stats() -> None:
    _state.reset_stats()


def snapshot() -> dict:
    """Stats + config, the ``perf.fleet`` payload."""
    return _state.snapshot()


def armed() -> bool:
    """True when requests should route through the fleet (>= 2 replicas
    — a 1-replica fleet is just an engine with extra steps, served by
    the plain dispatch path)."""
    return _config.enabled and _config.replicas >= 2


# -- the process fleet engine ----------------------------------------------
# Built lazily on first armed dispatch, rebuilt when the knobs that
# shape the topology change (the TpuEngine batcher_key precedent), and
# torn down explicitly by tests / the worker-transport harnesses.

_engine = None
_engine_key = None


def fleet_engine():
    """The process-wide FleetEngine for the current config (lazy; a
    config change retires the old fleet and builds a fresh one)."""
    global _engine, _engine_key
    key = (_config.replicas, _config.transport, _config.request_timeout_s)
    if _engine is not None and _engine_key != key:
        _engine.shutdown()
        _engine = None
    if _engine is None:
        from adversarial_spec_tpu.fleet.router import FleetEngine

        _engine = FleetEngine(
            replicas=_config.replicas,
            transport=_config.transport,
            request_timeout_s=_config.request_timeout_s,
        )
        _engine_key = key
    return _engine


def install_engine(engine) -> None:
    """Replace the process fleet engine with a caller-built topology
    (harnesses and tests that need explicit worker envs / log dirs /
    kill triggers). The installed engine serves until the topology
    knobs change or ``shutdown_fleet`` runs."""
    global _engine, _engine_key
    if _engine is not None and _engine is not engine:
        _engine.shutdown()
    _engine = engine
    _engine_key = (_config.replicas, _config.transport, _config.request_timeout_s)


def shutdown_fleet() -> None:
    """Tear down the process fleet (tests; worker harness cleanup)."""
    global _engine, _engine_key
    if _engine is not None:
        _engine.shutdown()
    _engine = None
    _engine_key = None
