"""Elastic fleet: backlog-driven autoscaling with graceful membership
change and lose-nothing scale-in.

The :class:`Autoscaler` is a control loop between the serve
scheduler's token-backlog ledger (``ServeScheduler.pressure_snapshot``)
and the fleet router's membership hooks. It owns one lifecycle per
replica it manages::

    provisioning -> warming -> serving -> draining -> retired

with every terminal transition funnelled through ONE surgery,
:meth:`Autoscaler._decommission` (the fifth GL-LIFECYCLE machine —
``tools/graftlint`` enforces that every exit reaches it).

Scale-OUT — warm-before-ring. A new replica is spawned through the
bounded-retry hardening (:func:`fleet.replica.spawn_replica`; a typed
``SpawnFailed`` after the retries exhaust, counted, never a hot loop),
then WARMED — ping, shared-KV-store re-attach (engine construction
re-opens the fleet's DiskStore), and a weight-residency preload of the
hottest models in the scheduler's current mix — and only then admitted
to the hash ring via ``router.admit_replica``. Between spawn and
admission the replica is invisible to every routing path, so no
request ever routes to a cold replica. A replica that dies while
warming is decommissioned WITHOUT ever entering the ring
(:meth:`_abort_warm` closes its transport directly).

Scale-IN — lose-nothing handoff, the reverse order. The victim (the
LEAST-AFFINE routable replica: the one primarily owning the fewest
active debate keys, so the least warm prefix KV leaves with it) is
removed from the ring FIRST (``router.drain_replica`` — transport
stays open), in-flight units drain on it while new work routes to
survivors, then :meth:`_finish_scale_in` retires it through the
router's ``_retire_replica``. A victim that stalls past the drain
deadline is retired mid-batch: the transport close surfaces as
``ReplicaDead`` and the router's partial-merge + remainder re-route
machinery turns the retirement into a PLANNED handoff — exactly-once
``_resolve`` guarantees zero duplicated completions, and partial KV
survives via the shared DiskStore.

Flap control: a scale decision needs ``scale_out_ticks`` /
``scale_in_ticks`` CONSECUTIVE pressure readings (hysteresis) and is
suppressed inside ``scale_cooldown_s`` of the previous membership
change (counted in ``stats.flaps_suppressed``). Membership is clamped
to ``[min_replicas, max_replicas]`` — the floor and ceiling are hard.

Role-aware elasticity (disaggregated fleets): when the managed engine
is role-split (``prefill_replicas > 0``), the tick reads the
scheduler's per-role backlog split instead — prefill-token backlog
sizes the PREFILL pool, the decode remainder sizes the DECODE pool —
each under its own min/max (``min/max_prefill_replicas`` vs the
symmetric ``min/max_replicas``) and its own hysteresis streak. The
decisions run through the same spawn/warm/ring and drain/retire paths;
the per-replica lifecycle machine is reused unchanged.

The loop thread calls exactly :meth:`tick`; the deterministic drills
(``tests/test_autoscale.py``, ``tools/chaos_run.py --scale-storm``)
inject ``clock``/``sleep``/``rng`` and call ``tick()`` directly.
"""

from __future__ import annotations

import threading
import time

from adversarial_spec_tpu import fleet as fleet_mod
from adversarial_spec_tpu import obs as obs_mod
from adversarial_spec_tpu import serve as serve_mod
from adversarial_spec_tpu.fleet.replica import SpawnFailed
from adversarial_spec_tpu.resilience import lockdep as lockdep_mod

# Lifecycle states (one machine per managed replica).
PROVISIONING = "provisioning"
WARMING = "warming"
SERVING = "serving"
DRAINING = "draining"
RETIRED = "retired"

# How many of the hottest models from the scheduler's mix a fresh
# replica preloads before ring admission.
WARM_TOP_K = 4

# Poll cadence while waiting for a drain victim's in-flight count to
# reach zero (the injected ``sleep`` makes this deterministic in tests).
_DRAIN_POLL_S = 0.01


class Autoscaler:
    """Backlog-driven membership controller for one ``FleetEngine``.

    ``pressure`` is any zero-arg callable returning a
    ``pressure_snapshot``-shaped dict; it defaults to the given
    scheduler's. ``clock``/``sleep``/``rng`` are injectable for the
    mock-clock drills.
    """

    def __init__(
        self,
        engine,
        sched=None,
        *,
        pressure=None,
        clock=time.monotonic,
        sleep=time.sleep,
        rng=None,
        stats=None,
    ):
        self._engine = engine
        self._router = engine.router
        self._sched = sched
        if pressure is not None:
            self._pressure = pressure
        elif sched is not None:
            self._pressure = sched.pressure_snapshot
        else:
            self._pressure = None
        self._clock = clock
        self._sleep = sleep
        self._rng = rng
        self.stats = stats if stats is not None else fleet_mod.stats
        # Lifecycle-owned: replica id -> state. Founders enter at
        # SERVING — they were warm before this controller existed.
        self._members: dict[str, str] = {
            rid: SERVING for rid in self._router.alive_ids()
        }
        # Spawned-but-never-ringed handles; _decommission closes these
        # directly (the router never knew them).
        self._pending: dict[str, object] = {}
        self._out_streak = 0
        self._in_streak = 0
        # Per-role streaks (disaggregated fleets): each pool carries
        # its own hysteresis so prefill pressure cannot spend decode's
        # streak or vice versa. The shared cooldown still serializes
        # membership changes across pools.
        self._out_streaks: dict[str, int] = {}
        self._in_streaks: dict[str, int] = {}
        self._last_change_t: float | None = None
        self._last_backlog = 0
        self._desired = max(1, len(self._members))
        self._lock = lockdep_mod.make_rlock("Autoscaler._lock")
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- observers ---------------------------------------------------------

    def capacity_factor(self) -> int:
        """Routable replica count — wired into
        ``ServeScheduler.set_capacity_provider`` so the admission
        ceiling and brownout thresholds stretch with the fleet."""
        return max(1, len(self._router.alive_ids()))

    def member_state(self, rid: str) -> str | None:
        with self._lock:
            return self._members.get(rid)

    def members_snapshot(self) -> dict[str, str]:
        with self._lock:
            return dict(self._members)

    @property
    def desired(self) -> int:
        return self._desired

    # -- lifecycle mutators (GL-LIFECYCLE-sanctioned) ----------------------

    def _begin_provision(self, rid: str) -> None:
        self._members[rid] = PROVISIONING

    def _advance(self, rid: str, state: str) -> None:
        self._members[rid] = state

    # -- THE lifecycle surgery ---------------------------------------------

    def _decommission(self, rid: str, reason: str, direction: str = "") -> None:
        """Every terminal transition funnels here: mark the member
        RETIRED, then either close a never-ringed transport directly
        (aborted warm-up — the router never knew this replica) or
        retire a known replica through the router's own surgery
        (``_retire_replica``: dead-ledger, ring removal, transport
        close, telemetry — one place for both machines)."""
        state = self._members.get(rid)
        if state is None or state == RETIRED:
            return
        self._members[rid] = RETIRED
        pending = self._pending.pop(rid, None)
        if pending is not None:
            try:
                pending.close()
            except Exception:
                pass  # a dead transport may fail its own close
        else:
            self._router._retire_replica(rid, reason)
        self._emit(
            "retired", replica=rid, direction=direction, reason=reason
        )

    # -- lifecycle exits ---------------------------------------------------

    def _abort_warm(self, rid: str, reason: str) -> None:
        """Exit: the scale-out aborted BEFORE ring admission (spawn
        retries exhausted, or the replica died while warming). The
        replica was never routable, so nothing needs re-routing —
        decommission closes whatever transport exists."""
        self._emit(
            "spawn_failed", replica=rid, direction="out", reason=reason
        )
        if obs_mod.config().enabled:
            obs_mod.hot.fleet_scale("out", reason).inc()
        self._decommission(rid, reason, direction="out")

    def _finish_scale_in(self, rid: str) -> None:
        """Exit: the planned scale-in completes. The victim left the
        ring when draining began; if units are still in flight the
        transport close below surfaces as ``ReplicaDead`` and the
        router's remainder machinery re-routes them to survivors —
        the planned handoff, zero duplicated completions."""
        self.stats.scale_ins += 1
        if obs_mod.config().enabled:
            obs_mod.hot.fleet_scale("in", "idle").inc()
        self._decommission(rid, "scale_in", direction="in")

    def shutdown(self) -> None:
        """Exit: stop the loop, then decommission every member still
        mid-transition (provisioning/warming members never entered the
        ring; draining members finish their handoff now). SERVING
        members are left alone — the fleet engine's own shutdown owns
        them."""
        self.stop()
        with self._lock:
            for rid, st in list(self._members.items()):
                if st in (PROVISIONING, WARMING, DRAINING):
                    self._decommission(rid, "shutdown")

    # -- control loop ------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="advspec-autoscale", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None

    def begin_drain(self) -> None:
        """SIGTERM path: freeze scaling decisions — the daemon's drain
        owns the fleet's fate from here."""
        self._stop.set()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception:
                # The loop must outlive a bad tick (a dead controller
                # is silent un-elasticity); the desired/alive gauge
                # divergence and scale counters surface persistent
                # failure.
                pass
            self._stop.wait(max(fleet_mod.config().scale_interval_s, 0.001))

    # -- the decision ------------------------------------------------------

    def tick(self) -> bool:
        """One scaling decision; True if membership changed. The loop
        thread calls exactly this — the deterministic drills call it
        directly with injected clocks."""
        cfg = fleet_mod.config()
        snap = self._pressure() if self._pressure is not None else {}
        backlog = int(snap.get("backlog_tokens", 0))
        brownout = bool(snap.get("brownout", False))
        draining = bool(snap.get("draining", False))
        with self._lock:
            self._last_backlog = backlog
            self._reconcile()
            if self._disagg():
                return self._tick_disagg(cfg, snap, brownout, draining)
            serving = self._serving_ids()
            n = len(serving)
            per = serve_mod.config().max_backlog_tokens
            want_out = (
                not draining
                and n < cfg.max_replicas
                and (
                    brownout
                    or backlog >= cfg.scale_out_fraction * per * max(n, 1)
                )
            )
            # Scale-in asks: would the backlog still be comfortable on
            # one fewer replica? Measured against the SHRUNK capacity
            # so out/in thresholds cannot overlap (no flapping band).
            want_in = (
                not draining
                and not brownout
                and n > cfg.min_replicas
                and backlog
                <= cfg.scale_in_fraction * per * max(n - 1, 1)
            )
            self._out_streak = self._out_streak + 1 if want_out else 0
            self._in_streak = self._in_streak + 1 if want_in else 0
            decision = None
            if self._out_streak >= cfg.scale_out_ticks and want_out:
                decision = "out"
            elif self._in_streak >= cfg.scale_in_ticks and want_in:
                decision = "in"
            if decision is None:
                self._set_desired(max(n, cfg.min_replicas))
                return False
            now = self._clock()
            if (
                self._last_change_t is not None
                and now - self._last_change_t < cfg.scale_cooldown_s
            ):
                # Hysteresis fired but the cooldown vetoes: a flap the
                # controller refused to make.
                self.stats.flaps_suppressed += 1
                return False
            if decision == "out":
                reason = "brownout" if brownout else "backlog"
                return self._scale_out(snap, n, reason=reason, cfg=cfg)
            return self._scale_in(snap, n, cfg=cfg)

    def _disagg(self) -> bool:
        """Whether the managed fleet is role-split (prefill/decode
        disaggregation) — flips the tick to per-role decisions."""
        return getattr(self._engine, "prefill_replicas", 0) > 0

    def _tick_disagg(self, cfg, snap, brownout: bool, draining: bool) -> bool:
        """Role-aware decision (caller holds the lock): each pool
        reads ITS half of the scheduler's backlog split — prefill-
        token backlog sizes the prefill pool, the decode remainder
        sizes the decode pool — under its own min/max and its own
        hysteresis streak. The winning decision runs through the SAME
        spawn/warm/ring and drain/retire paths as a symmetric fleet;
        the lifecycle machine never learns about roles."""
        per = serve_mod.config().max_backlog_tokens
        pools = (
            (
                "prefill",
                int(snap.get("prefill_backlog_tokens", 0)),
                cfg.min_prefill_replicas,
                cfg.max_prefill_replicas,
            ),
            (
                "decode",
                int(snap.get("decode_backlog_tokens", 0)),
                cfg.min_replicas,
                cfg.max_replicas,
            ),
        )
        n_total = len(self._serving_ids())
        decision = None
        for role, backlog, lo, hi in pools:
            n = len(self._serving_ids(role))
            want_out = (
                not draining
                and n < hi
                and (
                    brownout
                    or backlog >= cfg.scale_out_fraction * per * max(n, 1)
                )
            )
            want_in = (
                not draining
                and not brownout
                and n > lo
                and backlog
                <= cfg.scale_in_fraction * per * max(n - 1, 1)
            )
            self._out_streaks[role] = (
                self._out_streaks.get(role, 0) + 1 if want_out else 0
            )
            self._in_streaks[role] = (
                self._in_streaks.get(role, 0) + 1 if want_in else 0
            )
            if decision is not None:
                continue  # streaks still advance for the other pool
            if self._out_streaks[role] >= cfg.scale_out_ticks and want_out:
                decision = (
                    role, "out", "brownout" if brownout else "backlog"
                )
            elif self._in_streaks[role] >= cfg.scale_in_ticks and want_in:
                decision = (role, "in", "idle")
        if decision is None:
            self._set_desired(
                max(n_total, cfg.min_replicas + cfg.min_prefill_replicas)
            )
            return False
        now = self._clock()
        if (
            self._last_change_t is not None
            and now - self._last_change_t < cfg.scale_cooldown_s
        ):
            self.stats.flaps_suppressed += 1
            return False
        role, direction, reason = decision
        if direction == "out":
            return self._scale_out(
                snap, n_total, reason=reason, cfg=cfg, role=role
            )
        return self._scale_in(snap, n_total, cfg=cfg, role=role)

    def _reconcile(self) -> None:
        """Members the ROUTER retired behind our back (transport
        fault, heartbeat miss) funnel through the surgery too, so the
        two machines never disagree about who is alive."""
        ring = set(self._router.alive_ids())
        for rid, st in list(self._members.items()):
            if st == SERVING and rid not in ring:
                self._decommission(
                    rid, self._router.retired_reason(rid) or "dead"
                )

    def _serving_ids(self, role: str | None = None) -> list[str]:
        ring = set(self._router.alive_ids())
        out = []
        for rid, st in self._members.items():
            if st != SERVING or rid not in ring:
                continue
            if role is not None:
                rep = self._router.replica(rid)
                if getattr(rep, "role", "") != role:
                    continue
            out.append(rid)
        return out

    # -- scale-out: spawn -> warm -> ping -> ring --------------------------

    def _scale_out(
        self, snap: dict, n: int, *, reason: str, cfg, role: str = ""
    ) -> bool:
        rid = self._engine.reserve_replica_id()
        self._set_desired(n + 1)
        self._begin_provision(rid)
        self._emit("provision", replica=rid, direction="out", reason=reason)
        try:
            rep = self._engine.spawn_replica(
                rid,
                role=role,
                retries=cfg.spawn_retries,
                sleep=self._sleep,
                rng=self._rng,
            )
        except SpawnFailed:
            self.stats.spawn_failures += 1
            self._reset_streak("out", role)
            self._last_change_t = self._clock()  # never loop hot
            self._set_desired(n)
            self._abort_warm(rid, "spawn_failed")
            return False
        self._pending[rid] = rep
        self._advance(rid, WARMING)
        self._emit("warming", replica=rid, direction="out", reason=reason)
        try:
            rep.warm(self._hot_models(snap))
            if not rep.ping():
                raise RuntimeError(f"{rid} failed post-warm ping")
        except Exception:
            # Died WHILE warming: never entered the ring, never will.
            self._reset_streak("out", role)
            self._last_change_t = self._clock()
            self._set_desired(n)
            self._abort_warm(rid, "warm_failed")
            return False
        self._pending.pop(rid, None)
        self._router.admit_replica(rep)
        self._advance(rid, SERVING)
        self.stats.scale_outs += 1
        if obs_mod.config().enabled:
            obs_mod.hot.fleet_scale("out", reason).inc()
        self._emit("serving", replica=rid, direction="out", reason=reason)
        self._last_change_t = self._clock()
        self._reset_streak("out", role)
        return True

    def _reset_streak(self, direction: str, role: str = "") -> None:
        if direction == "out":
            self._out_streak = 0
            if role:
                self._out_streaks[role] = 0
        else:
            self._in_streak = 0
            if role:
                self._in_streaks[role] = 0

    def _hot_models(self, snap: dict) -> list[str]:
        """Hottest models in the scheduler's active mix (already
        sorted hottest-first) — the warm-up's residency preload."""
        mix = snap.get("model_mix") or {}
        return list(mix)[:WARM_TOP_K]

    # -- scale-in: un-ring -> drain -> retire ------------------------------

    def _scale_in(self, snap: dict, n: int, *, cfg, role: str = "") -> bool:
        serving = self._serving_ids(role or None)
        floor = (
            cfg.min_prefill_replicas if role == "prefill"
            else cfg.min_replicas
        )
        if len(serving) <= floor:
            return False
        load = self._router.affinity_load(snap.get("active_keys") or [])
        # Least-affine loses; ties break toward the NEWEST replica
        # (its prefix cache had the least time to warm).
        victim = min(
            serving, key=lambda rid: (load.get(rid, 0), -self._rid_index(rid))
        )
        self._set_desired(n - 1)
        self._advance(victim, DRAINING)
        self._emit("draining", replica=victim, direction="in", reason="idle")
        self._router.drain_replica(victim)
        # Out of the ring, transport open: wait for in-flight units to
        # finish on the victim. The cooldown doubles as the drain
        # budget — the next membership change cannot happen sooner
        # anyway. A stalled victim is retired mid-batch and the
        # remainder machinery hands its units to survivors.
        deadline = self._clock() + max(cfg.scale_cooldown_s, _DRAIN_POLL_S)
        while (
            self._router.inflight(victim) > 0 and self._clock() < deadline
        ):
            # Deliberate sleep under the membership lock: membership
            # changes are serialized by design, and nothing on the
            # serving path blocks on this lock (capacity and pressure
            # reads go through lock-free snapshots).
            self._sleep(_DRAIN_POLL_S)  # graftlint: disable=GL-LOCK-BLOCKING -- drain poll; membership changes are intentionally serialized under this lock
        self._finish_scale_in(victim)
        self._last_change_t = self._clock()
        self._reset_streak("in", role)
        return True

    @staticmethod
    def _rid_index(rid: str) -> int:
        try:
            return int(rid.lstrip("r"))
        except ValueError:
            return 0

    # -- telemetry ---------------------------------------------------------

    def _set_desired(self, desired: int) -> None:
        self._desired = desired
        if obs_mod.config().enabled:
            obs_mod.hot.fleet_replicas_desired.set(float(desired))

    def _emit(
        self, op: str, *, replica: str = "", direction: str = "", reason: str = ""
    ) -> None:
        if obs_mod.config().enabled:
            obs_mod.hot.fleet_replicas_desired.set(float(self._desired))
        obs_mod.emit(
            obs_mod.ScaleEvent(
                replica=replica,
                op=op,
                direction=direction,
                reason=reason,
                desired=self._desired,
                alive=len(self._router.alive_ids()),
                backlog_tokens=self._last_backlog,
            )
        )
