"""Cross-replica paged-KV handoff ledger: prefill → ship → adopt.

Disaggregated serving splits one request across two replicas: a
PREFILL-role replica runs admission + prefill and publishes the
produced KV blocks to the shared content-addressed store
(engine/kvtier.py demote-to-disk path), then a DECODE-role replica
adopts them — its first step starts from a tier hit instead of a
re-prefill. Between those two halves sits a race the fleet must never
lose *incorrectly*: the store write may be partial (prefill replica
SIGKILLed mid-publish), the shipped blocks may be quarantined or
evicted before the decode side promotes them, or the prefill replica
may simply die. Every one of those degrades to a LOCAL prefill on the
decode replica with byte-identical transcripts — the handoff is a
latency optimization, never a correctness dependency.

This module owns the bookkeeping for that contract as a one-way
lifecycle machine (graftlint ``handoff_lifecycle`` pins it):

    PLANNED → PREFILLING → PUBLISHED → {adopted | degraded | abandoned}

A handoff is born through the ``begin`` mutator and leaves through
exactly one of three exits — ``_finish_adopt`` (the decode replica
confirmed the shipped blocks in the store), ``_degrade`` (lost the
race: store miss, partial publish, replica death — decode side
re-prefills locally) or ``_abandon`` (the plan never produced blocks).
All three funnel into the ONE surgery, ``_publish_blocks``, the only
writer of the terminal-outcome ledger: fleet stats, the
``advspec_kv_handoff_total{outcome}`` counter and the handoff-latency
histogram all update in that single place, so a handoff can neither
be double-counted nor vanish between states.
"""

from __future__ import annotations

import dataclasses
import time

from adversarial_spec_tpu import fleet as fleet_mod
from adversarial_spec_tpu import obs as obs_mod

# Handoff states (one-way; terminal outcomes are lowercase because
# they double as the {outcome} metric label).
PLANNED = "PLANNED"
PREFILLING = "PREFILLING"
PUBLISHED = "PUBLISHED"

ADOPTED = "adopted"
DEGRADED = "degraded"
ABANDONED = "abandoned"

OUTCOMES = (ADOPTED, DEGRADED, ABANDONED)


@dataclasses.dataclass
class HandoffRecord:
    """One in-flight handoff: which key ships from where to where."""

    key: str
    prefill_replica: str
    decode_replica: str
    state: str = PLANNED
    chains: list = dataclasses.field(default_factory=list)
    blocks: int = 0
    reason: str = ""
    started_t: float = 0.0
    wall_s: float = 0.0


class HandoffLedger:
    """Tracks every cross-replica KV handoff from plan to outcome.

    The terminal ledger ``_outcomes`` is lifecycle-OWNED: written only
    by the ``_publish_blocks`` surgery (and ``__init__``); the router's
    orchestration moves records through the non-terminal states via
    the ``note_*`` helpers, which mutate the record, never the ledger.
    """

    def __init__(self, stats=None, clock=time.monotonic):
        self._clock = clock
        self._stats = stats
        # In-flight handoffs by affinity key (born via ``begin``).
        self._active: dict[str, HandoffRecord] = {}
        # Terminal outcome per key — written ONLY by the
        # _publish_blocks surgery (GL-LIFECYCLE handoff machine).
        self._outcomes: dict[str, str] = {}

    # -- reads -------------------------------------------------------------

    def active(self, key: str) -> HandoffRecord | None:
        return self._active.get(key)

    def outcome(self, key: str) -> str | None:
        return self._outcomes.get(key)

    def seen(self, key: str) -> bool:
        """Whether ``key`` already has a handoff in flight or decided —
        a debate's later rounds reuse the first round's shipped KV via
        the ordinary prefix path, so they never re-handoff."""
        return key in self._active or key in self._outcomes

    def snapshot(self) -> dict:
        counts = {o: 0 for o in OUTCOMES}
        for outcome in self._outcomes.values():
            counts[outcome] = counts.get(outcome, 0) + 1
        return {"active": len(self._active), **counts}

    # -- mutator (record birth) --------------------------------------------

    def begin(
        self, key: str, prefill_replica: str, decode_replica: str
    ) -> HandoffRecord:
        """Plan one handoff: ``key``'s prefill runs on
        ``prefill_replica``, its decode on ``decode_replica``."""
        rec = HandoffRecord(
            key=key,
            prefill_replica=prefill_replica,
            decode_replica=decode_replica,
            started_t=self._clock(),
        )
        self._active[key] = rec
        stats = self._stats if self._stats is not None else fleet_mod.stats
        stats.handoff_attempts += 1
        return rec

    # -- non-terminal transitions (record fields, not the ledger) ----------

    def note_prefilling(self, key: str) -> None:
        rec = self._active.get(key)
        if rec is not None:
            rec.state = PREFILLING

    def note_published(self, key: str, chains, blocks: int) -> None:
        rec = self._active.get(key)
        if rec is not None:
            rec.state = PUBLISHED
            rec.chains = list(chains)
            rec.blocks = int(blocks)

    # -- lifecycle surgery + exits -----------------------------------------

    def _publish_blocks(
        self, key: str, outcome: str, reason: str = ""
    ) -> HandoffRecord | None:
        """THE handoff surgery: every exit funnels here. Pops the
        in-flight record, writes the terminal outcome (the ONLY write
        to ``_outcomes``), and updates stats + telemetry exactly once.
        Idempotent: a key that already reached an outcome is a no-op
        (the first decision stands — zero double-counting)."""
        rec = self._active.pop(key, None)
        if rec is None or key in self._outcomes:
            return None
        self._outcomes[key] = outcome
        rec.state = outcome
        rec.reason = reason
        rec.wall_s = max(0.0, self._clock() - rec.started_t)
        stats = self._stats if self._stats is not None else fleet_mod.stats
        if outcome == ADOPTED:
            stats.handoff_adopted += 1
        elif outcome == DEGRADED:
            stats.handoff_degraded += 1
        else:
            stats.handoff_abandoned += 1
        if rec.blocks:
            stats.handoff_shipped_blocks += rec.blocks
        if obs_mod.config().enabled:
            obs_mod.hot.handoff(outcome).inc()
            obs_mod.hot.handoff_latency.observe(rec.wall_s)
        return rec

    def _finish_adopt(self, key: str) -> HandoffRecord | None:
        """Exit: the decode replica confirmed the shipped chains in the
        shared store — its first step is a tier hit."""
        return self._publish_blocks(key, ADOPTED)

    def _degrade(self, key: str, reason: str = "") -> HandoffRecord | None:
        """Exit: the handoff lost the race (store miss, partial
        publish, prefill-replica death) — the decode replica prefills
        locally; transcripts stay byte-identical."""
        return self._publish_blocks(key, DEGRADED, reason)

    def _abandon(self, key: str, reason: str = "") -> HandoffRecord | None:
        """Exit: the plan never produced publishable blocks (nothing
        shipped, nothing to adopt)."""
        return self._publish_blocks(key, ABANDONED, reason)
