"""Consistent-hash ring: affinity keys → replicas, stable under churn.

The routing problem prefix affinity sets: every round of one debate
must land on the SAME replica (that's where its prefix KV lives), and
when a replica joins or leaves, only the debates that hashed to the
affected arc may move — a modulo hash would reshuffle (N−1)/N of all
keys on every membership change and cold every replica's cache at
once.

Classic ring with virtual nodes: each replica owns ``vnodes`` points
on a 2^64 ring (sha256 of ``"<replica>#<k>"``), a key routes to the
first point clockwise from its own hash, and ``preference()`` keeps
walking to produce the failover order — the same order every caller
computes, with no coordination. Everything is deterministic (sha256,
no process randomness), so tests and the chaos harness can predict the
primary replica for a key.

Roles (fleet disaggregation): a node may carry a role tag
(``"prefill"`` / ``"decode"``; ``""`` = any). ``preference(role=...)``
walks the SAME ring but skips foreign-role owners, so a role filter
never perturbs the walk order of the nodes it keeps — membership
change inside a role pool still moves ~1/N of that pool's keys, and
only to the newcomer, exactly the un-roled guarantee scoped per pool.
Untagged nodes serve every role (the symmetric-fleet degenerate case).
"""

from __future__ import annotations

import bisect
import hashlib

DEFAULT_VNODES = 64


def _point(data: str) -> int:
    """A stable 64-bit ring position for a string."""
    return int.from_bytes(
        hashlib.sha256(data.encode("utf-8")).digest()[:8], "big"
    )


class HashRing:
    """Replica ids on a consistent-hash ring with virtual nodes."""

    def __init__(self, nodes=(), vnodes: int = DEFAULT_VNODES):
        self.vnodes = max(1, int(vnodes))
        self._points: list[int] = []  # sorted ring positions
        self._owner: dict[int, str] = {}  # position -> replica id
        self._nodes: set[str] = set()
        self._roles: dict[str, str] = {}  # node -> role ("" = any)
        for n in nodes:
            self.add(n)

    def __len__(self) -> int:
        return len(self._nodes)

    def __contains__(self, node: str) -> bool:
        return node in self._nodes

    @property
    def nodes(self) -> set[str]:
        return set(self._nodes)

    def role_of(self, node: str) -> str:
        """The node's role tag ("" = untagged, serves any role)."""
        return self._roles.get(node, "")

    def role_nodes(self, role: str) -> set[str]:
        """Nodes eligible for ``role``: tagged with it, or untagged."""
        return {
            n for n in self._nodes if self._roles.get(n, "") in ("", role)
        }

    def add(self, node: str, role: str = "") -> None:
        if node in self._nodes:
            return
        self._nodes.add(node)
        if role:
            self._roles[node] = role
        for k in range(self.vnodes):
            p = _point(f"{node}#{k}")
            # sha256 collisions between distinct vnode labels are not a
            # practical concern; first owner keeps the point.
            if p not in self._owner:
                self._owner[p] = node
                bisect.insort(self._points, p)

    def remove(self, node: str) -> None:
        if node not in self._nodes:
            return
        self._nodes.discard(node)
        self._roles.pop(node, None)
        dead = [p for p, n in self._owner.items() if n == node]
        for p in dead:
            del self._owner[p]
        dead_set = set(dead)
        self._points = [p for p in self._points if p not in dead_set]

    def primary(self, key: str, role: str | None = None) -> str | None:
        """The replica owning ``key`` (None on an empty ring / empty
        role pool)."""
        pref = self.preference(key, limit=1, role=role)
        return pref[0] if pref else None

    def preference(
        self,
        key: str,
        limit: int | None = None,
        role: str | None = None,
    ) -> list[str]:
        """Distinct replicas in ring-walk order from ``key``'s hash —
        element 0 is the affinity primary, the rest the deterministic
        failover order every caller agrees on. ``role`` filters the
        walk to that role's pool (tagged-with-it or untagged nodes)
        WITHOUT perturbing the kept nodes' relative order — the role
        pool behaves as its own consistent ring."""
        if not self._points:
            return []
        eligible = (
            self._nodes if role is None else self.role_nodes(role)
        )
        if not eligible:
            return []
        limit = (
            len(eligible) if limit is None else min(limit, len(eligible))
        )
        out: list[str] = []
        seen: set[str] = set()
        start = bisect.bisect_left(self._points, _point(key))
        for i in range(len(self._points)):
            owner = self._owner[self._points[(start + i) % len(self._points)]]
            if owner not in seen and owner in eligible:
                seen.add(owner)
                out.append(owner)
                if len(out) >= limit:
                    break
        return out
