"""Replica transports: one engine behind one id, in-process or worker.

The router (fleet/router.py) speaks to replicas through one tiny
surface — ``chat_batch`` (serve a request group, delivering each
completion the moment it resolves), ``ping`` (health probe),
``check`` (allocator/tier invariants, the chaos harness's survivor
assertion), ``stats`` (per-model serve counts + cache accounting) and
``close``. Two transports implement it:

- :class:`InProcessReplica` — a FRESH engine instance per replica
  (``engine.dispatch.new_engine``, the replica lifecycle seam: the
  process-wide engine cache is exactly what a fleet must NOT share,
  or every "replica" would be the same prefix cache). Deterministic,
  tier-1-testable, and the fleet bench's substrate.
- :class:`WorkerReplica` — one subprocess per replica (``python -m
  adversarial_spec_tpu.fleet.worker``) over a line-delimited JSON
  pipe protocol. The worker serves requests ONE AT A TIME and writes
  each completion line as it finishes, so a SIGKILL mid-batch loses
  only the unserved remainder — the router keeps what already
  arrived and fails the rest over. This is the topology
  ``tools/chaos_run.py --replica-kill`` SIGKILLs.

A dead transport raises :class:`ReplicaDead` carrying the completions
that resolved before death (``partial``) — the router's no-work-lost
contract starts here.
"""

from __future__ import annotations

import dataclasses
import json
import os
import select
import subprocess
import sys
import time
from pathlib import Path

from adversarial_spec_tpu.debate.usage import Usage
from adversarial_spec_tpu.engine.types import ChatRequest, Completion, SamplingParams
from adversarial_spec_tpu.resilience.faults import FaultKind

_REPO = Path(__file__).resolve().parent.parent.parent


class ReplicaDead(RuntimeError):
    """The replica's transport died (process gone, pipe closed, or a
    request deadline expired with the worker silent). Carries the
    completions that resolved BEFORE death, keyed by the submitted
    batch's local index — the router keeps them and re-routes only the
    remainder."""

    def __init__(
        self, replica: str, why: str, partial: dict[int, Completion] | None = None
    ):
        super().__init__(f"UNAVAILABLE: replica {replica} {why}")
        self.fault_kind = FaultKind.DEVICE_LOST
        self.seam = "replica"
        self.replica = replica
        self.partial = dict(partial or {})


class SpawnFailed(RuntimeError):
    """Replica provisioning exhausted its bounded retry: every spawn
    attempt either failed outright or came up unable to answer a ping.
    Typed so the autoscaler can COUNT it (stats.spawn_failures) and
    enter cooldown instead of hot-looping on a broken spawn path; the
    replica never existed as far as the ring is concerned."""

    def __init__(self, replica: str, attempts: int, why: str):
        super().__init__(
            f"UNAVAILABLE: replica {replica} failed to spawn after "
            f"{attempts} attempt(s): {why}"
        )
        self.fault_kind = FaultKind.DEVICE_LOST
        self.seam = "replica"
        self.replica = replica
        self.attempts = attempts


# -- wire codec (worker protocol; also reused by the worker itself) --------


def request_to_wire(req: ChatRequest) -> dict:
    return dataclasses.asdict(req)


def request_from_wire(obj: dict) -> ChatRequest:
    known = {f.name for f in dataclasses.fields(ChatRequest)}
    return ChatRequest(**{k: v for k, v in obj.items() if k in known})


def params_to_wire(params: SamplingParams) -> dict:
    return dataclasses.asdict(params)


def params_from_wire(obj: dict) -> SamplingParams:
    known = {f.name for f in dataclasses.fields(SamplingParams)}
    return SamplingParams(**{k: v for k, v in obj.items() if k in known})


def completion_to_wire(comp: Completion) -> dict:
    return {
        "text": comp.text,
        "error": comp.error,
        "transient": bool(comp.transient),
        "cancelled": bool(comp.cancelled),
        "usage": dataclasses.asdict(comp.usage),
    }


def completion_from_wire(obj: dict) -> Completion:
    u = obj.get("usage") or {}
    known = {f.name for f in dataclasses.fields(Usage)}
    return Completion(
        text=obj.get("text", ""),
        error=obj.get("error"),
        transient=bool(obj.get("transient", False)),
        cancelled=bool(obj.get("cancelled", False)),
        usage=Usage(**{k: v for k, v in u.items() if k in known}),
    )


def check_engine_invariants(engine) -> None:
    """Allocator + tier ``check_invariants`` for one replica's engine
    (raises on drift). Duck-typed on the mock engine's accounting
    handles — the chaos topology's replicas are mock workers; a real
    TPU engine's invariants are pinned by the scheduler suite."""
    alloc = getattr(engine, "_allocator", None)
    if alloc is not None:
        alloc.check_invariants()
    prefix = getattr(engine, "_prefix", None)
    if prefix is not None and getattr(prefix, "tiers", None) is not None:
        prefix.tiers.check_invariants()
    # Weight-residency conservation (engine/weightres.py): both engines
    # expose ``ledger`` (the mock's is None until its simulation arms);
    # the real engine's stricter ledger↔engine mirror rides along.
    if getattr(engine, "ledger", None) is not None:
        if hasattr(engine, "check_residency_invariants"):
            engine.check_residency_invariants()
        else:
            engine.ledger.check_invariants()


class InProcessReplica:
    """A fresh engine instance (per provider) behind a replica id."""

    def __init__(self, replica_id: str, engine_factory=None, role: str = ""):
        self.id = replica_id
        # Disaggregation role ("prefill" / "decode"; "" = any) — the
        # hash ring tags this node with it so role-filtered preference
        # walks keep ordinary traffic off prefill replicas.
        self.role = role
        # The lifecycle seam: fresh engines, NOT dispatch's process-wide
        # cache — each replica must own its allocator/prefix cache.
        if engine_factory is None:
            from adversarial_spec_tpu.engine.dispatch import new_engine

            engine_factory = new_engine
        self._engine_factory = engine_factory
        self._engines: dict[str, object] = {}
        self.served: dict[str, int] = {}  # model -> completions served
        self.busy_s: float = 0.0  # synthetic/real service seconds
        self.closed = False

    def _engine_for(self, model: str):
        key = model.partition("://")[0]
        eng = self._engines.get(key)
        if eng is None:
            eng = self._engines[key] = self._engine_factory(model)
        return eng

    def ping(self) -> bool:
        return not self.closed

    def warm(self, models: list[str]) -> int:
        """Pre-build the serving state for ``models`` BEFORE the router
        admits this replica to the ring (fleet/autoscale.py warm-before-
        ring contract): engine construction re-attaches the shared
        DiskStore (so prefix KV written by the rest of the fleet
        rehydrates instead of re-prefilling) and the weight-residency
        preload hint (engine/weightres.py) pre-touches the hottest
        models from the scheduler's model mix so the first routed
        request pays no cold load. Returns the models warmed."""
        if self.closed:
            raise ReplicaDead(self.id, "is closed")
        from adversarial_spec_tpu.engine import weightres as weightres_mod

        for model in models:
            eng = self._engine_for(model)
            ledger = getattr(eng, "ledger", None)
            if ledger is not None:
                # Freshen LRU standing for an already-admitted alias;
                # actual admission happens on first serve (the ledger's
                # one admission surgery), which the hint accounts for.
                ledger.touch(model)
        weightres_mod.preload_hint(models)
        return len(models)

    def chat_batch(
        self, requests, params, consumer=None, on_completion=None
    ) -> list[Completion]:
        """Serve the group as ONE batched ``chat`` per provider — the
        engine's batch dimension is the whole design (N co-resident
        opponents are N rows of one sharded decode, engine/types.py),
        and an in-process replica cannot die mid-batch, so there is
        nothing to buy by serializing. Only the WORKER transport serves
        one request at a time: its crash contract needs each completion
        durable on the pipe before the next decodes. Completions are
        delivered through ``on_completion(local_index, completion)``
        after each provider group resolves."""
        if self.closed:
            raise ReplicaDead(self.id, "is closed")
        results: list[Completion | None] = [None] * len(requests)
        by_provider: dict[str, list[int]] = {}
        for j, req in enumerate(requests):
            by_provider.setdefault(
                req.model.partition("://")[0], []
            ).append(j)
        for idxs in by_provider.values():
            engine = self._engine_for(requests[idxs[0]].model)
            wrapped = None
            if consumer is not None:
                # The consumer speaks the ORIGINAL batch's indexing;
                # remap this provider sub-batch's rows back to it.
                wrapped = (
                    lambda row, text, idxs=idxs: consumer(idxs[row], text)
                )
            comps = engine.chat(
                [requests[j] for j in idxs], params, consumer=wrapped
            )
            for row, j in enumerate(idxs):
                comp = comps[row]
                results[j] = comp
                self.served[requests[j].model] = (
                    self.served.get(requests[j].model, 0) + 1
                )
                u = comp.usage
                # Synthetic service seconds on the mock's tokens/1024
                # scale (prefill actually computed + decode produced):
                # the fleet bench's per-replica busy clock.
                self.busy_s += (
                    max(u.input_tokens - u.cached_tokens, 0)
                    + u.output_tokens
                ) / 1024.0
                if on_completion is not None:
                    on_completion(j, comp)
        return results  # type: ignore[return-value]

    def prefill(self, requests, params) -> list[dict]:
        """Disaggregated prefill: run admission + prefill ONLY for the
        group (no decode), publish the produced KV blocks to the
        shared store, and return each request's chain hashes — the
        handoff hint the decode-side replica prefetches against. Per
        provider group, mirroring ``chat_batch``."""
        if self.closed:
            raise ReplicaDead(self.id, "is closed")
        results: list[dict | None] = [None] * len(requests)
        by_provider: dict[str, list[int]] = {}
        for j, req in enumerate(requests):
            by_provider.setdefault(
                req.model.partition("://")[0], []
            ).append(j)
        for idxs in by_provider.values():
            engine = self._engine_for(requests[idxs[0]].model)
            outs = engine.prefill([requests[j] for j in idxs], params)
            for row, j in enumerate(idxs):
                out = outs[row]
                results[j] = out
                # Prefill seconds on the same synthetic tokens/1024
                # clock chat_batch uses (no decode half).
                self.busy_s += max(int(out.get("new_tokens", 0)), 0) / 1024.0
        return results  # type: ignore[return-value]

    def prefetch(self, model: str, chains) -> int:
        """Decode-side handoff hint: probe the shared store for the
        shipped chains (promoting what it can ahead of the adopting
        request). Returns how many of ``chains`` are available."""
        if self.closed:
            raise ReplicaDead(self.id, "is closed")
        engine = self._engine_for(model)
        if hasattr(engine, "prefetch"):
            return int(engine.prefetch(chains))
        return 0

    def validate(self, model: str) -> str | None:
        try:
            return self._engine_for(model).validate(model)
        except ValueError as e:
            # An unknown provider id is a validation VERDICT here, not
            # a crash — the preflight wants the actionable message.
            return str(e)

    def check(self) -> None:
        for eng in self._engines.values():
            check_engine_invariants(eng)

    def stats(self) -> dict:
        return {
            "replica": self.id,
            "role": self.role,
            "served": dict(self.served),
            "busy_s": round(self.busy_s, 6),
        }

    def close(self) -> None:
        self.closed = True
        self._engines.clear()


class WorkerReplica:
    """One subprocess per replica over line-delimited JSON pipes."""

    def __init__(
        self,
        replica_id: str,
        request_timeout_s: float = 30.0,
        env: dict | None = None,
        log_dir: str | None = None,
        role: str = "",
    ):
        self.id = replica_id
        self.role = role
        self.request_timeout_s = float(request_timeout_s)
        self._env = dict(env) if env is not None else None
        self._log_dir = log_dir
        self.closed = False
        self._proc: subprocess.Popen | None = None
        self._log = None
        # Our own receive buffer over the RAW stdout fd. select() only
        # sees bytes still in the kernel pipe — a buffered reader that
        # slurped two back-to-back lines (a completion plus its done
        # marker) would leave the second one invisible to select and
        # stall a healthy replica into a false ReplicaDead, so all
        # reads go through os.read + this buffer, never readline().
        self._rbuf = bytearray()
        self._spawn()

    @property
    def pid(self) -> int | None:
        return self._proc.pid if self._proc is not None else None

    def _spawn(self) -> None:
        env = dict(os.environ if self._env is None else self._env)
        # A worker must never build its own fleet (infinite recursion);
        # it is one replica, full stop.
        env["ADVSPEC_FLEET"] = "0"
        env["PYTHONPATH"] = (
            f"{_REPO}{os.pathsep}{env['PYTHONPATH']}"
            if env.get("PYTHONPATH")
            else str(_REPO)
        )
        stderr = subprocess.DEVNULL
        if self._log_dir:
            os.makedirs(self._log_dir, exist_ok=True)
            # The replica's stderr log: an OS-owned append stream for
            # post-mortems (the chaos drill reads it when a worker
            # misbehaves) — not a torn-write risk, sanctioned in
            # [tool.graftlint] atomic_funcs.
            self._log = open(
                os.path.join(self._log_dir, f"{self.id}.stderr.log"), "w"
            )
            stderr = self._log
        # Binary, unbuffered pipes: the reader below selects on the raw
        # fd and must never race a Python-level buffer (see _rbuf).
        argv = [
            sys.executable,
            "-m",
            "adversarial_spec_tpu.fleet.worker",
            "--replica-id",
            self.id,
        ]
        if self.role:
            argv += ["--role", self.role]
        self._proc = subprocess.Popen(
            argv,
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            stderr=stderr,
            bufsize=0,
            env=env,
        )

    # -- protocol ----------------------------------------------------------

    def _send(self, obj: dict) -> None:
        proc = self._proc
        if self.closed or proc is None or proc.poll() is not None:
            raise ReplicaDead(self.id, "process is gone")
        try:
            proc.stdin.write(
                (json.dumps(obj, separators=(",", ":")) + "\n").encode("utf-8")
            )
            proc.stdin.flush()
        except (BrokenPipeError, OSError, ValueError) as e:
            raise ReplicaDead(self.id, f"pipe write failed ({e})") from e

    def _read_line(self, timeout_s: float) -> dict:
        proc = self._proc
        deadline = time.monotonic() + timeout_s if timeout_s > 0 else None
        while True:
            # Serve a complete line from the receive buffer FIRST: the
            # worker writes lines back to back, and bytes already read
            # off the pipe are invisible to select().
            nl = self._rbuf.find(b"\n")
            if nl >= 0:
                raw = bytes(self._rbuf[:nl]).strip()
                del self._rbuf[: nl + 1]
                if not raw:
                    continue
                try:
                    return json.loads(raw.decode("utf-8"))
                except (json.JSONDecodeError, UnicodeDecodeError) as e:
                    raise ReplicaDead(
                        self.id, f"spoke garbage ({e})"
                    ) from e
            wait = (
                max(0.0, deadline - time.monotonic())
                if deadline is not None
                else 1.0
            )
            if deadline is not None and wait <= 0.0:
                raise ReplicaDead(
                    self.id,
                    f"silent past the {timeout_s:.1f}s request deadline",
                )
            ready, _, _ = select.select([proc.stdout], [], [], wait)
            if not ready:
                if proc.poll() is not None:
                    raise ReplicaDead(self.id, "process died mid-request")
                continue
            chunk = os.read(proc.stdout.fileno(), 1 << 16)
            if not chunk:
                raise ReplicaDead(self.id, "closed its pipe mid-request")
            self._rbuf += chunk

    def ping(self, timeout_s: float | None = None) -> bool:
        try:
            self._send({"op": "ping"})
            resp = self._read_line(
                timeout_s if timeout_s is not None else self.request_timeout_s
            )
            return bool(resp.get("pong"))
        except ReplicaDead:
            return False

    def chat_batch(
        self, requests, params, consumer=None, on_completion=None
    ) -> list[Completion]:
        """Serve the group through the worker. The consumer seam does
        not cross the process boundary (per-token callbacks over a
        pipe would serialize the decode) — worker replicas serve the
        blocking path; completions still stream back one line each, so
        a mid-batch death loses only the unserved remainder."""
        self._send(
            {
                "op": "chat",
                "requests": [request_to_wire(r) for r in requests],
                "params": params_to_wire(params),
            }
        )
        got: dict[int, Completion] = {}
        try:
            while len(got) < len(requests):
                obj = self._read_line(self.request_timeout_s)
                if obj.get("done"):
                    break
                j = int(obj.get("i", -1))
                if not 0 <= j < len(requests) or j in got:
                    raise ReplicaDead(
                        self.id, f"answered out of protocol (i={j})", got
                    )
                comp = completion_from_wire(obj.get("completion") or {})
                got[j] = comp
                if on_completion is not None:
                    on_completion(j, comp)
            if len(got) == len(requests):
                # Drain the done marker so the pipe stays aligned.
                obj = self._read_line(self.request_timeout_s)
                if not obj.get("done"):
                    raise ReplicaDead(
                        self.id, "missed its done marker", got
                    )
            else:
                raise ReplicaDead(
                    self.id,
                    f"finished early ({len(got)}/{len(requests)})",
                    got,
                )
        except ReplicaDead as e:
            if not e.partial:
                e.partial = dict(got)
            raise
        return [got[j] for j in range(len(requests))]

    def prefill(self, requests, params) -> list[dict]:
        """Disaggregated prefill through the worker (``prefill`` op).
        The worker settles each request's blocks to the shared store
        BEFORE flushing its result line, so every result that arrives
        here is durable — a SIGKILL mid-publish loses only the
        unflushed remainder, which the ReplicaDead ``partial`` carries
        back for the partial-publish degradation decision."""
        self._send(
            {
                "op": "prefill",
                "requests": [request_to_wire(r) for r in requests],
                "params": params_to_wire(params),
            }
        )
        got: dict[int, dict] = {}
        try:
            while len(got) < len(requests):
                obj = self._read_line(self.request_timeout_s)
                if obj.get("done"):
                    break
                j = int(obj.get("i", -1))
                if not 0 <= j < len(requests) or j in got:
                    raise ReplicaDead(
                        self.id, f"answered out of protocol (i={j})", got
                    )
                got[j] = dict(obj.get("result") or {})
            if len(got) == len(requests):
                obj = self._read_line(self.request_timeout_s)
                if not obj.get("done"):
                    raise ReplicaDead(
                        self.id, "missed its done marker", got
                    )
            else:
                raise ReplicaDead(
                    self.id,
                    f"finished early ({len(got)}/{len(requests)})",
                    got,
                )
        except ReplicaDead as e:
            if not e.partial:
                e.partial = dict(got)
            raise
        return [got[j] for j in range(len(requests))]

    def prefetch(self, model: str, chains) -> int:
        """Decode-side handoff hint through the worker (``prefetch``
        op): how many shipped chains its shared store can serve."""
        self._send(
            {"op": "prefetch", "model": model, "chains": list(chains)}
        )
        resp = self._read_line(self.request_timeout_s)
        return int(resp.get("found", 0))

    def warm(self, models: list[str]) -> int:
        """Worker-side warm (fleet/worker.py ``warm`` op): the worker
        builds its engines for ``models`` — shared-store re-attach plus
        the weight-residency preload hint — before this replica is ever
        routable. Raises ReplicaDead if the worker dies mid-warm; the
        autoscaler decommissions it without it ever entering the ring."""
        self._send({"op": "warm", "models": list(models)})
        resp = self._read_line(self.request_timeout_s)
        return int(resp.get("warmed", 0))

    def validate(self, model: str) -> str | None:
        self._send({"op": "validate", "model": model})
        resp = self._read_line(self.request_timeout_s)
        return resp.get("error")

    def check(self) -> None:
        self._send({"op": "check"})
        resp = self._read_line(self.request_timeout_s)
        if not resp.get("ok"):
            raise RuntimeError(
                f"replica {self.id} invariants violated: {resp.get('error')}"
            )

    def stats(self) -> dict:
        self._send({"op": "stats"})
        return self._read_line(self.request_timeout_s)

    def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        proc = self._proc
        if proc is not None:
            try:
                if proc.poll() is None:
                    proc.stdin.write(b'{"op":"shutdown"}\n')
                    proc.stdin.flush()
                    proc.wait(timeout=2.0)
            except (BrokenPipeError, OSError, ValueError, subprocess.TimeoutExpired):
                pass
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=5.0)
            for stream in (proc.stdin, proc.stdout):
                try:
                    if stream is not None:
                        stream.close()
                except OSError:
                    pass
        if self._log is not None:
            self._log.close()
            self._log = None


def spawn_replica(
    replica_id: str,
    transport: str = "inproc",
    *,
    retries: int = 3,
    backoff_base_s: float = 0.05,
    sleep=time.sleep,
    rng=None,
    engine_factory=None,
    request_timeout_s: float = 30.0,
    worker_env: dict | None = None,
    log_dir: str | None = None,
    role: str = "",
):
    """Provision one replica with BOUNDED retry: each attempt spawns
    the transport and requires a ping answer; a failed attempt is torn
    down and retried after a jittered exponential backoff
    (``backoff_base_s * 2^k * (0.5 + U[0,1))`` — the jitter keeps N
    autoscalers from stampeding a recovering host). After ``retries``
    extra attempts the typed :class:`SpawnFailed` propagates — the
    caller counts it and enters cooldown; this helper NEVER loops
    unbounded. ``sleep``/``rng`` are injectable for deterministic
    tests."""
    if rng is None:
        import random

        rng = random.random
    last_why = "never attempted"
    attempts = max(1, int(retries) + 1)
    for attempt in range(attempts):
        if attempt:
            sleep(backoff_base_s * (2 ** (attempt - 1)) * (0.5 + rng()))
        rep = None
        try:
            if transport == "worker":
                rep = WorkerReplica(
                    replica_id,
                    request_timeout_s=request_timeout_s,
                    env=worker_env,
                    log_dir=log_dir,
                    role=role,
                )
            else:
                rep = InProcessReplica(
                    replica_id, engine_factory=engine_factory, role=role
                )
            if not rep.ping():
                raise ReplicaDead(replica_id, "never answered its ping")
            return rep
        except (ReplicaDead, OSError) as e:
            last_why = str(e)
            if rep is not None:
                try:
                    rep.close()
                except Exception:
                    pass
    raise SpawnFailed(replica_id, attempts, last_why)
