"""Fleet router: prefix-affinity placement + breaker-aware failover.

The router owns the fleet's three contracts:

- **Placement.** Each request's affinity key (stamped by the debate
  layer: one stable id per debate) consistent-hashes onto a replica
  (fleet/hashring.py), so every round of one debate lands where its
  prefix KV already lives and a membership change moves only ~1/N of
  the keyspace. ``affinity=False`` (the bench's control arm) routes
  round-robin instead.
- **Failover.** Before dispatch the router consults the
  per-(replica, model) circuit breaker
  (``resilience.breaker.replica_key``) — a pair that keeps faulting is
  skipped without a probe until its cooldown. A replica whose
  TRANSPORT dies (:class:`fleet.replica.ReplicaDead`) is retired
  through the one shared surgery (``_retire_replica``: out of the
  ring, transport closed, telemetry) and every unresolved request
  re-routes to the next replica in ring order. Completions that
  arrived before the death are kept — a replica loss re-pays only the
  in-flight remainder.
- **Recovery.** Replicas share the content-addressed disk store
  (engine/kvtier.py), so a failed-over request's prefix rehydrates on
  its new replica instead of re-prefilling; the round journal
  (debate/journal.py) keeps opponents that already COMPLETED from
  ever re-issuing. Both are pinned end to end by ``tools/chaos_run.py
  --replica-kill``.

The chaos injector's ``replica`` seam fires before every group
dispatch: an injected fault there exercises the breaker-skip path
(the replica stays alive; its (replica, model) pairs absorb the
failure) without killing any process.

**Disaggregation** (``prefill_replicas > 0``): the fleet splits into a
PREFILL pool and a DECODE pool (role tags on the hash ring). Ordinary
chat traffic routes decode-side only; an admission whose estimated
prefill tokens clear ``handoff_threshold_tokens`` first runs
admission + prefill on a prefill-role replica, which publishes the
produced KV blocks to the shared disk store and returns the chain
hashes. The decode replica — chosen at the SAME time — receives a
prefetch hint (the chain list) so engine/kvtier.py promotes the
shipped blocks overlapped with the tail of the remote prefill; its
first step starts from a tier hit. A handoff that loses the race
(store miss, partial publish, prefill-replica death) degrades to a
local prefill with byte-identical transcripts — the lifecycle ledger
(fleet/handoff.py) pins every path to exactly one outcome.
"""

from __future__ import annotations

import threading

from adversarial_spec_tpu import fleet as fleet_mod
from adversarial_spec_tpu import obs as obs_mod
from adversarial_spec_tpu.engine.types import ChatRequest, Completion, SamplingParams
from adversarial_spec_tpu.fleet.handoff import HandoffLedger
from adversarial_spec_tpu.fleet.hashring import HashRing
from adversarial_spec_tpu.fleet.replica import (
    InProcessReplica,
    ReplicaDead,
    WorkerReplica,
    spawn_replica,
)
from adversarial_spec_tpu.resilience import breaker as breaker_mod
from adversarial_spec_tpu.resilience import faults as faults_mod
from adversarial_spec_tpu.resilience import lockdep as lockdep_mod
from adversarial_spec_tpu.resilience import injector


class FleetRouter:
    """Routes request groups across replicas; owns the replica
    lifecycle state machine (alive → retired, one-way, through
    ``_retire_replica`` only)."""

    def __init__(
        self,
        replicas,
        *,
        breakers: breaker_mod.BreakerRegistry | None = None,
        affinity: bool = True,
        stats=None,
    ):
        self._replicas = {r.id: r for r in replicas}
        self._ring = HashRing()
        for r in replicas:
            self._ring.add(r.id, getattr(r, "role", ""))
        # Role every ORDINARY chat routes under (None = any replica;
        # "decode" when the fleet is disaggregated — prefill replicas
        # then only ever see the explicit handoff hop).
        self.route_role: str | None = None
        # Retired replicas and why — the lifecycle surgery's ledger,
        # written ONLY by _retire_replica (GL-LIFECYCLE pins this).
        self._dead: dict[str, str] = {}
        # Membership lock: the autoscaler mutates ring membership from
        # its own thread while daemon debate threads walk preference
        # orders mid-submit — ring reads and membership writes both
        # take it (RLock: a locked path may re-enter through the
        # retirement surgery).
        self._mlock = lockdep_mod.make_rlock("FleetRouter._mlock")
        # Per-replica in-flight request counts (submit increments
        # around each dispatch): the scale-in drain watches this reach
        # zero before retiring the victim.
        self._inflight: dict[str, int] = {}
        self._affinity = bool(affinity)
        self._rr = 0  # round-robin cursor (affinity=False control arm)
        self._breakers = (
            breakers if breakers is not None else breaker_mod.default_registry()
        )
        self.stats = stats if stats is not None else fleet_mod.stats
        if obs_mod.config().enabled:
            obs_mod.hot.fleet_replicas_alive.set(len(self._ring))

    # -- membership --------------------------------------------------------

    def alive_ids(self, role: str | None = None) -> list[str]:
        with self._mlock:
            nodes = (
                self._ring.nodes
                if role is None
                else self._ring.role_nodes(role)
            )
            return sorted(nodes)

    def replica(self, rid: str):
        return self._replicas.get(rid)

    def retired_reason(self, rid: str) -> str | None:
        """Why a replica left service (``None`` while alive) — the
        autoscaler's reconciliation reads this when the router retires
        a member behind its back."""
        with self._mlock:
            return self._dead.get(rid)

    def admit_replica(self, rep) -> bool:
        """Ring-change hook for scale-OUT (fleet/autoscale.py): admit
        a replica to the hash ring, making it routable. The caller
        MUST have spawned, pinged, and WARMED it first — between spawn
        and this call the replica is invisible to every routing path
        (the warm-before-ring contract the elasticity tests pin), so
        no request can ever land on a cold replica. Returns False for
        a retired or already-ringed id (idempotent)."""
        rid = rep.id
        with self._mlock:
            if rid in self._dead or rid in self._ring.nodes:
                return False
            self._replicas[rid] = rep
            self._ring.add(rid, getattr(rep, "role", ""))
            alive = len(self._ring)
        if obs_mod.config().enabled:
            obs_mod.hot.replica_op("ready").inc()
            obs_mod.hot.fleet_replicas_alive.set(alive)
        obs_mod.emit(
            obs_mod.ReplicaEvent(replica=rid, op="ready", alive=alive)
        )
        return True

    def drain_replica(self, rid: str) -> bool:
        """Ring-change hook for scale-IN (fleet/autoscale.py): take a
        replica OUT of the ring while its transport stays open — new
        requests route to survivors (and the shared store lets their
        prefixes rehydrate there), in-flight units keep completing on
        the victim. NOT a lifecycle exit: the replica is alive until
        the autoscaler's drain wait finishes and ``_retire_replica``
        runs; a victim that stalls past the drain deadline is retired
        mid-batch and the ReplicaDead-remainder machinery re-routes
        the rest — the planned-handoff half of the drain contract."""
        with self._mlock:
            if rid in self._dead or rid not in self._ring.nodes:
                return False
            self._ring.remove(rid)
            alive = len(self._ring)
        if obs_mod.config().enabled:
            obs_mod.hot.fleet_replicas_alive.set(alive)
        return True

    def inflight(self, rid: str) -> int:
        """Requests currently dispatched to ``rid`` (the scale-in
        drain's wait condition)."""
        with self._mlock:
            return self._inflight.get(rid, 0)

    def affinity_load(self, keys) -> dict[str, int]:
        """How many of the given affinity keys each ROUTABLE replica
        primarily owns — the least-affine victim picker's input (the
        replica owning the fewest active keys loses the least warm
        prefix KV when it leaves the ring)."""
        with self._mlock:
            out: dict[str, int] = {rid: 0 for rid in self._ring.nodes}
            if not out:
                return out
            for key in keys:
                rid = self._ring.primary(str(key))
                if rid in out:
                    out[rid] += 1
            return out

    def _retire_replica(self, rid: str, reason: str) -> None:
        """THE lifecycle surgery: every path that removes a replica
        from service funnels here (transport failure, heartbeat miss,
        planned scale-in, orderly shutdown) — ring membership, the
        dead-ledger, the transport close, and the telemetry stay in
        one place."""
        with self._mlock:
            if rid in self._dead or rid not in self._replicas:
                return
            self._dead[rid] = reason
            self._ring.remove(rid)
            alive = len(self._ring)
        try:
            self._replicas[rid].close()
        except Exception:
            pass  # a dead transport may fail its own close
        self.stats.replicas_retired += 1
        if obs_mod.config().enabled:
            obs_mod.hot.replica_op("retire").inc()
            obs_mod.hot.fleet_replicas_alive.set(alive)
        obs_mod.emit(
            obs_mod.ReplicaEvent(
                replica=rid, op="retire", reason=reason, alive=alive
            )
        )

    def _on_replica_fault(self, rid: str, exc: BaseException) -> None:
        """A replica's transport died mid-service: classify, count,
        retire."""
        faults_mod.record(faults_mod.classify(exc), "replica")
        self._retire_replica(rid, "dead")

    def _heartbeat_failure(self, rid: str) -> None:
        self._retire_replica(rid, "heartbeat")

    def shutdown(self) -> None:
        for rid in self.alive_ids():
            self._retire_replica(rid, "shutdown")
        obs_mod.emit(obs_mod.ReplicaEvent(op="shutdown", alive=0))
        if obs_mod.config().enabled:
            obs_mod.hot.replica_op("shutdown").inc()

    def health_check(self) -> None:
        """One heartbeat round: ping every routable replica; a miss
        drains it (retire + re-route of anything later submitted)."""
        for rid in self.alive_ids():
            rep = self._replicas[rid]
            self.stats.heartbeats += 1
            ok = False
            try:
                ok = rep.ping()
            except Exception:
                ok = False
            if not ok:
                self.stats.heartbeat_failures += 1
                if obs_mod.config().enabled:
                    obs_mod.hot.replica_op("heartbeat_miss").inc()
                obs_mod.emit(
                    obs_mod.ReplicaEvent(
                        replica=rid,
                        op="heartbeat_miss",
                        alive=len(self.alive_ids()),
                    )
                )
                self._heartbeat_failure(rid)

    def check_invariants(self) -> None:
        """Allocator/tier invariants on every routable replica."""
        for rid in self.alive_ids():
            self._replicas[rid].check()

    def replica_stats(self) -> list[dict]:
        return [self._replicas[rid].stats() for rid in self.alive_ids()]

    # -- routing -----------------------------------------------------------

    @staticmethod
    def affinity_key(req: ChatRequest) -> str:
        return req.affinity_key or req.model

    def _choose(
        self, req: ChatRequest, excluded: set[str]
    ) -> tuple[str | None, str, bool]:
        """Pick the replica for one request: (replica id | None,
        route reason, is-affinity-primary). Walks the ring's
        deterministic preference order (or round-robin with affinity
        off), skipping excluded replicas (prior failover hops this
        submit) and open (replica, model) breakers."""
        key = self.affinity_key(req)
        if self._affinity:
            # Under the membership lock: the autoscaler inserts/removes
            # vnode points from its own thread, and a preference walk
            # racing an insort would misread the ring.
            with self._mlock:
                order = self._ring.preference(key, role=self.route_role)
                if not order and self.route_role is not None:
                    # The routed role's pool emptied (every decode
                    # replica died): spill to the other pool rather
                    # than fail — availability beats specialization.
                    order = self._ring.preference(key)
            reason = "affinity"
        else:
            alive = self.alive_ids(role=self.route_role) or self.alive_ids()
            with self._mlock:
                self._rr += 1
                cut = self._rr % len(alive) if alive else 0
            order = alive[cut:] + alive[:cut]
            reason = "random"
        primary = order[0] if order else None
        for rid in order:
            if rid in excluded:
                reason = "failover"
                continue
            if not self._breakers.allow(
                breaker_mod.replica_key(rid, req.model)
            ):
                self.stats.breaker_skips += 1
                reason = "breaker_open"
                continue
            return rid, reason, rid == primary and self._affinity
        return None, reason, False

    def handoff_pair(self, key: str) -> tuple[str | None, str | None]:
        """The (prefill, decode) replica pair ``key`` hashes to — both
        chosen at the SAME time, from the same ring walk, so the
        prefetch hint can race ahead of the remote prefill. ``None``
        entries mean that role's pool is empty."""
        with self._mlock:
            pre = self._ring.preference(key, limit=1, role="prefill")
            dec = self._ring.preference(key, limit=1, role="decode")
        return (pre[0] if pre else None, dec[0] if dec else None)

    def _record_route(
        self, i: int, req: ChatRequest, rid: str, hop: int, reason: str,
        is_primary: bool,
    ) -> None:
        self.stats.routed_requests += 1
        if is_primary:
            self.stats.affinity_hits += 1
        if hop > 0:
            self.stats.failover_hops += 1
        if obs_mod.config().enabled:
            obs_mod.hot.route(reason).inc()
            obs_mod.hot.fleet_affinity_ratio.set(
                round(
                    self.stats.affinity_hits / self.stats.routed_requests, 6
                )
            )
        obs_mod.emit(
            obs_mod.RouteEvent(
                replica=rid,
                req_id=i,
                key=self.affinity_key(req),
                model=req.model,
                hop=hop,
                reason=reason,
                trace_id=req.trace_id,
                span_id=req.span_id,
            )
        )

    def _resolve(
        self, rid: str, i: int, req: ChatRequest, comp: Completion, results
    ) -> None:
        """Finalize one request's completion — exactly once. A second
        completion for an already-resolved request (a zombie replica
        answering after its retirement) is counted and DROPPED: the
        zero-duplicates invariant the chaos harness pins."""
        if results[i] is not None:
            self.stats.duplicated_completions += 1
            return
        results[i] = comp
        self.stats.completed_requests += 1
        pair = breaker_mod.replica_key(rid, req.model)
        if comp.ok:
            self._breakers.record(pair, ok=True)
        else:
            self._breakers.record(
                pair,
                ok=False,
                kind=faults_mod.classify_message(comp.error or ""),
            )

    def submit(
        self,
        requests: list[ChatRequest],
        params: SamplingParams,
        consumer=None,
    ) -> list[Completion]:
        """Serve one request group across the fleet. Requests sharing
        an affinity primary dispatch as one batch to it; a replica
        death mid-group keeps the completions that landed and re-routes
        only the remainder (hop+1), until every request resolves or no
        routable replica remains."""
        n = len(requests)
        results: list[Completion | None] = [None] * n
        hops = [0] * n
        excluded: list[set[str]] = [set() for _ in range(n)]
        pending = list(range(n))
        while pending:
            assign: dict[str, list[int]] = {}
            for i in pending:
                rid, reason, is_primary = self._choose(
                    requests[i], excluded[i]
                )
                if rid is None:
                    results[i] = Completion(
                        error=(
                            "UNAVAILABLE: fleet has no routable replica "
                            f"for {requests[i].model} "
                            f"({len(self._dead)} retired, "  # graftlint: disable=GL-LOCK-GUARD -- diagnostic count in an error string; a stale read is harmless
                            f"{self.stats.breaker_skips} breaker skip(s))"
                        ),
                        transient=False,
                    )
                    continue
                self._record_route(
                    i, requests[i], rid, hops[i], reason, is_primary
                )
                assign.setdefault(rid, []).append(i)
            pending = []
            for rid, idxs in assign.items():
                rep = self._replicas[rid]
                batch = [requests[i] for i in idxs]
                got: dict[int, Completion] = {}
                wrapped = None
                if consumer is not None:
                    # The consumer speaks the fleet batch's indexing;
                    # remap each sub-batch row back to it.
                    wrapped = (
                        lambda j, text, idxs=idxs: consumer(idxs[j], text)
                    )
                with self._mlock:
                    self._inflight[rid] = (
                        self._inflight.get(rid, 0) + len(idxs)
                    )
                try:
                    # The replica chaos seam: an injected fault here is
                    # a replica-level failure the breakers absorb — the
                    # process stays up, the pair opens, routing drains.
                    injector.fire("replica")
                    rep.chat_batch(
                        batch,
                        params,
                        consumer=wrapped,
                        on_completion=lambda j, c: got.__setitem__(j, c),
                    )
                except ReplicaDead as e:
                    for j, comp in e.partial.items():
                        got.setdefault(j, comp)
                    for j, comp in sorted(got.items()):
                        self._resolve(rid, idxs[j], batch[j], comp, results)
                    self._on_replica_fault(rid, e)
                    for i in idxs:
                        if results[i] is None:
                            excluded[i].add(rid)
                            hops[i] += 1
                            self.stats.reissued_requests += 1
                            pending.append(i)
                    continue
                except injector.InjectedFault as e:
                    kind = faults_mod.classify(e)
                    faults_mod.record(kind, "replica")
                    for i in idxs:
                        self._breakers.record(
                            breaker_mod.replica_key(rid, requests[i].model),
                            ok=False,
                            kind=kind,
                        )
                        excluded[i].add(rid)
                        hops[i] += 1
                        pending.append(i)
                    continue
                finally:
                    with self._mlock:
                        self._inflight[rid] = max(
                            0, self._inflight.get(rid, 0) - len(idxs)
                        )
                for j, comp in sorted(got.items()):
                    self._resolve(rid, idxs[j], batch[j], comp, results)
                for i in idxs:
                    if results[i] is None:
                        # The transport returned without this request's
                        # completion: treat as a failover hop.
                        excluded[i].add(rid)
                        hops[i] += 1
                        self.stats.reissued_requests += 1
                        pending.append(i)
        return results  # type: ignore[return-value]


class FleetEngine:
    """The Engine-protocol face of a replica fleet: ``chat`` routes
    through the fleet router; the debate layer cannot tell it from a
    single engine (grouping, retries, breakers, journaling all work
    unchanged — that is the point)."""

    def __init__(
        self,
        replicas: int = 2,
        transport: str = "inproc",
        request_timeout_s: float = 30.0,
        *,
        engine_factory=None,
        breakers: breaker_mod.BreakerRegistry | None = None,
        affinity: bool = True,
        worker_env: dict | None = None,
        log_dir: str | None = None,
        stats=None,
        prefill_replicas: int = 0,
        handoff_threshold_tokens: int | None = None,
    ):
        n = max(1, int(replicas))
        # Disaggregation: the first P founders take the prefill role,
        # the rest decode; at least one decode replica always remains
        # (P is clamped), and P=0 keeps every node untagged — the
        # symmetric fleet, byte-identical to the pre-disagg topology.
        p = max(0, min(int(prefill_replicas), n - 1))
        built = []
        for k in range(n):
            rid = f"r{k}"
            role = ("prefill" if k < p else "decode") if p else ""
            if transport == "worker":
                rep = WorkerReplica(
                    rid,
                    request_timeout_s=request_timeout_s,
                    env=worker_env,
                    log_dir=log_dir,
                    role=role,
                )
            else:
                rep = InProcessReplica(
                    rid, engine_factory=engine_factory, role=role
                )
            built.append(rep)
            (stats if stats is not None else fleet_mod.stats).replicas_spawned += 1
            if obs_mod.config().enabled:
                obs_mod.hot.replica_op("spawn").inc()
            obs_mod.emit(
                obs_mod.ReplicaEvent(replica=rid, op="spawn", alive=k + 1)
            )
        # Topology parameters kept for elastic growth: the autoscaler's
        # spawn_replica() must build replicas indistinguishable from
        # the founders (same transport, factory, env, timeout).
        self.transport = transport
        self.request_timeout_s = request_timeout_s
        self._engine_factory = engine_factory
        self._worker_env = worker_env
        self._log_dir = log_dir
        self._stats = stats if stats is not None else fleet_mod.stats
        self._next_rid = n
        self.prefill_replicas = p
        self.handoff_threshold_tokens = (
            fleet_mod.config().handoff_threshold_tokens
            if handoff_threshold_tokens is None
            else max(0, int(handoff_threshold_tokens))
        )
        self.handoff = HandoffLedger(stats=stats)
        self.router = FleetRouter(
            built, breakers=breakers, affinity=affinity, stats=stats
        )
        if p:
            # Ordinary chat traffic never lands on a prefill replica.
            self.router.route_role = "decode"

    def reserve_replica_id(self) -> str:
        """Mint the next replica id WITHOUT spawning — the autoscaler
        declares the provisioning state (and emits its ScaleEvent)
        before the first spawn attempt runs."""
        rid = f"r{self._next_rid}"
        self._next_rid += 1
        return rid

    def spawn_replica(
        self,
        rid: str | None = None,
        *,
        role: str = "",
        retries: int = 3,
        backoff_base_s: float = 0.05,
        sleep=None,
        rng=None,
    ):
        """Provision one NEW replica matching this fleet's topology,
        through the bounded-retry spawn hardening
        (:func:`fleet.replica.spawn_replica` — a typed ``SpawnFailed``
        propagates after the retries exhaust). The returned handle is
        NOT routable: the caller must warm it and then admit it via
        ``router.admit_replica`` (the warm-before-ring contract)."""
        import time as _time

        if rid is None:
            rid = self.reserve_replica_id()
        rep = spawn_replica(
            rid,
            self.transport,
            retries=retries,
            backoff_base_s=backoff_base_s,
            sleep=sleep if sleep is not None else _time.sleep,
            rng=rng,
            engine_factory=self._engine_factory,
            request_timeout_s=self.request_timeout_s,
            worker_env=self._worker_env,
            log_dir=self._log_dir,
            role=role,
        )
        self._stats.replicas_spawned += 1
        if obs_mod.config().enabled:
            obs_mod.hot.replica_op("spawn").inc()
        obs_mod.emit(
            obs_mod.ReplicaEvent(
                replica=rid, op="spawn", alive=len(self.router.alive_ids())
            )
        )
        return rep

    # -- disaggregated prefill/decode handoff ------------------------------

    @staticmethod
    def estimate_prefill_tokens(req: ChatRequest) -> int:
        """Estimated prefill tokens for one request — the admission
        threshold's input, on the mock tokenizer's 4-chars-per-token
        scale (system + separator + user)."""
        return (len(req.system) + 1 + len(req.user)) // 4

    def disagg_armed(self) -> bool:
        """Whether a handoff can run right now: the fleet was built
        disaggregated AND both role pools still have routable
        members (a dead prefill pool silently disarms — every
        admission just prefills locally, the degradation contract)."""
        return bool(
            self.prefill_replicas
            and self.router.alive_ids("prefill")
            and self.router.alive_ids("decode")
        )

    def _run_handoff(self, key, batch, req_ids, params, pre_rid, dec_rid):
        """Drive ONE handoff through its lifecycle: remote prefill on
        ``pre_rid`` → publish to the shared store → prefetch hint to
        ``dec_rid``. Every path lands in exactly one ledger exit; a
        lost race degrades (the decode side prefills locally with
        byte-identical output) rather than erroring."""
        self.handoff.begin(key, pre_rid, dec_rid)
        for i, req in zip(req_ids, batch):
            if obs_mod.config().enabled:
                obs_mod.hot.route("prefill").inc()
            obs_mod.emit(
                obs_mod.RouteEvent(
                    replica=pre_rid,
                    req_id=i,
                    key=key,
                    model=req.model,
                    hop=0,
                    reason="prefill",
                    trace_id=req.trace_id,
                    span_id=req.span_id,
                )
            )
        self.handoff.note_prefilling(key)
        rep = self.router.replica(pre_rid)
        try:
            outs = rep.prefill(batch, params)
        except ReplicaDead as e:
            # The prefill replica died mid-publish. Results that hit
            # the wire before death are DURABLE (the worker settles
            # the store before flushing each line): a complete partial
            # set still ships; anything less degrades to local prefill.
            self.router._on_replica_fault(pre_rid, e)
            outs = [e.partial.get(j) for j in range(len(batch))]
            if any(o is None for o in outs):
                self.handoff._degrade(key, "partial_publish")
                return
        except Exception:
            self.handoff._degrade(key, "prefill_error")
            return
        chains: list[str] = []
        seen: set[str] = set()
        blocks = 0
        for o in outs:
            for c in o.get("chains", ()):
                if c not in seen:
                    seen.add(c)
                    chains.append(c)
            blocks += int(o.get("blocks", 0))
        if not chains:
            # Nothing page-aligned to ship (prompt below one KV page).
            self.handoff._abandon(key, "no_blocks")
            return
        self.handoff.note_published(key, chains, blocks)
        dec = self.router.replica(dec_rid)
        try:
            found = dec.prefetch(batch[0].model, chains)
        except ReplicaDead as e:
            self.router._on_replica_fault(dec_rid, e)
            self.handoff._degrade(key, "decode_dead")
            return
        except Exception:
            found = 0
        if found >= len(chains):
            self.handoff._finish_adopt(key)
        else:
            self.handoff._degrade(key, "store_miss")

    def _maybe_handoff(self, requests, params) -> None:
        """The routing split: admissions whose estimated prefill
        clears the threshold run their prefill on a prefill-role
        replica first. Grouped per affinity key — one handoff per
        debate; later rounds ride the shipped prefix through the
        ordinary tier path and never re-handoff."""
        threshold = self.handoff_threshold_tokens
        groups: dict[str, list[int]] = {}
        for i, req in enumerate(requests):
            groups.setdefault(self.router.affinity_key(req), []).append(i)
        for key, idxs in groups.items():
            if self.handoff.seen(key):
                continue
            est = max(
                self.estimate_prefill_tokens(requests[i]) for i in idxs
            )
            if est < threshold:
                continue
            pre_rid, dec_rid = self.router.handoff_pair(key)
            if pre_rid is None or dec_rid is None or pre_rid == dec_rid:
                continue
            self._run_handoff(
                key, [requests[i] for i in idxs], idxs, params,
                pre_rid, dec_rid,
            )

    def chat(
        self,
        requests: list[ChatRequest],
        params: SamplingParams,
        consumer=None,
    ) -> list[Completion]:
        self.router.health_check()
        if self.disagg_armed():
            self._maybe_handoff(requests, params)
        return self.router.submit(requests, params, consumer=consumer)

    def validate(self, model: str) -> str | None:
        last = f"fleet has no routable replica to validate {model!r}"
        for rid in self.router.alive_ids():
            try:
                return self.router.replica(rid).validate(model)
            except ReplicaDead as e:
                last = str(e)
                continue
        return last

    def shutdown(self) -> None:
        self.router.shutdown()
