"""Fleet worker: one engine replica as a subprocess.

``python -m adversarial_spec_tpu.fleet.worker --replica-id r0`` serves
the line-delimited JSON protocol :class:`fleet.replica.WorkerReplica`
speaks over stdin/stdout:

- ``{"op": "chat", "requests": [...], "params": {...}}`` — serve the
  group ONE REQUEST AT A TIME, writing ``{"i": <n>, "completion":
  {...}}`` the moment each resolves, then ``{"done": true, "served":
  <n>}``. Incremental delivery is the crash contract: a SIGKILL
  mid-batch loses only the unserved remainder, and the router keeps
  every line that landed.
- ``{"op": "ping"}`` → ``{"pong": true}`` — the heartbeat probe.
- ``{"op": "warm", "models": [...]}`` → ``{"warmed": <n>}`` — build
  the engines for the given models (shared-store re-attach + weight-
  residency preload hint) BEFORE the autoscaler admits this replica
  to the ring, so no request ever routes to a cold worker.
- ``{"op": "check"}`` → allocator + tier ``check_invariants`` on the
  worker's engines (the chaos harness's clean-survivor assertion).
- ``{"op": "stats"}`` → per-model serve counts plus the worker's
  prefix-cache / kv-tier accounting (the store-coherent-recovery
  assertion reads ``rehydrated_tokens`` here).
- ``{"op": "validate", "model": ...}`` / ``{"op": "shutdown"}``.

Trace ids ride the wire inside each request (``trace_id``/``span_id``
fields), so every event this process emits resolves back to the round
and opponent that caused it — the replica hop is invisible to causal
tracing.

Disaggregation ops (``--role prefill`` workers are the shipping end
of a cross-replica KV handoff):

- ``{"op": "prefill", "requests": [...], "params": {...}}`` — run
  admission + prefill ONLY, settle the produced blocks to the shared
  store, then write ``{"i": <n>, "result": {"chains": [...],
  "blocks": <b>, ...}}`` per request and a done marker. Each result
  line flushes only AFTER its blocks are durable, so a SIGKILL
  mid-publish loses exactly the unflushed remainder — the
  partial-publish degradation the router handles.
- ``{"op": "prefetch", "model": ..., "chains": [...]}`` →
  ``{"found": <n>}`` — the decode-side hint probe.
- ``{"op": "role"}`` → ``{"role": ...}``.

``ADVSPEC_REPLICA_KILL_AFTER`` is the chaos trigger (mirroring the
journal's ``ADVSPEC_JOURNAL_KILL_AFTER``): ``N`` or
``<replica-id>:N`` SIGKILLs THIS process the instant its N-th
completion line is flushed — a real kill at a deterministic
mid-round point (``tools/chaos_run.py --replica-kill``).
``ADVSPEC_PREFILL_KILL_AFTER`` is the same trigger counted on PREFILL
result lines instead (``tools/chaos_run.py --handoff-kill``: the
prefill replica dies after its blocks are durable but before the
decode side promotes them).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import traceback

from adversarial_spec_tpu.engine.dispatch import new_engine
from adversarial_spec_tpu.fleet.replica import (
    check_engine_invariants,
    completion_to_wire,
    params_from_wire,
    request_from_wire,
)


def _kill_after(replica_id: str, var: str = "ADVSPEC_REPLICA_KILL_AFTER") -> int:
    """Parse a kill trigger (``N`` arms every worker, ``<id>:N`` arms
    only the named replica). 0 = disarmed."""
    raw = os.environ.get(var, "")
    if not raw:
        return 0
    target, sep, n = raw.rpartition(":")
    if sep and target and target != replica_id:
        return 0
    try:
        return max(0, int(n))
    except ValueError:
        return 0


class _Worker:
    def __init__(self, replica_id: str, out, role: str = "") -> None:
        self.replica_id = replica_id
        self.role = role
        self.out = out
        self._engines: dict[str, object] = {}
        self.served: dict[str, int] = {}
        self._n_served = 0
        self._n_prefilled = 0
        self._kill_after = _kill_after(replica_id)
        self._prefill_kill_after = _kill_after(
            replica_id, "ADVSPEC_PREFILL_KILL_AFTER"
        )

    def _engine_for(self, model: str):
        key = model.partition("://")[0]
        eng = self._engines.get(key)
        if eng is None:
            eng = self._engines[key] = new_engine(model)
        return eng

    def _write(self, obj: dict) -> None:
        self.out.write(json.dumps(obj, separators=(",", ":")) + "\n")
        self.out.flush()

    def _chat(self, msg: dict) -> None:
        requests = [request_from_wire(r) for r in msg.get("requests", [])]
        params = params_from_wire(msg.get("params") or {})
        for j, req in enumerate(requests):
            try:
                comp = self._engine_for(req.model).chat([req], params)[0]
            except Exception as e:  # a request must not kill the worker
                from adversarial_spec_tpu.engine.types import Completion
                from adversarial_spec_tpu.resilience import faults

                comp = Completion(
                    error=f"{type(e).__name__}: {e}",
                    transient=faults.is_transient(e),
                )
            self.served[req.model] = self.served.get(req.model, 0) + 1
            self._write({"i": j, "completion": completion_to_wire(comp)})
            self._n_served += 1
            if self._kill_after and self._n_served >= self._kill_after:
                # The chaos trigger: die HARD the instant this
                # completion line is durable on the pipe — a real
                # SIGKILL at a reproducible mid-round point.
                os.kill(os.getpid(), signal.SIGKILL)
        self._write({"done": True, "served": self._n_served})

    def _prefill(self, msg: dict) -> None:
        """The handoff's shipping end: prefill each request, settle
        its blocks to the shared store, and only THEN flush the result
        line — every line the other end reads is durable, so the kill
        trigger below produces exactly the durable-then-dead window
        the ``--handoff-kill`` drill needs."""
        requests = [request_from_wire(r) for r in msg.get("requests", [])]
        params = params_from_wire(msg.get("params") or {})
        for j, req in enumerate(requests):
            try:
                out = self._engine_for(req.model).prefill([req], params)[0]
            except Exception as e:  # a request must not kill the worker
                out = {"error": f"{type(e).__name__}: {e}", "chains": []}
            self._write({"i": j, "result": out})
            self._n_prefilled += 1
            if (
                self._prefill_kill_after
                and self._n_prefilled >= self._prefill_kill_after
            ):
                # The handoff chaos trigger: die HARD with this
                # request's blocks durable in the shared store and its
                # result line flushed, before any decode-side adoption.
                os.kill(os.getpid(), signal.SIGKILL)
        self._write({"done": True, "prefilled": self._n_prefilled})

    def _stats(self) -> dict:
        from adversarial_spec_tpu.engine import kvtier, prefix_cache

        return {
            "replica": self.replica_id,
            "role": self.role,
            "pid": os.getpid(),
            "served": dict(self.served),
            "prefix_cache": prefix_cache.snapshot(),
            "kv_tier": kvtier.snapshot(),
        }

    def serve(self, lines) -> int:
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                msg = json.loads(line)
                op = msg.get("op")
                if op == "chat":
                    self._chat(msg)
                elif op == "prefill":
                    self._prefill(msg)
                elif op == "prefetch":
                    model = msg.get("model", "")
                    chains = [str(c) for c in msg.get("chains") or []]
                    eng = self._engine_for(model)
                    found = (
                        int(eng.prefetch(chains))
                        if hasattr(eng, "prefetch")
                        else 0
                    )
                    self._write({"found": found})
                elif op == "role":
                    self._write({"role": self.role})
                elif op == "ping":
                    self._write({"pong": True, "replica": self.replica_id})
                elif op == "warm":
                    from adversarial_spec_tpu.engine import weightres

                    models = [str(m) for m in msg.get("models") or []]
                    for model in models:
                        eng = self._engine_for(model)
                        ledger = getattr(eng, "ledger", None)
                        if ledger is not None:
                            ledger.touch(model)
                    weightres.preload_hint(models)
                    self._write({"warmed": len(models)})
                elif op == "validate":
                    model = msg.get("model", "")
                    try:
                        err = self._engine_for(model).validate(model)
                    except ValueError as e:
                        # Unknown provider: a verdict, not a crash.
                        err = str(e)
                    self._write({"error": err})
                elif op == "check":
                    try:
                        for eng in self._engines.values():
                            check_engine_invariants(eng)
                        self._write({"ok": True})
                    except Exception as e:
                        self._write({"ok": False, "error": str(e)})
                elif op == "stats":
                    self._write(self._stats())
                elif op == "shutdown":
                    self._write({"bye": True})
                    return 0
                else:
                    self._write({"error": f"unknown op {op!r}"})
            except BrokenPipeError:
                return 1
            except Exception:
                # Protocol-level failure: report on stderr (the router
                # treats a garbled line as replica death) and keep
                # serving — a worker only exits on shutdown or EOF.
                traceback.print_exc(file=sys.stderr)
                self._write({"error": "internal worker error"})
        return 0


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--replica-id", default="r0")
    ap.add_argument(
        "--role",
        default="",
        choices=("", "prefill", "decode"),
        help="disaggregation role this replica serves",
    )
    args = ap.parse_args(argv)
    worker = _Worker(args.replica_id, sys.stdout, role=args.role)
    return worker.serve(sys.stdin)


if __name__ == "__main__":
    sys.exit(main())
