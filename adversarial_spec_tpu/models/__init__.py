"""models subpackage."""
