"""Model-family configuration.

One generic decoder-only transformer (models/transformer.py) covers every
opponent family the debate targets — Llama-3, Mistral, Gemma-2, Qwen-2 —
via config flags, instead of one module per family. The families differ
only in: GQA ratio, activation, RoPE theta, norm placement (Gemma-2's
sandwich norms), attention/final logit softcapping (Gemma-2), sliding-window
attention (Mistral, alternating layers in Gemma-2), QKV bias (Qwen-2),
embedding scaling and tied embeddings (Gemma-2).

Replaces (reference): the per-provider model zoo behind litellm
(scripts/providers.py:18-43) — here a model is a shape, not an API endpoint.
"""

from __future__ import annotations

from dataclasses import dataclass, replace


@dataclass(frozen=True)
class ModelConfig:
    """Architecture hyperparameters for one decoder-only transformer."""

    vocab_size: int = 32000
    dim: int = 512
    n_layers: int = 4
    n_heads: int = 8
    n_kv_heads: int = 8
    head_dim: int = 64
    ffn_dim: int = 1376
    rope_theta: float = 500000.0
    rms_eps: float = 1e-5
    activation: str = "silu"  # silu | gelu
    tied_embeddings: bool = False
    # Gemma-2 extras.
    scale_embeddings: bool = False  # multiply embeddings by sqrt(dim)
    post_norms: bool = False  # post-attention/post-ffn sandwich norms
    logit_softcap: float = 0.0  # final-logit soft capping (30.0 in gemma-2)
    attn_softcap: float = 0.0  # attention-logit soft capping (50.0)
    # Sliding-window attention: 0 = global everywhere. When
    # ``sliding_window_pattern`` is 2 (gemma-2), odd layers are global and
    # even layers use the window; pattern 1 (mistral) windows every layer.
    sliding_window: int = 0
    sliding_window_pattern: int = 1
    qkv_bias: bool = False  # qwen-2
    # Llama-3.1/3.2 rope scaling (HF rope_type="llama3"): 0 = unscaled.
    rope_scaling_factor: float = 0.0
    rope_low_freq_factor: float = 1.0
    rope_high_freq_factor: float = 4.0
    rope_original_max: int = 8192
    max_seq_len: int = 8192
    norm_scale_plus_one: bool = False  # gemma RMSNorm uses (1 + weight)
    # Gemma-2 "query_pre_attn_scalar": attention scale is 1/sqrt(this)
    # instead of 1/sqrt(head_dim). 0 = use head_dim (all other families;
    # gemma-2-9b's value equals its head_dim, 27b's does NOT: 4608/32=144).
    query_pre_attn_scalar: float = 0.0

    @property
    def q_per_kv(self) -> int:
        return self.n_heads // self.n_kv_heads

    @property
    def rope_scaling(self) -> tuple[float, float, float, float] | None:
        """(factor, low, high, original_max) for ops/rope.py, or None."""
        if not self.rope_scaling_factor:
            return None
        return (
            self.rope_scaling_factor,
            self.rope_low_freq_factor,
            self.rope_high_freq_factor,
            float(self.rope_original_max),
        )

    @property
    def attn_scale(self) -> float:
        import math

        return 1.0 / math.sqrt(self.query_pre_attn_scalar or self.head_dim)


def _llama(dim, n_layers, n_heads, n_kv_heads, ffn_dim, vocab=128256, **kw):
    return ModelConfig(
        vocab_size=vocab,
        dim=dim,
        n_layers=n_layers,
        n_heads=n_heads,
        n_kv_heads=n_kv_heads,
        head_dim=dim // n_heads,
        ffn_dim=ffn_dim,
        rope_theta=500000.0,
        **kw,
    )


# Named (family, size) → config. "tiny" sizes are for tests/CI: real family
# semantics, toy widths (lane-aligned: dim multiple of 128 where possible).
CONFIGS: dict[tuple[str, str], ModelConfig] = {
    # Llama-3 family (HF meta-llama/Meta-Llama-3-8B etc.). 1b/3b are
    # Llama-3.2 (tied embeddings, rope scaling factor 32, 128k context);
    # 8b/70b are base Llama-3 (unscaled rope, 8k).
    ("llama", "tiny"): _llama(256, 2, 4, 2, 512, vocab=512),
    ("llama", "1b"): _llama(
        2048, 16, 32, 8, 8192,
        tied_embeddings=True, rope_scaling_factor=32.0,
        max_seq_len=131072,
    ),
    ("llama", "3b"): _llama(
        3072, 28, 24, 8, 8192,
        tied_embeddings=True, rope_scaling_factor=32.0,
        max_seq_len=131072,
    ),
    ("llama", "8b"): _llama(4096, 32, 32, 8, 14336),
    ("llama", "70b"): _llama(8192, 80, 64, 8, 28672),
    # Mistral-7B. The named "7b" is v0.3 (rope theta 1e6, NO sliding
    # window) — v0.1's theta-1e4 + window-4096 combination is a different
    # checkpoint generation and must not be mixed with v0.3 fields (no
    # real checkpoint has both). "tiny" keeps a window so the windowed
    # code path stays covered by the mistral family tests.
    ("mistral", "tiny"): ModelConfig(
        vocab_size=512,
        dim=256,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        head_dim=64,
        ffn_dim=512,
        rope_theta=10000.0,
        sliding_window=128,
    ),
    ("mistral", "7b"): ModelConfig(
        vocab_size=32768,  # v0.3 extended vocabulary (v0.2 was 32000)
        dim=4096,
        n_layers=32,
        n_heads=32,
        n_kv_heads=8,
        head_dim=128,
        ffn_dim=14336,
        rope_theta=1000000.0,
        max_seq_len=32768,
    ),
    # Gemma-2: sandwich norms, softcaps, tied+scaled embeddings, gelu,
    # alternating sliding window.
    ("gemma2", "tiny"): ModelConfig(
        vocab_size=512,
        dim=256,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        head_dim=64,
        ffn_dim=512,
        rope_theta=10000.0,
        activation="gelu",
        tied_embeddings=True,
        scale_embeddings=True,
        post_norms=True,
        logit_softcap=30.0,
        attn_softcap=50.0,
        sliding_window=128,
        sliding_window_pattern=2,
        norm_scale_plus_one=True,
    ),
    ("gemma2", "9b"): ModelConfig(
        vocab_size=256000,
        dim=3584,
        n_layers=42,
        n_heads=16,
        n_kv_heads=8,
        head_dim=256,
        ffn_dim=14336,
        rope_theta=10000.0,
        activation="gelu",
        tied_embeddings=True,
        scale_embeddings=True,
        post_norms=True,
        logit_softcap=30.0,
        attn_softcap=50.0,
        sliding_window=4096,
        sliding_window_pattern=2,
        norm_scale_plus_one=True,
    ),
    # Qwen-2: QKV bias, tied embeddings on small sizes.
    ("qwen2", "tiny"): ModelConfig(
        vocab_size=512,
        dim=256,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        head_dim=64,
        ffn_dim=512,
        rope_theta=1000000.0,
        qkv_bias=True,
    ),
    ("qwen2", "7b"): ModelConfig(
        vocab_size=152064,
        dim=3584,
        n_layers=28,
        n_heads=28,
        n_kv_heads=4,
        head_dim=128,
        ffn_dim=18944,
        rope_theta=1000000.0,
        qkv_bias=True,
    ),
    ("qwen2", "72b"): ModelConfig(
        vocab_size=152064,
        dim=8192,
        n_layers=80,
        n_heads=64,
        n_kv_heads=8,
        head_dim=128,
        ffn_dim=29568,
        rope_theta=1000000.0,
        qkv_bias=True,
    ),
    ("gemma2", "27b"): ModelConfig(
        vocab_size=256000,
        dim=4608,
        n_layers=46,
        n_heads=32,
        n_kv_heads=16,
        head_dim=128,
        ffn_dim=36864,
        rope_theta=10000.0,
        activation="gelu",
        tied_embeddings=True,
        scale_embeddings=True,
        post_norms=True,
        logit_softcap=30.0,
        attn_softcap=50.0,
        sliding_window=4096,
        sliding_window_pattern=2,
        norm_scale_plus_one=True,
        query_pre_attn_scalar=144.0,  # dim / n_heads, NOT head_dim
    ),
}


def get_config(family: str, size: str, max_seq_len: int = 0) -> ModelConfig:
    key = (family, size)
    if key not in CONFIGS:
        known = ", ".join(f"{f}/{s}" for f, s in sorted(CONFIGS))
        raise KeyError(f"no config for {family}/{size}; known: {known}")
    cfg = CONFIGS[key]
    if max_seq_len:
        cfg = replace(cfg, max_seq_len=max_seq_len)
    return cfg
