"""Generic decoder-only transformer — pure-functional JAX, scan-over-layers.

TPU-first design decisions (why this is not a torch translation):

- **Pure functions over param pytrees.** ``init_params`` builds a pytree;
  ``forward`` is a pure function of (params, tokens, cache). Sharding is
  applied by annotating the pytree leaves (parallel/sharding.py) and jitting
  — the model code itself is mesh-oblivious.
- **Layer-stacked params + ``lax.scan``.** Every per-layer weight carries a
  leading ``n_layers`` dim and the layer loop is one ``scan`` — one traced
  layer body regardless of depth, which keeps XLA compile time flat from
  2-layer test configs to 80-layer 70B.
- **Static shapes everywhere.** Batches are left-padded to a bucketed length
  (engine/generate.py); the KV cache is a dense preallocated
  ``[L, B, H_kv, S_max, D]`` buffer written with ``dynamic_update_slice``.
  Heads-major layout is a Mosaic requirement, not a style choice: the
  Pallas decode kernels stream ``[block_t, D]`` tiles, and TPU block
  shapes must keep the (sublane, lane) = (seq, head_dim) axes minor —
  a seq-major cache would need per-head blocks of sublane size 1, which
  the TPU lowering rejects. It also makes each tp shard's cache slice
  contiguous (heads axis is the sharded one).
  No data-dependent Python control flow — decode early-exit lives in a
  ``lax.while_loop`` in the generation loop, not here.
- **bf16 params/activations, f32 where it matters** (RMSNorm accumulation,
  attention softmax, final logits).

Family coverage (flags in models/config.py): Llama-3, Mistral (sliding
window), Gemma-2 (sandwich norms, softcaps, scaled/tied embeddings,
alternating window), Qwen-2 (QKV bias). GQA throughout.

Replaces (reference): nothing — the reference delegates all inference to
remote APIs (SURVEY §2: zero tensor math in the tree). This module is the
"native component" obligation of the TPU build (SURVEY §2, BASELINE north
star).
"""

from __future__ import annotations

import functools
import math
import os
from typing import Any

import jax
import jax.numpy as jnp

from adversarial_spec_tpu.models.config import ModelConfig
from adversarial_spec_tpu.ops.quant import matmul
from adversarial_spec_tpu.ops.rope import apply_rope, rope_angles

Params = dict[str, Any]
Cache = dict[str, jnp.ndarray]

# Unroll factor for the scan-over-layers during DECODE (token spans ≤ this
# many positions). Single-token layers are HBM-bound (stream the layer's
# weights, tiny compute); a rolled scan serializes layer i's compute behind
# layer i's weight fetch, while a modest unroll lets XLA software-pipeline
# layer i+1's weight DMA under layer i's compute. Prefill keeps the rolled
# scan: its per-layer compute is MXU-bound and compile time stays flat for
# 80-layer configs.
_DECODE_UNROLL = int(os.environ.get("ADVSPEC_DECODE_UNROLL", "4"))
_DECODE_UNROLL_MAX_SPAN = 16


def init_params(
    rng: jax.Array,
    cfg: ModelConfig,
    dtype: jnp.dtype = jnp.bfloat16,
    transposed_head: bool = True,
) -> Params:
    """Random init with truncated-normal fan-in scaling (for synthetic
    checkpoints and tests; real weights come from engine/loader.py).

    ``transposed_head``: for tied-embedding configs, also store the
    ``[dim, vocab]`` transposed head copy (see the comment at the
    assignment below). Disable to save the V·D bytes on memory-tight
    fits; the einsum fallback over the embed table computes the same
    logits (exactly equivalent until ``quantize_params`` runs — the
    copy quantizes like any head matmul, the embed-table einsum stays
    full precision).
    """
    keys = iter(jax.random.split(rng, 16))

    def dense(key, shape, fan_in):
        w = jax.random.truncated_normal(key, -2.0, 2.0, shape, jnp.float32)
        return (w / math.sqrt(fan_in)).astype(dtype)

    L, D, F = cfg.n_layers, cfg.dim, cfg.ffn_dim
    QD = cfg.n_heads * cfg.head_dim
    KD = cfg.n_kv_heads * cfg.head_dim
    layers: dict[str, jnp.ndarray] = {
        "attn_norm": jnp.ones((L, D), dtype),
        "wq": dense(next(keys), (L, D, QD), D),
        "wk": dense(next(keys), (L, D, KD), D),
        "wv": dense(next(keys), (L, D, KD), D),
        "wo": dense(next(keys), (L, QD, D), QD),
        "ffn_norm": jnp.ones((L, D), dtype),
        "w_gate": dense(next(keys), (L, D, F), D),
        "w_up": dense(next(keys), (L, D, F), D),
        "w_down": dense(next(keys), (L, F, D), F),
    }
    if cfg.qkv_bias:
        layers["bq"] = jnp.zeros((L, QD), dtype)
        layers["bk"] = jnp.zeros((L, KD), dtype)
        layers["bv"] = jnp.zeros((L, KD), dtype)
    if cfg.post_norms:
        layers["post_attn_norm"] = jnp.ones((L, D), dtype)
        layers["post_ffn_norm"] = jnp.ones((L, D), dtype)
    if cfg.norm_scale_plus_one:
        # Gemma stores RMSNorm scale as (1 + w); init w at zero.
        for name in ("attn_norm", "ffn_norm", "post_attn_norm", "post_ffn_norm"):
            if name in layers:
                layers[name] = jnp.zeros_like(layers[name])

    params: Params = {
        "embed": dense(next(keys), (cfg.vocab_size, D), D),
        "layers": layers,
        "final_norm": (
            jnp.zeros((D,), dtype)
            if cfg.norm_scale_plus_one
            else jnp.ones((D,), dtype)
        ),
    }
    if not cfg.tied_embeddings:
        params["lm_head"] = dense(next(keys), (D, cfg.vocab_size), D)
    elif transposed_head:
        # Tied embeddings force the head matmul to contract the embed
        # table's MINOR axis ("bsd,vd->bsv") — measured ~2-5x slower than
        # a [D, V] layout on TPU (the MXU wants the contraction on the
        # major axis; XLA inserts a relayout of the full table). A decode
        # step re-reads the whole head every token, so the head is the
        # single largest per-step HBM item for small models. Materialize
        # a transposed copy once at init/load: +V·D bytes of HBM buys the
        # full-bandwidth matmul every step.
        params["lm_head_t"] = jnp.swapaxes(params["embed"], 0, 1)
    return params


def init_cache(
    cfg: ModelConfig,
    batch: int,
    max_seq: int,
    dtype: jnp.dtype = jnp.bfloat16,
    device=None,
    kv_dtype: str = "",
) -> Cache:
    """``device`` may be a Sharding so the cache is born sharded (never
    materialized replicated on one chip).

    ``kv_dtype="int8"``: store K/V int8 with per-(token, head) symmetric
    scales (keys "ks"/"vs") — half the HBM bytes read per decoded token
    (decode is KV-bandwidth-bound at long contexts); dequant fuses into
    the attention matmuls. Presence of "ks" marks a quantized cache.
    """
    shape = (cfg.n_layers, batch, cfg.n_kv_heads, max_seq, cfg.head_dim)
    kw = {"device": device} if device is not None else {}
    if kv_dtype == "int8":
        sshape = shape[:-1] + (1,)
        return {
            "k": jnp.zeros(shape, jnp.int8, **kw),
            "v": jnp.zeros(shape, jnp.int8, **kw),
            "ks": jnp.zeros(sshape, jnp.float32, **kw),
            "vs": jnp.zeros(sshape, jnp.float32, **kw),
        }
    return {
        "k": jnp.zeros(shape, dtype, **kw),
        "v": jnp.zeros(shape, dtype, **kw),
    }


def _quantize_kv(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Per-(token, head) symmetric int8 over the feature axis."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(
        jnp.round(x.astype(jnp.float32) / scale), -127, 127
    ).astype(jnp.int8)
    return q, scale


def rms_norm(
    x: jnp.ndarray, weight: jnp.ndarray, eps: float, plus_one: bool
) -> jnp.ndarray:
    xf = x.astype(jnp.float32)
    norm = xf * jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    scale = weight.astype(jnp.float32)
    if plus_one:
        scale = scale + 1.0
    return (norm * scale).astype(x.dtype)


def _softcap(x: jnp.ndarray, cap: float) -> jnp.ndarray:
    return jnp.tanh(x / cap) * cap


def _activation(x: jnp.ndarray, kind: str) -> jnp.ndarray:
    if kind == "gelu":
        return jax.nn.gelu(x, approximate=True)
    return jax.nn.silu(x)


def attention(
    q: jnp.ndarray,  # [B, S, Hq, D]
    k: jnp.ndarray,  # [B, Hkv, T, D] — heads-major (cache layout)
    v: jnp.ndarray,  # [B, Hkv, T, D]
    mask: jnp.ndarray,  # [B, S, T] bool — True = attend
    attn_softcap: float = 0.0,
    scale: float | None = None,
) -> jnp.ndarray:
    """Masked GQA attention, f32 softmax. Returns [B, S, Hq, D]."""
    B, S, Hq, D = q.shape
    Hkv, T = k.shape[1], k.shape[2]
    g = Hq // Hkv
    scale = scale if scale is not None else 1.0 / math.sqrt(D)
    qg = q.reshape(B, S, Hkv, g, D)
    # [B, Hkv, g, S, T]
    logits = jnp.einsum(
        "bshgd,bhtd->bhgst", qg, k, preferred_element_type=jnp.float32
    )
    logits = logits * scale
    if attn_softcap > 0.0:
        logits = _softcap(logits, attn_softcap)
    # Masked softmax with the framework-wide contract that FULLY-masked
    # rows (left-pad query slots) produce EXACT zeros — matching the
    # Pallas kernels and the ring (which early-outs of windowed hops, so
    # pad garbage may not even see the same key set twice). -inf masking
    # with a guarded max keeps those rows NaN-free.
    logits = jnp.where(mask[:, None, None, :, :], logits, -jnp.inf)
    m = jnp.max(logits, axis=-1, keepdims=True)
    m = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(logits - m)
    probs = p / jnp.maximum(jnp.sum(p, axis=-1, keepdims=True), 1e-30)
    out = jnp.einsum(
        "bhgst,bhtd->bshgd", probs.astype(v.dtype), v
    )
    return out.reshape(B, S, Hq, D)


def _project_qkv(lp, cfg: ModelConfig, h, B: int, S: int, cos, sin, mm=matmul):
    """Shared QKV projection + bias + head reshape + RoPE (dense & paged).

    ``mm`` is the matmul implementation — the plain dispatch by default,
    or a partial carrying ``use_pallas``/``interpret`` when the caller
    enables the fused dequant-matmul kernels (ops/pallas_quant.py)."""
    q = mm(h, lp["wq"])
    k = mm(h, lp["wk"])
    v = mm(h, lp["wv"])
    if cfg.qkv_bias:
        q = q + lp["bq"]
        k = k + lp["bk"]
        v = v + lp["bv"]
    q = q.reshape(B, S, cfg.n_heads, cfg.head_dim)
    k = k.reshape(B, S, cfg.n_kv_heads, cfg.head_dim)
    v = v.reshape(B, S, cfg.n_kv_heads, cfg.head_dim)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    return q, k, v


def _attn_out_and_ffn(
    x, attn_out, lp, cfg: ModelConfig, B: int, S: int, psum_axis=None,
    mm=matmul,
):
    """Shared post-attention projection, residuals, and FFN block.

    ``psum_axis``: when running inside a manual-collective region
    (shard_map) with Megatron-style TP, the row-parallel matmuls (wo,
    w_down) produce partial sums that must all-reduce over the tp axis —
    BEFORE any post-norm reads them (norms of partial sums are wrong).
    Under GSPMD (jit) leave it None; the compiler inserts the psums.

    ``mm``: matmul implementation (see ``_project_qkv``).
    """
    out = mm(
        attn_out.reshape(B, S, cfg.n_heads * cfg.head_dim), lp["wo"]
    )
    if psum_axis is not None:
        out = jax.lax.psum(out, psum_axis)
    if cfg.post_norms:
        out = rms_norm(
            out, lp["post_attn_norm"], cfg.rms_eps, cfg.norm_scale_plus_one
        )
    x = x + out

    h = rms_norm(x, lp["ffn_norm"], cfg.rms_eps, cfg.norm_scale_plus_one)
    ff = _activation(mm(h, lp["w_gate"]), cfg.activation) * mm(
        h, lp["w_up"]
    )
    ff = mm(ff, lp["w_down"])
    if psum_axis is not None:
        ff = jax.lax.psum(ff, psum_axis)
    if cfg.post_norms:
        ff = rms_norm(
            ff, lp["post_ffn_norm"], cfg.rms_eps, cfg.norm_scale_plus_one
        )
    return x + ff


def _layer_window_start(cfg: ModelConfig, layer_id, base_start, q_pos):
    """Per-layer valid-window start: sliding window tightens it (on the
    windowed layers only, for alternating-pattern families)."""
    if cfg.sliding_window <= 0:
        return base_start
    win_start = jnp.maximum(base_start, q_pos - cfg.sliding_window + 1)
    if cfg.sliding_window_pattern > 1:
        use_window = (layer_id % cfg.sliding_window_pattern) == 0
        return jnp.where(use_window, win_start, base_start)
    return win_start


def forward(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,  # [B, S] int32
    positions: jnp.ndarray,  # [B, S] rope positions (0 at each row's start)
    cache: Cache,
    cache_index: jnp.ndarray,  # scalar or [B]: slot where this chunk's KV goes
    kv_valid: jnp.ndarray,  # [B, T] bool: slots holding real tokens
    *,
    use_pallas_decode: bool = False,
    use_pallas_matmul: bool = False,
    pallas_interpret: bool = False,
    lm_head_last_only: bool = False,
    mesh=None,
) -> tuple[jnp.ndarray, Cache]:
    """One forward pass over a chunk (prefill: S=chunk, decode: S=1).

    The caller maintains left-padded rows so every row writes its KV at the
    same scalar ``cache_index`` (static-shape dynamic_update_slice), and
    passes ``kv_valid`` marking which cache slots are real (pads
    excluded). A vector ``cache_index`` ([B]) writes each row's KV at its
    own slot (vmapped update) — the layout speculative decoding needs once
    rows accept different draft lengths and desynchronize.
    Returns (logits [B, S, vocab] f32, updated cache).

    ``use_pallas_decode`` routes S==1 attention through the fused Pallas
    flash-decoding kernel (ops/pallas_decode.py). On a multi-device
    ``mesh`` the kernel runs under shard_map — batch over dp, KV heads
    over tp (ops/pallas_decode.py:decode_attention_tp); callers gate on
    ``tp_decode_supported``. ``use_pallas_matmul`` routes quantized
    projection/MLP/head weights through the fused dequant-matmul kernels
    (ops/pallas_quant.py) — single-device only (a pallas_call cannot be
    GSPMD-partitioned, and the matmul weights shard under jit), so
    callers gate on ``mesh is None or mesh.size == 1``.
    """
    B, S = tokens.shape
    mm = (
        functools.partial(
            matmul, use_pallas=True, interpret=pallas_interpret
        )
        if use_pallas_matmul and (mesh is None or mesh.size == 1)
        else matmul
    )
    T = cache["k"].shape[3]  # [L, B, Hkv, T, D]
    pallas_decode = use_pallas_decode and S == 1
    # Short multi-query spans (speculative verification: S = γ+1) run
    # the multi-query kernel — one pass over the KV cache for the whole
    # span, int8 tiles included (scale tiles stream like the
    # single-query kernel's). Single-device.
    pallas_mq = (
        use_pallas_decode
        and 1 < S <= 16
        and (mesh is None or mesh.size == 1)
    )

    x = params["embed"][tokens]
    if cfg.scale_embeddings:
        x = (x.astype(jnp.float32) * math.sqrt(cfg.dim)).astype(x.dtype)

    cos, sin = rope_angles(
        positions, cfg.head_dim, cfg.rope_theta, cfg.rope_scaling
    )

    # Masks shared by all layers. Slot j is visible to in-chunk query i iff
    # it holds a real token and j <= cache_index + i (causality in slot
    # space — valid because rows are left-padded so slot order = position
    # order). Reshape unifies scalar ([1,1,1]) and per-row ([B,1,1])
    # cache_index under one broadcast.
    slot_ids = jnp.arange(T)[None, None, :]  # [1, 1, T]
    q_slot = (
        jnp.reshape(cache_index, (-1, 1, 1))
        + jnp.arange(S)[None, :, None]
    )  # [1|B, S, 1]
    causal = slot_ids <= q_slot
    base_mask = kv_valid[:, None, :] & causal  # [B, S, T]
    if cfg.sliding_window > 0:
        window_mask = base_mask & (slot_ids > q_slot - cfg.sliding_window)
    else:
        window_mask = base_mask

    layer_ids = jnp.arange(cfg.n_layers)

    if pallas_decode or pallas_mq:
        # Per-row valid window [start, end) for the fused kernels; the
        # sliding-window start tightening happens per layer below.
        pallas_start = jnp.argmax(kv_valid.astype(jnp.int32), axis=1).astype(
            jnp.int32
        )
        pallas_end = jnp.full((B,), 0, jnp.int32) + cache_index + 1
    if pallas_mq:
        # Per-query positions: query j of row b sits at slot
        # cache_index_b + j, sees [start_bj, cache_index_b + j + 1).
        mq_q_pos = jnp.broadcast_to(
            jnp.reshape(cache_index, (-1, 1))
            + jnp.arange(S, dtype=jnp.int32),
            (B, S),
        )

    quant_kv = "ks" in cache  # int8 K/V with per-(token, head) scales

    vector_index = jnp.ndim(cache_index) > 0

    def _write_and_read_kv(cache_l: Cache, k, v, x_dtype):
        """Store this chunk's K/V into the layer's cache slice and return
        (updated slice, attention-readable K, V). One site owns both the
        plain and int8 layouts, and both index modes (shared scalar slot
        vs per-row slots).

        Fresh k/v arrive token-major [B, S, Hkv, D|1] and are transposed
        to the heads-major cache layout [B, Hkv, S, D|1] here — the chunk
        transpose is O(S·H·D), negligible next to the cache read."""
        if vector_index:
            # Per-row slots: buf [Hkv, T, D], val [Hkv, S, D], seq at dim 1.
            upd = lambda buf, val: jax.vmap(  # noqa: E731
                lambda b, v_, i: jax.lax.dynamic_update_slice(
                    b, v_, (0, i) + (0,) * (b.ndim - 2)
                )
            )(buf, val, cache_index)
        else:
            upd = lambda buf, val: jax.lax.dynamic_update_slice(  # noqa: E731
                buf, val, (0, 0, cache_index, 0)
            )
        k = jnp.swapaxes(k, 1, 2)  # [B, Hkv, S, D]
        v = jnp.swapaxes(v, 1, 2)
        if quant_kv:
            kq, ks = _quantize_kv(k)
            vq, vs = _quantize_kv(v)
            out = {
                "k": upd(cache_l["k"], kq),
                "v": upd(cache_l["v"], vq),
                "ks": upd(cache_l["ks"], ks),
                "vs": upd(cache_l["vs"], vs),
            }
            # Dequant feeds the attention matmuls directly; XLA fuses the
            # elementwise producer into the dot's operand read.
            k_read = (out["k"].astype(jnp.float32) * out["ks"]).astype(x_dtype)
            v_read = (out["v"].astype(jnp.float32) * out["vs"]).astype(x_dtype)
            return out, k_read, v_read
        out = {
            "k": upd(cache_l["k"], k.astype(cache_l["k"].dtype)),
            "v": upd(cache_l["v"], v.astype(cache_l["v"].dtype)),
        }
        return out, out["k"], out["v"]

    def layer_body(x, scanned):
        lp, layer_id, cache_l = scanned
        h = rms_norm(x, lp["attn_norm"], cfg.rms_eps, cfg.norm_scale_plus_one)
        q, k, v = _project_qkv(lp, cfg, h, B, S, cos, sin, mm=mm)
        cache_l, k_read, v_read = _write_and_read_kv(cache_l, k, v, x.dtype)

        if pallas_decode:
            from adversarial_spec_tpu.ops.pallas_decode import (
                decode_attention,
                decode_attention_tp,
            )

            start = _layer_window_start(
                cfg, layer_id, pallas_start, cache_index
            )
            bounds = jnp.stack([start, pallas_end], axis=1)
            if quant_kv:
                # Hand the kernel the raw int8 tiles + scale tiles; the
                # dequantized k_read/v_read above are dead code here and
                # XLA drops them — HBM traffic stays at int8 bytes.
                k_in, v_in = cache_l["k"], cache_l["v"]
                qkw = dict(
                    k_scale=cache_l["ks"], v_scale=cache_l["vs"]
                )
            else:
                k_in, v_in, qkw = k_read, v_read, {}
            if mesh is not None and mesh.size > 1:
                out = decode_attention_tp(
                    q[:, 0],
                    k_in,
                    v_in,
                    bounds,
                    mesh,
                    attn_softcap=cfg.attn_softcap,
                    scale=cfg.attn_scale,
                    interpret=pallas_interpret,
                    **qkw,
                )[:, None]
            else:
                out = decode_attention(
                    q[:, 0],
                    k_in,
                    v_in,
                    bounds,
                    attn_softcap=cfg.attn_softcap,
                    scale=cfg.attn_scale,
                    interpret=pallas_interpret,
                    **qkw,
                )[:, None]
        elif pallas_mq:
            from adversarial_spec_tpu.ops.pallas_decode import (
                decode_attention_mq,
            )

            starts_l = _layer_window_start(
                cfg, layer_id, pallas_start[:, None], mq_q_pos
            )
            if quant_kv:
                # Raw int8 tiles + scale tiles; the dequantized
                # k_read/v_read are dead here (XLA drops them) so HBM
                # traffic stays at int8 bytes.
                mq_k, mq_v = cache_l["k"], cache_l["v"]
                mq_kw = dict(
                    k_scale=cache_l["ks"], v_scale=cache_l["vs"]
                )
            else:
                mq_k, mq_v, mq_kw = k_read, v_read, {}
            out = decode_attention_mq(
                q,
                mq_k,
                mq_v,
                starts_l,
                mq_q_pos + 1,
                attn_softcap=cfg.attn_softcap,
                scale=cfg.attn_scale,
                interpret=pallas_interpret,
                **mq_kw,
            )
        else:
            if cfg.sliding_window > 0 and cfg.sliding_window_pattern > 1:
                # Gemma-2: alternate windowed / global layers.
                use_window = (layer_id % cfg.sliding_window_pattern) == 0
                mask = jnp.where(use_window, window_mask, base_mask)
            elif cfg.sliding_window > 0:
                mask = window_mask
            else:
                mask = base_mask

            out = attention(
                q,
                k_read,
                v_read,
                mask,
                attn_softcap=cfg.attn_softcap,
                scale=cfg.attn_scale,
            )
        x = _attn_out_and_ffn(x, out, lp, cfg, B, S, mm=mm)
        return x, cache_l

    # The cache dict scans as a pytree: every leaf carries a leading
    # n_layers axis, so one scan serves both cache layouts. Decode spans
    # unroll (see _DECODE_UNROLL) so weight DMA pipelines across layers.
    x, new_cache = jax.lax.scan(
        layer_body,
        x,
        (params["layers"], layer_ids, cache),
        unroll=_DECODE_UNROLL if S <= _DECODE_UNROLL_MAX_SPAN else 1,
    )

    logits = _lm_head_logits(params, cfg, x, lm_head_last_only, mm=mm)
    return logits, new_cache


def _lm_head_logits(
    params: Params, cfg: ModelConfig, x, lm_head_last_only: bool, mm=matmul
):
    x = rms_norm(x, params["final_norm"], cfg.rms_eps, cfg.norm_scale_plus_one)
    if lm_head_last_only:
        # Prompt chunks only ever need the final position's logits; skip
        # the [B, S, vocab] projection (the largest prefill activation).
        x = x[:, -1:]
    if cfg.tied_embeddings:
        if "lm_head_t" in params:
            # Pre-transposed [D, V] copy (init_params/loader): contracts
            # the major axis at full HBM bandwidth instead of relayouting
            # the embed table every decode step.
            logits = mm(
                x, params["lm_head_t"], preferred_element_type=jnp.float32
            )
        else:
            logits = jnp.einsum(
                "bsd,vd->bsv",
                x,
                params["embed"],
                preferred_element_type=jnp.float32,
            )
    else:
        logits = mm(
            x, params["lm_head"], preferred_element_type=jnp.float32
        )
    if cfg.logit_softcap > 0.0:
        logits = _softcap(logits, cfg.logit_softcap)
    return logits


def forward_paged_decode(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,  # [B, S] int32 — decode step (S=1) or a short
    # multi-position verify span (S=γ+1, speculative decoding)
    positions: jnp.ndarray,  # [B, S] rope positions
    pool: Cache,  # {"k","v": [L, n_pages, Hkv, page_size, D]} (+"ks"/"vs"
    # [..., 1] f32 scale pages when the pool is int8)
    page_table: jnp.ndarray,  # [B, Pmax] int32; <= 0 = unmapped (0=trash)
    write_page: jnp.ndarray,  # [B(, S)] physical page per token's KV
    write_off: jnp.ndarray,  # [B(, S)] slot within that page
    bounds: jnp.ndarray,  # [B(, S), 2] (start, end) valid-slot window
    q_pos: jnp.ndarray,  # scalar, [B], or [B, S]: logical slot per token
    *,
    use_pallas: bool = False,
    use_pallas_matmul: bool = False,
    pallas_interpret: bool = False,
    mesh=None,
) -> tuple[jnp.ndarray, Cache]:
    """One decode step (or one multi-position verify span) over the
    PAGED KV pool.

    Same math as ``forward`` with short S (shared helpers), but K/V live
    in pages shared across rows: token (b, j)'s K/V scatters to
    (write_page[b, j], write_off[b, j]) and attention reads through the
    page table — fused Pallas kernels on real TPUs (S=1:
    paged_decode_attention; S>1: paged_decode_attention_mq, one pass
    over the pool for the whole span), a gather + masked jnp reference
    path elsewhere (same bounds semantics on every path).
    Returns (logits [B, S, vocab], updated pool).

    In-span causality (S>1, the speculative verify shape) comes from the
    per-query bounds: position j's window ends at its own slot
    (``bounds[b, j, 1] = q_pos[b, j] + 1``), and every span position's
    K/V scatters before attention in each layer, so position j sees
    exactly [start, q_pos_bj + 1) — byte-compatible with flattening the
    span into the batch axis, without paying B·span densifications.

    On a multi-device ``mesh`` the S=1 kernel runs under shard_map with
    the pool's head axis tp-sharded (ops/pallas_paged.py:
    paged_decode_attention_tp); callers gate on tp | n_kv_heads. The
    multi-position kernel is single-device (sharded spans take the
    gather path). The non-kernel math (projections, scatter, gather
    path) partitions under GSPMD as usual. ``use_pallas_matmul`` routes
    quantized weights through the fused dequant-matmul kernels
    (ops/pallas_quant.py) — single-device, like ``forward``.

    Composition contract: this function and ``forward`` are pure
    traceable graphs over disjoint state (the paged pool here, a dense
    per-call cache there), so the scheduler's fused step traces BOTH
    into one program (engine/scheduler.py:fused_prefill_decode_chunk —
    a newcomer's prompt chunk riding the residents' decode chunk).
    Nothing in either body may grow module-level state or host callbacks
    that would make the fused composition diverge from the standalone
    dispatches.
    """
    B, S = tokens.shape
    page_size = pool["k"].shape[3]
    layer_ids = jnp.arange(cfg.n_layers)
    quant_kv = "ks" in pool  # int8 pages + per-(token, head) scale pages
    single_device = mesh is None or mesh.size == 1
    # The S=1 legacy calling convention passes [B]/[B,2]/scalar shapes;
    # normalize everything to the per-(row, span-position) layout.
    write_page = write_page.reshape(B, S)
    write_off = write_off.reshape(B, S)
    bounds = bounds.reshape(B, S, 2)
    if jnp.ndim(q_pos) <= 1:
        q_pos = jnp.broadcast_to(jnp.reshape(q_pos, (-1, 1)), (B, S))
    mm = (
        functools.partial(
            matmul, use_pallas=True, interpret=pallas_interpret
        )
        if use_pallas_matmul and single_device
        else matmul
    )
    cos, sin = rope_angles(
        positions, cfg.head_dim, cfg.rope_theta, cfg.rope_scaling
    )

    x = params["embed"][tokens]
    if cfg.scale_embeddings:
        x = (x.astype(jnp.float32) * math.sqrt(cfg.dim)).astype(x.dtype)

    flat_page = write_page.reshape(-1)
    flat_off = write_off.reshape(-1)

    def layer_body(x, scanned):
        lp, layer_id, pool_l = scanned
        k_pages, v_pages = pool_l["k"], pool_l["v"]
        ks_pages = pool_l.get("ks")
        vs_pages = pool_l.get("vs")
        h = rms_norm(x, lp["attn_norm"], cfg.rms_eps, cfg.norm_scale_plus_one)
        q, k, v = _project_qkv(lp, cfg, h, B, S, cos, sin, mm=mm)

        # Pages are heads-major [n_pages, Hkv, page_size, D]; advanced
        # indices (write_page at dim 0, write_off at dim 2) separated by
        # the head slice put the flattened (row, span) axis first →
        # update [B·S, Hkv, D]. One scatter per layer regardless of span
        # width (rejected-draft targets are the trash page, never read).
        kf = k.reshape(B * S, cfg.n_kv_heads, cfg.head_dim)
        vf = v.reshape(B * S, cfg.n_kv_heads, cfg.head_dim)
        if quant_kv:
            kq, ks = _quantize_kv(kf)  # [B·S, Hkv, D], [B·S, Hkv, 1]
            vq, vs = _quantize_kv(vf)
            k_pages = k_pages.at[flat_page, :, flat_off].set(kq)
            v_pages = v_pages.at[flat_page, :, flat_off].set(vq)
            ks_pages = ks_pages.at[flat_page, :, flat_off].set(ks)
            vs_pages = vs_pages.at[flat_page, :, flat_off].set(vs)
        else:
            k_pages = k_pages.at[flat_page, :, flat_off].set(
                kf.astype(k_pages.dtype)
            )
            v_pages = v_pages.at[flat_page, :, flat_off].set(
                vf.astype(v_pages.dtype)
            )

        start = _layer_window_start(
            cfg, layer_id, bounds[..., 0], q_pos
        )  # [B, S]
        end = bounds[..., 1]  # [B, S]

        if use_pallas and S == 1:
            from adversarial_spec_tpu.ops.pallas_paged import (
                paged_decode_attention,
                paged_decode_attention_dp_tp,
                paged_decode_attention_tp,
            )

            layer_bounds = jnp.stack([start[:, 0], end[:, 0]], axis=1)
            qkw = (
                dict(k_scale=ks_pages, v_scale=vs_pages) if quant_kv else {}
            )
            if not single_device:
                from adversarial_spec_tpu.parallel.mesh import DP as _DPAX

                # Mixed dp×tp meshes shard rows + page slabs over dp as
                # well (per-slice pool layout, global ids — see the
                # wrapper's contract); tp-only meshes replicate the pool
                # over dp=1 trivially via the same specs.
                wrapper = (
                    paged_decode_attention_dp_tp
                    if mesh.shape[_DPAX] > 1
                    else paged_decode_attention_tp
                )
                out = wrapper(
                    q[:, 0],
                    k_pages,
                    v_pages,
                    page_table,
                    layer_bounds,
                    mesh,
                    attn_softcap=cfg.attn_softcap,
                    scale=cfg.attn_scale,
                    interpret=pallas_interpret,
                    **qkw,
                )[:, None]
            else:
                out = paged_decode_attention(
                    q[:, 0],
                    k_pages,
                    v_pages,
                    page_table,
                    layer_bounds,
                    attn_softcap=cfg.attn_softcap,
                    scale=cfg.attn_scale,
                    interpret=pallas_interpret,
                    **qkw,
                )[:, None]
        elif use_pallas and single_device:
            from adversarial_spec_tpu.ops.pallas_paged import (
                paged_decode_attention_mq,
            )

            # Multi-position span: the γ+1 queries of each row fold into
            # one grid pass over the row's pages, each under its OWN
            # [start, end) window (in-span causality).
            out = paged_decode_attention_mq(
                q,
                k_pages,
                v_pages,
                page_table,
                start,
                end,
                attn_softcap=cfg.attn_softcap,
                scale=cfg.attn_scale,
                interpret=pallas_interpret,
                **(
                    dict(k_scale=ks_pages, v_scale=vs_pages)
                    if quant_kv
                    else {}
                ),
            )
        else:
            # Gather reference path: page table → dense [B, Hkv, T, D]
            # (densified ONCE per row — the whole span reads it).
            safe_table = jnp.maximum(page_table, 0)

            def to_dense(pages):  # [B, P, Hkv, page, *] → [B, Hkv, T, *]
                g = pages[safe_table]
                return jnp.swapaxes(g, 1, 2).reshape(
                    B, cfg.n_kv_heads, -1, pages.shape[-1]
                )

            if quant_kv:
                k_dense = (
                    to_dense(k_pages).astype(jnp.float32)
                    * to_dense(ks_pages)
                ).astype(x.dtype)
                v_dense = (
                    to_dense(v_pages).astype(jnp.float32)
                    * to_dense(vs_pages)
                ).astype(x.dtype)
            else:
                k_dense = to_dense(k_pages)
                v_dense = to_dense(v_pages)
            T = k_dense.shape[2]
            slot = jnp.arange(T)[None, None, :]
            # <= 0 is unmapped: page 0 is the reserved trash page (callers
            # shift allocator ids +1), negatives are table padding. Same
            # convention as ops/pallas_paged.py.
            mapped = jnp.repeat(
                page_table > 0, page_size, axis=1
            )[:, None, :]
            mask = (
                mapped
                & (slot >= start[..., None])
                & (slot < end[..., None])
            )  # [B, S, T]
            out = attention(
                q,
                k_dense,
                v_dense,
                mask,
                attn_softcap=cfg.attn_softcap,
                scale=cfg.attn_scale,
            )
        x = _attn_out_and_ffn(x, out, lp, cfg, B, S, mm=mm)
        new_l = {"k": k_pages, "v": v_pages}
        if quant_kv:
            new_l.update(ks=ks_pages, vs=vs_pages)
        return x, new_l

    # The pool dict scans as a pytree (same pattern as forward()'s
    # cache): one scan serves both the raw and int8 layouts. Always a
    # decode step here (S=1) → always unrolled for weight-DMA pipelining.
    x, new_pool = jax.lax.scan(
        layer_body,
        x,
        (params["layers"], layer_ids, pool),
        unroll=_DECODE_UNROLL,
    )
    logits = _lm_head_logits(params, cfg, x, lm_head_last_only=False)
    return logits, new_pool


def count_params(params: Params) -> int:
    return sum(x.size for x in jax.tree.leaves(params))
