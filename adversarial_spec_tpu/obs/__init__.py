"""Observability subsystem: metrics registry + flight recorder + retrace watch.

After PRs 1-4 every subsystem kept private counters; this package is the
shared substrate (the north-star metric — per-round wall / tokens/sec/chip
— needs ONE place the next perf PRs read from):

- ``metrics`` — the process-wide :class:`MetricsRegistry` (counters,
  gauges, fixed-bucket histograms; ``snapshot()`` + ``render_prometheus()``).
- ``recorder`` — the process-wide :class:`FlightRecorder` ring of typed
  events (Step/Request/Fault/Breaker/Cache/Compile/Spec/Swap/Span);
  dumped as JSONL on demand (``--events-out``) and automatically on
  fault/timeout eviction and per-request SLO breach.
- ``retrace`` — the :class:`RetraceWatch` counting jit compiles per
  program and flagging unexpected recompiles in the report.
- ``trace`` — causal trace/span ids (one trace per debate round, one
  span per opponent request) every event carries, minted by the debate
  layer and propagated down to the device-step emit sites.

Process-wide config + reset semantics follow the established
``resilience.faults`` / ``prefix_cache`` / ``interleave`` pattern: the
CLI arms per round (``--events-out``, ``--metrics-out``,
``--flight-recorder-size``), stats reset per invocation, engines keep
live handles. Pure stdlib, imports no jax and nothing from engine/ or
resilience/ (they all import obs; cycles are impossible this way).

The one hot-path concession: every emit goes through module-level
``emit()`` / ``record_sync()`` which check ``enabled`` first — when obs
is off the serving path pays a single attribute load per site.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass

from adversarial_spec_tpu.obs import trace  # noqa: F401 (re-export)
from adversarial_spec_tpu.obs.events import (  # noqa: F401 (re-export)
    BreakerEvent,
    CacheEvent,
    CancelEvent,
    CompileEvent,
    EVENT_FIELDS,
    FaultEvent,
    FlightRecorder,
    JournalEvent,
    RecoveryEvent,
    ReplicaEvent,
    RequestEvent,
    RouteEvent,
    LockEvent,
    ScaleEvent,
    ServeEvent,
    SpanEvent,
    SpecEvent,
    StepEvent,
    SwapEvent,
    WeightEvent,
    atomic_write_text,
    validate_event,
)
from adversarial_spec_tpu.obs.metrics import (  # noqa: F401 (re-export)
    LATENCY_BUCKETS_S,
    RATIO_BUCKETS,
    MetricsRegistry,
)
from adversarial_spec_tpu.obs.retrace import RetraceWatch

DEFAULT_RECORDER_SIZE = 512


@dataclass
class ObsConfig:
    """Process-wide knobs, set once per CLI round (or by tests)."""

    enabled: bool = True
    recorder_size: int = DEFAULT_RECORDER_SIZE
    # Where the end-of-round event JSONL lands. Armed by --events-out;
    # fault/timeout auto-dumps write to a sibling path derived from it
    # (``<stem>.<trigger>.jsonl``) so the final dump can never clobber
    # the fault-time snapshot (no path = no auto-dump).
    events_out: str | None = None
    dump_on_fault: bool = True
    # Per-request SLO budgets (0 = disabled). A request breaching its
    # budget arms ONE automatic flight-recorder dump scoped to its
    # trace (same sibling-file discipline as fault dumps), so slow
    # requests self-capture in production: ``slo_ttft_ms`` bounds the
    # request's own prefill wall through its first sampled token,
    # ``slo_round_s`` its full service wall (prefill + decode).
    slo_ttft_ms: float = 0.0
    slo_round_s: float = 0.0
    # Arrival capture (``ADVSPEC_OBS_ARRIVALS``): stamp admission-edge
    # events (RequestEvent/ServeEvent ``arrival_s``) with a monotonic
    # offset from the obs epoch so tools/load_replay.py can reconstruct
    # arrival processes. DEFAULT OFF: real walls on mock events would
    # break the byte-determinism pins every mock dump carries.
    arrivals: bool = False


def env_enabled() -> bool:
    """The process default for the master switch (``ADVSPEC_OBS``)."""
    return os.environ.get("ADVSPEC_OBS", "1") != "0"


def env_recorder_size() -> int:
    """The process default ring size (``ADVSPEC_FLIGHT_RECORDER_SIZE``)."""
    try:
        n = int(
            os.environ.get(
                "ADVSPEC_FLIGHT_RECORDER_SIZE", DEFAULT_RECORDER_SIZE
            )
        )
    except ValueError:
        n = DEFAULT_RECORDER_SIZE
    return max(1, n)


def _env_float(name: str) -> float:
    try:
        return max(0.0, float(os.environ.get(name, "0") or "0"))
    except ValueError:
        return 0.0


def env_slo_ttft_ms() -> float:
    """Process default per-request TTFT budget (``ADVSPEC_SLO_TTFT_MS``,
    milliseconds; 0 = disabled)."""
    return _env_float("ADVSPEC_SLO_TTFT_MS")


def env_slo_round_s() -> float:
    """Process default per-request service budget
    (``ADVSPEC_SLO_ROUND_S``, seconds; 0 = disabled)."""
    return _env_float("ADVSPEC_SLO_ROUND_S")


def env_arrivals() -> bool:
    """Process default for arrival capture (``ADVSPEC_OBS_ARRIVALS``;
    default OFF — the mock byte-determinism pins depend on it)."""
    return os.environ.get("ADVSPEC_OBS_ARRIVALS", "0") == "1"


_config = ObsConfig(
    enabled=env_enabled(),
    recorder_size=env_recorder_size(),
    events_out=os.environ.get("ADVSPEC_EVENTS_OUT") or None,
    slo_ttft_ms=env_slo_ttft_ms(),
    slo_round_s=env_slo_round_s(),
    arrivals=env_arrivals(),
)
# The arrival epoch: ``arrival_s`` offsets are monotonic seconds since
# this point, re-based by reset_stats() so one CLI invocation (or one
# replay run) starts its arrival clock at ~0.
_arrival_t0 = time.monotonic()
# (kind, span_id) pairs that already fired their SLO capture — the
# exactly-once-per-breaching-request guard; cleared by reset_stats().
_slo_fired: set[tuple[str, str]] = set()

metrics = MetricsRegistry()
recorder = FlightRecorder(
    size=_config.recorder_size, enabled=_config.enabled
)
# Route through emit() (defined below; resolved at call time) so
# CompileEvents pick up the ambient trace/span like every other event.
retrace = RetraceWatch(emit=lambda ev: emit(ev))


class HotMetrics:
    """Cached handles into the fixed serving-path metric catalog.

    The registry returns the same object for the same name+labels and
    ``reset()`` zeroes in place, so handles cached once at import stay
    live for the life of the process — hot emit sites (the drive loops,
    the mock's per-request accounting) pay one attribute load per
    observation instead of a lock acquire + label-key build per call.
    Label-dynamic families (sync reasons, fault seam/kind, breaker
    target states) get small per-label dicts, filled on first use.
    """

    __slots__ = (
        "ttft",
        "step_wall",
        "inter_token",
        "prefill_chunk",
        "pool_util",
        "hit_ratio",
        "req_finished",
        "req_evicted",
        "req_timeout",
        "mock_chat_requests",
        "spec_tokens_per_step",
        "spec_acceptance",
        "cancel_tokens_saved",
        "journal_fsync",
        "fleet_replicas_alive",
        "fleet_replicas_desired",
        "fleet_affinity_ratio",
        "serve_backlog",
        "serve_queue_wait",
        "weight_resident",
        "handoff_latency",
        "_m",
        "_sync",
        "_fault",
        "_breaker",
        "_tier_hit",
        "_swap",
        "_cancel",
        "_route",
        "_replica_op",
        "_fleet_scale",
        "_serve_op",
        "_serve_shed",
        "_weight_swap",
        "_handoff",
        "_lock_hold",
        "_lock_wait",
    )

    def __init__(self, m: MetricsRegistry) -> None:
        self._m = m
        self.ttft = m.histogram(
            "advspec_ttft_seconds",
            help="admission prefill through first sampled token",
        )
        self.step_wall = m.histogram(
            "advspec_step_wall_seconds",
            help="drive-loop iteration wall (dispatch+fetch)",
        )
        self.inter_token = m.histogram(
            "advspec_inter_token_seconds",
            help="step wall / decode-chunk budget",
        )
        self.prefill_chunk = m.histogram(
            "advspec_prefill_chunk_wall_seconds",
            help="standalone (stalled) admission prefill chunk wall",
        )
        self.pool_util = m.gauge(
            "advspec_page_pool_utilization",
            help="fraction of KV pages allocated",
        )
        self.hit_ratio = m.gauge(
            "advspec_prefix_cache_hit_ratio",
            help="prefix-cache lookup hit ratio (this round)",
        )
        self.req_finished = m.counter(
            "advspec_requests_total",
            help="resolved requests by outcome",
            outcome="finished",
        )
        self.req_evicted = m.counter(
            "advspec_requests_total", outcome="evicted"
        )
        self.req_timeout = m.counter(
            "advspec_requests_total", outcome="timeout"
        )
        self.mock_chat_requests = m.counter(
            "advspec_engine_chat_requests_total",
            help="chat requests by serving engine",
            engine="mock",
        )
        # Speculative decoding (engine/scheduler.py spec steps and the
        # mock's deterministic acceptance model): tokens each row
        # emitted per verify step (1 = a fully rejected draft, γ+1 = a
        # fully accepted one), and per-request acceptance rate at
        # completion.
        self.spec_tokens_per_step = m.histogram(
            "advspec_spec_tokens_per_step",
            help="tokens emitted per row per speculative verify step",
            buckets=(1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0, 32.0),
        )
        self.spec_acceptance = m.histogram(
            "advspec_spec_acceptance_ratio",
            help="per-request accepted/drafted ratio at completion",
            buckets=RATIO_BUCKETS,
        )
        # Streaming early-convergence cancellation (engine/streaming.py):
        # budget tokens each cancelled request never decoded — the
        # capacity the cancellation converted back into served traffic.
        self.cancel_tokens_saved = m.histogram(
            "advspec_cancel_tokens_saved",
            help="decode-budget tokens saved per cancelled request",
            buckets=(
                8.0, 16.0, 32.0, 64.0, 128.0, 256.0, 512.0, 1024.0,
                2048.0, 4096.0,
            ),
        )
        # Round-journal durability tax (debate/journal.py): the wall of
        # each fsync'd record append — the price of crash-safe rounds,
        # kept visible so a slow disk shows up as a fat tail here
        # instead of as mystery round latency.
        self.journal_fsync = m.histogram(
            "advspec_journal_fsync_seconds",
            help="round-journal fsync'd append wall",
        )
        # Fleet topology (fleet/router.py): routable replica count and
        # the round's affinity hit ratio (requests the ring's PRIMARY
        # choice actually served — failover and breaker-open hops
        # lower it, which is exactly what the gauge is for).
        self.fleet_replicas_alive = m.gauge(
            "advspec_fleet_replicas_alive",
            help="routable engine replicas in the fleet ring",
        )
        self.fleet_affinity_ratio = m.gauge(
            "advspec_fleet_affinity_hit_ratio",
            help="requests served by their affinity-primary replica "
            "(this round)",
        )
        # Elastic fleet (fleet/autoscale.py): the autoscaler's target
        # population next to the actual ring population
        # (fleet_replicas_alive above) — a persistent desired > actual
        # gap is a spawn-failure loop, visible without reading events.
        self.fleet_replicas_desired = m.gauge(
            "advspec_fleet_replicas_desired",
            help="autoscaler target replica count (actual is "
            "advspec_fleet_replicas_alive)",
        )
        # Serve daemon (adversarial_spec_tpu/serve): the scheduler's
        # estimated token backlog (the admission-control pressure
        # signal) and per-unit queue wait (admission -> dispatch — the
        # fairness the stride scheduler is accountable for).
        self.serve_backlog = m.gauge(
            "advspec_serve_backlog_tokens",
            help="serve scheduler estimated token backlog",
        )
        self.serve_queue_wait = m.histogram(
            "advspec_serve_queue_wait_seconds",
            help="opponent-unit wait from admission to dispatch",
        )
        # Weight residency (engine/weightres.py): how many opponent
        # models are device-resident right now — the "one debate pool
        # per TPU" unit-economics gauge.
        self.weight_resident = m.gauge(
            "advspec_weight_resident_models",
            help="opponent models resident in device HBM",
        )
        # Cross-replica KV handoff (fleet/handoff.py): prefill-publish
        # through decode-adoption wall — the disaggregation tax a
        # handoff pays instead of a local re-prefill.
        self.handoff_latency = m.histogram(
            "advspec_kv_handoff_seconds",
            help="cross-replica KV handoff wall (prefill publish "
            "through decode adoption)",
        )
        self._sync: dict = {}
        self._fault: dict = {}
        self._breaker: dict = {}
        self._tier_hit: dict = {}
        self._swap: dict = {}
        self._cancel: dict = {}
        self._route: dict = {}
        self._replica_op: dict = {}
        self._fleet_scale: dict = {}
        self._serve_op: dict = {}
        self._serve_shed: dict = {}
        self._weight_swap: dict = {}
        self._handoff: dict = {}
        self._lock_hold: dict = {}
        self._lock_wait: dict = {}

    def sync(self, reason: str):
        c = self._sync.get(reason)
        if c is None:
            c = self._sync[reason] = self._m.counter(
                "advspec_host_syncs_total",
                help="sanctioned host syncs by reason",
                reason=reason,
            )
        return c

    def fault(self, seam: str, kind: str):
        c = self._fault.get((seam, kind))
        if c is None:
            c = self._fault[(seam, kind)] = self._m.counter(
                "advspec_faults_total",
                help="classified faults by seam and kind",
                seam=seam,
                kind=kind,
            )
        return c

    def breaker(self, to: str):
        c = self._breaker.get(to)
        if c is None:
            c = self._breaker[to] = self._m.counter(
                "advspec_breaker_transitions_total",
                help="circuit-breaker transitions by target state",
                to=to,
            )
        return c

    def tier_hit_ratio(self, tier: str):
        """Per-tier KV hit-ratio gauge (engine/kvtier.py lookups)."""
        g = self._tier_hit.get(tier)
        if g is None:
            g = self._tier_hit[tier] = self._m.gauge(
                "advspec_kv_tier_hit_ratio",
                help="tiered-KV lookup hit ratio by tier (this round)",
                tier=tier,
            )
        return g

    def cancel(self, reason: str):
        """Mid-decode cancellation counter by reason (early_converge
        from the debate layer's marker scanner; other consumers may
        name their own)."""
        c = self._cancel.get(reason)
        if c is None:
            c = self._cancel[reason] = self._m.counter(
                "advspec_cancelled_total",
                help="mid-decode request cancellations by reason",
                reason=reason,
            )
        return c

    def route(self, reason: str):
        """Fleet routing decisions by reason (affinity = the ring's
        primary choice; breaker_open/failover = a re-route hop)."""
        c = self._route.get(reason)
        if c is None:
            c = self._route[reason] = self._m.counter(
                "advspec_fleet_routes_total",
                help="fleet routing decisions by reason",
                reason=reason,
            )
        return c

    def replica_op(self, op: str):
        """Fleet replica lifecycle transitions by op (fleet/router.py
        state machine: spawn/ready/heartbeat_miss/retire/shutdown)."""
        c = self._replica_op.get(op)
        if c is None:
            c = self._replica_op[op] = self._m.counter(
                "advspec_fleet_replica_events_total",
                help="fleet replica lifecycle transitions by op",
                op=op,
            )
        return c

    def fleet_scale(self, direction: str, reason: str):
        """Autoscaler membership changes by direction and trigger
        (fleet/autoscale.py: out/backlog, out/brownout, in/idle,
        out/spawn_failed for an aborted scale-out…)."""
        c = self._fleet_scale.get((direction, reason))
        if c is None:
            c = self._fleet_scale[(direction, reason)] = self._m.counter(
                "advspec_fleet_scale_total",
                help="autoscaler membership changes by direction and "
                "trigger",
                direction=direction,
                reason=reason,
            )
        return c

    def serve_op(self, op: str):
        """Serve-daemon lifecycle transitions by op (serve/sched.py
        state machine: accepted/queued/running/finished/shed/preempted/
        drained plus brownout_enter/brownout_exit)."""
        c = self._serve_op.get(op)
        if c is None:
            c = self._serve_op[op] = self._m.counter(
                "advspec_serve_requests_total",
                help="serve-daemon request lifecycle transitions by op",
                op=op,
            )
        return c

    def serve_shed(self, reason: str):
        """Typed load-shed rejections by reason (serve/protocol.py
        SHED_REASONS) — the shed-not-collapse ledger the overload
        chaos drill audits."""
        c = self._serve_shed.get(reason)
        if c is None:
            c = self._serve_shed[reason] = self._m.counter(
                "advspec_serve_shed_total",
                help="serve-daemon typed load-shed rejections by reason",
                reason=reason,
            )
        return c

    def lock_hold(self, lock: str):
        """Per-lock hold-wall histogram (resilience/lockdep.py
        TrackedLock release path) — a critical section that grew past
        its budget shows up as a fat column here before it shows up as
        contention anywhere else."""
        h = self._lock_hold.get(lock)
        if h is None:
            h = self._lock_hold[lock] = self._m.histogram(
                "advspec_lock_hold_seconds",
                help="tracked-lock hold wall by lock (lockdep)",
                lock=lock,
            )
        return h

    def lock_wait(self, lock: str):
        """Per-lock acquisition-wait histogram (TrackedLock acquire
        path): the contention ledger — waits fatten here long before a
        stall is user-visible, and the deadlock-hammer drill pins the
        families exist."""
        h = self._lock_wait.get(lock)
        if h is None:
            h = self._lock_wait[lock] = self._m.histogram(
                "advspec_lock_wait_seconds",
                help="tracked-lock acquisition wait wall by lock (lockdep)",
                lock=lock,
            )
        return h

    def weight_swap_latency(self, direction: str):
        """Weight-residency swap wall histogram by direction (load:
        cold materialization; in: host→device promotion; out:
        device→host demotion) — residency thrash shows up here as a
        fat ``load`` column that should have been ``in``."""
        h = self._weight_swap.get(direction)
        if h is None:
            h = self._weight_swap[direction] = self._m.histogram(
                "advspec_weight_swap_seconds",
                help="weight residency swap wall by direction",
                direction=direction,
            )
        return h

    def handoff(self, outcome: str):
        """Cross-replica KV handoffs by terminal outcome
        (fleet/handoff.py state machine: adopted = the decode replica's
        first step started from a tier hit; degraded = the lost-race
        fallback re-prefilled locally; abandoned = the handoff died
        before publication)."""
        c = self._handoff.get(outcome)
        if c is None:
            c = self._handoff[outcome] = self._m.counter(
                "advspec_kv_handoff_total",
                help="cross-replica KV handoffs by outcome",
                outcome=outcome,
            )
        return c

    def swap_latency(self, direction: str):
        """KV swap wall histogram by direction (in: promote/rehydrate
        toward the device; out: demote/spill/store away from it)."""
        h = self._swap.get(direction)
        if h is None:
            h = self._swap[direction] = self._m.histogram(
                "advspec_kv_swap_seconds",
                help="KV tier swap wall by direction",
                direction=direction,
            )
        return h


hot = HotMetrics(metrics)


def config() -> ObsConfig:
    return _config


def configure(
    enabled: bool | None = None,
    recorder_size: int | None = None,
    events_out: str | None = None,
    dump_on_fault: bool | None = None,
    slo_ttft_ms: float | None = None,
    slo_round_s: float | None = None,
    arrivals: bool | None = None,
) -> ObsConfig:
    if enabled is not None:
        _config.enabled = bool(enabled)
        recorder.enabled = _config.enabled
    if recorder_size is not None:
        _config.recorder_size = max(1, int(recorder_size))
        recorder.resize(_config.recorder_size)
    if events_out is not None:
        _config.events_out = events_out or None
    if dump_on_fault is not None:
        _config.dump_on_fault = bool(dump_on_fault)
    if slo_ttft_ms is not None:
        _config.slo_ttft_ms = max(0.0, float(slo_ttft_ms))
    if slo_round_s is not None:
        _config.slo_round_s = max(0.0, float(slo_round_s))
    if arrivals is not None:
        _config.arrivals = bool(arrivals)
    return _config


def reset_stats() -> None:
    """Per-invocation reset (one CLI invocation = one round): metrics
    zero in place, the ring clears, the retrace watch starts fresh, and
    the trace-id counter + ambient context + fired-SLO set clear (trace
    state must never leak across CLI invocations). The arrival epoch
    re-bases so a replay run's ``arrival_s`` offsets start at ~0."""
    global _arrival_t0
    metrics.reset()
    recorder.clear()
    retrace.reset()
    trace.reset()
    _slo_fired.clear()
    _arrival_t0 = time.monotonic()


def arrival_now() -> float:
    """The monotonic arrival offset to stamp on an admission-edge event
    RIGHT NOW: seconds since the obs epoch (last reset_stats()), or 0.0
    when arrival capture is unarmed — the default, which keeps mock
    event dumps byte-deterministic. Emit sites call this once at
    admission and thread the value into the event they emit."""
    if _config.enabled and _config.arrivals:
        return time.monotonic() - _arrival_t0
    return 0.0


def emit(ev) -> None:
    """Append one event to the flight recorder (no-op when disabled).
    Events whose ``trace_id``/``span_id`` are empty are stamped from
    the ambient trace context (obs/trace.py): emit sites that know
    their request stamp explicitly; everything else (prefix-cache,
    tier, retrace emits) inherits the request being served."""
    if _config.enabled:
        amb = trace.ambient
        if not ev.trace_id:
            ev.trace_id = amb.trace
        if not ev.span_id:
            ev.span_id = amb.span
        recorder.append(ev)


trace_scope = trace.scope  # re-export: the emitters' stamping scope


def record_sync(reason: str) -> None:
    """Count one sanctioned host sync, labeled by WHY (the runtime
    mirror of GL-SYNC's static triage: every sync the linter sanctions
    shows up here by reason, so an operator sees which sanctioned point
    dominates)."""
    if _config.enabled:
        hot.sync(reason).inc()


def autodump_path(trigger: str) -> str | None:
    """Where an auto-dump for ``trigger`` lands: a sibling of the armed
    ``events_out`` (``ev.jsonl`` -> ``ev.fault.jsonl``). A distinct file
    so the end-of-round dump can never overwrite the fault-time ring
    snapshot — on a long round that survives an early fault, the fault
    events may have aged out of the ring by final dump."""
    base = _config.events_out
    if not base:
        return None
    root, ext = os.path.splitext(base)
    return f"{root}.{trigger}{ext or '.jsonl'}"


def autodump(trigger: str, trace_id: str | None = None) -> str | None:
    """Fault/timeout/SLO auto-dump: write the ring NOW (the drive loop
    may be about to unwind) to the trigger's sibling of ``events_out``.
    ``trace_id`` scopes the dump to one round's causal story (the SLO
    capture path). Returns the path written, or None when no
    destination is armed."""
    path = autodump_path(trigger)
    if not (_config.enabled and _config.dump_on_fault and path):
        return None
    metrics.counter(
        "advspec_flight_recorder_dumps_total",
        help="flight-recorder dumps by trigger",
        trigger=trigger,
    ).inc()
    recorder.dump_jsonl(path, trace_id=trace_id)
    return path


def slo_check(kind: str, span_id: str, wall_s: float) -> str | None:
    """Check one request's measured wall against its SLO budget and, on
    a breach, self-capture: count it and arm ONE flight-recorder dump
    scoped to the request's trace (sibling file ``<stem>.slo_<kind>``,
    the fault-dump discipline). ``kind`` is ``"ttft"`` (budget
    ``slo_ttft_ms``, milliseconds) or ``"round"`` (``slo_round_s``,
    seconds — the per-opponent service wall the source paper's
    convergence protocol makes the user-facing cost unit). Fires at
    most once per (kind, request) — the breach metric and the dump
    alike — so a persistent offender cannot flood the disk. Returns
    the dump path when a capture was written, else None."""
    if not _config.enabled or not span_id:
        return None
    budget = (
        _config.slo_ttft_ms / 1000.0
        if kind == "ttft"
        else _config.slo_round_s
    )
    if budget <= 0.0 or wall_s <= budget:
        return None
    key = (kind, span_id)
    if key in _slo_fired:
        return None
    _slo_fired.add(key)
    metrics.counter(
        "advspec_slo_breaches_total",
        help="per-request SLO budget breaches by kind",
        kind=kind,
    ).inc()
    return autodump(f"slo_{kind}", trace_id=trace.trace_of(span_id))


def slo_breaches() -> dict[str, int]:
    """Breach counts by kind this round (the ``perf.obs.slo`` view)."""
    out: dict[str, int] = {}
    for kind, _ in _slo_fired:
        out[kind] = out.get(kind, 0) + 1
    return dict(sorted(out.items()))


def dump_events(path: str) -> int:
    """On-demand dump (--events-out at end of round). Atomic tmp+rename
    like every obs file write — a tailing reader never sees half a
    dump."""
    return recorder.dump_jsonl(path)


def write_metrics(path: str) -> None:
    """Write the Prometheus text exposition (--metrics-out) atomically
    (tmp+rename, DiskStore's discipline): a scraper hitting the file
    mid-round must read the previous complete exposition, never a torn
    one."""
    atomic_write_text(path, metrics.render_prometheus())


def snapshot() -> dict:
    """The ``perf.obs`` payload: recorder occupancy, event mix, sync
    reasons, and the retrace watch's compile report."""
    syncs = {}
    for key, value in metrics.snapshot().items():
        if key.startswith("advspec_host_syncs_total{"):
            reason = key.split('reason="', 1)[1].rstrip('"}')
            syncs[reason] = value
    return {
        "enabled": _config.enabled,
        "recorder": {
            "size": _config.recorder_size,
            "recorded": recorder.seq,
            "buffered": len(recorder),
            "dropped": recorder.dropped,
        },
        "events_by_type": recorder.counts_by_type(),
        "host_syncs": syncs,
        "retrace": retrace.snapshot(),
        "slo": {
            "ttft_ms": _config.slo_ttft_ms,
            "round_s": _config.slo_round_s,
            "breaches": slo_breaches(),
        },
    }
