"""Flight recorder: a bounded ring buffer of typed structured events.

When a round misbehaves (a fault eviction, a pipeline stall, an
unexpected retrace) the metrics registry says THAT something happened;
the flight recorder says WHAT the batcher was doing step by step.
Design constraints, in order:

- **Cheap when idle.** ``append`` stores a dataclass in a
  ``deque(maxlen=N)`` — no formatting, no I/O. Formatting happens only
  at dump time. Since the serve daemon made multi-threaded emitters
  real (thread-per-debate round drivers emitting SpanEvents
  concurrently), ``append`` takes one uncontended lock so ``seq`` and
  ``dropped`` stay exact; the obs-overhead bench budget absorbs it
  (BENCH_obs.json sat at 0.57% of a 3% budget before the lock).
- **Bounded.** The ring holds the LAST ``size`` events; older ones are
  dropped and counted (``dropped``), never grown over.
- **Deterministic.** Events carry a monotonic ``seq`` and NO wall-clock
  timestamps; float fields hold either synthetic deterministic seconds
  (mock engine) or real walls (TPU scheduler), rounded at dump time. A
  mock round's JSONL is byte-identical across runs.

Event vocabulary (the schema ``tools/obs_dump.py`` validates):

- ``StepEvent`` — one drive-loop dispatch: slot occupancy, the riding
  admission, prefill/decode token counts, pipeline depth, sync reason.
- ``RequestEvent`` — lifecycle transitions
  queued → admitted → prefill → decode → finished / evicted / timeout.
- ``FaultEvent`` — a classified fault with eviction context (slot id,
  pages freed, whether the request was requeued).
- ``BreakerEvent`` — a circuit-breaker state transition.
- ``CacheEvent`` — prefix-cache lookup / insert / evict.
- ``CompileEvent`` — the retrace watch saw a jit compile.
- ``SpecEvent`` — one row's speculative draft/verify outcome.
- ``SwapEvent`` — one KV-tier transition (demote/promote/rehydrate/
  spill/store/free/quarantine) with post-op per-tier residency.
- ``CancelEvent`` — one streaming early-convergence cancellation
  (tokens emitted before the cancel, budget tokens saved).
- ``SpanEvent`` — a causal-trace stage boundary (begin/end, or
  ``cancelled`` closing a request envelope mid-decode) with the
  stage's measured wall on the end record.
- ``JournalEvent`` — one durable round-journal append (record type,
  fsync wall) or journal-serve decision (debate/journal.py).
- ``RecoveryEvent`` — one journal replay at round start: how many
  opponents were served from durable records vs re-issued.
- ``ReplicaEvent`` — one fleet-replica lifecycle transition
  (spawn/ready/heartbeat_miss/retire/shutdown) with the post-op alive
  count (fleet/router.py).
- ``RouteEvent`` — one fleet routing decision: which replica a request
  landed on, the affinity key it hashed, and the failover hop count
  (0 = the ring's primary choice).
- ``WeightEvent`` — one weight-residency transition
  (engine/weightres.py): a model loaded cold, demoted to the host
  tier, promoted back, freed, or a promotion aborted by a fault —
  with the post-op resident/host model counts so residency thrash is
  visible in the timeline, not inferred from round latency.
- ``ServeEvent`` — one serve-daemon lifecycle/pressure transition
  (adversarial_spec_tpu/serve): a debate accepted/shed at admission, an
  opponent unit queued/running/finished/preempted/drained, a brownout
  entry/exit — with the tenant, tier, and post-op backlog so the
  timeline shows WHO was being served and WHO was shed when pressure
  hit.
- ``ScaleEvent`` — one autoscaler membership transition
  (fleet/autoscale.py): a replica provisioned/warming/serving/
  draining/retired, or a spawn that exhausted its retries — with the
  desired and alive counts and the backlog that drove the decision,
  so the timeline shows capacity FOLLOWING pressure, not just
  pressure building.

Causal tracing (obs/trace.py): EVERY event additionally carries
``trace_id`` (the debate round that caused it) and ``span_id`` (the
opponent request), stamped explicitly where the emitter knows its
request and from the ambient trace context otherwise (``obs.emit``
fills empty fields). Both default to "" so events emitted outside any
round (tests, tools) stay valid.
"""

from __future__ import annotations

import dataclasses
import json
import threading
from collections import deque
from dataclasses import dataclass, field


@dataclass(slots=True)
class StepEvent:
    TYPE = "step"
    kind: str = "decode"  # fused | decode | prefill | spec | fused_spec
    n_live: int = 0  # resident rows decoding this step
    admission_slot: int = -1  # slot of the riding admission (-1: none)
    prefill_tokens: int = 0  # prompt tokens advanced this step
    decode_chunk: int = 0  # decode-chunk budget per live row
    pipeline_depth: int = 0  # steps in flight after this dispatch
    sync_reason: str = ""  # why the host synced this step ("" = no sync)
    trace_id: str = ""  # round the step served (ambient)
    span_id: str = ""  # riding admission's request ("" = batch-level)


@dataclass(slots=True)
class RequestEvent:
    TYPE = "request"
    req_id: int = -1
    state: str = "queued"  # queued|admitted|prefill|decode|finished|evicted|timeout
    slot: int = -1
    tokens: int = 0  # tokens relevant to this transition
    cached_tokens: int = 0
    # Monotonic arrival offset (seconds since obs reset) recorded at
    # admission when ADVSPEC_OBS_ARRIVALS is armed (obs.arrival_now());
    # 0.0 otherwise — the default keeps mock dumps byte-deterministic,
    # armed dumps feed tools/load_replay.py's trace reconstruction.
    arrival_s: float = 0.0
    trace_id: str = ""
    span_id: str = ""


@dataclass(slots=True)
class FaultEvent:
    TYPE = "fault"
    seam: str = ""
    kind: str = ""
    slot: int = -1
    req_id: int = -1
    pages_freed: int = 0
    requeued: bool = False
    error: str = ""
    trace_id: str = ""  # the injured request's round
    span_id: str = ""  # the injured request itself


@dataclass(slots=True)
class BreakerEvent:
    TYPE = "breaker"
    model: str = ""
    frm: str = ""
    to: str = ""
    trace_id: str = ""
    span_id: str = ""


@dataclass(slots=True)
class CacheEvent:
    TYPE = "cache"
    op: str = "lookup"  # lookup | insert | evict
    matched_tokens: int = 0
    blocks: int = 0
    pages: int = 0
    hit: bool = False
    trace_id: str = ""  # admission that drove the op (ambient)
    span_id: str = ""


@dataclass(slots=True)
class CompileEvent:
    TYPE = "compile"
    program: str = ""
    key: str = ""
    n_compiles: int = 0
    unexpected: bool = False
    trace_id: str = ""  # request whose dispatch compiled (ambient)
    span_id: str = ""


@dataclass(slots=True)
class SpecEvent:
    """One row's speculative draft/verify outcome (CacheEvent-style:
    per-observation, the recorder's bounded ring keeps the recent ones).
    ``drafted`` counts positions ELIGIBLE to commit (the budget/page
    clamped draft width), so accepted/drafted is a true acceptance rate;
    ``emitted`` includes the bonus/rejection token; ``rolled_back_pages``
    is the draft tail the host released after the accept fetch."""

    TYPE = "spec"
    slot: int = -1
    req_id: int = -1
    drafted: int = 0
    accepted: int = 0
    emitted: int = 0
    rolled_back_pages: int = 0
    trace_id: str = ""
    span_id: str = ""


@dataclass(slots=True)
class SwapEvent:
    """One KV-tier state transition (engine/kvtier.py). ``op`` names
    the edge of the tier state machine (demote: device→host; promote:
    host→device; rehydrate: disk→device; spill: host LRU→disk; store:
    insert write-through→disk; free: host LRU drop; quarantine: corrupt
    disk entry moved aside; ship: prefill-side handoff publication to
    the shared store; prefetch: decode-side hint probe ahead of
    adoption). ``host_resident``/``disk_resident`` are the per-tier
    block counts AFTER the op — tools/obs_dump.py's occupancy timeline
    reads tier residency off these."""

    TYPE = "swap"
    op: str = "demote"
    tier: str = "host"  # tier the op targets
    blocks: int = 0
    tokens: int = 0
    slot: int = -1  # admission slot driving the swap (-1: none)
    host_resident: int = 0
    disk_resident: int = 0
    trace_id: str = ""  # admission that drove the swap (ambient)
    span_id: str = ""


@dataclass(slots=True)
class CancelEvent:
    """One streaming early-convergence cancellation
    (engine/streaming.py): the request's consumer saw everything it
    needed (its verdict marker arrived) and the batcher stopped
    decoding it — a HAPPY-path event, not a fault. ``tokens_emitted``
    is the partial transcript's length at the cancel point;
    ``tokens_saved`` the budget remainder that was never decoded (the
    capacity the freed slot immediately re-admits queued work into)."""

    TYPE = "cancel"
    req_id: int = -1
    slot: int = -1
    reason: str = "early_converge"
    tokens_emitted: int = 0
    tokens_saved: int = 0
    trace_id: str = ""
    span_id: str = ""


@dataclass(slots=True)
class SpanEvent:
    """A causal-trace stage boundary (obs/trace.py id model). ``begin``
    marks entry into a stage (``wall_s`` 0), ``end`` carries the
    stage's measured wall — synthetic deterministic seconds from the
    mock engine, real walls from the scheduler, exactly the float
    convention every other event follows. The per-request stage
    vocabulary the scheduler and mock both emit (``queued`` →
    ``prefill`` → ``decode`` under a ``request`` envelope whose end
    wall is the request's SERVICE time, prefill + decode — the
    decomposition ``tools/trace_view.py`` CHECKS, not just renders)
    plus the debate layer's ``round``/``opponent`` spans."""

    TYPE = "span"
    name: str = ""  # request|queued|prefill|decode|round|opponent|...
    phase: str = "begin"  # begin | end | cancelled (request envelopes)
    req_id: int = -1
    slot: int = -1
    wall_s: float = 0.0  # stage duration, set on the end record
    trace_id: str = ""
    span_id: str = ""


@dataclass(slots=True)
class JournalEvent:
    """One crash-safe round-journal operation (debate/journal.py).
    ``append`` is a durable fsync'd record append (``fsync_s`` holds
    the write+fsync wall — the durability tax the journal-fsync
    histogram aggregates); ``serve`` marks one opponent resolved from
    a replayed record with zero engine work."""

    TYPE = "journal"
    op: str = "append"  # append | serve
    rtype: str = ""  # record type (round_start|completion|partial|round_commit)
    round_num: int = 0
    index: int = -1  # opponent index within the round (-1: round-level)
    fsync_s: float = 0.0
    trace_id: str = ""
    span_id: str = ""


@dataclass(slots=True)
class RecoveryEvent:
    """One journal replay at round start (``--resume`` after a crash):
    ``served`` opponents resolved from durable completion records,
    ``reissued`` re-enter the engine, ``records`` journal records were
    readable and ``skipped`` were torn/foreign-version and ignored."""

    TYPE = "recovery"
    round_num: int = 0
    served: int = 0
    reissued: int = 0
    records: int = 0
    skipped: int = 0
    trace_id: str = ""
    span_id: str = ""


@dataclass(slots=True)
class ReplicaEvent:
    """One fleet-replica lifecycle transition (fleet/router.py state
    machine). ``op`` names the edge: spawn (handle created), ready
    (transport answered its first ping), heartbeat_miss (a health
    probe failed), retire (the shared retirement surgery ran — the
    replica left the ring and its in-flight work was re-routed),
    shutdown (orderly fleet teardown). ``alive`` is the routable
    replica count AFTER the op, so the timeline shows capacity
    draining the moment it happens."""

    TYPE = "replica"
    replica: str = ""
    op: str = "spawn"
    reason: str = ""  # retire cause: dead | heartbeat | fault | shutdown
    alive: int = 0  # routable replicas after this op
    trace_id: str = ""
    span_id: str = ""


@dataclass(slots=True)
class RouteEvent:
    """One fleet routing decision (fleet/router.py). ``hop`` counts
    failover re-routes for the request (0 = the consistent-hash ring's
    primary choice for its affinity key); ``reason`` says why THIS
    replica: affinity (primary), breaker_open (primary's per-
    (replica, model) circuit was open), failover (an earlier hop's
    replica died mid-request), random (affinity routing disabled —
    the bench's control arm)."""

    TYPE = "route"
    replica: str = ""
    req_id: int = -1
    key: str = ""  # affinity key the ring hashed
    model: str = ""
    hop: int = 0
    reason: str = "affinity"
    trace_id: str = ""
    span_id: str = ""


@dataclass(slots=True)
class WeightEvent:
    """One weight-residency state transition (engine/weightres.py).
    ``op`` names the edge of the residency state machine (load: cold
    materialization; demote: device→host shard paging; promote:
    host→device re-activation; free: eviction without paging / host
    LRU overflow; swap_fault: a promotion aborted mid-swap — the host
    entry survives untouched). ``resident``/``host`` are the per-tier
    model counts AFTER the op; ``wall_s`` the swap's measured wall
    (synthetic deterministic seconds from the mock engine)."""

    TYPE = "weight"
    op: str = "load"
    alias: str = ""
    nbytes: int = 0
    wall_s: float = 0.0
    resident: int = 0
    host: int = 0
    trace_id: str = ""  # round whose group drove the swap (ambient)
    span_id: str = ""


@dataclass(slots=True)
class ServeEvent:
    """One serve-daemon transition (adversarial_spec_tpu/serve). ``op``
    names the edge of the request lifecycle state machine (accepted →
    queued → running → finished | shed | preempted | drained) or a
    pressure transition (brownout_enter / brownout_exit). ``debate`` is
    the daemon-assigned request id; ``index`` the opponent unit within
    it (-1 = debate-level). ``reason`` carries the typed shed/preempt/
    drain cause (serve/protocol.py SHED_REASONS and ``tier_pressure``
    for policy preemptions); ``backlog_tokens`` is the scheduler's
    estimated token backlog AFTER the op, so the timeline shows
    pressure building and draining."""

    TYPE = "serve"
    op: str = "accepted"
    tenant: str = ""
    tier: str = "interactive"
    debate: str = ""
    index: int = -1
    reason: str = ""
    tokens: int = 0
    backlog_tokens: int = 0
    # Monotonic arrival offset (seconds since obs reset) stamped on the
    # admission-edge ops (accepted/shed) when ADVSPEC_OBS_ARRIVALS is
    # armed; 0.0 otherwise (the byte-determinism default). The replay
    # harness (tools/load_replay.py) reconstructs per-tenant arrival
    # processes from these offsets.
    arrival_s: float = 0.0
    trace_id: str = ""
    span_id: str = ""


@dataclass(slots=True)
class ScaleEvent:
    """One elastic-fleet membership transition (fleet/autoscale.py
    lifecycle machine). ``op`` names the edge the replica crossed
    (provision → warming → serving on scale-out; draining → retired on
    scale-in; spawn_failed when the bounded spawn retry gave up).
    ``direction`` is the scaling decision that caused it ("out"/"in",
    "" for shutdown teardown); ``reason`` the trigger (backlog,
    brownout, idle, spawn_failed, shutdown…). ``desired``/``alive``
    are the autoscaler's target and the routable ring population AFTER
    the op, and ``backlog_tokens`` the scheduler backlog that drove
    the decision — the timeline shows capacity following pressure."""

    TYPE = "scale"
    replica: str = ""
    op: str = "provision"
    direction: str = ""  # out | in | "" (teardown / informational)
    reason: str = ""
    desired: int = 0
    alive: int = 0
    backlog_tokens: int = 0
    trace_id: str = ""
    span_id: str = ""


@dataclass(slots=True)
class LockEvent:
    """One lockdep sanitizer detection (resilience/lockdep.py). ``op``
    is the violation kind; ``lock`` the lock whose acquisition closed
    the cycle, ``held`` the lock held at that moment, and ``edge`` the
    offending acquisition-order edge (``"held->lock"``). The full
    stacks live in the LockOrderViolation the sanitizer records (and
    in the auto-dumped ring's surrounding events) — an event field is
    not the place for a multi-KB traceback."""

    TYPE = "lock"
    op: str = "violation"
    lock: str = ""
    held: str = ""
    edge: str = ""
    trace_id: str = ""
    span_id: str = ""


EVENT_TYPES = (
    StepEvent,
    RequestEvent,
    FaultEvent,
    BreakerEvent,
    CacheEvent,
    CompileEvent,
    SpecEvent,
    SwapEvent,
    CancelEvent,
    SpanEvent,
    JournalEvent,
    RecoveryEvent,
    ReplicaEvent,
    RouteEvent,
    WeightEvent,
    ServeEvent,
    ScaleEvent,
    LockEvent,
)

# ``cancelled`` closes a request envelope mid-decode (streaming early
# convergence): it carries the service wall exactly like ``end``, so
# trace_view's decomposition check covers cancelled requests too.
SPAN_PHASES = ("begin", "end", "cancelled")

SWAP_OPS = (
    "demote",
    "promote",
    "rehydrate",
    "spill",
    "store",
    "free",
    "quarantine",
    "ship",
    "prefetch",
)

# The weight-residency state machine's edges (engine/weightres.py) —
# graftlint's fourth GL-LIFECYCLE machine enforces the code side of
# the same contract (every transition through one ledger surgery).
WEIGHT_OPS = (
    "load",
    "demote",
    "promote",
    "free",
    "swap_fault",
)

REPLICA_OPS = (
    "spawn",
    "ready",
    "heartbeat_miss",
    "retire",
    "shutdown",
)

ROUTE_REASONS = (
    "affinity",
    "breaker_open",
    "failover",
    "random",
    # Disaggregated fleet (fleet/handoff.py): the prefill-role hop of
    # a cross-replica KV handoff — the decode hop that follows it
    # routes with its own reason (affinity within the decode pool).
    "prefill",
)

# The serve-daemon request lifecycle (docs/serving.md state machine)
# plus the brownout pressure transitions. graftlint's third
# GL-LIFECYCLE machine enforces the code side of the same contract:
# every exit op below maps to a path through the scheduler's one
# release surgery.
SERVE_OPS = (
    "accepted",
    "queued",
    "running",
    "finished",
    "shed",
    "preempted",
    "drained",
    "brownout_enter",
    "brownout_exit",
)

SERVE_TIERS = ("interactive", "batch")

# The autoscaler's replica lifecycle (fleet/autoscale.py state
# machine) — graftlint's fifth GL-LIFECYCLE machine enforces the code
# side of the same contract (every exit through one ``_decommission``
# surgery). ``spawn_failed`` is the one non-state edge: a scale-out
# whose bounded spawn retry exhausted before the replica ever existed.
SCALE_OPS = (
    "provision",
    "warming",
    "serving",
    "draining",
    "retired",
    "spawn_failed",
)

SCALE_DIRECTIONS = ("out", "in", "")

# The lockdep sanitizer's detections (resilience/lockdep.py): today
# only order inversions — the op whitelist exists so a future
# hold-too-long / wait-too-long detector extends the vocabulary here
# instead of minting untyped strings.
LOCK_OPS = ("violation",)

REQUEST_STATES = (
    "queued",
    "admitted",
    "prefill",
    "decode",
    "finished",
    "evicted",
    "timeout",
    "cancelled",
)

# type name -> {field name: python type} — the schema contract
# tools/obs_dump.py validates every JSONL line against. Derived from
# the dataclasses so it can never drift from the emitters.
EVENT_FIELDS: dict[str, dict[str, type]] = {
    cls.TYPE: {f.name: f.type for f in dataclasses.fields(cls)}
    for cls in EVENT_TYPES
}
_PY_TYPES = {"int": int, "str": str, "bool": bool, "float": float}


def event_to_dict(seq: int, ev) -> dict:
    """Stable field order: seq, type, then dataclass declaration order."""
    out: dict = {"seq": seq, "type": ev.TYPE}
    for f in dataclasses.fields(ev):
        v = getattr(ev, f.name)
        if isinstance(v, float):
            v = round(v, 6)
        out[f.name] = v
    return out


def validate_event(obj) -> list[str]:
    """Schema-check one decoded JSONL line; returns human-readable
    problems (empty = valid). Shared by the recorder's own tests and
    tools/obs_dump.py."""
    errors: list[str] = []
    if not isinstance(obj, dict):
        return [f"not an object: {obj!r}"]
    etype = obj.get("type")
    if etype not in EVENT_FIELDS:
        return [f"unknown event type {etype!r}"]
    if not isinstance(obj.get("seq"), int):
        errors.append("missing/non-int 'seq'")
    fields = EVENT_FIELDS[etype]
    for name, anno in fields.items():
        if name not in obj:
            errors.append(f"{etype}: missing field {name!r}")
            continue
        py = _PY_TYPES.get(anno if isinstance(anno, str) else anno.__name__)
        v = obj[name]
        if py is bool:
            ok = isinstance(v, bool)
        elif py is int:
            ok = isinstance(v, int) and not isinstance(v, bool)
        elif py is float:
            ok = isinstance(v, (int, float)) and not isinstance(v, bool)
        elif py is str:
            ok = isinstance(v, str)
        else:  # pragma: no cover - schema only uses the four above
            ok = True
        if not ok:
            errors.append(
                f"{etype}: field {name!r} expected {anno}, got {type(v).__name__}"
            )
    for name in obj:
        if name not in fields and name not in ("seq", "type"):
            errors.append(f"{etype}: unknown field {name!r}")
    if etype == "request" and obj.get("state") not in REQUEST_STATES:
        errors.append(f"request: unknown state {obj.get('state')!r}")
    if etype == "swap" and obj.get("op") not in SWAP_OPS:
        errors.append(f"swap: unknown op {obj.get('op')!r}")
    if etype == "span" and obj.get("phase") not in SPAN_PHASES:
        errors.append(f"span: unknown phase {obj.get('phase')!r}")
    if etype == "weight" and obj.get("op") not in WEIGHT_OPS:
        errors.append(f"weight: unknown op {obj.get('op')!r}")
    if etype == "replica" and obj.get("op") not in REPLICA_OPS:
        errors.append(f"replica: unknown op {obj.get('op')!r}")
    if etype == "route" and obj.get("reason") not in ROUTE_REASONS:
        errors.append(f"route: unknown reason {obj.get('reason')!r}")
    if etype == "serve":
        if obj.get("op") not in SERVE_OPS:
            errors.append(f"serve: unknown op {obj.get('op')!r}")
        if obj.get("tier") not in SERVE_TIERS:
            errors.append(f"serve: unknown tier {obj.get('tier')!r}")
    if etype == "scale":
        if obj.get("op") not in SCALE_OPS:
            errors.append(f"scale: unknown op {obj.get('op')!r}")
        if obj.get("direction") not in SCALE_DIRECTIONS:
            errors.append(
                f"scale: unknown direction {obj.get('direction')!r}"
            )
    if etype == "lock" and obj.get("op") not in LOCK_OPS:
        errors.append(f"lock: unknown op {obj.get('op')!r}")
    return errors


def _recorder_lock():
    """The recorder's mutation lock through the lockdep seam
    (resilience/lockdep.py), ``metrics=False``: a histogram observe
    takes the metrics-registry lock, so obs-internal locks must never
    observe themselves. Lazy import — obs loads before resilience in
    some import orders, and the recorder must construct either way."""
    try:
        from adversarial_spec_tpu.resilience import lockdep
    except ImportError:  # pragma: no cover - partial-init fallback
        return threading.Lock()
    return lockdep.make_lock("FlightRecorder._lock", metrics=False)


@dataclass
class FlightRecorder:
    """Bounded ring of (seq, event); the last ``size`` events survive."""

    size: int = 512
    enabled: bool = True
    seq: int = 0  # total events ever appended (monotonic)
    dropped: int = 0  # events pushed out of the ring
    _buf: deque = field(default_factory=deque)
    # Serializes seq/dropped/_buf mutation: the serve daemon's debate
    # threads emit concurrently (buffered + dropped == seq must hold
    # exactly — the chaos fuzz pins it).
    _lock: object = field(default_factory=_recorder_lock)

    def __post_init__(self) -> None:
        self._buf = deque(self._buf, maxlen=self.size)

    def append(self, ev) -> None:
        if not self.enabled:
            return
        with self._lock:
            self.seq += 1
            if len(self._buf) == self._buf.maxlen:
                self.dropped += 1
            self._buf.append((self.seq, ev))

    def __len__(self) -> int:
        return len(self._buf)

    def resize(self, size: int) -> None:
        size = max(1, int(size))
        if size != self.size:
            with self._lock:
                self.size = size
                # Shrinking ages out the oldest events — they are drops
                # like any other (buffered + dropped == seq must hold).
                self.dropped += max(0, len(self._buf) - size)
                self._buf = deque(self._buf, maxlen=size)

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()
            self.seq = 0
            self.dropped = 0

    def events(self, trace_id: str | None = None) -> list[dict]:
        """Buffered events as dicts; ``trace_id`` scopes to one round's
        causal story (the SLO auto-dump's view). Snapshots the ring
        under the lock first — a daemon auto-dump must not race a
        concurrent debate thread's append mid-iteration."""
        with self._lock:
            items = list(self._buf)
        return [
            event_to_dict(seq, ev)
            for seq, ev in items
            if trace_id is None or ev.trace_id == trace_id
        ]

    def counts_by_type(self) -> dict[str, int]:
        with self._lock:
            items = list(self._buf)
        out: dict[str, int] = {}
        for _, ev in items:
            out[ev.TYPE] = out.get(ev.TYPE, 0) + 1
        return dict(sorted(out.items()))

    def to_jsonl(self, trace_id: str | None = None) -> str:
        lines = [
            json.dumps(e, separators=(",", ":"))
            for e in self.events(trace_id)
        ]
        return "\n".join(lines) + ("\n" if lines else "")

    def dump_jsonl(self, path: str, trace_id: str | None = None) -> int:
        """Write the buffered events as JSONL; returns the line count
        written. ``trace_id`` scopes the dump to one round's events
        (SLO-triggered captures). Atomic via the shared tmp+rename
        discipline (DiskStore's): the auto-dump fires mid-fault,
        possibly mid-crash, and a reader must never see a torn file."""
        data = self.to_jsonl(trace_id)
        atomic_write_text(path, data)
        return data.count("\n")


def atomic_write_text(path: str, data: str) -> None:
    """Write ``data`` to ``path`` atomically: a pid-suffixed temp file
    in the same directory, then ``os.replace`` (atomic on POSIX) —
    DiskStore's discipline (engine/kvtier.py). A reader polling the
    path (a Prometheus scraper on --metrics-out, a tail on the events
    JSONL) sees either the old complete file or the new complete file,
    never a torn one; a crashed writer leaves only a ``.tmp`` orphan."""
    import os

    tmp = f"{path}.{os.getpid()}.tmp"
    try:
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(data)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
