"""Process-wide metrics registry: counters, gauges, fixed-bucket histograms.

The serving path grew one ad-hoc stats module per subsystem
(``resilience.faults``, ``prefix_cache.stats``, ``interleave.stats``);
this registry is the shared substrate the next perf PRs are measured
with — ONE process-wide home for named metrics with:

- a stable ``snapshot()`` dict (sorted keys, plain scalars — the
  ``perf.obs`` building block);
- Prometheus text exposition (``render_prometheus()``) so an operator
  can scrape a serving host with zero extra plumbing;
- per-invocation ``reset()`` semantics matching the existing pattern:
  values zero in place, so engines holding a metric handle keep
  recording into the same object across rounds.

Deliberately pure stdlib and jax-free: the mock engine records the same
metric names with synthetic deterministic values, so the whole catalog
pins on CPU. No wall-clock timestamps ever enter a metric — rendered
output is byte-deterministic given deterministic observations.
"""

from __future__ import annotations

import math
import threading
from dataclasses import dataclass, field

# Fixed default latency buckets (seconds). Chosen to straddle the
# serving path's real scales: sub-ms host bookkeeping, ms-scale chunk
# dispatches, and multi-second model loads. Fixed buckets (vs adaptive)
# keep exposition byte-stable across runs.
LATENCY_BUCKETS_S = (
    0.0005,
    0.001,
    0.0025,
    0.005,
    0.01,
    0.025,
    0.05,
    0.1,
    0.25,
    0.5,
    1.0,
    2.5,
    5.0,
    10.0,
    30.0,
)

# Ratio-shaped histograms (utilization, hit rates) bucket on [0, 1].
RATIO_BUCKETS = (0.1, 0.25, 0.5, 0.75, 0.9, 0.95, 0.99, 1.0)

# The quantiles every histogram exposes in snapshot()/render_prometheus()
# — THE shared percentile vocabulary (load_replay, the SLO gates, and
# bench.py report the same three, so a latency tail reads the same
# everywhere).
QUANTILES = (0.5, 0.95, 0.99)


def percentile(samples, q: float) -> float:
    """Exact nearest-rank percentile of a sample list (sorted copy made
    here) — THE one sample-percentile implementation: the overload
    drill's SLO gate (tools/chaos_run.py), bench.py's per-arm TTFT
    tails, and tools/load_replay.py all report through this instead of
    each hand-rolling an off-by-one index. Returns 0.0 on an empty
    sample set (a quantile of nothing is not a latency)."""
    xs = sorted(float(v) for v in samples)
    if not xs:
        return 0.0
    q = min(max(float(q), 0.0), 1.0)
    rank = max(1, math.ceil(q * len(xs)))
    return xs[rank - 1]


def _fmt(v: float) -> str:
    """Deterministic Prometheus value formatting: integral floats render
    as integers (``3`` not ``3.0``), the rest via repr (shortest
    round-trip form — stable for a given float)."""
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _label_str(labels: tuple[tuple[str, str], ...]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in labels)
    return "{" + inner + "}"


@dataclass
class Counter:
    """Monotonic counter. ``inc`` only; ``reset`` zeroes in place."""

    value: float = 0.0

    def inc(self, v: float = 1.0) -> None:
        self.value += v

    def reset(self) -> None:
        self.value = 0.0


@dataclass
class Gauge:
    """Last-write-wins scalar."""

    value: float = 0.0

    def set(self, v: float) -> None:
        self.value = float(v)

    def reset(self) -> None:
        self.value = 0.0


@dataclass
class Histogram:
    """Fixed-bucket histogram (cumulative on render, like Prometheus).

    ``buckets`` holds upper bounds in ascending order; observations
    above the last bound land only in the implicit +Inf bucket.
    """

    buckets: tuple = LATENCY_BUCKETS_S
    counts: list[int] = field(default_factory=list)
    sum: float = 0.0
    count: int = 0

    def __post_init__(self) -> None:
        if not self.counts:
            self.counts = [0] * len(self.buckets)

    def observe(self, v: float) -> None:
        v = float(v)
        self.sum += v
        self.count += 1
        for i, bound in enumerate(self.buckets):
            if v <= bound:
                self.counts[i] += 1
                break

    def cumulative(self) -> list[int]:
        out, running = [], 0
        for c in self.counts:
            running += c
            out.append(running)
        return out

    def quantile(self, q: float) -> float:
        """Bucket-interpolated quantile estimate (Prometheus
        ``histogram_quantile`` semantics): find the bucket holding the
        q·count-th observation and interpolate linearly between its
        bounds. Observations past the last bound clamp to the last
        bound — a fixed-bucket histogram cannot see further. 0.0 when
        empty."""
        if self.count <= 0:
            return 0.0
        q = min(max(float(q), 0.0), 1.0)
        rank = q * self.count
        running = 0
        for i, bound in enumerate(self.buckets):
            prev = running
            running += self.counts[i]
            if running >= rank and self.counts[i] > 0:
                lo = self.buckets[i - 1] if i > 0 else 0.0
                frac = (rank - prev) / self.counts[i]
                return lo + (bound - lo) * min(max(frac, 0.0), 1.0)
        return float(self.buckets[-1]) if self.buckets else 0.0

    def reset(self) -> None:
        self.counts = [0] * len(self.buckets)
        self.sum = 0.0
        self.count = 0


def _registry_lock():
    """The registry lock through the lockdep seam, ``metrics=False``:
    every histogram observe takes THIS lock, so instrumenting it would
    recurse. Lazy import — obs loads before resilience in some import
    orders, and the registry must construct either way."""
    try:
        from adversarial_spec_tpu.resilience import lockdep
    except ImportError:  # pragma: no cover - partial-init fallback
        return threading.Lock()
    return lockdep.make_lock("MetricsRegistry._lock", metrics=False)


class MetricsRegistry:
    """Named metrics with optional labels; one instance per process.

    ``counter("x", seam="generate")`` returns the same Counter object on
    every call with the same name+labels — handles are cacheable and
    reset-in-place keeps them live across rounds. A name is permanently
    one kind: re-registering ``x`` as a gauge after a counter raises
    (silent kind drift would corrupt the exposition).
    """

    def __init__(self) -> None:
        self._lock = _registry_lock()
        # name -> (kind, help, {labels_tuple: metric})
        self._families: dict[str, tuple[str, str, dict]] = {}

    def _get(self, kind: str, name: str, help_: str, labels: dict, factory):
        key = tuple(sorted((k, str(v)) for k, v in labels.items()))
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = (kind, help_, {})
                self._families[name] = fam
            elif fam[0] != kind:
                raise ValueError(
                    f"metric {name!r} already registered as {fam[0]}, "
                    f"not {kind}"
                )
            metric = fam[2].get(key)
            if metric is None:
                metric = factory()
                fam[2][key] = metric
            return metric

    def counter(self, name: str, help: str = "", **labels) -> Counter:
        return self._get("counter", name, help, labels, Counter)

    def gauge(self, name: str, help: str = "", **labels) -> Gauge:
        return self._get("gauge", name, help, labels, Gauge)

    def histogram(
        self, name: str, help: str = "", buckets: tuple | None = None, **labels
    ) -> Histogram:
        b = tuple(buckets) if buckets is not None else LATENCY_BUCKETS_S
        return self._get(
            "histogram", name, help, labels, lambda: Histogram(buckets=b)
        )

    def reset(self) -> None:
        """Zero every metric IN PLACE (handles stay valid — the
        resilience/interleave reset contract)."""
        with self._lock:
            for _, _, series in self._families.values():
                for metric in series.values():
                    metric.reset()

    def snapshot(self) -> dict:
        """Stable dict of every series: ``name{labels}`` → scalar for
        counters/gauges, ``{count, sum, p50, p95, p99}`` for histograms
        (bucket-estimated quantiles — see ``Histogram.quantile``).
        Sorted keys."""
        out: dict = {}
        with self._lock:
            for name in sorted(self._families):
                kind, _, series = self._families[name]
                for key in sorted(series):
                    metric = series[key]
                    k = name + _label_str(key)
                    if kind == "histogram":
                        out[k] = {
                            "count": metric.count,
                            "sum": round(metric.sum, 6),
                            "p50": round(metric.quantile(0.5), 6),
                            "p95": round(metric.quantile(0.95), 6),
                            "p99": round(metric.quantile(0.99), 6),
                        }
                    else:
                        out[k] = (
                            int(metric.value)
                            if float(metric.value).is_integer()
                            else metric.value
                        )
        return out

    def render_prometheus(self) -> str:
        """Prometheus text exposition format v0.0.4. Families sort by
        name and series by labels, so output is byte-deterministic for
        deterministic observations (no timestamps are ever emitted)."""
        lines: list[str] = []
        with self._lock:
            for name in sorted(self._families):
                kind, help_, series = self._families[name]
                if help_:
                    lines.append(f"# HELP {name} {help_}")
                lines.append(f"# TYPE {name} {kind}")
                for key in sorted(series):
                    metric = series[key]
                    if kind == "histogram":
                        cum = metric.cumulative()
                        total = metric.count
                        for bound, c in zip(metric.buckets, cum):
                            lbl = key + (("le", _fmt(bound)),)
                            lines.append(
                                f"{name}_bucket{_label_str(lbl)} {c}"
                            )
                        lbl = key + (("le", "+Inf"),)
                        lines.append(
                            f"{name}_bucket{_label_str(lbl)} {total}"
                        )
                        lines.append(
                            f"{name}_sum{_label_str(key)} {_fmt(metric.sum)}"
                        )
                        lines.append(
                            f"{name}_count{_label_str(key)} {total}"
                        )
                        for q in QUANTILES:
                            suffix = f"p{int(q * 100)}"
                            val = round(metric.quantile(q), 6)
                            lines.append(
                                f"{name}_{suffix}{_label_str(key)} "
                                f"{_fmt(val)}"
                            )
                    else:
                        lines.append(
                            f"{name}{_label_str(key)} {_fmt(metric.value)}"
                        )
        return "\n".join(lines) + ("\n" if lines else "")
