"""Retrace watch: count jit compiles per program; flag unexpected ones.

GL-RETRACE (tools/graftlint) statically proves the scheduler's jit
static args are bounded; this is its runtime counterpart. Every chunk
dispatch reports its program name + the host-side dispatch key (the
static-arg/shape tuple the trace cache keys on, as the caller knows it).
Compiles are detected two ways:

- **cache-miss probe**: when the jitted callable exposes a trace-cache
  size (``_cache_size()`` on PjitFunction), a growth between dispatches
  IS a compile — exact, including recompiles the host key missed;
- **key novelty** (fallback): a never-seen dispatch key means a compile
  on any correct cache.

A compile whose dispatch key was ALREADY seen is an **unexpected
recompile** — some argument the host believed static/stable wasn't
(weak_type flips, dtype drift, a donated-buffer shape change). Those
are exactly the silent 100x slowdowns the report must surface, so they
are flagged per program and totalled in ``snapshot()``.
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class _ProgramWatch:
    keys: set = field(default_factory=set)
    compiles: int = 0
    unexpected: int = 0
    dispatches: int = 0
    last_cache_size: int | None = None


def _cache_size(fn) -> int | None:
    if fn is None:
        return None
    probe = getattr(fn, "_cache_size", None)
    if probe is None:
        return None
    try:
        return int(probe())
    except Exception:
        return None


class RetraceWatch:
    """Per-program compile accounting. Host-side dict ops per dispatch;
    emits a CompileEvent (via the callback installed by obs.__init__)
    only when a compile actually happened."""

    def __init__(self, emit=None) -> None:
        self._programs: dict[str, _ProgramWatch] = {}
        self._emit = emit  # callable(CompileEvent) | None

    def observe(self, program: str, key: tuple, fn=None) -> bool:
        """Record one dispatch of ``program`` with host dispatch ``key``
        (call AFTER the dispatch so a cache-size probe sees the new
        entry). Returns True when a compile was detected."""
        w = self._programs.get(program)
        if w is None:
            w = self._programs[program] = _ProgramWatch()
        w.dispatches += 1
        new_key = key not in w.keys
        w.keys.add(key)
        size = _cache_size(fn)
        if size is not None:
            compiled = w.last_cache_size is None or size > w.last_cache_size
            w.last_cache_size = size
        else:
            compiled = new_key
        if not compiled:
            return False
        w.compiles += 1
        unexpected = not new_key
        if unexpected:
            w.unexpected += 1
        if self._emit is not None:
            from adversarial_spec_tpu.obs.events import CompileEvent

            self._emit(
                CompileEvent(
                    program=program,
                    key=repr(key),
                    n_compiles=w.compiles,
                    unexpected=unexpected,
                )
            )
        return True

    def reset(self) -> None:
        """Per-invocation reset: zero the COUNTS but keep the compile
        baselines (seen keys, last cache size). The jit trace caches
        live for the process — TpuEngine keeps one batcher per model
        across rounds — so forgetting the baselines would report the
        first warm dispatch of every round as a fresh compile."""
        for w in self._programs.values():
            w.compiles = 0
            w.unexpected = 0
            w.dispatches = 0

    def clear(self) -> None:
        """Forget baselines too (cold-start accounting — test isolation;
        only correct when the process's jit caches are also considered
        cold, e.g. fresh shapes per test)."""
        self._programs.clear()

    def snapshot(self) -> dict:
        """Per-program compile counts + the unexpected-recompile flags
        the ``perf.obs`` report surfaces."""
        programs = {
            name: {
                "compiles": w.compiles,
                "distinct_keys": len(w.keys),
                "dispatches": w.dispatches,
                "unexpected_recompiles": w.unexpected,
            }
            for name, w in sorted(self._programs.items())
        }
        return {
            "programs": programs,
            "unexpected_recompiles": sum(
                w.unexpected for w in self._programs.values()
            ),
        }
