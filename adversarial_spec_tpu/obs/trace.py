"""Causal request tracing: trace/span ids + the ambient stamping context.

The metrics registry answers "how much", the flight recorder answers
"what was the batcher doing" — this module answers "to whom": every
flight-recorder event carries a ``trace_id`` (one per debate round) and
a ``span_id`` (one per opponent request), so a ``FaultEvent`` or a TTFT
sample ties back to the exact round and opponent that caused it.

Id model
--------

- ``trace_id`` — minted once per debate round by the debate layer
  (``run_round``): ``tr-<round:03d>-<n:02d>`` where ``n`` is a
  process-wide counter, reset per CLI invocation (``reset()``). Minting
  is DETERMINISTIC: the same invocation sequence yields byte-identical
  ids on the mock and real engines alike (the debate layer mints before
  any engine is chosen), which is what lets tier-1 pin trace parity on
  CPU.
- ``span_id`` — minted per opponent request as ``<trace_id>/s<i:02d>``
  (``i`` = the request's index in the round). A span id embeds its
  trace id, so a span alone resolves to exactly one round + opponent.

Propagation is by VALUE down the serving stack (``ChatRequest`` →
``SchedRequest`` → per-slot batcher state) and by AMBIENT context for
emit sites that do not know their request (prefix-cache CacheEvents,
tier SwapEvents, retrace CompileEvents): ``obs.emit`` stamps any event
whose ``trace_id``/``span_id`` fields are empty from the ambient pair
set here. The drive loop is single-threaded, so plain module state
suffices — no contextvars, no locks (same concession the recorder
makes).

``reset()`` clears BOTH the counter and the ambient pair; it rides
``obs.reset_stats()`` so one CLI invocation's trace state can never
leak into the next (one invocation = one round).
"""

from __future__ import annotations

import hashlib
from contextlib import contextmanager


class _Ambient:
    """The current (trace_id, span_id) pair ``obs.emit`` stamps from.

    A tiny slotted object rather than two module globals so the emit
    hot path pays one attribute load to reach both fields.
    """

    __slots__ = ("trace", "span")

    def __init__(self) -> None:
        self.trace = ""
        self.span = ""


ambient = _Ambient()
_trace_counter = 0


def mint_trace(round_num: int = 0, seed: int | None = None) -> str:
    """Mint the next trace id for ``round_num``.

    Counter-based and deterministic: the n-th mint of a process (post
    ``reset()``) always yields the same id, so mock and real rounds of
    the same shape carry byte-identical ids. ``seed`` (optional) mixes
    an 8-hex suffix in for callers that need ids unique across
    processes (a serving daemon would pass its instance seed); the CLI
    round path leaves it None so tier-1 can pin exact ids.
    """
    global _trace_counter
    _trace_counter += 1
    tid = f"tr-{round_num:03d}-{_trace_counter:02d}"
    if seed is not None:
        suffix = hashlib.sha256(
            f"{seed}:{round_num}:{_trace_counter}".encode()
        ).hexdigest()[:8]
        tid = f"{tid}-{suffix}"
    return tid


def mint_span(trace_id: str, index: int) -> str:
    """Span id for opponent request ``index`` of ``trace_id``. Embeds
    the trace id so a span alone resolves to one round + opponent."""
    return f"{trace_id}/s{index:02d}"


def trace_of(span_id: str) -> str:
    """The trace id a span id embeds ('' for an empty/foreign id)."""
    return span_id.rsplit("/s", 1)[0] if "/s" in span_id else ""


def set_ambient(trace_id: str = "", span_id: str = "") -> None:
    ambient.trace = trace_id
    ambient.span = span_id


def get_ambient() -> tuple[str, str]:
    return ambient.trace, ambient.span


@contextmanager
def scope(trace_id: str, span_id: str = ""):
    """Temporarily set the ambient pair (restores the previous pair on
    exit, even through exceptions) — the scheduler wraps admission and
    per-slot work in this so prefix-cache/tier/retrace emits inside
    stamp the request that caused them."""
    prev_trace, prev_span = ambient.trace, ambient.span
    ambient.trace = trace_id
    ambient.span = span_id
    try:
        yield
    finally:
        ambient.trace = prev_trace
        ambient.span = prev_span


def reset() -> None:
    """Per-invocation reset: counter back to zero, ambient cleared.
    Rides ``obs.reset_stats()`` (no-leak across CLI invocations)."""
    global _trace_counter
    _trace_counter = 0
    ambient.trace = ""
    ambient.span = ""
