"""Causal request tracing: trace/span ids + the ambient stamping context.

The metrics registry answers "how much", the flight recorder answers
"what was the batcher doing" — this module answers "to whom": every
flight-recorder event carries a ``trace_id`` (one per debate round) and
a ``span_id`` (one per opponent request), so a ``FaultEvent`` or a TTFT
sample ties back to the exact round and opponent that caused it.

Id model
--------

- ``trace_id`` — minted once per debate round by the debate layer
  (``run_round``): ``tr-<round:03d>-<n:02d>`` where ``n`` is a
  process-wide counter, reset per CLI invocation (``reset()``). Minting
  is DETERMINISTIC: the same invocation sequence yields byte-identical
  ids on the mock and real engines alike (the debate layer mints before
  any engine is chosen), which is what lets tier-1 pin trace parity on
  CPU.
- ``span_id`` — minted per opponent request as ``<trace_id>/s<i:02d>``
  (``i`` = the request's index in the round). A span id embeds its
  trace id, so a span alone resolves to exactly one round + opponent.

Daemon scopes (``advspec serve``)
---------------------------------

One process-wide counter is exactly right for the CLI's one-invocation-
one-round world and exactly wrong for a long-lived daemon running many
concurrent debates: two debates minting round 1 would collide on
``tr-001-01``, and the per-invocation ``reset()`` cascade would zero a
counter mid-flight for every other debate. ``mint_trace(scope=...)``
is the daemon-safe variant: each scope (one debate/session id) gets
its OWN counter and an 8-hex scope suffix —
``tr-<round:03d>-<n:02d>-<8hex(scope)>`` — so ids are deterministic
PER DEBATE, collision-free ACROSS debates, and a reset of one scope's
counter (``reset_scope``) never touches another's.

Propagation is by VALUE down the serving stack (``ChatRequest`` →
``SchedRequest`` → per-slot batcher state) and by AMBIENT context for
emit sites that do not know their request (prefix-cache CacheEvents,
tier SwapEvents, retrace CompileEvents): ``obs.emit`` stamps any event
whose ``trace_id``/``span_id`` fields are empty from the ambient pair
set here. The ambient pair is THREAD-LOCAL: the CLI's single-threaded
drive loop behaves exactly as before, and the serve daemon's
thread-per-debate round drivers each stamp their own round's ids
instead of stomping a module global (the collision ISSUE 14 fixes).
Minting takes a small lock for the same reason.

``reset()`` clears the counters and the calling thread's ambient pair;
it rides ``obs.reset_stats()`` so one CLI invocation's trace state can
never leak into the next (one invocation = one round). The daemon
deliberately does NOT run the per-invocation reset cascade mid-serve —
it resets once at startup and relies on scoped minting after that.
"""

from __future__ import annotations

import hashlib
import threading
from contextlib import contextmanager


class _Ambient(threading.local):
    """The current (trace_id, span_id) pair ``obs.emit`` stamps from.

    Thread-local: each serve-daemon debate thread carries its own
    ambient pair (its round's ids), while the single-threaded CLI pays
    one attribute load exactly as before.
    """

    def __init__(self) -> None:
        self.trace = ""
        self.span = ""


ambient = _Ambient()
_trace_counter = 0
# Per-scope counters for daemon minting (scope = one debate/session id).
_scope_counters: dict[str, int] = {}


def _make_mint_lock():
    """Minting lock through the lockdep seam, ``metrics=False`` (a
    histogram observe would re-enter obs). Lazy import — obs loads
    before resilience in some import orders, and minting must work
    either way."""
    try:
        from adversarial_spec_tpu.resilience import lockdep
    except ImportError:  # pragma: no cover - partial-init fallback
        return threading.Lock()
    return lockdep.make_lock("trace._mint_lock", metrics=False)


_mint_lock = _make_mint_lock()


def _scope_suffix(scope: str) -> str:
    return hashlib.sha256(scope.encode("utf-8")).hexdigest()[:8]


def mint_trace(
    round_num: int = 0, seed: int | None = None, scope: str | None = None
) -> str:
    """Mint the next trace id for ``round_num``.

    Counter-based and deterministic: the n-th mint of a process (post
    ``reset()``) always yields the same id, so mock and real rounds of
    the same shape carry byte-identical ids. ``seed`` (optional) mixes
    an 8-hex suffix in for callers that need ids unique across
    processes; the CLI round path leaves it None so tier-1 can pin
    exact ids. ``scope`` (optional, the serve daemon's variant) mints
    from that scope's OWN counter with an 8-hex scope suffix — ids stay
    deterministic per debate and collision-free across the concurrent
    debates of one long-lived process.
    """
    global _trace_counter
    with _mint_lock:
        if scope is not None:
            n = _scope_counters.get(scope, 0) + 1
            _scope_counters[scope] = n
            return f"tr-{round_num:03d}-{n:02d}-{_scope_suffix(scope)}"
        _trace_counter += 1
        n = _trace_counter
    tid = f"tr-{round_num:03d}-{n:02d}"
    if seed is not None:
        suffix = hashlib.sha256(
            f"{seed}:{round_num}:{n}".encode()
        ).hexdigest()[:8]
        tid = f"{tid}-{suffix}"
    return tid


def mint_span(trace_id: str, index: int) -> str:
    """Span id for opponent request ``index`` of ``trace_id``. Embeds
    the trace id so a span alone resolves to one round + opponent."""
    return f"{trace_id}/s{index:02d}"


def trace_of(span_id: str) -> str:
    """The trace id a span id embeds ('' for an empty/foreign id)."""
    return span_id.rsplit("/s", 1)[0] if "/s" in span_id else ""


def set_ambient(trace_id: str = "", span_id: str = "") -> None:
    ambient.trace = trace_id
    ambient.span = span_id


def get_ambient() -> tuple[str, str]:
    return ambient.trace, ambient.span


@contextmanager
def scope(trace_id: str, span_id: str = ""):
    """Temporarily set the ambient pair (restores the previous pair on
    exit, even through exceptions) — the scheduler wraps admission and
    per-slot work in this so prefix-cache/tier/retrace emits inside
    stamp the request that caused them. Thread-local, so a daemon
    debate thread's scope never leaks into a concurrent debate's."""
    prev_trace, prev_span = ambient.trace, ambient.span
    ambient.trace = trace_id
    ambient.span = span_id
    try:
        yield
    finally:
        ambient.trace = prev_trace
        ambient.span = prev_span


def reset_scope(scope_id: str) -> None:
    """Drop ONE scope's counter (a debate retired from the daemon) —
    other scopes' counters are untouched, which is the whole point of
    scoped minting (a per-invocation global reset mid-serve would
    restart every concurrent debate's ids)."""
    with _mint_lock:
        _scope_counters.pop(scope_id, None)


def reset() -> None:
    """Per-invocation reset: counters back to zero, the calling
    thread's ambient cleared. Rides ``obs.reset_stats()`` (no-leak
    across CLI invocations). The serve daemon calls this ONCE at
    startup, never mid-serve."""
    global _trace_counter
    with _mint_lock:
        _trace_counter = 0
        _scope_counters.clear()
    ambient.trace = ""
    ambient.span = ""
