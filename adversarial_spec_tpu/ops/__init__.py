"""ops subpackage."""
