"""Shared flash-attention (online-softmax) update for the Pallas kernels.

Both decode kernels (dense ops/pallas_decode.py, paged ops/pallas_paged.py)
accumulate attention block-by-block with the same recurrence; the -inf
handling for fully-masked blocks (m stays -inf, alpha forced to 0 so no
NaN ever enters l/acc) is subtle enough that it must live in exactly one
place.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def flash_update_heads(
    q_ref,  # VMEM ref [1, n_kv, G, D]
    k_ref,  # VMEM ref [1, n_kv, Tb, D]
    v_ref,  # VMEM ref [1, n_kv, Tb, D]
    ks_ref,  # VMEM ref [1, n_kv, Tb, 1] or None (int8 KV scales)
    vs_ref,  # VMEM ref [1, n_kv, Tb, 1] or None
    m_ref,  # VMEM scratch [n_kv, G, 1]
    l_ref,  # VMEM scratch [n_kv, G, 1]
    acc_ref,  # VMEM scratch [n_kv, G, D]
    t0,  # scalar: global slot index of this tile's first token
    starts,  # scalar or [G, 1]: first valid slot per query row
    ends,  # scalar or [G, 1]
    *,
    scale: float,
    attn_softcap: float,
) -> None:
    """One online-softmax accumulation over a HEAD-FOLDED K/V tile.

    The head-folded kernels (dense, multi-query, paged) all run this
    static per-head loop — 2D dots per head against head slices of one
    big resident tile (the fold is what makes each DMA large enough to
    amortize); like ``flash_update`` itself, it must live in exactly one
    place so the dense and paged paths can never drift numerically.

    Practical Hkv ceiling: the loop unrolls Hkv-fold in the kernel body
    (Mosaic code size/compile time scale with it), and the (Hkv, G8, D)
    f32 scratch plus double-buffered [Hkv, block_t, D] tiles share VMEM
    — fine for the supported configs (Hkv ≤ 16; _pick_block_t shrinks
    the tile as Hkv grows), but a many-KV-head config (Hkv ≥ 32) should
    fold only a fixed head group and keep the remainder in the grid.
    """
    n_kv = q_ref.shape[1]
    for h in range(n_kv):
        q = q_ref[0, h].astype(jnp.float32) * scale
        k = k_ref[0, h].astype(jnp.float32)
        v = v_ref[0, h].astype(jnp.float32)
        if ks_ref is not None:
            k = k * ks_ref[0, h]  # [Tb, 1] broadcasts over D
            v = v * vs_ref[0, h]
        m, l, acc = flash_update(
            q,
            k,
            v,
            t0,
            starts,
            ends,
            m_ref[h],
            l_ref[h],
            acc_ref[h],
            attn_softcap=attn_softcap,
        )
        m_ref[h] = m
        l_ref[h] = l
        acc_ref[h] = acc


def flash_update(
    q: jnp.ndarray,  # [G, D] f32, pre-scaled
    k: jnp.ndarray,  # [Tb, D] f32
    v: jnp.ndarray,  # [Tb, D] f32
    t0,  # scalar: global slot index of k[0]
    start,  # scalar: first valid slot (inclusive)
    end,  # scalar: first invalid slot (exclusive)
    m: jnp.ndarray,  # [G, 1] running max
    l: jnp.ndarray,  # [G, 1] running normalizer
    acc: jnp.ndarray,  # [G, D] running weighted values
    *,
    attn_softcap: float,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One online-softmax accumulation over a K/V block; returns (m, l, acc)."""
    G, Tb = q.shape[0], k.shape[0]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )  # [G, Tb]
    if attn_softcap > 0.0:
        s = jnp.tanh(s / attn_softcap) * attn_softcap
    slot = t0 + jax.lax.broadcasted_iota(jnp.int32, (G, Tb), 1)
    s = jnp.where((slot >= start) & (slot < end), s, -jnp.inf)

    m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
    # Fully-masked-so-far rows keep m = -inf; m_safe pins the exp argument
    # so those rows contribute exact zeros instead of NaNs.
    m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    alpha = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), jnp.zeros_like(m))
    p = jnp.exp(s - m_safe)
    l_new = l * alpha + jnp.sum(p, axis=1, keepdims=True)
    acc_new = acc * alpha + jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    return m_new, l_new, acc_new
