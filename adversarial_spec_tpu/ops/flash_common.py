"""Shared flash-attention (online-softmax) update for the Pallas kernels.

Both decode kernels (dense ops/pallas_decode.py, paged ops/pallas_paged.py)
accumulate attention block-by-block with the same recurrence; the -inf
handling for fully-masked blocks (m stays -inf, alpha forced to 0 so no
NaN ever enters l/acc) is subtle enough that it must live in exactly one
place.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def flash_update(
    q: jnp.ndarray,  # [G, D] f32, pre-scaled
    k: jnp.ndarray,  # [Tb, D] f32
    v: jnp.ndarray,  # [Tb, D] f32
    t0,  # scalar: global slot index of k[0]
    start,  # scalar: first valid slot (inclusive)
    end,  # scalar: first invalid slot (exclusive)
    m: jnp.ndarray,  # [G, 1] running max
    l: jnp.ndarray,  # [G, 1] running normalizer
    acc: jnp.ndarray,  # [G, D] running weighted values
    *,
    attn_softcap: float,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One online-softmax accumulation over a K/V block; returns (m, l, acc)."""
    G, Tb = q.shape[0], k.shape[0]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )  # [G, Tb]
    if attn_softcap > 0.0:
        s = jnp.tanh(s / attn_softcap) * attn_softcap
    slot = t0 + jax.lax.broadcasted_iota(jnp.int32, (G, Tb), 1)
    s = jnp.where((slot >= start) & (slot < end), s, -jnp.inf)

    m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
    # Fully-masked-so-far rows keep m = -inf; m_safe pins the exp argument
    # so those rows contribute exact zeros instead of NaNs.
    m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    alpha = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), jnp.zeros_like(m))
    p = jnp.exp(s - m_safe)
    l_new = l * alpha + jnp.sum(p, axis=1, keepdims=True)
    acc_new = acc * alpha + jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    return m_new, l_new, acc_new
