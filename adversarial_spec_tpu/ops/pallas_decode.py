"""Pallas TPU kernel: single-token (decode) attention over a dense KV cache.

The decode hot loop's attention reads the whole KV cache once per step; the
XLA fallback materializes [B, H, T] logits through HBM. This kernel fuses
QK^T → online softmax → PV into one pass with the cache genuinely streamed:

  grid = (B, T/block_t); the T dimension lives IN THE GRID, so only one
  [Hkv, block_t, D] K tile and V tile are VMEM-resident at a time (Pallas
  double-buffers the next tile's DMA behind the current tile's compute) —
  VMEM stays O(Hkv·block_t·D) regardless of context length, which is what
  makes 16k+ contexts decodable. Each row program folds ALL Hkv KV heads:
  a static per-head loop over [g, D] query groups (g = Hq/Hkv, padded to
  the f32 sublane tile of 8) against that head's K/V tile slice. Folding
  the head axis into the program (rather than the grid, the round-2
  design) matters at SHORT context — the north-star bench shape
  (B=4, Hkv=8, T=1280) drops from 160 sequential programs moving 32 KB
  tiles to 20 programs moving 256 KB tiles, so per-program dispatch
  overhead and sub-DMA-granularity transfers stop dominating (measured
  round 2: the 160-program grid LOST to XLA attention at T=1280, 384 vs
  491 tok/s, and had to hide behind a context-length threshold). The
  online-softmax state (m, l, acc — ops/flash_common.py) persists in VMEM
  scratch across the sequential innermost grid dimension, initialized at
  block 0 and finalized at the last block. Per-row validity windows
  [start, end) ride in as scalar prefetch so left-pad slots and
  not-yet-written slots never contribute.

North-star relevance: this is the op BASELINE.json names ("autoregressive
decode ... implemented as Pallas kernels"); tokens/sec/chip during a debate
round is bounded by this read of the cache (HBM bandwidth).

CPU testing runs the same kernel under ``interpret=True`` against the jnp
reference (tests/test_pallas.py), the SURVEY §4 fake-at-the-seam strategy
applied to kernels.
"""

from __future__ import annotations

import functools
import math
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from adversarial_spec_tpu.ops.flash_common import flash_update_heads

_SUBLANE = 8

# Per-K-tile VMEM budget for block_t selection: tiles are [Hkv, block_t, D],
# double-buffered, ×2 for K and V — 1 MiB per tile keeps the working set
# ≈4 MiB, well inside a TensorCore's ~16 MiB VMEM with room for q/scratch.
_TILE_VMEM_BUDGET = 1 << 20


# Operator/harvest override for the KV tile length: the VMEM-budget
# heuristic below picks the largest fitting block, but the DMA-size vs
# grid-parallelism balance is an empirical question the ladder's blockt
# sweep (tpu_ladder.py) answers on chip. 0 = auto.
_BLOCK_T_OVERRIDE = int(os.environ.get("ADVSPEC_BLOCK_T", "0"))
_warned_block_t: set[int] = set()


def _warn_block_t_fallback(T: int) -> None:
    """Say ONCE per cache length that the override was unusable there —
    a silent fallback would let an operator attribute auto-pick timings
    to the block_t they exported."""
    if T not in _warned_block_t:
        _warned_block_t.add(T)
        import sys

        # graftlint: disable=GL-TRACE -- deliberate trace-time warn-once: block_t is chosen at trace time (T is a static shape), so the fallback must report during tracing or never
        print(
            f"warning: ADVSPEC_BLOCK_T={_BLOCK_T_OVERRIDE} unusable at "
            f"cache length T={T} (needs a positive multiple of "
            f"{_SUBLANE} dividing T within 8x the VMEM budget); using "
            "the auto pick for this shape",
            file=sys.stderr,
        )


def _pick_block_t(T: int, n_kv: int, D: int, itemsize: int) -> int:
    """Largest block that divides the (static) cache length AND keeps one
    [Hkv, block_t, D] tile under the VMEM budget.

    T must be divisible by some candidate (generate() always passes a
    power-of-two bucket ≥128, which 128 or smaller divides). Silently
    falling back to block_t=T here would materialize an [Hkv, T, D]
    tile — Hkv× the VMEM blowup of a normal tile, a silent OOM trap for
    direct kernel callers — so refuse instead (ADVICE r3)."""
    if _BLOCK_T_OVERRIDE:
        ok = (
            _BLOCK_T_OVERRIDE > 0
            and _BLOCK_T_OVERRIDE % _SUBLANE == 0
            and T % _BLOCK_T_OVERRIDE == 0
            # Generous ceiling (8× the auto heuristic's budget): an
            # override may deliberately trade VMEM for DMA size, but an
            # [Hkv, T, D]-scale tile is the OOM trap this function
            # exists to refuse.
            and n_kv * _BLOCK_T_OVERRIDE * D * itemsize
            <= 8 * _TILE_VMEM_BUDGET
        )
        if ok:
            return _BLOCK_T_OVERRIDE
        _warn_block_t_fallback(T)
    # An unusable override falls through to the auto pick (a sweep must
    # stay valid across every shape the run touches); the auto path
    # still refuses shapes with NO valid block below.
    fit = [
        c
        for c in (512, 256, 128, 64, 32, 16, 8)
        if n_kv * c * D * itemsize <= _TILE_VMEM_BUDGET
    ]
    block = next((c for c in fit if T % c == 0), None)
    if block is None:
        raise ValueError(
            f"cache length T={T} has no block_t divisor in {fit}: pad T "
            "to a multiple of 8 (generate() buckets to powers of two "
            "≥128, which never hits this)"
        )
    return block


def _decode_attn_kernel(
    bounds_ref,  # SMEM [B, 2] int32: (start, end) valid-slot window per row
    q_ref,  # VMEM [1, Hkv, G8, D]
    k_ref,  # VMEM [1, Hkv, block_t, D] — one streamed tile (heads-major)
    v_ref,  # VMEM [1, Hkv, block_t, D]
    *rest,  # [ks_ref, vs_ref,] o_ref, m_ref, l_ref, acc_ref
    scale: float,
    attn_softcap: float,
    block_t: int,
    quantized: bool,
):
    # int8-KV mode streams per-(token, head) scale tiles alongside the
    # int8 K/V tiles and dequantizes IN VMEM — the HBM read per decoded
    # token stays at the int8 byte count (the whole point of the int8
    # cache; previously int8 forced the jnp fallback path).
    if quantized:
        ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        o_ref, m_ref, l_ref, acc_ref = rest
    b = pl.program_id(0)
    t = pl.program_id(1)
    n_blocks = pl.num_programs(1)
    n_kv, G8, D = q_ref.shape[1], q_ref.shape[2], q_ref.shape[3]

    @pl.when(t == 0)
    def _init():
        m_ref[:] = jnp.full((n_kv, G8, 1), -jnp.inf, jnp.float32)
        l_ref[:] = jnp.zeros((n_kv, G8, 1), jnp.float32)
        acc_ref[:] = jnp.zeros((n_kv, G8, D), jnp.float32)

    start = bounds_ref[b, 0]
    end = bounds_ref[b, 1]
    t0 = t * block_t

    # Skip compute for tiles wholly outside the valid window (the DMA still
    # lands — block skipping is a masking optimization, not a gather).
    @pl.when((t0 < end) & (t0 + block_t > start))
    def _accumulate():
        flash_update_heads(
            q_ref,
            k_ref,
            v_ref,
            ks_ref if quantized else None,
            vs_ref if quantized else None,
            m_ref,
            l_ref,
            acc_ref,
            t0,
            start,
            end,
            scale=scale,
            attn_softcap=attn_softcap,
        )

    @pl.when(t == n_blocks - 1)
    def _finalize():
        o_ref[0] = (
            acc_ref[:] / jnp.maximum(l_ref[:], 1e-30)
        ).astype(o_ref.dtype)


def _mq_attn_kernel(
    bounds_ref,  # VMEM [1, G8, 2]: per (query-row) [start, end) — shared
    # by every KV head of the row (bounds are per query position).
    # VMEM, not SMEM scalar-prefetch: Mosaic can only load SCALARS from
    # SMEM, and this kernel needs the whole per-query bounds vector.
    q_ref,  # VMEM [1, Hkv, G8, D] — G8 = pad(S·g) query rows per head
    k_ref,  # VMEM [1, Hkv, block_t, D]
    v_ref,  # VMEM [1, Hkv, block_t, D]
    *rest,  # [ks_ref, vs_ref,] o_ref, m_ref, l_ref, acc_ref
    scale: float,
    attn_softcap: float,
    block_t: int,
    quantized: bool,
):
    # int8-KV mode mirrors _decode_attn_kernel: scale tiles stream
    # alongside the int8 K/V tiles, dequant in VMEM.
    if quantized:
        ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        o_ref, m_ref, l_ref, acc_ref = rest
    t = pl.program_id(1)
    n_blocks = pl.num_programs(1)
    n_kv, G8, D = q_ref.shape[1], q_ref.shape[2], q_ref.shape[3]

    @pl.when(t == 0)
    def _init():
        m_ref[:] = jnp.full((n_kv, G8, 1), -jnp.inf, jnp.float32)
        l_ref[:] = jnp.zeros((n_kv, G8, 1), jnp.float32)
        acc_ref[:] = jnp.zeros((n_kv, G8, D), jnp.float32)

    starts = bounds_ref[0, :, 0]  # [G8]
    ends = bounds_ref[0, :, 1]
    t0 = t * block_t

    # Skip tiles wholly outside EVERY query's window.
    @pl.when((t0 < jnp.max(ends)) & (t0 + block_t > jnp.min(starts)))
    def _accumulate():
        flash_update_heads(
            q_ref,
            k_ref,
            v_ref,
            ks_ref if quantized else None,
            vs_ref if quantized else None,
            m_ref,
            l_ref,
            acc_ref,
            t0,
            starts[:, None],  # per-query bounds broadcast inside
            ends[:, None],
            scale=scale,
            attn_softcap=attn_softcap,
        )

    @pl.when(t == n_blocks - 1)
    def _finalize():
        o_ref[0] = (
            acc_ref[:] / jnp.maximum(l_ref[:], 1e-30)
        ).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("attn_softcap", "scale", "interpret")
)
def decode_attention_mq(
    q: jnp.ndarray,  # [B, S, Hq, D] — a SHORT query span (spec verify)
    k_cache: jnp.ndarray,  # [B, Hkv, T, D] heads-major (any float or int8)
    v_cache: jnp.ndarray,  # [B, Hkv, T, D]
    starts: jnp.ndarray,  # [B, S] int32 first valid slot per query
    ends: jnp.ndarray,  # [B, S] int32 one-past-last valid slot per query
    attn_softcap: float = 0.0,
    scale: float | None = None,
    interpret: bool = False,
    k_scale: jnp.ndarray | None = None,  # [B, Hkv, T, 1] f32 (int8 KV)
    v_scale: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Multi-query fused decode attention. Returns [B, S, Hq, D].

    The speculative-verification shape: γ+1 query positions per row, each
    attending to the KV cache under its OWN [start, end) window (end
    grows by one per query — in-span causality). Same streamed-tile
    flash recurrence as ``decode_attention``; the queries of one
    (row, kv-head) program stack into the sublane dimension, so the
    whole span costs ONE pass over the KV cache instead of γ+1. This is
    what lets speculative decoding keep the fused kernel instead of
    dropping the entire call to the jnp path (round-1 shortcut).
    """
    B, S, Hq, D = q.shape
    Hkv, T = k_cache.shape[1], k_cache.shape[2]
    g = Hq // Hkv
    rows = S * g
    G8 = -(-rows // _SUBLANE) * _SUBLANE
    scale = scale if scale is not None else 1.0 / math.sqrt(D)
    quantized = k_scale is not None
    block_t = _pick_block_t(T, Hkv, D, k_cache.dtype.itemsize)

    # [B, Hkv, S·g, D]: row r = query (r // g), group lane (r % g).
    qg = jnp.transpose(
        q.reshape(B, S, Hkv, g, D), (0, 2, 1, 3, 4)
    ).reshape(B, Hkv, rows, D)
    # Per-row bounds; pad rows get an empty window [0, 0) → masked
    # everywhere → zero output (dropped below). starts/ends may arrive
    # [B, 1] (global layers share one start per row) — broadcast first.
    starts = jnp.broadcast_to(starts, (B, S))
    ends = jnp.broadcast_to(ends, (B, S))
    bnd = jnp.stack(
        [
            jnp.repeat(starts, g, axis=1),
            jnp.repeat(ends, g, axis=1),
        ],
        axis=2,
    ).astype(jnp.int32)  # [B, rows, 2]
    if G8 != rows:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, G8 - rows), (0, 0)))
        # Pad rows get the empty window [T, 0): a zero start would feed
        # the kernel's min(starts) tile-skip guard and silently disable
        # leading-tile skipping for windowed layers.
        bnd = jnp.pad(bnd, ((0, 0), (0, G8 - rows), (0, 0)))
        bnd = bnd.at[:, rows:, 0].set(T)

    kv_spec = pl.BlockSpec(
        (1, Hkv, block_t, D), lambda b, t: (b, 0, t, 0)
    )
    in_specs = [
        # Bounds ride in VMEM ([1, G8, 2] block — sublane G8 is a
        # multiple of 8, lane 2 spans the array) because the kernel
        # reads them as vectors; SMEM only serves scalar loads.
        pl.BlockSpec((1, G8, 2), lambda b, t: (b, 0, 0)),
        pl.BlockSpec((1, Hkv, G8, D), lambda b, t: (b, 0, 0, 0)),
        kv_spec,
        kv_spec,
    ]
    operands = [bnd, qg, k_cache, v_cache]
    if quantized:
        scale_spec = pl.BlockSpec(
            (1, Hkv, block_t, 1), lambda b, t: (b, 0, t, 0)
        )
        in_specs += [scale_spec, scale_spec]
        operands += [k_scale, v_scale]
    out = pl.pallas_call(
        functools.partial(
            _mq_attn_kernel,
            scale=scale,
            attn_softcap=attn_softcap,
            block_t=block_t,
            quantized=quantized,
        ),
        grid=(B, T // block_t),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, Hkv, G8, D), lambda b, t: (b, 0, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((Hkv, G8, 1), jnp.float32),
            pltpu.VMEM((Hkv, G8, 1), jnp.float32),
            pltpu.VMEM((Hkv, G8, D), jnp.float32),
        ],
        out_shape=jax.ShapeDtypeStruct((B, Hkv, G8, D), q.dtype),
        interpret=interpret,
    )(*operands)

    out = out[:, :, :rows, :].reshape(B, Hkv, S, g, D)
    return jnp.transpose(out, (0, 2, 1, 3, 4)).reshape(B, S, Hq, D)


def decode_attention_tp(
    q: jnp.ndarray,  # [B, Hq, D]
    k_cache: jnp.ndarray,  # [B, Hkv, T, D] heads-major
    v_cache: jnp.ndarray,  # [B, Hkv, T, D]
    bounds: jnp.ndarray,  # [B, 2]
    mesh,
    attn_softcap: float = 0.0,
    scale: float | None = None,
    interpret: bool = False,
    k_scale: jnp.ndarray | None = None,  # [B, Hkv, T, 1] f32 (int8 KV)
    v_scale: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Fused decode attention on a GSPMD-sharded mesh.

    GSPMD cannot partition a pallas_call, so the sharded configs
    (BASELINE 3-5: dp over opponents, tp over heads) would otherwise fall
    back to the jnp path. shard_map splits the batch over ``dp`` and the
    KV-head axis over ``tp`` and runs the single-device kernel on each
    device's local shard; GQA groups stay device-local (every KV head and
    its g query heads live on one chip), so there is no cross-device
    softmax and no collectives in the kernel at all.

    Requires B % dp == 0 (generate() pads rows to a dp multiple) and
    Hkv % tp == 0 — callers gate on ``tp_decode_supported``. Axes beyond
    dp/tp (sp during decode) see replicated operands and compute
    identical local results.
    """
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from adversarial_spec_tpu.parallel.mesh import DP, TP

    kernel = functools.partial(
        decode_attention,
        attn_softcap=attn_softcap,
        scale=scale,
        interpret=interpret,
    )
    in_specs = [
        P(DP, TP, None),
        P(DP, TP, None, None),
        P(DP, TP, None, None),
        P(DP, None),
    ]
    operands = [q, k_cache, v_cache, bounds]
    if k_scale is not None:
        fn = lambda q_, k_, v_, b_, ks_, vs_: kernel(  # noqa: E731
            q_, k_, v_, b_, k_scale=ks_, v_scale=vs_
        )
        in_specs += [P(DP, TP, None, None), P(DP, TP, None, None)]
        operands += [k_scale, v_scale]
    else:
        fn = kernel
    return shard_map(
        fn,
        mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=P(DP, TP, None),
        check_rep=False,
    )(*operands)


def tp_decode_supported(n_kv_heads: int, mesh) -> bool:
    """True iff the mesh's tp degree keeps GQA groups device-local."""
    from adversarial_spec_tpu.parallel.mesh import TP

    return n_kv_heads % mesh.shape.get(TP, 1) == 0


@functools.partial(
    jax.jit, static_argnames=("attn_softcap", "scale", "interpret")
)
def decode_attention(
    q: jnp.ndarray,  # [B, Hq, D] one query token per row
    k_cache: jnp.ndarray,  # [B, Hkv, T, D] heads-major (any float, or int8)
    v_cache: jnp.ndarray,  # [B, Hkv, T, D]
    bounds: jnp.ndarray,  # [B, 2] int32 (start, end) valid slot window
    attn_softcap: float = 0.0,
    scale: float | None = None,
    interpret: bool = False,
    k_scale: jnp.ndarray | None = None,  # [B, Hkv, T, 1] f32 (int8 KV)
    v_scale: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Fused decode attention. Returns [B, Hq, D] in q.dtype.

    ``k_scale``/``v_scale`` (both or neither): the caches are int8 with
    per-(token, head) symmetric scales (models/transformer.py:
    _quantize_kv); dequant happens inside the kernel tiles.
    """
    B, Hq, D = q.shape
    Hkv, T = k_cache.shape[1], k_cache.shape[2]
    g = Hq // Hkv
    G8 = max(_SUBLANE, g)
    scale = scale if scale is not None else 1.0 / math.sqrt(D)
    quantized = k_scale is not None
    block_t = _pick_block_t(T, Hkv, D, k_cache.dtype.itemsize)

    # [B, Hkv, G8, D] — query heads grouped under their KV head, padded to
    # the sublane tile. Pad rows attend to garbage harmlessly (dropped).
    qg = q.reshape(B, Hkv, g, D)
    if G8 != g:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, G8 - g), (0, 0)))

    kv_spec = pl.BlockSpec(
        (1, Hkv, block_t, D), lambda b, t, _: (b, 0, t, 0)
    )
    scale_spec = pl.BlockSpec(
        (1, Hkv, block_t, 1), lambda b, t, _: (b, 0, t, 0)
    )
    in_specs = [
        pl.BlockSpec((1, Hkv, G8, D), lambda b, t, _: (b, 0, 0, 0)),
        kv_spec,
        kv_spec,
    ]
    operands = [qg, k_cache, v_cache]
    if quantized:
        in_specs += [scale_spec, scale_spec]
        operands += [k_scale, v_scale]

    grid = (B, T // block_t)
    out = pl.pallas_call(
        functools.partial(
            _decode_attn_kernel,
            scale=scale,
            attn_softcap=attn_softcap,
            block_t=block_t,
            quantized=quantized,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (1, Hkv, G8, D), lambda b, t, _: (b, 0, 0, 0)
            ),
            scratch_shapes=[
                pltpu.VMEM((Hkv, G8, 1), jnp.float32),
                pltpu.VMEM((Hkv, G8, 1), jnp.float32),
                pltpu.VMEM((Hkv, G8, D), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, Hkv, G8, D), q.dtype),
        interpret=interpret,
    )(bounds, *operands)

    return out[:, :, :g, :].reshape(B, Hq, D)
