"""Pallas TPU kernel: decode attention over a PAGED KV cache.

Paged KV (the second kernel BASELINE.json's north star names): instead of
one dense [B, H, T_max, D] buffer per batch — which must be sized for the
longest sequence and reallocated/copied as debates grow — key/value live in
fixed-size pages [n_pages, Hkv, page_size, D] shared by all sequences, and
each row owns an ordered page list (the page table). Debate rounds grow
sequences at different rates (opponents finish at different lengths), so
paging keeps HBM occupancy at O(tokens actually written) and makes
prefix-sharing across opponents real: same spec prompt → same physical
pages, refcounted by engine/prefix_cache.py (shipped in PR 2 — rows
whose tables alias a cached prefix read it through this kernel like any
other page).

Kernel shape: grid (B, n_pages_per_seq); the page table rides in as a
scalar-prefetch operand so each grid step's BlockSpec ``index_map`` selects
the physical page to DMA next — the gather happens in the pipeline, not in
the kernel body. One physical page id selects the whole heads-major
[Hkv, page_size, D] slab, so each program folds ALL KV heads (static
per-head loop), mirroring ops/pallas_decode.py's short-context redesign:
Hkv× fewer sequential programs and Hkv× larger DMAs than the round-2
(B, Hkv, P) grid. Online-softmax state (m, l, acc) persists in VMEM
scratch across the sequential innermost grid dimension: initialized at
page 0, finalized and written at the last page.

Two entry shapes share that design: ``paged_decode_attention`` (S=1, one
query token per row — the decode hot loop) and
``paged_decode_attention_mq`` (a short S=γ+1 query span per row with
per-position causal bounds — speculative verify reads the pool ONCE for
the whole span instead of flattening the span into the batch axis and
re-gathering γ+1 times).

Tested under ``interpret=True`` on CPU against the dense jnp reference
(tests/test_pallas.py).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from adversarial_spec_tpu.ops.flash_common import flash_update_heads

_SUBLANE = 8


def _paged_attn_kernel(
    bounds_ref,  # SMEM [B, 2]: (start, end) token window per row
    table_ref,  # SMEM [B, P]: physical page id per (row, logical page)
    q_ref,  # VMEM [1, Hkv, G8, D]
    k_ref,  # VMEM [1, Hkv, page, D] — page slab selected by index_map
    v_ref,  # VMEM [1, Hkv, page, D]
    *rest,  # [ks_ref, vs_ref,] o_ref, m_ref, l_ref, acc_ref
    scale: float,
    page_size: int,
    attn_softcap: float,
    quantized: bool,
):
    # int8 pools stream per-(token, head) scale pages alongside the int8
    # K/V pages and dequantize IN VMEM — HBM read per decoded token stays
    # at the int8 byte count (mirrors ops/pallas_decode.py's dense mode).
    if quantized:
        ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        o_ref, m_ref, l_ref, acc_ref = rest
    b = pl.program_id(0)
    p = pl.program_id(1)
    n_pages = pl.num_programs(1)
    n_kv, G8, D = q_ref.shape[1], q_ref.shape[2], q_ref.shape[3]

    @pl.when(p == 0)
    def _init():
        m_ref[:] = jnp.full((n_kv, G8, 1), -jnp.inf, jnp.float32)
        l_ref[:] = jnp.zeros((n_kv, G8, 1), jnp.float32)
        acc_ref[:] = jnp.zeros((n_kv, G8, D), jnp.float32)

    start = bounds_ref[b, 0]
    end = bounds_ref[b, 1]
    page_id = table_ref[b, p]
    t0 = p * page_size  # logical token offset of this page

    # Unmapped pages — id <= 0: physical page 0 is the reserved TRASH page
    # (callers shift allocator ids +1; engine/scheduler.py:TRASH_PAGE) and
    # negative ids are table padding — and pages wholly outside
    # [start, end) are masked; compute still runs (SPMD) but contributes
    # nothing.
    @pl.when((page_id > 0) & (t0 < end))
    def _accumulate():
        flash_update_heads(
            q_ref,
            k_ref,
            v_ref,
            ks_ref if quantized else None,
            vs_ref if quantized else None,
            m_ref,
            l_ref,
            acc_ref,
            t0,
            start,
            end,
            scale=scale,
            attn_softcap=attn_softcap,
        )

    @pl.when(p == n_pages - 1)
    def _finalize():
        o_ref[0] = (
            acc_ref[:] / jnp.maximum(l_ref[:], 1e-30)
        ).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("attn_softcap", "scale", "interpret")
)
def paged_decode_attention(
    q: jnp.ndarray,  # [B, Hq, D]
    k_pages: jnp.ndarray,  # [n_pages, Hkv, page_size, D] heads-major
    v_pages: jnp.ndarray,  # [n_pages, Hkv, page_size, D]
    page_table: jnp.ndarray,  # [B, P] int32; <= 0 = unmapped (see below)
    bounds: jnp.ndarray,  # [B, 2] int32 (start, end) token window
    attn_softcap: float = 0.0,
    scale: float | None = None,
    interpret: bool = False,
    k_scale: jnp.ndarray | None = None,  # [n_pages, Hkv, page, 1] (int8)
    v_scale: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Fused paged decode attention. Returns [B, Hq, D].

    Page-table sentinel convention (shared with the jnp gather path in
    models/transformer.py:forward_paged_decode): physical page 0 is the
    reserved TRASH page — callers allocate real pages from id 1 up — so
    any table entry <= 0 (trash or negative padding) is treated as
    unmapped and masked out of the softmax.

    ``k_scale``/``v_scale`` (both or neither): the pages are int8 with
    per-(token, head) symmetric scale pages; dequant happens inside the
    kernel on the VMEM-resident page.
    """
    B, Hq, D = q.shape
    Hkv, page_size = k_pages.shape[1], k_pages.shape[2]
    P = page_table.shape[1]
    g = Hq // Hkv
    G8 = max(_SUBLANE, g)
    scale = scale if scale is not None else 1.0 / math.sqrt(D)
    quantized = k_scale is not None

    qg = q.reshape(B, Hkv, g, D)
    if G8 != g:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, G8 - g), (0, 0)))

    def page_map(b, p, bounds_ref, table_ref):
        return (jnp.maximum(table_ref[b, p], 0), 0, 0, 0)

    page_spec = pl.BlockSpec((1, Hkv, page_size, D), page_map)
    in_specs = [
        pl.BlockSpec((1, Hkv, G8, D), lambda b, p, *_: (b, 0, 0, 0)),
        page_spec,
        page_spec,
    ]
    operands = [qg, k_pages, v_pages]
    if quantized:
        scale_spec = pl.BlockSpec((1, Hkv, page_size, 1), page_map)
        in_specs += [scale_spec, scale_spec]
        operands += [k_scale, v_scale]

    out = pl.pallas_call(
        functools.partial(
            _paged_attn_kernel,
            scale=scale,
            page_size=page_size,
            attn_softcap=attn_softcap,
            quantized=quantized,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B, P),
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (1, Hkv, G8, D), lambda b, p, *_: (b, 0, 0, 0)
            ),
            scratch_shapes=[
                pltpu.VMEM((Hkv, G8, 1), jnp.float32),
                pltpu.VMEM((Hkv, G8, 1), jnp.float32),
                pltpu.VMEM((Hkv, G8, D), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, Hkv, G8, D), q.dtype),
        interpret=interpret,
    )(bounds, page_table, *operands)

    return out[:, :, :g, :].reshape(B, Hq, D)


def _paged_mq_attn_kernel(
    table_ref,  # SMEM [B, P]: physical page id per (row, logical page)
    bounds_ref,  # VMEM [1, G8, 2]: per query-row [start, end). VMEM, not
    # SMEM scalar-prefetch: Mosaic only loads SCALARS from SMEM and this
    # kernel needs the whole per-query bounds vector (the _mq_attn_kernel
    # pattern from ops/pallas_decode.py).
    q_ref,  # VMEM [1, Hkv, G8, D] — G8 = pad(S·g) query rows per head
    k_ref,  # VMEM [1, Hkv, page, D] — page slab selected by index_map
    v_ref,  # VMEM [1, Hkv, page, D]
    *rest,  # [ks_ref, vs_ref,] o_ref, m_ref, l_ref, acc_ref
    scale: float,
    page_size: int,
    attn_softcap: float,
    quantized: bool,
):
    # int8 pools mirror _paged_attn_kernel: scale pages stream alongside
    # the int8 K/V pages, dequant in VMEM.
    if quantized:
        ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        o_ref, m_ref, l_ref, acc_ref = rest
    b = pl.program_id(0)
    p = pl.program_id(1)
    n_pages = pl.num_programs(1)
    n_kv, G8, D = q_ref.shape[1], q_ref.shape[2], q_ref.shape[3]

    @pl.when(p == 0)
    def _init():
        m_ref[:] = jnp.full((n_kv, G8, 1), -jnp.inf, jnp.float32)
        l_ref[:] = jnp.zeros((n_kv, G8, 1), jnp.float32)
        acc_ref[:] = jnp.zeros((n_kv, G8, D), jnp.float32)

    starts = bounds_ref[0, :, 0]  # [G8]
    ends = bounds_ref[0, :, 1]
    page_id = table_ref[b, p]
    t0 = p * page_size  # logical token offset of this page

    # Unmapped pages (id <= 0: trash page or table padding — the same
    # sentinel convention as _paged_attn_kernel) and pages wholly outside
    # EVERY query's window are skipped.
    @pl.when(
        (page_id > 0)
        & (t0 < jnp.max(ends))
        & (t0 + page_size > jnp.min(starts))
    )
    def _accumulate():
        flash_update_heads(
            q_ref,
            k_ref,
            v_ref,
            ks_ref if quantized else None,
            vs_ref if quantized else None,
            m_ref,
            l_ref,
            acc_ref,
            t0,
            starts[:, None],  # per-query bounds broadcast inside
            ends[:, None],
            scale=scale,
            attn_softcap=attn_softcap,
        )

    @pl.when(p == n_pages - 1)
    def _finalize():
        o_ref[0] = (
            acc_ref[:] / jnp.maximum(l_ref[:], 1e-30)
        ).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("attn_softcap", "scale", "interpret")
)
def paged_decode_attention_mq(
    q: jnp.ndarray,  # [B, S, Hq, D] — a SHORT query span (spec verify)
    k_pages: jnp.ndarray,  # [n_pages, Hkv, page_size, D] heads-major
    v_pages: jnp.ndarray,  # [n_pages, Hkv, page_size, D]
    page_table: jnp.ndarray,  # [B, P] int32; <= 0 = unmapped
    starts: jnp.ndarray,  # [B, S] int32 first valid slot per query
    ends: jnp.ndarray,  # [B, S] int32 one-past-last valid slot per query
    attn_softcap: float = 0.0,
    scale: float | None = None,
    interpret: bool = False,
    k_scale: jnp.ndarray | None = None,  # [n_pages, Hkv, page, 1] (int8)
    v_scale: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Multi-position fused paged attention. Returns [B, S, Hq, D].

    The speculative-verification shape over the PAGED pool: γ+1 query
    positions per row, each attending through the row's page table under
    its OWN [start, end) window (end grows by one per position — in-span
    causality). Same (B, n_pages) grid and scalar-prefetch page gather
    as ``paged_decode_attention``; the span's queries stack into the
    sublane dimension (row r = query r//g, group lane r%g), so the whole
    span costs ONE pass over the row's pages instead of the batch-axis
    flatten paying the gather γ+1 times. Page-table sentinel convention
    unchanged: entries <= 0 are unmapped and masked.
    """
    B, S, Hq, D = q.shape
    Hkv, page_size = k_pages.shape[1], k_pages.shape[2]
    P = page_table.shape[1]
    g = Hq // Hkv
    rows = S * g
    G8 = -(-rows // _SUBLANE) * _SUBLANE
    T = P * page_size  # logical slot horizon of the table
    scale = scale if scale is not None else 1.0 / math.sqrt(D)
    quantized = k_scale is not None

    # [B, Hkv, S·g, D]: row r = query (r // g), group lane (r % g).
    qg = jnp.transpose(
        q.reshape(B, S, Hkv, g, D), (0, 2, 1, 3, 4)
    ).reshape(B, Hkv, rows, D)
    starts = jnp.broadcast_to(starts, (B, S))
    ends = jnp.broadcast_to(ends, (B, S))
    bnd = jnp.stack(
        [
            jnp.repeat(starts, g, axis=1),
            jnp.repeat(ends, g, axis=1),
        ],
        axis=2,
    ).astype(jnp.int32)  # [B, rows, 2]
    if G8 != rows:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, G8 - rows), (0, 0)))
        # Pad rows get the empty window [T, 0): a zero start would feed
        # the min(starts) page-skip guard and disable leading-page
        # skipping for windowed layers (same trap as decode_attention_mq).
        bnd = jnp.pad(bnd, ((0, 0), (0, G8 - rows), (0, 0)))
        bnd = bnd.at[:, rows:, 0].set(T)

    def page_map(b, p, table_ref):
        return (jnp.maximum(table_ref[b, p], 0), 0, 0, 0)

    page_spec = pl.BlockSpec((1, Hkv, page_size, D), page_map)
    in_specs = [
        pl.BlockSpec((1, G8, 2), lambda b, p, *_: (b, 0, 0)),
        pl.BlockSpec((1, Hkv, G8, D), lambda b, p, *_: (b, 0, 0, 0)),
        page_spec,
        page_spec,
    ]
    operands = [bnd, qg, k_pages, v_pages]
    if quantized:
        scale_spec = pl.BlockSpec((1, Hkv, page_size, 1), page_map)
        in_specs += [scale_spec, scale_spec]
        operands += [k_scale, v_scale]

    out = pl.pallas_call(
        functools.partial(
            _paged_mq_attn_kernel,
            scale=scale,
            page_size=page_size,
            attn_softcap=attn_softcap,
            quantized=quantized,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(B, P),
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (1, Hkv, G8, D), lambda b, p, *_: (b, 0, 0, 0)
            ),
            scratch_shapes=[
                pltpu.VMEM((Hkv, G8, 1), jnp.float32),
                pltpu.VMEM((Hkv, G8, 1), jnp.float32),
                pltpu.VMEM((Hkv, G8, D), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, Hkv, G8, D), q.dtype),
        interpret=interpret,
    )(page_table, *operands)

    out = out[:, :, :rows, :].reshape(B, Hkv, S, g, D)
    return jnp.transpose(out, (0, 2, 1, 3, 4)).reshape(B, S, Hq, D)


def paged_decode_attention_dp_tp(
    q: jnp.ndarray,  # [B, Hq, D]
    k_pages: jnp.ndarray,  # [n_pages, Hkv, page_size, D]
    v_pages: jnp.ndarray,  # [n_pages, Hkv, page_size, D]
    page_table: jnp.ndarray,  # [B, P] GLOBAL physical ids (see contract)
    bounds: jnp.ndarray,  # [B, 2]
    mesh,
    attn_softcap: float = 0.0,
    scale: float | None = None,
    interpret: bool = False,
    k_scale: jnp.ndarray | None = None,
    v_scale: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Fused paged decode attention on a MIXED dp×tp mesh.

    Rows and page slabs shard over ``dp``, the head axis over ``tp`` —
    all heavy operands stay device-local; there are no collectives in or
    around the kernel.

    Layout contract (generate()'s mixed paged setup): the pages axis is
    laid out per-dp-slice — slice d owns global pages [d·Lp, (d+1)·Lp)
    with Lp = n_pages/dp, local page 0 of each slice is that slice's
    trash page, and every row's pages live in the row's OWN slice. The
    page table carries GLOBAL ids because the surrounding chunk loop
    (scatter + gather fallback) runs under GSPMD, which is global-view;
    this wrapper subtracts the slice base so the kernel indexes its
    local block. Global trash (id 0) and negative padding land ≤ 0
    after the shift and stay masked; out-of-slice ids cannot occur by
    construction.
    """
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from adversarial_spec_tpu.parallel.mesh import DP, TP

    n_pages = k_pages.shape[0]
    dp = mesh.shape[DP]
    local_pages = n_pages // dp

    kernel = functools.partial(
        paged_decode_attention,
        attn_softcap=attn_softcap,
        scale=scale,
        interpret=interpret,
    )

    def fn(q_, k_, v_, t_, b_, *scales):
        base = jax.lax.axis_index(DP) * local_pages
        t_local = t_ - base
        if scales:
            return kernel(
                q_, k_, v_, t_local, b_,
                k_scale=scales[0], v_scale=scales[1],
            )
        return kernel(q_, k_, v_, t_local, b_)

    page_spec = P(DP, TP, None, None)
    in_specs = [P(DP, TP, None), page_spec, page_spec, P(DP, None), P(DP, None)]
    operands = [q, k_pages, v_pages, page_table, bounds]
    if k_scale is not None:
        in_specs += [page_spec, page_spec]
        operands += [k_scale, v_scale]
    return shard_map(
        fn,
        mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=P(DP, TP, None),
        check_rep=False,
    )(*operands)


def paged_decode_attention_tp(
    q: jnp.ndarray,  # [B, Hq, D]
    k_pages: jnp.ndarray,  # [n_pages, Hkv, page_size, D]
    v_pages: jnp.ndarray,  # [n_pages, Hkv, page_size, D]
    page_table: jnp.ndarray,  # [B, P] GLOBAL physical ids
    bounds: jnp.ndarray,  # [B, 2]
    mesh,
    attn_softcap: float = 0.0,
    scale: float | None = None,
    interpret: bool = False,
    k_scale: jnp.ndarray | None = None,
    v_scale: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Fused paged decode attention with the HEAD axis tp-sharded.

    The paged-pool counterpart of ops/pallas_decode.py:
    decode_attention_tp: GSPMD cannot partition a pallas_call, so
    tp-sharded paged configs (BASELINE 5: TP over a 70B judge) would
    fall back to the gather path. shard_map splits the pool's Hkv axis
    (and q's head axis) over ``tp``; the page table and bounds replicate
    — every device reads the same pages, its own head slice. GQA groups
    stay device-local (callers gate on tp | n_kv_heads), so there are no
    collectives in the kernel. The batch axis stays UNSHARDED here: the
    global-page-table layout has no per-device page locality (dp-local
    pools are the scheduler's sharded path, engine/scheduler.py).
    """
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from adversarial_spec_tpu.parallel.mesh import TP

    kernel = functools.partial(
        paged_decode_attention,
        attn_softcap=attn_softcap,
        scale=scale,
        interpret=interpret,
    )
    in_specs = [
        P(None, TP, None),  # q: heads over tp
        P(None, TP, None, None),  # pages: Hkv over tp
        P(None, TP, None, None),
        P(None, None),  # table: replicated
        P(None, None),  # bounds: replicated
    ]
    operands = [q, k_pages, v_pages, page_table, bounds]
    if k_scale is not None:
        fn = lambda q_, k_, v_, t_, b_, ks_, vs_: kernel(  # noqa: E731
            q_, k_, v_, t_, b_, k_scale=ks_, v_scale=vs_
        )
        in_specs += [P(None, TP, None, None), P(None, TP, None, None)]
        operands += [k_scale, v_scale]
    else:
        fn = kernel
    return shard_map(
        fn,
        mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=P(None, TP, None),
        check_rep=False,
    )(*operands)
